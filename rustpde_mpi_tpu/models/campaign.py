"""The CampaignModel contract: what a physics model must provide to run
under everything PRs 1–6 built — vmapped ensembles, the stability governor,
elastic checkpoints, ``ResilientRunner`` and the ``SimServer`` scheduler.

PRs 1–6 grew this contract ad hoc on :class:`~.navier.Navier2D`; this module
makes it explicit so the rest of the reference's physics (``Navier2DLnse``,
``Navier2DAdjoint``, scenario-modified DNS) plugs into the same serving and
resilience stack.  The contract has two halves:

**The protocol** (:data:`CAMPAIGN_MODEL_ATTRS`, checked by
:func:`~rustpde_mpi_tpu.workloads.registry.validate_campaign_model`):

* a ``state`` pytree (NamedTuple of device arrays) threaded through a pure
  jitted step,
* hoisted entry points — ``_step_cc``/``_step_consts`` and
  ``_obs_cc``/``_obs_consts`` (the closure-converted step and observables
  jaxprs the ensemble engine re-vmaps; one physics code path, batch as a
  leading axis),
* ``update_n`` with the in-chunk early-exit, ``update_n_pending`` (the
  lag=1 deferred-commit sentinel chunk of the overlapped driver), and
  ``set_stability`` compiling on-device sentinels into the scanned chunk,
* ``set_dt`` with per-rung artifact caching (bounded re-jits under a
  governor ladder),
* ``compat_key`` — the operator-constant bucket key, now prefixed with the
  model kind so mixed-model campaigns bucket correctly,
* observable futures (``get_observables_async``) with per-model
  ``observable_names``,
* the sharded-snapshot surface (``snapshot_state_items`` /
  ``snapshot_root_items`` / ``apply_restored_state``) plus ``read``/``write``.

**The machinery** (:class:`CampaignModelBase`): everything in that list that
is generic over the step function is implemented HERE, once — the scanned
chunk with divergence early-exit and buffer donation, the sentinel-armed
variant, the deferred-commit pending chunk, the dt-rung cache, the cached
observable future, exit/exit_future.  A model supplies the physics hooks:

* ``_make_step(with_sentinels=False)`` — the pure step (with the optional
  ``(cfl, ke, div)`` sentinel tuple),
* ``_make_observables()`` — the fused per-state scalar diagnostics,
* ``_state_example()`` — ShapeDtypeStructs of one state,
* ``_scan_ok(state)`` — the in-scan continue criterion (default: temp is
  finite; the steady-state finder additionally stops on residual
  convergence — the residual-based exit sentinel),
* ``_rebuild_dt_artifacts()`` — rebuild whatever a dt change invalidates.

``Navier2D`` inherits this base (its PR 1–4 behavior is unchanged — the
code moved, the traced programs did not), and ``Navier2DLnse`` /
``Navier2DAdjoint`` ride the same machinery instead of hand-rolled loops.
"""

from __future__ import annotations

import numpy as np

from .. import config

#: the attribute surface the workloads registry validates a campaign model
#: against (see module docstring) — kept as data so the check and the docs
#: cannot drift apart
CAMPAIGN_MODEL_ATTRS = (
    "MODEL_KIND",
    "observable_names",
    "state",
    "compat_key",
    "update_n",
    "update_n_pending",
    "set_stability",
    "clear_pre_divergence",
    "set_stats",
    "stats_armed",
    "set_integrity",
    "integrity_armed",
    "state_digest_async",
    "set_dt",
    "get_dt",
    "get_time",
    "get_observables_async",
    "exit",
    "exit_future",
    "state_healthy",
    "init_random",
    "snapshot_state_items",
    "snapshot_root_items",
    "apply_restored_state",
    "read",
    "write",
    "_step_cc",
    "_step_consts",
    "_obs_cc",
    "_obs_consts",
    "_make_step",
    "_make_observables",
    "_scan_ok",
    "_scope",
)


class CampaignModelBase:
    """Generic campaign-model machinery (see module docstring).

    Subclasses must call :meth:`_init_campaign` early in ``__init__`` (before
    :meth:`_compile_entry_points`) and provide the physics hooks."""

    #: registry kind prefix of :attr:`compat_key` (per subclass)
    MODEL_KIND = "model"
    #: names of the four scalars ``_make_observables`` returns, in order;
    #: index 3 is by convention the NaN detector (a divergence norm)
    observable_names = ("obs0", "obs1", "obs2", "div")

    # overlapped-IO hooks (utils/io_pipeline.py): an attached IOPipeline
    # routes callback IO through the background writer / lag queue, and
    # io_overlap opts the chunked driver into lagged break checks
    # (utils/integrate.py).  Class-level defaults keep plain models fully
    # synchronous.
    io_pipeline = None
    io_overlap = False
    # journal hook (utils/journal.JournalWriter): the resilient runner
    # attaches its writer for the duration of a session so model-side
    # statistics failures surface as typed journal events
    # (models/stats.report_stats_event) instead of swallowed prints
    journal_writer = None

    # -- construction-time bookkeeping ---------------------------------------

    def _init_campaign(self) -> None:
        self.time = 0.0
        self._obs_cache: tuple | None = None
        # stability sentinels (utils/governor.py): None = plain stepping
        self._stability = None
        self.last_chunk_status = None
        self._pre_div_latch = False
        # per-rung cache of dt-baked artifacts (solvers + compiled entry
        # points), so a governor cycling a bounded dt ladder re-jits each
        # rung at most once; recompile_count tracks actual rebuilds
        self._dt_cache: dict[float, dict] = {}
        self.recompile_count = 0
        # AOT executables (aot_compile): static-n chunk executables built
        # ahead of traffic via .lower().compile() — dispatch prefers them,
        # aot_reuse_count tallies dispatches served by a prebuilt executable
        self._aot_step_n: dict[int, object] = {}
        self.aot_reuse_count = 0
        # in-scan physics-stats engine (models/stats.py): None = off;
        # set_stats arms it — the running-sum pytree + its sample-cadence
        # tick then ride the scanned chunks, the snapshot surface and the
        # rollback snapshots exactly like the state itself
        self._stats_engine = None
        self.stats_state = None
        self._stats_tick = None
        # end-to-end integrity layer (integrity/): None = off; set_integrity
        # arms it — the on-device digest entry point is compiled next to the
        # step/observables jaxprs and streamed as futures by the runner
        self._integrity_cfg = None

    # -- physics hooks (per subclass) ----------------------------------------

    def _make_step(self, with_sentinels: bool = False):
        raise NotImplementedError

    def _make_observables(self):
        raise NotImplementedError

    def _state_example(self):
        """ShapeDtypeStruct pytree of one state (hoisting example)."""
        raise NotImplementedError

    def _scan_ok(self, state):
        """In-scan continue criterion over a (traced) state: keep stepping
        while True.  The default is the PR-1 divergence detector — temp is
        finite (a NaN anywhere infects temp within one step via buoyancy/
        convection).  The steady-state finder overrides this with
        ``finite AND residual > tol`` so convergence freezes the member
        inside the chunk — the residual-based exit sentinel."""
        import jax.numpy as jnp

        return jnp.isfinite(jnp.sum(state.temp))

    def _scan_done_ok(self, state):
        """True when a member that STOPPED advancing (``_scan_ok`` False)
        stopped *successfully* (converged) rather than by divergence.
        Default: stopping is always a failure (the DNS semantics)."""
        import jax.numpy as jnp

        del state
        return jnp.asarray(False)

    def _scan_commit_ok(self, state):
        """Is a CANDIDATE stepped state worth committing?  The ensemble's
        per-member freeze keeps the previous state when this is False (the
        NaN-isolation semantics: never commit a poisoned state).  Default:
        same as ``_scan_ok`` — but a model whose ``_scan_ok`` also stops on
        SUCCESS (the adjoint finder's convergence) overrides this to plain
        finiteness, so the converged state IS committed before the member
        freezes (discarding it would pin the member one step shy of its
        answer forever)."""
        return self._scan_ok(state)

    def _gspmd_split_sep_fallback(self) -> bool:
        """True when the fused jitted chunk must be avoided (the GSPMD
        split-sep miscompile guard — see Navier2D); the base assumes no
        such poisoned layout."""
        return False

    def restart_fill(self, name: str, like):
        """Fill value for a state leaf a gathered (restart-equivalent)
        snapshot does not carry — default zero; override for leaves whose
        pristine value is not zero (the adjoint's residual norms)."""
        import jax.numpy as jnp

        del name
        return jnp.zeros_like(like)

    def _rebuild_dt_artifacts(self) -> None:
        """Rebuild everything ``self.dt`` is baked into (solvers, lift
        fields, compiled entry points) — called by :meth:`set_dt` on a
        cache-miss rung, AFTER ``self.dt`` was updated."""
        self._compile_entry_points()

    def _dt_changed(self, dt: float) -> None:
        """Propagation hook run on EVERY dt change (cache hit or miss),
        before artifacts are restored/rebuilt — a wrapper model syncs its
        embedded model here (``Navier2DLnse`` -> inner ``Navier2D``)."""

    # -- sharding helpers ----------------------------------------------------

    def _scope(self):
        """Activate this model's mesh for the duration of a trace/dispatch."""
        from ..parallel.mesh import use_mesh

        if getattr(self, "mesh", None) is None:
            import contextlib

            return contextlib.nullcontext()
        return use_mesh(self.mesh)

    def _place(self, arr):
        """Put a spectral array into x-pencil layout under the mesh."""
        from ..parallel.mesh import SPEC, device_put

        return device_put(arr, SPEC)

    # -- compiled entry points ------------------------------------------------

    def _compile_entry_points(self) -> None:
        """Hoist + jit the step/observables entry points (see Navier2D's
        original docstring: closure-converted constants keep the HLO small
        at large grids) and build the chunked ``step_n`` with the in-chunk
        early-exit and buffer donation.

        The wall time of every pass through here is recorded per model
        kind (telemetry/compile_log.py): dt-ladder re-jits and restores
        re-enter this seam without a model rebuild, and the cold-start
        ROADMAP item needs that attribution separated from build time."""
        import time as _time

        from ..telemetry import compile_log

        t0 = _time.perf_counter()
        try:
            self._compile_entry_points_impl()
        finally:
            compile_log.observe_entry_compile(
                str(getattr(self, "MODEL_KIND", type(self).__name__)),
                _time.perf_counter() - t0,
            )

    def _compile_entry_points_impl(self) -> None:
        import jax
        import jax.numpy as jnp

        from ..utils.jit import hoist_constants

        example = self._state_example()
        self.recompile_count += 1
        self._step_n_jit = None
        self._aot_step_n = {}
        self._sent_cc = None
        self._sent_consts = None
        self._step_n_sent = None
        self._stats_cc = None
        self._stats_consts = None
        self._step_n_stats = None
        self._stats_health_cc = None
        self._stats_health_consts = None
        self._stats_health_fn = None
        self._dig_cc = None
        self._dig_consts = None
        self._dig_fn = None
        with self._scope():
            step_cc, step_consts = hoist_constants(self._make_step(), example)
            obs_cc, obs_consts = hoist_constants(self._make_observables(), example)
        self._step_consts = step_consts
        self._obs_consts = obs_consts
        # retained for the ensemble engine (models/ensemble.py): the SAME
        # traced jaxpr is vmapped over a leading member axis there — one
        # physics code path, batch as a leading axis, no forked step
        self._step_cc = step_cc
        self._obs_cc = obs_cc

        # the digest is a pure elementwise+reduction read of the state —
        # safe on every layout, including the eager fallback below
        if self._integrity_cfg is not None:
            self._compile_integrity_entry_points(example)

        if self._gspmd_split_sep_fallback():
            self._compile_eager_entry_points()
            return

        step_jit = jax.jit(step_cc)
        self._step = lambda s: step_jit(self._step_consts, s)

        def step_n(consts, state, n: int):
            """n scanned steps with in-chunk early-exit: a continue flag
            (``_scan_ok`` — is-finite for the DNS, finite-and-unconverged
            for the steady finder) rides the carry, and once it drops the
            remaining iterations take the identity branch of a ``lax.cond``
            — the device stops paying for GEMMs mid-chunk.  Returns
            ``(state, steps_done)``."""

            def advance(carry):
                st, _, done = carry
                st2 = step_cc(consts, st)
                ok2 = self._scan_ok(st2)
                return st2, ok2, done + 1

            def body(carry, _):
                carry2 = jax.lax.cond(carry[1], advance, lambda c: c, carry)
                return carry2, None

            init = (state, jnp.asarray(True), jnp.asarray(0, jnp.int32))
            (final, _, done), _ = jax.lax.scan(body, init, None, length=n)
            return final, done

        # donate the state: XLA aliases the input coefficient buffers to the
        # scan carry's outputs, so a chunked dispatch updates the state in
        # place instead of holding a second resident copy in HBM.  Callers
        # must hand in buffers they no longer need — update_n dispatches a
        # fresh copy first, keeping references retained to ``self.state``
        # across the call valid (no use-after-donate on the public API).
        step_n_jit = jax.jit(step_n, static_argnames=("n",), donate_argnums=(1,))
        # retained for aot_compile: .lower(...).compile() against these jit
        # objects builds static-n executables ahead of traffic; a recompile
        # pass invalidates any prebuilt executables (the consts changed)
        self._step_n_jit = step_n_jit

        def dispatch_step_n(s, n):
            exe = self._aot_step_n.get(int(n))
            if exe is not None:
                self.aot_reuse_count += 1
                return exe(self._step_consts, s)
            return step_n_jit(self._step_consts, s, n=n)

        self._step_n = dispatch_step_n
        obs_jit = jax.jit(obs_cc)
        self._obs_fn = lambda s: obs_jit(self._obs_consts, s)

        if self._stats_engine is not None:
            self._compile_stats_entry_points(step_cc, example)

        if self._stability is not None:
            self._compile_sentinel_entry_points(example)

    def _compile_stats_entry_points(self, step_cc, example) -> None:
        """Stats-armed variant of the scanned chunk: the StatsState running
        sums and a sample-cadence tick ride the carry next to the state.
        The accumulator only READS the stepped state — it is a pure
        consumer, so the state trajectory stays BIT-identical to the plain
        chunk (the same contract the sentinel reductions ship under,
        CI-asserted).  Accumulation is gated on the stride cond AND on the
        step surviving ``_scan_ok`` (a corpse is never sampled)."""
        import jax
        import jax.numpy as jnp

        from ..utils.jit import hoist_constants

        eng = self._stats_engine
        sx = eng.state_example()
        with self._scope():
            stats_cc, stats_consts = hoist_constants(eng.accum_fn(), sx, example)
            health_cc, health_consts = hoist_constants(eng.health_fn(), sx)
        self._stats_cc = stats_cc
        self._stats_consts = stats_consts
        self._stats_health_cc = health_cc
        self._stats_health_consts = health_consts
        health_jit = jax.jit(health_cc)
        self._stats_health_fn = lambda ss: health_jit(health_consts, ss)
        stride = int(eng.stride)

        def step_n_stats(consts, sconsts, state, ss, tick, n: int):
            def advance(carry):
                st, ss, tk, ok, done = carry
                st2 = step_cc(consts, st)
                ok2 = self._scan_ok(st2)
                tk2 = tk + 1
                take = jnp.logical_and(ok2, (tk2[0] % stride) == 0)
                ss2 = jax.lax.cond(
                    take, lambda s: stats_cc(sconsts, s, st2), lambda s: s, ss
                )
                return st2, ss2, tk2, ok2, done + 1

            def body(carry, _):
                carry2 = jax.lax.cond(carry[3], advance, lambda c: c, carry)
                return carry2, None

            init = (state, ss, tick, jnp.asarray(True), jnp.asarray(0, jnp.int32))
            (st, ss, tk, _, done), _ = jax.lax.scan(body, init, None, length=n)
            return st, ss, tk, done

        stats_jit = jax.jit(
            step_n_stats, static_argnames=("n",), donate_argnums=(2, 3, 4)
        )
        self._step_n_stats = lambda s, ss, tk, n: stats_jit(
            self._step_consts, self._stats_consts, s, ss, tk, n=n
        )

    def _compile_eager_entry_points(self) -> None:
        """Per-stage eager fallback (the GSPMD split-sep miscompile guard):
        slow but right; same early-exit semantics as the scanned fast path
        (the state that first failed ``_scan_ok`` is kept, later steps are
        identity)."""
        import jax.numpy as jnp

        step_fn = self._make_step()
        obs_fn = self._make_observables()
        self._step = step_fn

        def step_n_eager(state, n):
            done = 0
            for _ in range(int(n)):
                state = step_fn(state)
                done += 1
                if not bool(self._scan_ok(state)):
                    break
            return state, jnp.asarray(done, jnp.int32)

        self._step_n = step_n_eager
        self._obs_fn = obs_fn

    def aot_compile(self, chunk_steps: int) -> int:
        """AOT-build the chunked-step executables a ``chunk_steps``-sized
        dispatch needs — every static scan bucket of ``run_scanned``'s
        decomposition — via ``.lower().compile()`` on the retained jit
        objects.  Populates the persistent compile cache (the executables
        survive process death when it is armed) AND retains the compiled
        objects so dispatch skips the jit machinery entirely (reuse tallied
        in :attr:`aot_reuse_count`).  Returns how many executables were
        newly built (0 on the eager-fallback path, where there is nothing
        to compile ahead of time)."""
        from ..utils.jit import scan_buckets

        step_n_jit = getattr(self, "_step_n_jit", None)
        if step_n_jit is None:
            return 0
        built = 0
        with self._scope():
            for n in scan_buckets(chunk_steps):
                if n in self._aot_step_n:
                    continue
                self._aot_step_n[n] = step_n_jit.lower(
                    self._step_consts, self.state, n=n
                ).compile()
                built += 1
        return built

    def _compile_sentinel_entry_points(self, example) -> None:
        """Sentinel variant of the scanned chunk (set_stability): the carry
        additionally holds a CFL-ok flag and running sentinel reductions, and
        the early-exit fires on EITHER a failed ``_scan_ok`` (the NaN path)
        or a per-step CFL above ``max_cfl`` — the *pre-divergence* catch,
        taken while the state is still finite so the chunk can be recovered
        by an in-memory rollback instead of a checkpoint restore."""
        import jax
        import jax.numpy as jnp

        from ..utils.jit import hoist_constants

        with self._scope():
            sent_cc, sent_consts = hoist_constants(
                self._make_step(with_sentinels=True), example
            )
        self._sent_cc = sent_cc
        self._sent_consts = sent_consts
        ceiling = float(self._stability.max_cfl)
        # with the stats engine armed, the running sums + sample tick ride
        # the sentinel carry too (appended AFTER the sentinel slots, so the
        # fetch indices the pending-resolve path reads stay put); sampling
        # is gated on the step being finite AND under the ceiling — a
        # tripping chunk's accumulation is discarded by the rollback anyway
        stats_cc = self._stats_cc
        stats_stride = int(self._stats_engine.stride) if stats_cc is not None else 0

        def step_n_sent(consts, sconsts, carry, n: int):
            def advance(carry):
                st, fin, cok, done, cflm, gm, dvm, kep = carry[:8]
                st2, (cfl, ke, dv) = sent_cc(consts, st)
                fin2 = self._scan_ok(st2)
                # NaN cfl must read as the NaN path, not a ceiling trip:
                # NaN > ceiling is False, so ~(cfl > ceiling) stays True
                cok2 = jnp.logical_not(cfl > ceiling)
                growth = jnp.where(kep > 0.0, ke / kep, 1.0)
                out = (
                    st2,
                    fin2,
                    cok2,
                    done + 1,
                    jnp.maximum(cflm, cfl),
                    jnp.maximum(gm, growth),
                    jnp.maximum(dvm, dv),
                    ke,
                )
                if stats_cc is not None:
                    ss, tk = carry[8], carry[9]
                    tk2 = tk + 1
                    take = fin2 & cok2 & ((tk2[0] % stats_stride) == 0)
                    ss2 = jax.lax.cond(
                        take,
                        lambda s: stats_cc(sconsts, s, st2),
                        lambda s: s,
                        ss,
                    )
                    out = out + (ss2, tk2)
                return out

            def body(carry, _):
                carry2 = jax.lax.cond(
                    carry[1] & carry[2], advance, lambda c: c, carry
                )
                return carry2, None

            final, _ = jax.lax.scan(body, carry, None, length=n)
            return final

        sent_jit = jax.jit(step_n_sent, static_argnames=("n",), donate_argnums=(2,))
        self._step_n_sent = lambda c, n: sent_jit(
            self._sent_consts, self._stats_consts, c, n=n
        )

    # -- Integrate protocol ---------------------------------------------------

    def update(self) -> None:
        with self._scope():
            self.state = self._step(self.state)
        self.time += self.dt

    def update_n(self, n: int):
        """Advance n steps on the device via scanned power-of-two chunks
        (utils/jit.run_scanned).  Dispatches stay asynchronous and donate
        their input state buffers; on divergence the in-scan early exit
        freezes the state, ``exit()`` reports it at the next chunk boundary,
        and ``self.time`` deliberately counts the scheduled steps.

        With stability sentinels armed (:meth:`set_stability`) the chunk
        additionally returns a
        :class:`~rustpde_mpi_tpu.utils.governor.ChunkStatus` (also stored as
        ``self.last_chunk_status``): a per-step CFL above the hard ceiling
        early-exits the scan with ``pre_divergence`` while the state is
        still finite, the chunk is rolled back in memory and ``exit()``
        latches True until a governor acknowledges
        (:meth:`clear_pre_divergence`)."""
        import jax
        import jax.numpy as jnp

        from ..utils.jit import run_scanned

        if self._step_n_sent is not None:
            return self._update_n_sentinel(n)
        with self._scope():
            # the chunked dispatch donates its input buffers; hand it a copy
            # so a state reference the caller retained stays readable, while
            # every inter-bucket hand-off inside the chain is donated
            state = jax.tree.map(jnp.copy, self.state)
            if self._step_n_stats is not None:
                ss = jax.tree.map(jnp.copy, self.stats_state)
                tick = jnp.copy(self._stats_tick)
                st, ss, tick = run_scanned(
                    lambda c, k: self._step_n_stats(c[0], c[1], c[2], k)[:3],
                    (state, ss, tick),
                    n,
                )
                self.state, self.stats_state, self._stats_tick = st, ss, tick
            else:
                self.state = run_scanned(
                    lambda s, k: self._step_n(s, k)[0], state, n
                )
        self.time += n * self.dt
        return None

    def _update_n_sentinel(self, n: int):
        """Sentinel-armed chunk: scan with CFL/KE/|div| reductions riding the
        carry, one scalar fetch at the end (the only extra host sync)."""
        return self.update_n_pending(n).resolve()

    def update_n_pending(self, n: int):
        """Sentinel-armed chunk with a DEFERRED commit decision (the lag=1
        contract of the overlapped driver, utils/io_pipeline.py): dispatch
        the scanned chunk, PROVISIONALLY advance ``state``/``time`` to its
        end, and return a
        :class:`~rustpde_mpi_tpu.utils.io_pipeline.PendingChunkStatus` whose
        ``resolve()`` fetches the sentinel scalars and either confirms the
        advance or restores the chunk-start snapshot (+ latches ``exit()``)
        — exactly the synchronous :meth:`update_n` outcome, decided one host
        round-trip later."""
        import jax
        import jax.numpy as jnp

        from ..utils.governor import ChunkStatus
        from ..utils.io_pipeline import PendingChunkStatus
        from ..utils.jit import run_scanned

        if self._step_n_sent is None:
            raise RuntimeError(
                "update_n_pending requires armed stability sentinels "
                "(set_stability)"
            )
        self._pre_div_latch = False
        rdt = config.real_dtype()
        stats_on = self._stats_cc is not None
        with self._scope():
            state = jax.tree.map(jnp.copy, self.state)
            carry = (
                state,
                jnp.asarray(True),
                jnp.asarray(True),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(0.0, rdt),  # cfl max
                jnp.asarray(0.0, rdt),  # ke growth max
                jnp.asarray(0.0, rdt),  # |div| max
                jnp.asarray(0.0, rdt),  # previous-step ke
            )
            if stats_on:
                # the running sums + tick ride the sentinel carry (and the
                # rollback snapshot below — a tripped chunk's samples are
                # discarded with its steps)
                carry = carry + (
                    jax.tree.map(jnp.copy, self.stats_state),
                    jnp.copy(self._stats_tick),
                )
            carry = run_scanned(lambda c, k: self._step_n_sent(c, k), carry, n)
        st, fin, cok, done, cflm, gm, dvm, ke = carry[:8]
        snapshot = (self.state, self.time, self.stats_state, self._stats_tick)
        self.state = st  # provisional: resolve() confirms or restores
        if stats_on:
            self.stats_state, self._stats_tick = carry[8], carry[9]
        self.time += n * self.dt
        dt = self.dt

        def finish(fetched):
            fin_h, cok_h, done_h, cflm_h, gm_h, dvm_h, ke_h = fetched
            fin_b, cok_b = bool(fin_h), bool(cok_h)
            pre_div = fin_b and not cok_b
            if pre_div:
                # in-memory rollback: the dispatch stepped a donated COPY,
                # so the snapshot still holds the chunk-start state — put it
                # back and latch exit() until a governor acts
                (self.state, self.time, self.stats_state, self._stats_tick) = (
                    snapshot
                )
                self._pre_div_latch = True
            status = ChunkStatus(
                requested=int(n),
                steps_done=int(done_h),
                finite=fin_b,
                cfl_ok=cok_b,
                pre_divergence=pre_div,
                cfl_max=float(cflm_h),
                ke=float(ke_h),
                ke_growth_max=float(gm_h),
                div_max=float(dvm_h),
                dt=dt,
            )
            self.last_chunk_status = status
            return status

        return PendingChunkStatus((fin, cok, done, cflm, gm, dvm, ke), finish)

    def set_stability(self, cfg) -> None:
        """Arm/disarm (``None``) the on-device stability sentinels
        (:class:`~rustpde_mpi_tpu.config.StabilityConfig`): compiles the
        sentinel variant of the scanned chunk into :meth:`update_n`.  Under
        the GSPMD split-sep fallback the sentinel path is unavailable and
        stepping stays plain (a one-time warning is emitted)."""
        self._stability = cfg
        self._dt_cache.clear()  # cached artifacts lack/stale sentinel entries
        self._compile_entry_points()
        if cfg is not None and self._step_n_sent is None:
            import warnings

            warnings.warn(
                "stability sentinels are not available on the per-stage "
                "eager GSPMD fallback path; stepping stays plain",
                RuntimeWarning,
                stacklevel=2,
            )
        self.last_chunk_status = None
        self._pre_div_latch = False

    def clear_pre_divergence(self) -> None:
        """Acknowledge a ``pre_divergence`` catch (the governor changed dt /
        killed members and wants the chunk retried): unlatch ``exit()``."""
        self._pre_div_latch = False

    # -- in-scan physics statistics (models/stats.py) --------------------------

    def set_stats(self, cfg) -> None:
        """Arm/disarm (``None``) the in-scan physics-stats engine
        (:class:`~rustpde_mpi_tpu.config.StatsConfig`): compiles the
        stats-carrying variants of the scanned chunks and zero-initializes
        the running sums.  Under the GSPMD split-sep eager fallback the
        in-scan engine is unavailable and stepping stays plain (a one-time
        warning, like the sentinels)."""
        import jax.numpy as jnp

        if cfg is None:
            self._stats_engine = None
            self.stats_state = None
            self._stats_tick = None
            self._dt_cache.clear()
            self._compile_entry_points()
            return
        from .stats import StatsEngine

        self._stats_engine = StatsEngine(self, cfg)
        self._dt_cache.clear()
        self._compile_entry_points()
        if self._stats_cc is None:
            import warnings

            warnings.warn(
                "the in-scan stats engine is not available on the "
                "per-stage eager GSPMD fallback path; stats stay disarmed",
                RuntimeWarning,
                stacklevel=2,
            )
            self._stats_engine = None
            return
        with self._scope():
            self.stats_state = self._stats_engine.init_state()
            self._stats_tick = jnp.zeros((1,), jnp.int32)

    def reset_stats(self) -> None:
        """Zero the running sums + sample tick (a fresh averaging window)."""
        import jax.numpy as jnp

        if not self.stats_armed:
            return
        with self._scope():
            self.stats_state = self._stats_engine.init_state()
            self._stats_tick = jnp.zeros((1,), jnp.int32)

    @property
    def stats_engine(self):
        """The armed :class:`~rustpde_mpi_tpu.models.stats.StatsEngine`
        (None when disarmed) — public surface for the runner/scheduler."""
        return self._stats_engine

    @property
    def stats_armed(self) -> bool:
        return self._stats_engine is not None and self.stats_state is not None

    def stats_health_async(self):
        """Dispatch the compiled :data:`~rustpde_mpi_tpu.models.stats
        .HEALTH_NAMES` readout over the running sums and return an
        observable future — the runner resolves it one boundary later and
        exports gauges / typed journal events (``resolution_warning``,
        ``budget_drift``)."""
        from ..utils.io_pipeline import ObservableFuture

        if not self.stats_armed:
            raise RuntimeError("stats_health_async needs an armed stats engine")
        with self._scope():
            return ObservableFuture(
                self._stats_health_fn(self.stats_state),
                convert=lambda vals: tuple(
                    np.asarray(v) for v in vals  # lint-ok: RPD005 health scalars are replicated reductions
                ),
            )

    def stats_summary(self) -> dict | None:
        """Synchronous health readout as a dict (None when disarmed)."""
        if not self.stats_armed:
            return None
        from .stats import HEALTH_NAMES

        vals = self.stats_health_async().result()
        return {
            name: (float(v) if np.ndim(v) == 0 else [float(x) for x in v])
            for name, v in zip(HEALTH_NAMES, vals)
        }

    def stats_host_items(self) -> list:
        """Gathered-snapshot rows for the stats leaves
        (:meth:`StatsEngine.host_items`); empty when disarmed."""
        if not self.stats_armed:
            return []
        return self._stats_engine.host_items(self.stats_state, self._stats_tick)

    def apply_restored_stats(self, data: dict | None) -> None:
        """Install stats leaves read back from a gathered snapshot (keys =
        leaf names + ``tick``) via :meth:`StatsEngine.restore_state`:
        ``None``/missing leaves reset to zero — a checkpoint written before
        stats were armed restarts the averaging window instead of failing
        the restore."""
        if not self.stats_armed:
            return
        with self._scope():
            self.stats_state, self._stats_tick = (
                self._stats_engine.restore_state(
                    data, k=self.k if hasattr(self, "k") else None
                )
            )

    # -- end-to-end integrity (integrity/) ------------------------------------

    def _compile_integrity_entry_points(self, example) -> None:
        """Hoist + jit the on-device state digest (integrity/digest.py):
        a pure uint32 read of the state, retained closure-converted
        (``_dig_cc``/``_dig_consts``) so the ensemble engine re-vmaps the
        SAME jaxpr over the member axis — per-member digests localize a
        corrupted member exactly like the observables localize NaNs."""
        import jax

        from ..integrity import digest_tree
        from ..utils.jit import hoist_constants

        with self._scope():
            dig_cc, dig_consts = hoist_constants(digest_tree, example)
        self._dig_cc = dig_cc
        self._dig_consts = dig_consts
        dig_jit = jax.jit(dig_cc)
        self._dig_fn = lambda s: dig_jit(self._dig_consts, s)

    def set_integrity(self, cfg) -> None:
        """Arm/disarm (``None``) the integrity layer
        (:class:`~rustpde_mpi_tpu.config.IntegrityConfig`): compiles the
        on-device digest entry point.  The digest is a pure consumer of
        the state — the trajectory stays bit-identical armed vs not (the
        same CI-asserted contract the stats/sentinel chunks ship under)."""
        self._integrity_cfg = cfg
        self._dt_cache.clear()
        self._compile_entry_points()

    @property
    def integrity_config(self):
        return self._integrity_cfg

    @property
    def integrity_armed(self) -> bool:
        return (
            self._integrity_cfg is not None
            and getattr(self, "_dig_fn", None) is not None
        )

    def _digest_future(self, device_val):
        from ..utils.io_pipeline import ObservableFuture

        return ObservableFuture(
            device_val,
            convert=lambda v: np.asarray(v)  # lint-ok: RPD005 a replicated uint32 scalar
        )

    def state_digest_async(self):
        """Dispatch the on-device digest of the CURRENT state and return
        an observable future (uint32 scalar; ``(k,)`` per-member vector on
        ensembles) — streamed by the runner with the observables futures,
        no extra host sync per chunk."""
        if not self.integrity_armed:
            raise RuntimeError(
                "state_digest_async needs an armed integrity layer "
                "(set_integrity)"
            )
        with self._scope():
            return self._digest_future(self._dig_fn(self.state))

    def digest_of_async(self, state):
        """Digest an arbitrary state pytree (the runner's retained
        chunk-start copies) without touching ``self.state``."""
        with self._scope():
            return self._digest_future(self._dig_fn(state))

    def shadow_digest_async(self, snap: dict, n: int):
        """Shadow re-execution audit kernel: re-step ``n`` steps from the
        retained :meth:`integrity_snapshot` through the PLAIN chunked path
        and digest the result.  The snapshot is not consumed (the chunk
        donates a copy).  The plain chunk is bit-identical to the live
        sentinel/stats chunks by the pure-consumer contract, and XLA
        executables are deterministic — a digest differing from the live
        chunk's means corrupted state."""
        import jax
        import jax.numpy as jnp

        from ..utils.jit import run_scanned

        if not self.integrity_armed:
            raise RuntimeError(
                "shadow_digest_async needs an armed integrity layer "
                "(set_integrity)"
            )
        with self._scope():
            st = jax.tree.map(jnp.copy, snap["state"])
            st = run_scanned(lambda s, k: self._step_n(s, k)[0], st, n)
            return self._digest_future(self._dig_fn(st))

    def integrity_snapshot(self) -> dict:
        """Un-donated device-side copy of everything an in-memory
        integrity rollback must restore (state/time + armed stats)."""
        import jax
        import jax.numpy as jnp

        with self._scope():
            snap = {
                "state": jax.tree.map(jnp.copy, self.state),
                "time": self.time,
            }
            if self.stats_armed:
                snap["stats"] = (
                    jax.tree.map(jnp.copy, self.stats_state),
                    jnp.copy(self._stats_tick),
                )
        return snap

    def integrity_restore(self, snap: dict) -> None:
        """Roll back to a digest-verified :meth:`integrity_snapshot` (the
        snapshot stays reusable — the install copies)."""
        import jax
        import jax.numpy as jnp

        with self._scope():
            self.state = jax.tree.map(jnp.copy, snap["state"])
            self.time = snap["time"]
            if "stats" in snap and self.stats_armed:
                ss, tick = snap["stats"]
                self.stats_state = jax.tree.map(jnp.copy, ss)
                self._stats_tick = jnp.copy(tick)
        self._obs_cache = None
        self._pre_div_latch = False

    def _verify_restored_digest(self, expected) -> None:
        """Recompute the on-device digest after a (bit-exact, sharded)
        restore and compare with the manifest's — the device→disk→device
        loop the host-side sha256 cannot close.  No-op when the
        checkpoint predates the integrity layer or it is disarmed."""
        if expected is None or not self.integrity_armed:
            return
        got = np.asarray(  # lint-ok: RPD005 fully-replicated uint32 digest
            self.state_digest_async().result()
        )
        exp = np.asarray(  # lint-ok: RPD005 manifest root data, host array
            expected
        ).astype(got.dtype).reshape(got.shape)
        if not np.array_equal(got, exp):
            from ..integrity import IntegrityError

            raise IntegrityError(
                f"restored state digest {got.tolist()} does not match the "
                f"checkpoint manifest digest {exp.tolist()} — the snapshot "
                "was corrupted between device and disk",
                check="checkpoint",
            )

    def get_time(self) -> float:
        return self.time

    def get_dt(self) -> float:
        return self.dt

    def reset_time(self) -> None:
        self.time = 0.0

    # -- dt rung cache --------------------------------------------------------

    #: attributes a dt change swaps out, cached per rung so a governor
    #: cycling a bounded dt ladder re-jits each rung ONCE (per subclass —
    #: extend with whatever else dt is baked into)
    _DT_ARTIFACTS = (
        "_step",
        "_step_n",
        "_obs_fn",
        "_step_cc",
        "_obs_cc",
        "_step_consts",
        "_obs_consts",
        "_sent_cc",
        "_sent_consts",
        "_step_n_sent",
        "_stats_cc",
        "_stats_consts",
        "_step_n_stats",
        "_stats_health_cc",
        "_stats_health_consts",
        "_stats_health_fn",
        "_dig_cc",
        "_dig_consts",
        "_dig_fn",
    )

    def _dt_artifacts(self) -> dict:
        return {k: getattr(self, k, None) for k in self._DT_ARTIFACTS}

    def set_dt(self, dt: float) -> None:
        """Change the time-step size of a live model (the governor's dt
        ladder and the divergence-retry backoff).

        dt is baked deep into the pipeline, so a FIRST visit to a dt
        rebuilds the dt-baked artifacts (:meth:`_rebuild_dt_artifacts`) and
        re-traces the jitted entry points.  Every artifact is then cached
        per dt value, so revisiting a rung swaps the cached objects back in
        — the retained jit closures keep their identity, so XLA's executable
        cache hits and the total re-jit count over a long governed run is
        bounded by the ladder size.  State and time are untouched either
        way: the run continues from the same fields at the new step size."""
        dt = float(dt)
        if dt <= 0.0:
            raise ValueError(f"dt must be positive, got {dt}")
        if dt == self.dt:
            return
        self._dt_cache[self.dt] = self._dt_artifacts()
        self.dt = dt
        self._dt_changed(dt)
        cached = self._dt_cache.get(dt)
        if cached is not None:
            for key, value in cached.items():
                setattr(self, key, value)
            self._obs_cache = None
            return
        self._rebuild_dt_artifacts()
        self._obs_cache = None

    # -- observables / exit ---------------------------------------------------

    def get_observables_async(self):
        """Dispatch the fused observables computation and return an
        :class:`~rustpde_mpi_tpu.utils.io_pipeline.ObservableFuture` WITHOUT
        waiting for it — the device keeps working while the host decides
        when (if ever) to fetch.  Cached per state, shared with the
        synchronous accessors and :meth:`exit_future`, so diagnostics +
        break checks cost ONE dispatch and ONE host transfer per state."""
        from ..utils.io_pipeline import ObservableFuture

        if self._obs_cache is None or self._obs_cache[0] is not self.state:
            with self._scope():
                fut = ObservableFuture(
                    self._obs_fn(self.state),
                    convert=lambda vals: tuple(float(v) for v in vals),
                )
            self._obs_cache = (self.state, fut)
        return self._obs_cache[1]

    def get_observables(self) -> tuple[float, float, float, float]:
        """The four per-model scalars (:attr:`observable_names`) — one fused
        device dispatch, cached per state, fetched in ONE host transfer."""
        return self.get_observables_async().result()

    def device_fence(self) -> None:
        """Block until every dispatched device computation whose output this
        model still holds has completed: the state chunk, the running stats
        sums, and the cached observables dispatch.  The serve scheduler runs
        this before any host-level collective while the campaign occupies a
        PROPER sub-mesh — a full-device barrier would otherwise start on the
        sub-mesh's idle complement and its wire traffic interleaves with the
        campaign's in-flight collectives (multihost.set_device_fence)."""
        if self.state is not None:
            jax.block_until_ready(self.state)
        stats = getattr(self, "stats_state", None)
        if stats is not None:
            jax.block_until_ready(stats)
        cache = self._obs_cache
        if cache is not None and not cache[1].ready():
            cache[1].result()

    def div_norm(self) -> float:
        """The NaN-detector observable (index 3 by convention)."""
        return self.get_observables()[3]

    def exit(self) -> bool:
        """NaN-divergence break criterion, extended by the pre-divergence
        latch: a CFL-ceiling catch (sentinels armed) reads as a break until
        a governor clears it."""
        if self._pre_div_latch:
            return True
        return bool(np.isnan(self.div_norm()))

    def exit_future(self):
        """Non-blocking form of :meth:`exit` for the overlapped driver
        (utils/integrate.py ``overlap``): a latched pre-divergence catch
        resolves immediately (host-side fact); otherwise the break flag
        rides the cached observables dispatch."""
        from ..utils.io_pipeline import MappedFuture, immediate

        if self._pre_div_latch:
            return immediate(True)
        return MappedFuture(
            self.get_observables_async(), lambda vals: bool(np.isnan(vals[3]))
        )

    def state_healthy(self) -> bool:
        """Is the current state worth checkpointing?  Distinct from
        :meth:`exit`: a steady-state finder that CONVERGED exits the run
        loop but its state is the answer, not a corpse.  The resilient
        runner consults this before every checkpoint."""
        if self._pre_div_latch:
            return False
        return bool(np.isfinite(self.div_norm()))

    # -- sharded (shard-wise) snapshot surface --------------------------------

    def snapshot_state_items(self) -> list:
        """``(name, device_array)`` for every state leaf the sharded
        checkpoint must carry — the full restart set, generic over the
        state NamedTuple.  With the stats engine armed the running sums +
        sample tick join the set, so long-horizon averages ride the
        two-phase sharded checkpoints and survive kill/resume bit-exactly."""
        items = [
            (f"state/{name}", getattr(self.state, name))
            for name in self.state._fields
        ]
        if self.stats_armed:
            items += [
                (f"stats/{name}", getattr(self.stats_state, name))
                for name in self.stats_state._fields
            ]
            items.append(("stats/tick", self._stats_tick))
        return items

    def _split_restored_stats(self, updates: dict) -> None:
        """Pull the stats leaves out of a sharded-restore ``updates`` dict
        (missing ones reset to zero — an older checkpoint restarts the
        averaging window) and install them; the remaining entries are the
        state leaves the caller installs."""
        if not self.stats_armed:
            return
        self.apply_restored_stats(self._stats_engine.split_restored(updates))

    def snapshot_root_items(self) -> list:
        """Replicated host-side data for the sharded manifest root.  With
        the integrity layer armed the on-device state digest rides the
        manifest: the sharded format is bit-exact, so a restore recomputes
        and compares it (:meth:`_verify_restored_digest`) — a verified
        checkpoint closes the device→disk→device loop."""
        items = [("time", np.asarray(float(self.time), dtype=np.float64), "raw")]
        for key, value in getattr(self, "params", {}).items():
            items.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
        if self.integrity_armed:
            items.append((
                "integrity_digest",
                np.asarray(self.state_digest_async().result()),  # lint-ok: RPD005 a replicated uint32 scalar
                "raw",
            ))
        return items

    def apply_restored_state(self, updates: dict, attrs: dict, root: dict) -> None:
        """Install state leaves assembled by the sharded reader (already
        placed in this model's target layout) + the manifest's time.  Stats
        leaves (engine armed) are split off first — restored exactly when
        the checkpoint carries them, reset to zero when it predates the
        arming."""
        self._split_restored_stats(updates)
        self.state = self.state._replace(**updates)
        self.time = float(np.asarray(root["time"]))
        self._obs_cache = None
        self._pre_div_latch = False
        self._verify_restored_digest(root.get("integrity_digest"))

    # -- compatibility bucketing ----------------------------------------------

    def _compat_fields(self) -> tuple:
        """Everything (beyond the model kind) baked into the compiled step —
        per subclass."""
        raise NotImplementedError

    @property
    def compat_key(self) -> tuple:
        """Operator-constant bucket key, prefixed with the model kind: two
        requests/models with equal keys share one compiled (vmapped) step
        jaxpr — the serve scheduler buckets by this; anything differing
        forces a fresh model build + compile."""
        return (str(self.MODEL_KIND),) + tuple(self._compat_fields())
