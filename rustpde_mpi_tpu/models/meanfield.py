"""MeanFields — base-state container for the linearized/perturbation solvers.

Rebuild of /root/reference/src/navier_stokes_lnse/meanfield.rs:26-121: the
velx/vely/temp base state in the full orthogonal space (chebyshev^2 confined,
fourier x chebyshev periodic), with built-in RBC (linear conduction profile)
and HC (cos-bottom parabola) constructors and a read-from-file variant that
falls back to the analytic profile when the file is missing.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from .. import config
from ..bases import Space2, chebyshev, fourier_r2c


class MeanFields:
    """velx/vely/temp spectral coefficients on the full ortho space."""

    def __init__(self, space: Space2, velx=None, vely=None, temp=None):
        self.space = space
        zero = space.ndarray_spectral()
        self.velx = zero if velx is None else velx
        self.vely = zero if vely is None else vely
        self.temp = zero if temp is None else temp

    # -- constructors (meanfield.rs:27-90, 133-207) --------------------------

    @classmethod
    def _space(cls, nx: int, ny: int, periodic: bool) -> Space2:
        x_base = fourier_r2c if periodic else chebyshev
        return Space2(x_base(nx), chebyshev(ny))

    @classmethod
    def new_rbc(cls, nx: int, ny: int, periodic: bool = False) -> "MeanFields":
        """Linear conduction profile T = 0.5 at the bottom to -0.5 at the top."""
        space = cls._space(nx, ny, periodic)
        y = space.bases[1].points
        height = y[-1] - y[0]
        profile = -(y - y[0]) / height + 0.5
        v = np.broadcast_to(profile[None, :], space.shape_physical)
        temp = space.forward(jnp.asarray(v, dtype=config.real_dtype()))
        return cls(space, temp=temp)

    @classmethod
    def new_hc(cls, nx: int, ny: int, periodic: bool = False) -> "MeanFields":
        """Horizontal convection: T = -0.5 cos(2 pi x~) at the bottom,
        parabola in y with vertex at the top wall."""
        space = cls._space(nx, ny, periodic)
        x = space.bases[0].points
        y = space.bases[1].points
        f_x = -0.5 * np.cos(2.0 * np.pi * (x - x[0]) / (x[-1] - x[0]))
        a = f_x / (y[0] - y[-1]) ** 2
        v = a[:, None] * (y[None, :] - y[-1]) ** 2
        temp = space.forward(jnp.asarray(v, dtype=config.real_dtype()))
        return cls(space, temp=temp)

    @classmethod
    def read_from(
        cls, nx: int, ny: int, filename: str, bc: str | None = None, periodic: bool = False
    ) -> "MeanFields":
        """Read a mean field from a flow snapshot; fall back to the analytic
        bc profile when the file does not exist (meanfield.rs:92-121)."""
        if os.path.isfile(filename):
            mean = cls(cls._space(nx, ny, periodic))
            mean.read(filename)
            return mean
        print(f"File {filename!r} does not exist. Use {bc!r} meanfield.")
        if bc == "hc":
            return cls.new_hc(nx, ny, periodic)
        return cls.new_rbc(nx, ny, periodic)

    # -- IO (reference snapshot layout, vars ux/uy/temp) ---------------------

    _VARS = (("ux", "velx"), ("uy", "vely"), ("temp", "temp"))

    def read(self, filename: str) -> None:
        """Read the base state from a flow snapshot.

        Deliberate fix over the reference: its MeanFields read assigns the
        snapshot's *composite* (e.g. cheb_dirichlet) coefficients into the
        mean's *orthogonal* space via the shape-mismatch zero-pad
        (meanfield.rs:92-106 + field/io.rs:74-83), which misinterprets the
        Galerkin coefficients.  Here the stored physical values ``{var}/v``
        are forward-transformed in the ortho space — exact for any source
        space.  Falls back to ``vhat`` if ``v`` is absent (then the source
        must be ortho-space data, e.g. one written by this class)."""
        import h5py

        from ..utils.checkpoint import read_field_vhat

        rdt = config.real_dtype()
        with h5py.File(filename, "r") as h5:
            for varname, attr in self._VARS:
                if f"{varname}/v" in h5:
                    v = np.asarray(h5[f"{varname}/v"])
                    if v.shape != self.space.shape_physical:
                        raise ValueError(
                            f"{varname}/v shape {v.shape} != grid "
                            f"{self.space.shape_physical}; resample the "
                            "snapshot first"
                        )
                    vhat = self.space.forward(jnp.asarray(v, dtype=rdt))
                else:
                    vhat = jnp.asarray(
                        read_field_vhat(h5, varname, self.space),
                        dtype=self.space.spectral_dtype(),
                    )
                setattr(self, attr, vhat)
        print(f" <== {filename}")

    def write(self, filename: str) -> None:
        import h5py

        from ..field import grid_deltas
        from ..utils.checkpoint import write_field

        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        xs = [b.points for b in self.space.bases]
        dxs = [grid_deltas(b.points, b.is_periodic) for b in self.space.bases]
        with h5py.File(filename, "a") as h5:
            for varname, attr in self._VARS:
                write_field(h5, varname, self.space, getattr(self, attr), xs, dxs)

    def physical(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        return (
            np.asarray(self.space.backward_ortho(self.velx)),
            np.asarray(self.space.backward_ortho(self.vely)),
            np.asarray(self.space.backward_ortho(self.temp)),
        )
