"""Batched ensemble execution engine: K independent RBC simulations per dispatch.

The inference-stack analogue of request batching, applied to DNS: at the
small/medium grids that dominate parameter sweeps and optimal-perturbation
campaigns a single 129² step fills ~4% of the chip (BENCH_FULL.json
``rbc129.mfu``), so K independent members are stacked on a leading axis and
advanced by ONE vmapped, jitted, chunked ``lax.scan`` dispatch.  Design
points:

* **one physics code path** — the member step is :class:`Navier2D`'s own
  hoisted jaxpr (``model._step_cc``) under ``jax.vmap``; the ensemble forks
  no physics, it only adds the batch axis.  Members therefore share the
  model's operator constants (grid, Ra, Pr, dt — the implicit solvers bake
  ``dt*nu`` into their factorizations), so a parameter *scan* maps to one
  ensemble per parameter value with K seed-decorrelated members inside
  (``examples/navier_rbc_ensemble.py``).
* **buffer donation** — the chunked step donates states + mask + counters
  (``donate_argnums``): XLA aliases the input coefficient buffers to the
  outputs, so the resident HBM footprint is ONE stacked state, not a double
  buffer per dispatch.  :meth:`update_n` dispatches a fresh copy first so
  references retained to ``.state`` / ``.mask`` stay valid.
* **per-member fault isolation** — the single-run in-chunk NaN early-exit
  (a scalar is-finite carry flag, models/navier.py) generalizes to a
  per-member finite **mask**: a diverging member freezes at its last finite
  state (``jnp.where`` select — inside a vmapped batch a ``lax.cond`` lowers
  to a select anyway, so the frozen member costs its lanes but cannot
  corrupt or kill the batch), ``steps_done`` records how far each member
  got, and the whole-batch scalar early-exit still fires once EVERY member
  is dead.  Graceful degradation, reported per member.
* **batched observables / IO** — the fused ``(Nu, Nuvol, Re, |div|)``
  diagnostics vmap to shape ``(K,)``; snapshots write per-member groups
  (utils/checkpoint.write_ensemble_snapshot); ``benchmark_steps`` reports
  aggregate member-steps/s and ensemble MFU.

Composes with the pencil-sharding mesh: the member axis is a leading batch
dim, which the transform layer replicates across shards (bases.Space2), so
members are batched *within* each pencil shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.integrate import Integrate
from .navier import Navier2D, NavierState


class NavierEnsemble(Integrate):
    """K member states of one :class:`Navier2D`, stepped as one dispatch.

    ``states`` is either a sequence of K :class:`NavierState` pytrees or an
    already-stacked state (every leaf carrying a leading K axis).  Members
    share ``model``'s spaces, solvers and parameters; only the state differs.
    """

    # overlapped-IO hooks — see Navier2D: class-level defaults keep plain
    # ensembles fully synchronous
    io_pipeline = None
    io_overlap = False
    # journal hook — see CampaignModelBase.journal_writer
    journal_writer = None

    def __init__(self, model, states):
        if hasattr(states, "_fields"):  # a state pytree, maybe pre-stacked
            if np.ndim(states.temp) != np.ndim(model.state.temp) + 1:
                raise TypeError(
                    "NavierEnsemble expects a sequence of member states or a "
                    "state pytree whose leaves carry a leading K axis; got "
                    "an unbatched state — wrap it in a list for K=1"
                )
            stacked = states
        else:
            members = list(states)
            if not members:
                raise ValueError("ensemble needs at least one member state")
            with model._scope():
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *members)
        self.model = model
        self.k = int(stacked.temp.shape[0])
        self.dt = model.dt
        self.time = 0.0
        self.write_intervall = model.write_intervall
        # per-member diagnostics history: each append is a length-K list
        self.diagnostics: dict[str, list] = {}
        self._obs_cache: tuple | None = None
        # stability sentinels (mirrors Navier2D; armed when the template
        # model's set_stability was called) + per-rung artifact cache
        self.last_chunk_status = None
        self._pre_div_latch = False
        self._dt_cache: dict[float, dict] = {}
        self.recompile_count = 0
        # AOT executables (aot_compile, mirrors the template model): static-n
        # batched-chunk executables built ahead of traffic; dispatch prefers
        # them, aot_reuse_count tallies dispatches they served
        self._aot_step_n: dict[int, object] = {}
        self.aot_reuse_count = 0
        # config-carried PRNG stream for respawn_dead donor perturbations
        # (reproducible recovery runs); None falls back to per-call seeds
        self.respawn_seed: int | None = None
        self._respawn_rng = None
        # in-scan stats (models/stats.py): per-member running sums with a
        # leading K axis, armed when the template model's engine is
        self.stats_state = None
        self._stats_tick = None
        self._compile_entry_points()
        with model._scope():
            self.state = stacked
            self.mask = self._finite_mask(stacked)
            self.steps_done = jnp.zeros((self.k,), jnp.int32)
            self._init_stats_state()

    # -- construction --------------------------------------------------------

    @classmethod
    def from_seeds(cls, model: Navier2D, seeds, amp: float = 0.1) -> "NavierEnsemble":
        """K members from the model's random-IC generator, one seed each —
        the DNS-statistics / parameter-scan workload (decorrelated initial
        conditions under shared operators).  The model's own state is
        restored afterwards."""
        keep = model.state
        members = []
        try:
            for seed in seeds:
                model.init_random(amp, seed=int(seed))
                members.append(model.state)
        finally:
            model.state = keep
        return cls(model, members)

    @classmethod
    def replicate(cls, model: Navier2D, k: int) -> "NavierEnsemble":
        """K copies of the model's current state (perturbation campaigns
        differentiate members afterwards via :meth:`set_member`)."""
        return cls(model, [model.state] * int(k))

    @classmethod
    def from_config(cls, cfg, mesh=None) -> "NavierEnsemble":
        """Build the template model from a
        :class:`~rustpde_mpi_tpu.config.NavierConfig` and seed
        ``cfg.ensemble`` members (seeds 0..K-1).  An unset/zero
        ``init_random_amp`` means what it means on the single-run path — no
        random IC — so the members replicate the model's current state
        (differentiate them afterwards via :meth:`set_member`)."""
        model = Navier2D.from_config(cfg, mesh=mesh)
        k = max(1, cfg.ensemble)
        if not cfg.init_random_amp:
            ens = cls.replicate(model, k)
        else:
            ens = cls.from_seeds(model, range(k), amp=cfg.init_random_amp)
        if cfg.resilience is not None:
            ens.respawn_seed = cfg.resilience.respawn_seed
        return ens

    # -- member access -------------------------------------------------------

    @property
    def ensemble_size(self) -> int:
        """Member count (read by utils/profiling.benchmark_steps)."""
        return self.k

    @property
    def nx(self) -> int:
        return self.model.nx

    @property
    def ny(self) -> int:
        return self.model.ny

    @property
    def compat_key(self) -> tuple:
        """The template model's operator-constant key
        (:attr:`Navier2D.compat_key`): members NECESSARILY share it — the
        batch is one vmapped jaxpr over shared constants — so a slot can be
        refilled mid-campaign (``set_member``) by any request with an equal
        key, without recompiling."""
        return self.model.compat_key

    def member_state(self, i: int) -> NavierState:
        """Member ``i``'s state as an unbatched :class:`NavierState`."""
        return jax.tree.map(lambda x: x[i], self.state)

    def fresh_member_state(self, seed: int, amp: float = 0.1) -> NavierState:
        """A new random-IC member state from the template model's generator
        (the slot-refill donor for a freshly admitted request): the model's
        own state is restored afterwards, and the returned state is ready
        for :meth:`set_member` — same shapes/dtypes by construction."""
        keep = self.model.state
        try:
            self.model.init_random(float(amp), seed=int(seed))
            return self.model.state
        finally:
            self.model.state = keep

    def set_member(self, i: int, state: NavierState) -> None:
        """Replace member ``i``'s state (and re-derive its mask/counter).
        With the stats engine armed the member's running sums reset too —
        a refilled lane is a NEW trajectory (the serve scheduler's
        per-request averaging window starts at claim time)."""
        with self.model._scope():
            self.state = jax.tree.map(
                lambda st, leaf: st.at[i].set(leaf), self.state, state
            )
            self.mask = self.mask.at[i].set(self.model._scan_ok(state))
            self.steps_done = self.steps_done.at[i].set(0)
            if self.stats_state is not None:
                zero = self.model.stats_engine.init_state()
                self.stats_state = jax.tree.map(
                    lambda full, z: full.at[i].set(z), self.stats_state, zero
                )
        self._obs_cache = None

    def get_field(self, name: str, member: int) -> np.ndarray:
        """Physical values of one member's variable."""
        space = getattr(self.model, f"{name}_space")
        with self.model._scope():
            return np.asarray(space.backward(getattr(self.member_state(member), name)))

    # -- the batched step ----------------------------------------------------

    def _finite_mask(self, stacked):
        """Per-member continue criterion — the template model's ``_scan_ok``
        vmapped over the member axis.  For the DNS that is the one-reduction
        is-finite detector (a NaN anywhere infects temp within one step via
        buoyancy/convection); the steady-state adjoint additionally drops a
        member on residual CONVERGENCE, so a frozen member there may be a
        finished one, not a corpse (``done_ok_members`` tells them apart)."""
        return jax.vmap(self.model._scan_ok)(stacked)

    def done_ok_members(self) -> np.ndarray:
        """Per-member successfully-finished mask (host bools): members that
        stopped advancing via the model's *success* criterion (e.g. the
        adjoint finder's residual convergence) rather than by divergence."""
        with self.model._scope():
            done = jax.vmap(self.model._scan_done_ok)(self.state)
        return np.asarray(done)

    def state_healthy(self) -> bool:
        """Checkpoint guard (utils/resilience._state_ok): an ensemble is
        worth checkpointing while any member is still advancing OR any
        member finished successfully — but an all-dead batch must never
        overwrite the rollback target."""
        if self._pre_div_latch:
            return False
        if bool(np.any(self.alive())):
            return True
        return bool(self.done_ok_members().any())

    @property
    def observable_names(self) -> tuple:
        """The template model's observable vocabulary (shape (K,) each)."""
        return self.model.observable_names

    def _compile_entry_points(self) -> None:
        # same attribution seam as the base model's entry-point compile
        # (models/campaign.py): the K-member vmap trace is the serving
        # path's dominant build cost and is re-entered by set_dt/
        # set_stability without a model rebuild — it must not vanish from
        # the per-kind compile metrics
        import time as _time

        from ..telemetry import compile_log as _compile_log

        t0 = _time.perf_counter()
        try:
            self._compile_entry_points_impl()
        finally:
            _compile_log.observe_entry_compile(
                f"ensemble:{getattr(self.model, 'MODEL_KIND', 'model')}",
                _time.perf_counter() - t0,
            )

    def _compile_entry_points_impl(self) -> None:
        model = self.model
        step_cc = model._step_cc
        obs_cc = model._obs_cc
        self.recompile_count += 1
        self._step_n_jit = None
        self._aot_step_n = {}
        self._step_n_sent = None
        self._step_n_stats = None
        self._stats_health_fn = None
        self._dig_fn = None

        if model._dig_cc is not None:
            # the per-member digest is a pure elementwise+reduction read of
            # the stacked states — safe on every layout, including the
            # eager fallback below (the template model compiles its own
            # digest before ITS fallback return for the same reason)
            self._compile_integrity_entry_points()

        if model._gspmd_split_sep_fallback():
            # same poisoned layout the single-run guard reroutes (fused
            # split-sep periodic step miscompiled by GSPMD under a mesh): a
            # jitted vmap of step_cc would compile the SAME fused program,
            # and an eager vmap trips with_sharding_constraint on batch
            # tracers — so members step per-member through the eager path
            # proven correct for the single run.  Slow but right; the
            # per-member freeze semantics (keep the last FINITE state, stop
            # counting) are preserved.
            step_fn = model._make_step()
            obs_fn = model._make_observables()

            def ens_step_n_eager(states, mask, done, n):
                alive = np.asarray(mask).copy()
                counts = np.asarray(done).copy()
                members = [
                    jax.tree.map(lambda x, i=i: x[i], states) for i in range(self.k)
                ]
                for i in range(self.k):
                    if not alive[i]:
                        continue
                    st = members[i]
                    for _ in range(int(n)):
                        cand = step_fn(st)
                        if bool(self.model._scan_commit_ok(cand)):
                            st = cand
                            counts[i] += 1
                        if not bool(self.model._scan_ok(cand)):
                            alive[i] = False
                            break
                    members[i] = st
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *members)
                return (
                    stacked,
                    jnp.asarray(alive),
                    jnp.asarray(counts, dtype=jnp.int32),
                )

            def obs_eager(states):
                outs = [
                    obs_fn(jax.tree.map(lambda x, i=i: x[i], states))
                    for i in range(self.k)
                ]
                return tuple(jnp.stack(vals) for vals in zip(*outs))

            self._step_n = ens_step_n_eager
            self._obs_fn = obs_eager
            return

        def ens_step_n(consts, states, mask, done, n: int):
            """n vmapped steps with per-member fault isolation: the carry
            holds (states, alive-mask, per-member step counters).  An alive
            member whose stepped temp goes non-finite is frozen at its last
            finite state via a per-member select; once NO member is alive the
            remaining iterations take the identity branch of the scalar
            ``lax.cond`` (the single-run early-exit, batch-wide)."""

            vstep = jax.vmap(lambda s: step_cc(consts, s))
            vcommit = jax.vmap(self.model._scan_commit_ok)

            def advance(carry):
                st, ok, dn = carry
                st2 = vstep(st)
                # commit any candidate the model deems valid (finite; for
                # the DNS identical to the continue mask), CONTINUE only
                # while _scan_ok holds — the adjoint finder's converged
                # state commits on its final step before the freeze
                commit = ok & vcommit(st2)
                ok2 = ok & self._finite_mask(st2)

                def freeze(new, old):
                    sel = jnp.reshape(commit, commit.shape + (1,) * (new.ndim - 1))
                    return jnp.where(sel, new, old)

                return (
                    jax.tree.map(freeze, st2, st),
                    ok2,
                    dn + commit.astype(jnp.int32),
                )

            def body(carry, _):
                carry2 = jax.lax.cond(jnp.any(carry[1]), advance, lambda c: c, carry)
                return carry2, None

            (st, mk, dn), _ = jax.lax.scan(body, (states, mask, done), None, length=n)
            return st, mk, dn

        # donation: states + mask + counters alias input->output buffers, so
        # the resident footprint is one stacked state (see module docstring);
        # the consts (operator matrices) are shared and NEVER donated
        ens_jit = jax.jit(
            ens_step_n, static_argnames=("n",), donate_argnums=(1, 2, 3)
        )
        # retained for aot_compile: the warm pool lowers+compiles the
        # batched chunk for the scheduler's static dispatch sizes ahead of
        # traffic; dispatch prefers a prebuilt executable when one exists
        self._step_n_jit = ens_jit

        def dispatch_step_n(st, mk, dn, n):
            exe = self._aot_step_n.get(int(n))
            if exe is not None:
                self.aot_reuse_count += 1
                return exe(model._step_consts, st, mk, dn)
            return ens_jit(model._step_consts, st, mk, dn, n=n)

        self._step_n = dispatch_step_n

        # fused (Nu, Nuvol, Re, |div|) vmapped to shape (K,)
        obs_jit = jax.jit(jax.vmap(obs_cc, in_axes=(None, 0)))
        self._obs_fn = lambda st: obs_jit(model._obs_consts, st)

        if model._stats_cc is not None:
            self._compile_stats_entry_points()

        if model._sent_cc is not None:
            self._compile_sentinel_entry_points()

    def _compile_stats_entry_points(self) -> None:
        """Vmapped stats-carrying chunk (template model's ``set_stats``):
        the per-member running sums ride the carry with a leading K axis, a
        SHARED scalar sample tick drives the stride cond (one real branch,
        not a per-member select), and accumulation commits per member only
        where the step itself commits — a frozen member's averages freeze
        with it.  Pure consumers of the stepped states: the member
        trajectories stay bit-identical to the stats-off chunk."""
        model = self.model
        step_cc = model._step_cc
        stats_cc = model._stats_cc
        stride = int(model.stats_engine.stride)

        def ens_step_n_stats(consts, sconsts, states, ss, tick, mask, done, n: int):
            vstep = jax.vmap(lambda s: step_cc(consts, s))
            vcommit = jax.vmap(model._scan_commit_ok)
            vaccum = jax.vmap(lambda s, st: stats_cc(sconsts, s, st))

            def advance(carry):
                st, ss, tk, ok, dn = carry
                st2 = vstep(st)
                commit = ok & vcommit(st2)
                ok2 = ok & self._finite_mask(st2)
                tk2 = tk + 1

                def do_accum(ss):
                    ss_new = vaccum(ss, st2)

                    def sel(new, old):
                        m = jnp.reshape(
                            commit, commit.shape + (1,) * (new.ndim - 1)
                        )
                        return jnp.where(m, new, old)

                    return jax.tree.map(sel, ss_new, ss)

                ss2 = jax.lax.cond(
                    (tk2[0] % stride) == 0, do_accum, lambda s: s, ss
                )

                def freeze(new, old):
                    m = jnp.reshape(commit, commit.shape + (1,) * (new.ndim - 1))
                    return jnp.where(m, new, old)

                return (
                    jax.tree.map(freeze, st2, st),
                    ss2,
                    tk2,
                    ok2,
                    dn + commit.astype(jnp.int32),
                )

            def body(carry, _):
                carry2 = jax.lax.cond(
                    jnp.any(carry[3]), advance, lambda c: c, carry
                )
                return carry2, None

            (st, ss, tk, mk, dn), _ = jax.lax.scan(
                body, (states, ss, tick, mask, done), None, length=n
            )
            return st, ss, tk, mk, dn

        stats_jit = jax.jit(
            ens_step_n_stats,
            static_argnames=("n",),
            donate_argnums=(2, 3, 4, 5, 6),
        )
        self._step_n_stats = lambda st, ss, tk, mk, dn, n: stats_jit(
            model._step_consts, model._stats_consts, st, ss, tk, mk, dn, n=n
        )

        h_jit = jax.jit(jax.vmap(model._stats_health_cc, in_axes=(None, 0)))
        self._stats_health_fn = lambda ss: h_jit(model._stats_health_consts, ss)

    def _compile_sentinel_entry_points(self) -> None:
        """Vmapped sentinel chunk (stability governor, utils/governor.py):
        the per-member carry holds finite AND CFL-ok masks plus running
        per-member sentinel reductions.  A member whose per-step CFL exceeds
        the ceiling freezes at its last under-ceiling state (it does NOT
        take the tripping step) while staying finite — distinct from death —
        and the batch-wide scalar early-exit fires once no member is both
        finite and under the ceiling.  Per-member CFL reduces to the batch
        max host-side (members share the baked dt)."""
        model = self.model
        sent_cc = model._sent_cc
        ceiling = float(model._stability.max_cfl)
        # stats engine armed: running sums + shared tick appended to the
        # carry (after the sentinel slots — fetch indices stay put); a
        # member samples only where its step commits under the ceiling
        stats_cc = model._stats_cc
        stats_stride = (
            int(model.stats_engine.stride) if stats_cc is not None else 0
        )

        def ens_step_n_sent(consts, sconsts, carry, n: int):
            vstep = jax.vmap(lambda s: sent_cc(consts, s))
            vcommit = jax.vmap(model._scan_commit_ok)
            vaccum = (
                jax.vmap(lambda s, st: stats_cc(sconsts, s, st))
                if stats_cc is not None
                else None
            )

            def advance(carry):
                st, fin, cok, dn, cflm, gm, dvm, kep = carry[:8]
                st2, (cfl, ke, dv) = vstep(st)
                active = fin & cok
                fin2 = jnp.where(active, self._finite_mask(st2), fin)
                cok2 = jnp.where(active, jnp.logical_not(cfl > ceiling), cok)
                # commit-vs-continue split, as in the plain chunk: a
                # convergence-stopped member's final state still commits
                keep = active & vcommit(st2) & cok2

                def freeze(new, old):
                    sel = jnp.reshape(keep, keep.shape + (1,) * (new.ndim - 1))
                    return jnp.where(sel, new, old)

                def upd(old, new):
                    return jnp.where(active, jnp.maximum(old, new), old)

                growth = jnp.where(kep > 0.0, ke / kep, 1.0)
                out = (
                    jax.tree.map(freeze, st2, st),
                    fin2,
                    cok2,
                    dn + keep.astype(jnp.int32),
                    upd(cflm, cfl),
                    upd(gm, growth),
                    upd(dvm, dv),
                    jnp.where(active, ke, kep),
                )
                if vaccum is not None:
                    ss, tk = carry[8], carry[9]
                    tk2 = tk + 1

                    def do_accum(ss):
                        ss_new = vaccum(ss, st2)

                        def sel(new, old):
                            m = jnp.reshape(
                                keep, keep.shape + (1,) * (new.ndim - 1)
                            )
                            return jnp.where(m, new, old)

                        return jax.tree.map(sel, ss_new, ss)

                    ss2 = jax.lax.cond(
                        (tk2[0] % stats_stride) == 0, do_accum, lambda s: s, ss
                    )
                    out = out + (ss2, tk2)
                return out

            def body(carry, _):
                carry2 = jax.lax.cond(
                    jnp.any(carry[1] & carry[2]), advance, lambda c: c, carry
                )
                return carry2, None

            final, _ = jax.lax.scan(body, carry, None, length=n)
            return final

        sent_jit = jax.jit(
            ens_step_n_sent, static_argnames=("n",), donate_argnums=(2,)
        )
        self._step_n_sent = lambda c, n: sent_jit(
            model._sent_consts, model._stats_consts, c, n=n
        )

    def _compile_integrity_entry_points(self) -> None:
        """Vmapped on-device state digest (integrity/digest.py): the
        template model's retained digest jaxpr re-vmapped over the member
        axis — ONE fused dispatch returns a ``(K,)`` uint32 vector, one
        digest per member, localizing a corrupted member exactly like the
        observables localize NaNs.  The digest's positional mix uses
        LOGICAL indices, so member ``i``'s entry equals the digest the
        same state would produce solo (the layout-invariance the tests
        assert)."""
        model = self.model
        dig_jit = jax.jit(jax.vmap(model._dig_cc, in_axes=(None, 0)))
        self._dig_fn = lambda st: dig_jit(model._dig_consts, st)

    def _make_step(self):
        """vmapped single-member step — profiling.step_flops introspects this
        (the batched dot_generals in its jaxpr carry the K factor, so the
        reported ensemble MFU is per dispatch, all members included)."""
        return jax.vmap(self.model._make_step())

    def aot_compile(self, chunk_steps: int) -> int:
        """AOT-build the batched-chunk executables a ``chunk_steps``-sized
        dispatch needs (every static scan bucket of ``run_scanned``'s
        decomposition) via ``.lower().compile()`` — the warm pool's
        cold-start killer: populates the persistent compile cache and
        retains the executables so the first live dispatch reuses them
        instead of entering jit.  Returns how many executables were newly
        built (0 on the eager-fallback path)."""
        from ..utils.jit import scan_buckets

        step_n_jit = getattr(self, "_step_n_jit", None)
        if step_n_jit is None:
            return 0
        built = 0
        with self.model._scope():
            for n in scan_buckets(chunk_steps):
                if n in self._aot_step_n:
                    continue
                self._aot_step_n[n] = step_n_jit.lower(
                    self.model._step_consts,
                    self.state,
                    self.mask,
                    self.steps_done,
                    n=n,
                ).compile()
                built += 1
        return built

    # -- Integrate protocol --------------------------------------------------

    def update(self) -> None:
        self.update_n(1)

    def update_n(self, n: int):
        """Advance every alive member n steps in scanned power-of-two chunks.

        The chunked dispatch donates its carry, so it must never receive the
        user-visible buffers — one copy of (state, mask, counters) per call
        keeps retained references valid while every inter-bucket hand-off
        inside the chain is donated.  ``self.time`` counts scheduled steps;
        ``self.steps_done`` records how far each member actually advanced.

        With stability sentinels armed (template model's ``set_stability``)
        the chunk returns a :class:`~rustpde_mpi_tpu.utils.governor.ChunkStatus`
        carrying per-member chunk-max CFL (``cfl_members``) and ceiling-trip
        masks (``pinned``); ANY alive member tripping the hard CFL ceiling
        rolls the whole chunk back in memory (members share the baked dt, so
        the dt response is batch-wide) and latches ``exit()`` until a
        governor acknowledges."""
        from ..utils.jit import run_scanned

        if self._step_n_sent is not None:
            return self._update_n_sentinel(n)
        with self.model._scope():
            if self._step_n_stats is not None:
                carry = jax.tree.map(
                    jnp.copy,
                    (
                        self.state,
                        self.stats_state,
                        self._stats_tick,
                        self.mask,
                        self.steps_done,
                    ),
                )
                carry = run_scanned(
                    lambda c, k: self._step_n_stats(
                        c[0], c[1], c[2], c[3], c[4], k
                    ),
                    carry,
                    n,
                )
                (
                    self.state,
                    self.stats_state,
                    self._stats_tick,
                    self.mask,
                    self.steps_done,
                ) = carry
            else:
                carry = jax.tree.map(
                    jnp.copy, (self.state, self.mask, self.steps_done)
                )
                carry = run_scanned(
                    lambda c, k: self._step_n(c[0], c[1], c[2], k), carry, n
                )
                self.state, self.mask, self.steps_done = carry
        self.time += n * self.dt
        self._obs_cache = None
        return None

    def _update_n_sentinel(self, n: int):
        """Sentinel-armed batched chunk (see :meth:`update_n`)."""
        return self.update_n_pending(n).resolve()

    def update_n_pending(self, n: int):
        """Batched sentinel chunk with a DEFERRED commit decision — the
        ensemble form of :meth:`Navier2D.update_n_pending` (the lag=1
        contract of the overlapped driver): state/mask/counters advance
        PROVISIONALLY at dispatch, and ``resolve()`` fetches the per-member
        sentinels in one transfer, rolling the whole chunk back (and
        latching ``exit()``) when any member pinned the CFL ceiling.  The
        previous chunk-start ``steps_done`` rides the same deferred fetch —
        the synchronous form used to pay a blocking pre-dispatch read for
        it."""
        from .. import config
        from ..utils.governor import ChunkStatus
        from ..utils.io_pipeline import PendingChunkStatus
        from ..utils.jit import run_scanned

        if self._step_n_sent is None:
            raise RuntimeError(
                "update_n_pending requires armed stability sentinels "
                "(set_stability)"
            )
        self._pre_div_latch = False
        rdt = config.real_dtype()
        stats_on = self.model._stats_cc is not None
        done_before = self.steps_done  # fetched with the sentinel scalars
        with self.model._scope():
            # distinct buffers per slot: the dispatch donates the whole
            # carry, and donation rejects the same buffer appearing twice
            carry = (
                jax.tree.map(jnp.copy, self.state),
                jnp.copy(self.mask),
                jnp.ones((self.k,), bool),
                jnp.copy(self.steps_done),
                jnp.zeros((self.k,), rdt),  # per-member cfl max
                jnp.zeros((self.k,), rdt),  # per-member ke growth max
                jnp.zeros((self.k,), rdt),  # per-member |div| max
                jnp.zeros((self.k,), rdt),  # per-member previous-step ke
            )
            if stats_on:
                carry = carry + (
                    jax.tree.map(jnp.copy, self.stats_state),
                    jnp.copy(self._stats_tick),
                )
            carry = run_scanned(lambda c, k: self._step_n_sent(c, k), carry, n)
        st, fin, cok, dn, cflm, gm, dvm, kep = carry[:8]
        snapshot = (
            self.state,
            self.mask,
            self.steps_done,
            self.time,
            self.stats_state,
            self._stats_tick,
        )
        self.state, self.mask, self.steps_done = st, fin, dn  # provisional
        if stats_on:
            self.stats_state, self._stats_tick = carry[8], carry[9]
        self.time += n * self.dt
        self._obs_cache = None
        dt = self.dt

        def finish(fetched):
            fin_h, cok_h, dn_h, cflm_h, gm_h, dvm_h, kep_h, before_h = (
                np.asarray(a) for a in fetched
            )
            pinned = fin_h & ~cok_h
            pre_div = bool(pinned.any())
            if pre_div:
                # in-memory rollback of the whole chunk: state/mask/counters
                # (and the stats sums) are the un-donated chunk-start
                # snapshots — put them back
                (
                    self.state,
                    self.mask,
                    self.steps_done,
                    self.time,
                    self.stats_state,
                    self._stats_tick,
                ) = snapshot
                self._pre_div_latch = True
                self._obs_cache = None
            delta = dn_h - before_h
            status = ChunkStatus(
                requested=int(n),
                steps_done=int(delta.max(initial=0)),
                finite=bool(fin_h.any()),
                cfl_ok=not pre_div,
                pre_divergence=pre_div,
                cfl_max=float(cflm_h.max(initial=0.0)),  # batch-max reduction
                ke=float(kep_h.max(initial=0.0)),
                ke_growth_max=float(gm_h.max(initial=0.0)),
                div_max=float(dvm_h.max(initial=0.0)),
                dt=dt,
                cfl_members=tuple(float(c) for c in cflm_h),
                pinned=tuple(bool(p) for p in pinned),
            )
            self.last_chunk_status = status
            return status

        return PendingChunkStatus(
            (fin, cok, dn, cflm, gm, dvm, kep, done_before), finish
        )

    @property
    def _stability(self):
        """The sentinel config lives on the shared template model."""
        return self.model._stability

    def set_stability(self, cfg) -> None:
        """Arm/disarm the stability sentinels on the shared template model
        and re-vmap the ensemble entry points on top."""
        self.model.set_stability(cfg)
        self._dt_cache.clear()
        self._compile_entry_points()
        self.last_chunk_status = None
        self._pre_div_latch = False

    def clear_pre_divergence(self) -> None:
        """Acknowledge a ``pre_divergence`` catch (governor handled it)."""
        self._pre_div_latch = False

    # -- in-scan physics statistics (models/stats.py) --------------------------

    def _init_stats_state(self) -> None:
        """Zeroed per-member running sums when the template model's engine
        is armed (callers hold the model scope)."""
        if self._step_n_stats is None:
            self.stats_state = None
            self._stats_tick = None
            return
        self.stats_state = self.model.stats_engine.init_state(k=self.k)
        self._stats_tick = jnp.zeros((1,), jnp.int32)

    def set_stats(self, cfg) -> None:
        """Arm/disarm the in-scan stats engine on the shared template model
        and re-vmap the ensemble entry points on top; per-member running
        sums zero-initialize (a fresh averaging window for every member)."""
        self.model.set_stats(cfg)
        self._dt_cache.clear()
        self._compile_entry_points()
        with self.model._scope():
            self._init_stats_state()

    def reset_stats(self) -> None:
        """Zero every member's running sums + the shared sample tick."""
        with self.model._scope():
            self._init_stats_state()

    @property
    def stats_engine(self):
        """The template model's engine (None when disarmed)."""
        return self.model.stats_engine

    @property
    def stats_armed(self) -> bool:
        return self._step_n_stats is not None and self.stats_state is not None

    def stats_health_async(self):
        """Vmapped :data:`~rustpde_mpi_tpu.models.stats.HEALTH_NAMES`
        readout — an observable future of (K,) arrays, one health vector
        per member (the serve scheduler summarizes a finished member's
        entry into its done record)."""
        from ..utils.io_pipeline import ObservableFuture

        if not self.stats_armed:
            raise RuntimeError("stats_health_async needs an armed stats engine")
        with self.model._scope():
            return ObservableFuture(
                self._stats_health_fn(self.stats_state),
                convert=lambda vals: tuple(np.asarray(v) for v in vals),
            )

    def stats_summary(self) -> dict | None:
        """Synchronous per-member health readout (None when disarmed):
        each name maps to a length-K list."""
        if not self.stats_armed:
            return None
        from .stats import HEALTH_NAMES

        vals = self.stats_health_async().result()
        return {
            name: [float(x) for x in np.asarray(v).reshape(-1)]
            for name, v in zip(HEALTH_NAMES, vals)
        }

    def stats_host_items(self) -> list:
        """Gathered-snapshot rows for the stacked stats leaves
        (:meth:`StatsEngine.host_items`); empty when disarmed."""
        if not self.stats_armed:
            return []
        return self.model.stats_engine.host_items(
            self.stats_state, self._stats_tick
        )

    def apply_restored_stats(self, data: dict | None) -> None:
        """Install stacked stats leaves from a gathered snapshot (leading
        axis = the file's member count, which the caller already installed
        as ``self.k``) via :meth:`StatsEngine.restore_state`;
        ``None``/missing leaves reset to zero."""
        if not self.stats_armed:
            return
        with self.model._scope():
            self.stats_state, self._stats_tick = (
                self.model.stats_engine.restore_state(data, k=self.k)
            )

    # -- end-to-end integrity (integrity/) ------------------------------------

    def set_integrity(self, cfg) -> None:
        """Arm/disarm the integrity layer on the shared template model and
        re-vmap the ensemble entry points on top (the per-member digest
        rides the same retained jaxpr, ``_compile_integrity_entry_points``)."""
        self.model.set_integrity(cfg)
        self._dt_cache.clear()
        self._compile_entry_points()

    @property
    def integrity_config(self):
        """The template model's integrity config (None when disarmed)."""
        return self.model.integrity_config

    @property
    def integrity_armed(self) -> bool:
        return (
            self.model.integrity_config is not None
            and getattr(self, "_dig_fn", None) is not None
        )

    def _digest_future(self, device_val):
        from ..utils.io_pipeline import ObservableFuture

        return ObservableFuture(
            device_val,
            convert=lambda v: np.asarray(v)  # lint-ok: RPD005 a (K,) uint32 vector
        )

    def state_digest_async(self):
        """Dispatch the vmapped digest of the CURRENT member states and
        return an observable future of a ``(K,)`` uint32 vector — one
        digest per member (a mismatch names the corrupted member)."""
        if not self.integrity_armed:
            raise RuntimeError(
                "state_digest_async needs an armed integrity layer "
                "(set_integrity)"
            )
        with self.model._scope():
            return self._digest_future(self._dig_fn(self.state))

    def digest_of_async(self, state):
        """Digest an arbitrary stacked state pytree (the runner's retained
        chunk-start copies) without touching ``self.state``."""
        with self.model._scope():
            return self._digest_future(self._dig_fn(state))

    def shadow_digest_async(self, snap: dict, n: int):
        """Shadow re-execution audit kernel (ensemble form): re-step ``n``
        steps from the retained :meth:`integrity_snapshot` through the
        PLAIN batched chunk — threading the snapshot's alive mask and step
        counters, so per-member freeze decisions replay exactly — and
        digest the resulting member states.  Bit-equal to the live chunk's
        digests by XLA determinism, unless the state was corrupted."""
        from ..utils.jit import run_scanned

        if not self.integrity_armed:
            raise RuntimeError(
                "shadow_digest_async needs an armed integrity layer "
                "(set_integrity)"
            )
        with self.model._scope():
            carry = jax.tree.map(
                jnp.copy, (snap["state"], snap["mask"], snap["steps_done"])
            )
            carry = run_scanned(
                lambda c, k: self._step_n(c[0], c[1], c[2], k), carry, n
            )
            return self._digest_future(self._dig_fn(carry[0]))

    def integrity_snapshot(self) -> dict:
        """Un-donated device-side copy of everything an in-memory
        integrity rollback must restore: member states + alive mask +
        per-member counters + time (+ armed stats sums)."""
        with self.model._scope():
            snap = {
                "state": jax.tree.map(jnp.copy, self.state),
                "mask": jnp.copy(self.mask),
                "steps_done": jnp.copy(self.steps_done),
                "time": self.time,
            }
            if self.stats_armed:
                snap["stats"] = (
                    jax.tree.map(jnp.copy, self.stats_state),
                    jnp.copy(self._stats_tick),
                )
        return snap

    def integrity_restore(self, snap: dict) -> None:
        """Roll back to a digest-verified :meth:`integrity_snapshot` (the
        snapshot stays reusable — the install copies)."""
        with self.model._scope():
            self.state = jax.tree.map(jnp.copy, snap["state"])
            self.mask = jnp.copy(snap["mask"])
            self.steps_done = jnp.copy(snap["steps_done"])
            self.time = snap["time"]
            if "stats" in snap and self.stats_armed:
                ss, tick = snap["stats"]
                self.stats_state = jax.tree.map(jnp.copy, ss)
                self._stats_tick = jnp.copy(tick)
        self._obs_cache = None
        self._pre_div_latch = False

    def _verify_restored_digest(self, expected) -> None:
        """Recompute the per-member digests after a bit-exact sharded
        restore and compare with the manifest's ``(K,)`` vector (see
        ``CampaignModelBase._verify_restored_digest``)."""
        if expected is None or not self.integrity_armed:
            return
        got = np.asarray(self.state_digest_async().result())
        exp = np.asarray(expected).astype(got.dtype).reshape(got.shape)
        if not np.array_equal(got, exp):
            from ..integrity import IntegrityError

            bad = [int(i) for i in np.flatnonzero(got != exp)]
            raise IntegrityError(
                f"restored member digests differ from the checkpoint "
                f"manifest for members {bad} — the snapshot was corrupted "
                "between device and disk",
                check="checkpoint",
                member=bad[0] if bad else None,
            )

    @property
    def pre_divergence_latched(self) -> bool:
        """True while an unacknowledged sentinel catch latches ``exit()`` —
        the public form the serve scheduler's per-bucket dt governor reads
        (``last_chunk_status.pinned`` names the tripping members)."""
        return bool(self._pre_div_latch)

    def mark_dead(self, members) -> None:
        """Declare members dead (persistently CFL-pinned, governor decision):
        they freeze like diverged members and become ``respawn_dead``
        candidates."""
        with self.model._scope():
            mask = self.mask
            for i in members:
                mask = mask.at[int(i)].set(False)
            self.mask = mask
        self._obs_cache = None

    def get_time(self) -> float:
        return self.time

    def get_dt(self) -> float:
        return self.dt

    # swapped per dt change, cached per rung like Navier2D._DT_ARTIFACTS
    _DT_ARTIFACTS = (
        "_step_n",
        "_obs_fn",
        "_step_n_sent",
        "_step_n_stats",
        "_stats_health_fn",
        "_dig_fn",
    )

    def set_dt(self, dt: float) -> None:
        """Propagate a dt change (the governor's ladder / divergence-retry
        backoff) through the shared template model — which rebuilds its
        dt-baked solvers and re-traces ``_step_cc``, both cached per dt rung
        — then re-vmap the ensemble entry points on top of the new jaxpr
        (also rung-cached: a revisited rung restores the retained closures,
        so the jit executable cache hits).  Member states are untouched."""
        dt = float(dt)
        if dt == self.dt:
            return
        self._dt_cache[self.dt] = {
            k: getattr(self, k, None) for k in self._DT_ARTIFACTS
        }
        self.model.set_dt(dt)
        self.dt = self.model.dt
        cached = self._dt_cache.get(dt)
        if cached is not None:
            for key, value in cached.items():
                setattr(self, key, value)
        else:
            self._compile_entry_points()
        self._obs_cache = None

    def reset_time(self) -> None:
        self.time = 0.0

    def respawn_dead(self, amp: float = 1e-3, seed=None) -> int:
        """Re-seed every dead member from a perturbed healthy donor instead
        of leaving it frozen forever (utils/resilience.py calls this at
        rollback when ``respawn_members`` is on).

        Each dead member receives a healthy member's state with a small
        multiplicative spectral perturbation (``coeff * (1 + amp*noise)``) —
        enough to decorrelate the respawned trajectory without restarting
        the transient from scratch.  Donors round-robin over the healthy
        members; surviving members' states are NOT touched (their buffers
        are updated per-index, ``set_member``).  Returns the number of
        members respawned (0 when all alive or none alive — with no healthy
        donor there is nothing to copy from).

        ``seed`` may be an int or a sequence of ints (a SeedSequence
        entropy key, e.g. ``(campaign_seed, step, attempt)``); when ``None``
        and a config-carried ``respawn_seed`` is set
        (``ResilienceConfig.respawn_seed``), draws come from that persistent
        stream — so two identical recovery runs respawn identically."""
        alive = self.alive()
        if alive.all() or not alive.any():
            return 0
        if seed is None and self.respawn_seed is not None:
            if self._respawn_rng is None:
                self._respawn_rng = np.random.default_rng(self.respawn_seed)
            rng = self._respawn_rng
        else:
            rng = np.random.default_rng(seed)
        donors = np.flatnonzero(alive)
        respawned = 0
        for i in np.flatnonzero(~alive):
            donor = int(donors[respawned % len(donors)])
            state = self.member_state(donor)
            with self.model._scope():
                perturbed = jax.tree.map(
                    lambda x: x
                    * (
                        1.0
                        + amp
                        * jnp.asarray(
                            rng.standard_normal(x.shape),
                            dtype=jnp.real(x).dtype,
                        )
                    ),
                    state,
                )
            self.set_member(int(i), perturbed)
            respawned += 1
        return respawned

    def alive(self) -> np.ndarray:
        """Per-member alive mask as a host bool array of shape (K,)."""
        return np.asarray(self.mask)

    def exit(self) -> bool:
        """Graceful degradation: the break criterion fires only when EVERY
        member has diverged — one NaN member freezes (update_n) and is
        reported per member, it does not kill the batch.  A latched
        ``pre_divergence`` catch (stability sentinels) also reads as a break
        until a governor clears it (see ``Navier2D.exit``)."""
        if self._pre_div_latch:
            return True
        return not bool(np.any(self.alive()))

    def exit_future(self):
        """Non-blocking :meth:`exit` for the overlapped driver: the
        all-members-dead reduction rides the device queue (the mask is
        maintained on device by the chunked step) and resolves when the
        driver fetches it — a latched sentinel catch resolves immediately."""
        import jax.numpy as jnp

        from ..utils.io_pipeline import ObservableFuture, immediate

        if self._pre_div_latch:
            return immediate(True)
        with self.model._scope():
            dead = jnp.logical_not(jnp.any(self.mask))
        return ObservableFuture(dead, convert=bool)

    # -- observables / IO ----------------------------------------------------

    def get_observables_async(self):
        """Dispatch the vmapped observables and return an
        :class:`~rustpde_mpi_tpu.utils.io_pipeline.ObservableFuture` (shape
        ``(K,)`` per entry) without waiting — cached per state and shared
        with the synchronous accessors, like the single-run form."""
        from ..utils.io_pipeline import ObservableFuture

        if self._obs_cache is None or self._obs_cache[0] is not self.state:
            with self.model._scope():
                fut = ObservableFuture(
                    self._obs_fn(self.state),
                    convert=lambda vals: tuple(np.asarray(v) for v in vals),
                )
            self._obs_cache = (self.state, fut)
        return self._obs_cache[1]

    def get_observables(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """(Nu, Nuvol, Re, |div|), each a float ndarray of shape (K,) — one
        fused vmapped dispatch, cached per state, fetched in ONE host
        transfer.  NOTE a member that diverged mid-run is frozen at its last
        FINITE state, so its entries are finite but STALE; only a member
        whose IC was already non-finite reports NaN.  Liveness is
        :meth:`alive` / ``mask``, not ``isfinite(nu)``."""
        return self.get_observables_async().result()

    def device_fence(self) -> None:
        """Block until every dispatched device computation whose output this
        ensemble still holds has completed: the vmapped state chunk, the
        stats sums, and the cached observables dispatch.  Same contract as
        the sharded campaign's fence — the serve scheduler runs it before
        host-level collectives while the ensemble occupies a proper
        sub-mesh (multihost.set_device_fence)."""
        if self.state is not None:
            jax.block_until_ready(self.state)
        stats = getattr(self, "stats_state", None)
        if stats is not None:
            jax.block_until_ready(stats)
        cache = self._obs_cache
        if cache is not None and not cache[1].ready():
            cache[1].result()

    def eval_nu(self) -> np.ndarray:
        return self.get_observables()[0]

    def eval_nuvol(self) -> np.ndarray:
        return self.get_observables()[1]

    def eval_re(self) -> np.ndarray:
        return self.get_observables()[2]

    def div_norm(self) -> np.ndarray:
        return self.get_observables()[3]

    def _emit_callback_line(self, t: float, vals, alive: np.ndarray) -> None:
        """Diagnostics append + aggregate print for one boundary (shared by
        the synchronous path and the io_pipeline's lagged emission)."""
        nu, nuvol, re, div = vals[:4]
        # extended vocabularies (the passive-scalar sherwood) append by name
        extra_names = tuple(self.observable_names)[4:]
        for key, val in (
            ("time", [t] * self.k),
            ("nu", nu),
            ("nuvol", nuvol),
            ("re", re),
            ("div", div),
            *zip(extra_names, vals[4:]),
            ("alive", alive.astype(float)),
        ):
            self.diagnostics.setdefault(key, []).append(list(map(float, val)))
        n_alive = int(alive.sum())
        if n_alive:
            live = np.asarray(nu)[alive]
            nu_info = f"Nu = {live.mean():5.3e} [{live.min():5.3e}, {live.max():5.3e}]"
        else:
            nu_info = "Nu = --- (all members diverged)"
        print(f"time = {t:9.3f}      alive = {n_alive}/{self.k}      {nu_info}")

    def callback(self) -> None:
        """Per-interval reporting: append per-member diagnostics, print an
        aggregate line, write the ensemble snapshot when ``write_intervall``
        says so (the single-run callback's throttling rule).

        With an attached ``io_pipeline`` the diagnostics ride observable
        futures (emitted at most one boundary late, FIFO) and the snapshot
        serialization runs on the background worker — the device queue is
        never fenced at the boundary (see utils/navier_io.callback)."""
        t = self.time
        pipeline = self.io_pipeline
        if pipeline is not None:
            from ..utils.io_pipeline import ObservableFuture

            obs_fut = self.get_observables_async()
            # the mask rides the same device queue as the observables: when
            # the obs future resolves, this fetch is already complete
            mask_fut = ObservableFuture(self.mask, convert=np.asarray)

            def emit(vals, t=t):
                self._emit_callback_line(t, vals, mask_fut.result().astype(bool))

            pipeline.push_diag(emit, obs_fut)
        else:
            self._emit_callback_line(t, self.get_observables(), self.alive())
        # single-run rule (utils/navier_io.callback): write every save
        # interval unless write_intervall throttles it further
        wi = self.write_intervall
        if wi is None or (t + self.dt / 2.0) % wi < self.dt:
            fname = f"data/ensemble{t:08.2f}.h5"
            if pipeline is not None:
                from ..utils import checkpoint

                snap = checkpoint.ensemble_snapshot_to_host(self)

                def write_snap(snap=snap, fname=fname):
                    try:
                        checkpoint.write_host_snapshot(snap, fname)
                    except OSError as exc:
                        print(f"unable to write ensemble snapshot: {exc}")

                pipeline.submit_write(write_snap, fname, nbytes=snap.nbytes)
            else:
                try:
                    self.write(fname)
                except OSError as exc:  # never fatal, like the single-run callback
                    print(f"unable to write ensemble snapshot: {exc}")

    @property
    def mesh(self):
        """The template model's pencil mesh (None = single device) — the
        sharded-checkpoint layer reads this to build target layouts."""
        return self.model.mesh

    # -- sharded (shard-wise) snapshot surface -------------------------------

    def snapshot_state_items(self) -> list:
        """``(name, device_array)`` per batched state leaf (leading K axis
        rides along as replicated batch under the pencil spec) — see
        ``Navier2D.snapshot_state_items``.  Armed stats leaves join the set
        so per-member running averages survive kill/resume bit-exactly."""
        items = [
            (f"state/{name}", getattr(self.state, name))
            for name in self.state._fields
        ]
        if self.stats_armed:
            items += [
                (f"stats/{name}", getattr(self.stats_state, name))
                for name in self.stats_state._fields
            ]
            items.append(("stats/tick", self._stats_tick))
        return items

    def _split_restored_stats(self, updates: dict) -> None:
        """Sharded-restore side of the stats leaves (mirrors
        ``CampaignModelBase._split_restored_stats``): present leaves
        install exactly, missing ones zero — then the caller installs the
        remaining state leaves."""
        if not self.stats_armed:
            return
        self.apply_restored_stats(
            self.model.stats_engine.split_restored(updates)
        )

    def snapshot_root_items(self) -> list:
        """Replicated manifest-root data: time, params AND the ensemble
        bookkeeping (member count, alive mask, per-member step counters)."""
        items = [("time", np.asarray(float(self.time), dtype=np.float64), "raw")]
        items.append(("members", np.asarray(int(self.k), dtype=np.int64), "raw"))
        items.append(("alive", np.asarray(self.mask).astype(np.int8), "raw"))
        items.append(
            ("steps_done", np.asarray(self.steps_done, dtype=np.int64), "raw")
        )
        for key, value in self.model.params.items():
            items.append((key, np.asarray(float(value), dtype=np.float64), "raw"))
        if self.integrity_armed:
            items.append((
                "integrity_digest",
                np.asarray(self.state_digest_async().result()),  # lint-ok: RPD005 (K,) uint32 manifest row
                "raw",
            ))
        return items

    def apply_restored_state(self, updates: dict, attrs: dict, root: dict) -> None:
        """Install the assembled batched leaves + bookkeeping.  The sharded
        format is exact (bit-equal restore), so the member count must match
        — the reader rejects K mismatches before assembly (K-elastic
        restarts go through the gathered per-member layout)."""
        self._split_restored_stats(updates)
        self.state = self.state._replace(**updates)
        self.mask = jnp.asarray(np.asarray(root["alive"], dtype=bool))
        self.steps_done = jnp.asarray(np.asarray(root["steps_done"]), jnp.int32)
        self.time = float(np.asarray(root["time"]))
        self._obs_cache = None
        self._pre_div_latch = False
        self._verify_restored_digest(root.get("integrity_digest"))

    def write(self, filename: str) -> None:
        """Write a K-member snapshot (per-member groups, utils/checkpoint)."""
        from ..utils import checkpoint

        checkpoint.write_ensemble_snapshot(self, filename)

    def read(self, filename: str) -> None:
        """Restore members (+ mask, counters, time) from an ensemble snapshot."""
        from ..utils import checkpoint

        checkpoint.read_ensemble_snapshot(self, filename)
