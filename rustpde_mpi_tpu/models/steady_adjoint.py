"""Navier2DAdjoint — steady-state finder by adjoint descent, TPU-native.

Rebuild of /root/reference/src/navier_stokes/steady_adjoint{,_eq,_io}.rs
(Farazmand 2016 JFM 795; Reiter et al. 2022): each ``update()`` performs

1. one forward Navier-Stokes step at the fixed inner timestep
   ``DT_NAVIER = 1e-3`` (steady_adjoint.rs:64, 541-581),
2. the residual ``res_q = (q_new - q_old) / DT_NAVIER`` per evolved variable,
3. a smoothing-norm solve ``q_adj = -(I - 0.1*D2)^-1 res_q`` (the
   ``WEIGHT_LAPLACIAN`` Hholtz norm, steady_adjoint.rs:62, 316-338), and
4. one explicit adjoint-descent step of pseudo-time ``dt`` that drives the
   *physical* fields toward the steady state using the adjoint convection
   terms, explicit adjoint diffusion and a pressure projection
   (steady_adjoint_eq.rs:355-437).

Converged when the mean smoothed-residual norm drops below
``RES_TOL = 1e-7`` (steady_adjoint.rs:624-638).

Functional JAX design: the whole iteration (forward step + residual + norm
solves + adjoint step) is ONE jitted function scanned on device via
``update_n``; residual norms ride along in the carry so the convergence test
costs no extra dispatch.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..field import norm_l2
from ..solver import Hholtz
from ..utils.integrate import Integrate
from .campaign import CampaignModelBase
from .navier import Navier2D, NavierState

RES_TOL = 1e-7  # steady_adjoint.rs:60
WEIGHT_LAPLACIAN = 1e-1  # steady_adjoint.rs:62
DT_NAVIER = 1e-3  # steady_adjoint.rs:64


class AdjointState(NamedTuple):
    """Physical fields + adjoint pressure + last residual norms."""

    temp: jax.Array
    velx: jax.Array
    vely: jax.Array
    pres: jax.Array
    pseu: jax.Array
    pres_adj: jax.Array
    res_norms: jax.Array  # (3,): |velx_adj|, |vely_adj|, |temp_adj|


class Navier2DAdjoint(CampaignModelBase, Integrate):
    """Steady-state RBC solver; same parameter vocabulary as Navier2D.

    A full campaign model (models/campaign.py): the whole adjoint-descent
    iteration is hoisted into ``_step_cc``, so steady-state finds run as
    vmapped K-member ensembles under ``ResilientRunner`` — and since the
    residual norms ride the state, RESIDUAL CONVERGENCE is compiled into
    the scanned chunk's early-exit (:meth:`_scan_ok`): a member whose mean
    smoothed residual drops below ``res_tol`` freezes at its converged
    state mid-chunk, costing no further GEMMs — the residual-based exit
    sentinel of the steady-find workload (workloads/steady.py)."""

    MODEL_KIND = "adjoint"
    observable_names = ("res", "res_u", "res_t", "div")

    def __init__(
        self,
        nx: int,
        ny: int,
        ra: float,
        pr: float,
        dt: float,
        aspect: float,
        bc: str,
        periodic: bool = False,
        mesh=None,
        res_tol: float = RES_TOL,
    ):
        # the embedded forward model is built at DT_NAVIER so its implicit
        # Helmholtz solvers carry the correct dt (steady_adjoint.rs:286-300)
        self.navier = Navier2D(nx, ny, ra, pr, DT_NAVIER, aspect, bc, periodic, mesh=mesh)
        self.mesh = mesh
        self.dt = dt
        self.res_tol = float(res_tol)
        self.params = self.navier.params
        self.scale = self.navier.scale
        self.write_intervall: float | None = None
        self.statistics = None
        self._init_campaign()

        nav = self.navier
        sx2, sy2 = self.scale[0] ** 2, self.scale[1] ** 2
        c_norm = (WEIGHT_LAPLACIAN / sx2, WEIGHT_LAPLACIAN / sy2)
        # smoothing norms (1 - 0.1*D2)^-1 per variable space
        # (steady_adjoint.rs:316-338); velx/vely share a space -> one solver
        self._norm_vel = Hholtz(nav.velx_space, c_norm)
        self._norm_temp = Hholtz(nav.temp_space, c_norm)

        self._compile_entry_points()
        with nav._scope():
            zero = nav._place(nav.pres_space.ndarray_spectral())
            self.state = AdjointState(
                temp=nav.state.temp,
                velx=nav.state.velx,
                vely=nav.state.vely,
                pres=nav.state.pres,
                pseu=nav.state.pseu,
                pres_adj=zero,
                res_norms=jnp.full((3,), np.inf, dtype=config.real_dtype()),
            )

    @property
    def nx(self) -> int:
        return self.navier.nx

    @property
    def ny(self) -> int:
        return self.navier.ny

    def _compat_fields(self) -> tuple:
        # self.dt is the DESCENT pseudo-step (the inner forward model runs
        # at the fixed DT_NAVIER); res_tol is compiled into the chunk's
        # convergence early-exit, so it buckets too
        return (
            int(self.navier.nx),
            int(self.navier.ny),
            float(self.params["ra"]),
            float(self.params["pr"]),
            float(self.dt),
            float(self.scale[0]),
            str(self.navier.bc),
            bool(self.navier.periodic),
            # variant slot: only a NON-default tolerance buckets separately
            # (so registry-built default models match kind-prefixed request
            # keys, which cannot express a custom tolerance)
            () if self.res_tol == RES_TOL else (("res_tol", float(self.res_tol)),),
        )

    def _gspmd_split_sep_fallback(self) -> bool:
        # like Navier2DLnse: no manual shard_map counterpart for the
        # adjoint step yet — shared eager-guard policy
        return self.navier._split_sep_eager_unless_forced()

    def restart_fill(self, name: str, like):
        """Gathered-restore fill: residual norms restart at +inf (unknown —
        zero would read as instantly converged), everything else at zero."""
        if name == "res_norms":
            return jnp.full_like(like, np.inf)
        return jnp.zeros_like(like)

    # space delegates (checkpoint layer vocabulary)
    @property
    def temp_space(self):
        return self.navier.temp_space

    @property
    def velx_space(self):
        return self.navier.velx_space

    @property
    def vely_space(self):
        return self.navier.vely_space

    @property
    def pres_space(self):
        return self.navier.pres_space

    @property
    def pseu_space(self):
        return self.navier.pseu_space

    @property
    def field_space(self):
        return self.navier.field_space

    @property
    def x(self):
        return self.navier.x

    def _scan_ok(self, state):
        """Continue while finite AND unconverged: the mean smoothed-residual
        convergence test (steady_adjoint.rs:624-638) compiled into the
        scanned chunk — a converged state freezes mid-chunk (identity
        steps), which is the workload's exit sentinel."""
        finite = jnp.isfinite(jnp.sum(state.temp))
        return finite & (jnp.mean(state.res_norms) >= self.res_tol)

    def _scan_done_ok(self, state):
        """A member that stopped advancing CONVERGED (rather than died)
        when its residual is finite and below tolerance."""
        res = jnp.mean(state.res_norms)
        return jnp.isfinite(res) & (res < self.res_tol)

    def _scan_commit_ok(self, state):
        """Commit any FINITE candidate: convergence stops the member (via
        ``_scan_ok``) but the converged state is the answer and must land
        in the carry before the freeze."""
        return jnp.isfinite(jnp.sum(state.temp))

    # -- construction ---------------------------------------------------------

    @classmethod
    def new_confined(cls, nx, ny, ra, pr, dt, aspect, bc, mesh=None) -> "Navier2DAdjoint":
        return cls(nx, ny, ra, pr, dt, aspect, bc, periodic=False, mesh=mesh)

    @classmethod
    def new_periodic(cls, nx, ny, ra, pr, dt, aspect, bc, mesh=None) -> "Navier2DAdjoint":
        return cls(nx, ny, ra, pr, dt, aspect, bc, periodic=True, mesh=mesh)

    @classmethod
    def from_config(cls, cfg, mesh=None) -> "Navier2DAdjoint":
        """Construct from a :class:`~rustpde_mpi_tpu.config.NavierConfig`
        (same field handling as Navier2D.from_config)."""
        model = cls(*cfg.ctor_args(), periodic=cfg.periodic, mesh=mesh)
        if cfg.init_random_amp:
            model.init_random(cfg.init_random_amp)
        model.write_intervall = cfg.write_intervall
        model.navier.params.update(cfg.params)
        return model

    # -- the adjoint iteration ------------------------------------------------

    def _make_step(self, with_sentinels: bool = False):
        nav = self.navier
        dt = self.dt
        scale = nav.scale
        nu, ka = nav.params["nu"], nav.params["ka"]
        inv_dx, inv_dy = nav._inv_dx, nav._inv_dy
        w0s, w1s = nav._w0, nav._w1
        sp_t, sp_u, sp_v = nav.temp_space, nav.velx_space, nav.vely_space
        sp_p, sp_q, sp_f = nav.pres_space, nav.pseu_space, nav.field_space
        from ..bases import fused_projection_gradient

        _gx = fused_projection_gradient(sp_u, sp_q, (1, 0))
        _gy = fused_projection_gradient(sp_v, sp_q, (0, 1))
        proj_grad = (*_gx, *_gy) if _gx and _gy else None
        mask = nav._dealias
        tb_ortho = nav.tempbc_ortho
        nav_step = nav._make_step()
        sol_p = nav.solver_pres
        norm_u, norm_t = self._norm_vel, self._norm_temp

        def grad_phys(space, vhat, deriv):
            return sp_f.backward_ortho(space.gradient(vhat, deriv, scale))

        def lap(space, vhat):
            return space.gradient(vhat, (2, 0), scale) + space.gradient(vhat, (0, 2), scale)

        def step(state: AdjointState) -> AdjointState:
            ns_old = NavierState(state.temp, state.velx, state.vely, state.pres, state.pseu)

            # *** forward Navier step at DT_NAVIER (steady_adjoint.rs:541-567)
            ns = nav_step(ns_old)

            # *** residual + smoothing norm (steady_adjoint.rs:568-581)
            res_u = (sp_u.to_ortho(ns.velx) - sp_u.to_ortho(ns_old.velx)) / DT_NAVIER
            res_v = (sp_v.to_ortho(ns.vely) - sp_v.to_ortho(ns_old.vely)) / DT_NAVIER
            res_t = (sp_t.to_ortho(ns.temp) - sp_t.to_ortho(ns_old.temp)) / DT_NAVIER
            velx_adj = -norm_u.solve(res_u)
            vely_adj = -norm_u.solve(res_v)
            temp_adj = -norm_t.solve(res_t)
            res_norms = jnp.stack(
                [norm_l2(velx_adj), norm_l2(vely_adj), norm_l2(temp_adj)]
            )

            # *** adjoint descent step (steady_adjoint.rs:584-605)
            ux = sp_u.backward(ns.velx)
            uy = sp_v.backward(ns.vely)

            if with_sentinels:
                # advective CFL of the embedded FORWARD step (the stiff,
                # explicitly-convected part of the iteration) + flow KE
                cfl = DT_NAVIER * jnp.max(
                    jnp.abs(ux) * inv_dx[:, None] + jnp.abs(uy) * inv_dy[None, :]
                )
                ke = 0.5 * jnp.sum((ux**2 + uy**2) * w0s[:, None] * w1s[None, :])
            ta = sp_t.backward(temp_adj)

            # physical gradients of the evolved + adjoint fields
            that_full = sp_t.to_ortho(ns.temp) + tb_ortho

            def conv(total):
                if any(sp_f.sep):
                    return sp_f.forward_dealiased(total)
                return sp_f.forward(total) * mask

            # x-momentum adjoint convection (steady_adjoint_eq.rs:258-289):
            # U.grad(u*_x) + U.(d_x u*) - theta* d_x(T + Tbc)
            conv_x = conv(
                ux * grad_phys(sp_u, velx_adj, (1, 0))
                + uy * grad_phys(sp_u, velx_adj, (0, 1))
                + ux * grad_phys(sp_u, velx_adj, (1, 0))
                + uy * grad_phys(sp_v, vely_adj, (1, 0))
                - ta * grad_phys(sp_f, that_full, (1, 0))
            )
            # y-momentum (steady_adjoint_eq.rs:292-321)
            conv_y = conv(
                ux * grad_phys(sp_v, vely_adj, (1, 0))
                + uy * grad_phys(sp_v, vely_adj, (0, 1))
                + ux * grad_phys(sp_u, velx_adj, (0, 1))
                + uy * grad_phys(sp_v, vely_adj, (0, 1))
                - ta * grad_phys(sp_f, that_full, (0, 1))
            )
            # temperature (steady_adjoint_eq.rs:324-341): U.grad(theta*)
            conv_t = conv(
                ux * grad_phys(sp_t, temp_adj, (1, 0))
                + uy * grad_phys(sp_t, temp_adj, (0, 1))
            )

            # explicit updates (steady_adjoint_eq.rs:355-437): the *physical*
            # fields descend along the adjoint direction
            rhs = sp_u.to_ortho(ns.velx)
            rhs = rhs - dt * sp_p.gradient(state.pres_adj, (1, 0), scale)
            rhs = rhs + dt * conv_x
            rhs = rhs + dt * nu * lap(sp_u, velx_adj)
            velx_n = sp_u.from_ortho(rhs)

            rhs = sp_v.to_ortho(ns.vely)
            rhs = rhs - dt * sp_p.gradient(state.pres_adj, (0, 1), scale)
            rhs = rhs + dt * conv_y
            rhs = rhs + dt * nu * lap(sp_v, vely_adj)
            vely_n = sp_v.from_ortho(rhs)

            # projection (steady_adjoint.rs:597-600)
            div = sp_u.gradient(velx_n, (1, 0), scale) + sp_v.gradient(
                vely_n, (0, 1), scale
            )
            pseu_n = sol_p.solve(div)
            pseu_n = sp_q.pin_zero_mode(pseu_n)
            if proj_grad is not None:
                gx0, gx1, gy0, gy1 = proj_grad
                pax = pseu_n.ndim - 2
                velx_n = velx_n - gx1.apply(gx0.apply(pseu_n, pax), pax + 1) / scale[0]
                vely_n = vely_n - gy1.apply(gy0.apply(pseu_n, pax), pax + 1) / scale[1]
            else:
                velx_n = velx_n - sp_u.from_ortho(sp_q.gradient(pseu_n, (1, 0), scale))
                vely_n = vely_n - sp_v.from_ortho(sp_q.gradient(pseu_n, (0, 1), scale))
            # adjoint pressure update: pres_adj += pseu/dt
            # (steady_adjoint_eq.rs:226-236)
            pres_adj_n = state.pres_adj + sp_q.to_ortho(pseu_n) / dt

            # temperature descent (steady_adjoint_eq.rs:408-437)
            rhs = sp_t.to_ortho(ns.temp)
            rhs = rhs + dt * conv_t
            rhs = rhs + dt * sp_v.to_ortho(vely_adj)  # adjoint buoyancy
            rhs = rhs + dt * ka * lap(sp_t, temp_adj)
            temp_n = sp_t.from_ortho(rhs)

            state_n = AdjointState(
                temp_n, velx_n, vely_n, ns.pres, pseu_n, pres_adj_n, res_norms
            )
            if with_sentinels:
                return state_n, (cfl, ke, norm_l2(div))
            return state_n

        return step

    def _make_observables(self):
        """Fused convergence diagnostics ``(res, res_u, res_t, |div|)``:
        the mean smoothed-residual norm (the convergence measure,
        steady_adjoint.rs:633) plus its velocity/temperature components —
        all riding the state carry, so the per-chunk convergence check
        costs no extra dispatch — and the velocity divergence norm as the
        NaN detector."""
        nav = self.navier
        sp_u, sp_v = nav.velx_space, nav.vely_space
        scale = nav.scale

        def observables(state: AdjointState):
            res = jnp.mean(state.res_norms)
            div = norm_l2(
                sp_u.gradient(state.velx, (1, 0), scale)
                + sp_v.gradient(state.vely, (0, 1), scale)
            )
            return res, state.res_norms[0], state.res_norms[2], div

        return observables

    def _state_example(self):
        nav = self.navier
        rdt = config.real_dtype()

        def sds(space):
            return jax.ShapeDtypeStruct(space.shape_spectral, space.spectral_dtype())

        return AdjointState(
            temp=sds(nav.temp_space),
            velx=sds(nav.velx_space),
            vely=sds(nav.vely_space),
            pres=sds(nav.pres_space),
            pseu=sds(nav.pseu_space),
            pres_adj=sds(nav.pres_space),
            res_norms=jax.ShapeDtypeStruct((3,), rdt),
        )

    # -- field access (delegates keep the Navier2D vocabulary) ---------------

    def _sync_navier(self) -> None:
        """Mirror the physical fields into the embedded model (for
        observables/IO, which read navier.state)."""
        self.navier.state = NavierState(
            self.state.temp, self.state.velx, self.state.vely,
            self.state.pres, self.state.pseu,
        )
        self.navier.time = self.time
        self.navier._obs_cache = None

    def _pull_navier(self) -> None:
        """Adopt navier.state (after set_field/read) into the adjoint state
        (residual norms reset — they describe the previous iterate)."""
        ns = self.navier.state
        self.state = self.state._replace(
            temp=ns.temp, velx=ns.velx, vely=ns.vely, pres=ns.pres, pseu=ns.pseu,
            res_norms=jnp.full((3,), np.inf, dtype=config.real_dtype()),
        )
        self._obs_cache = None

    def set_velocity(self, amp, m, n):
        self.navier.set_velocity(amp, m, n)
        self._pull_navier()

    def set_temperature(self, amp, m, n):
        self.navier.set_temperature(amp, m, n)
        self._pull_navier()

    def init_random(self, amp, seed: int = 0):
        self.navier.init_random(amp, seed)
        self._pull_navier()

    def get_field(self, name):
        self._sync_navier()
        return self.navier.get_field(name)

    def read(self, filename: str) -> None:
        from ..utils import checkpoint

        if checkpoint.is_sharded_checkpoint(filename):
            # manifest restore targets THIS model's snapshot surface (every
            # AdjointState leaf incl. pres_adj/res_norms — bit-exact resume)
            checkpoint.read_sharded_snapshot(self, filename)
            return
        self.navier.read(filename)
        self._pull_navier()
        self.time = self.navier.time

    def write(self, filename: str) -> None:
        self._sync_navier()
        self.navier.write(filename)

    # -- Integrate protocol ---------------------------------------------------
    # update/update_n/update_n_pending, sentinels, set_dt (rung-cached; the
    # descent dt only lives in the compiled step — _rebuild_dt_artifacts is
    # the base recompile) and observable futures come from CampaignModelBase

    def norm_residual(self) -> tuple[float, float, float]:
        """Smoothed-residual norms (|u*_x|, |u*_y|, |theta*|)
        (steady_adjoint_eq.rs:44-51)."""
        return tuple(float(v) for v in np.asarray(self.state.res_norms))

    def residual(self) -> float:
        """Mean residual — the convergence measure (steady_adjoint.rs:633)."""
        return float(np.mean(np.asarray(self.state.res_norms)))

    def eval_nu(self):
        """Nusselt of the current iterate (DNS vocabulary, via the embedded
        model; the campaign observables are the residual norms)."""
        self._sync_navier()
        return self.navier.get_observables()[0]

    def eval_nuvol(self):
        self._sync_navier()
        return self.navier.get_observables()[1]

    def eval_re(self):
        self._sync_navier()
        return self.navier.get_observables()[2]

    def callback(self) -> None:
        from ..utils import navier_io

        self._sync_navier()
        # propagate the adjoint's own IO throttles onto the embedded model
        # navier_io reads (the reference passes self.write_intervall,
        # steady_adjoint.rs:621)
        self.navier.write_intervall = self.write_intervall
        self.navier.statistics = self.statistics
        res = self.residual()
        navier_io.callback(
            self.navier,
            flowname=f"data/adjoint{self.time:08.2f}.h5",
            io_name="data/info_adjoint.txt",
            extra=f"res = {res:5.3e}",
        )

    def exit(self) -> bool:
        """NaN divergence (or a latched sentinel catch), or converged: mean
        residual < ``res_tol`` (steady_adjoint.rs:624-638).  A converged
        exit is a SUCCESS — :meth:`state_healthy` (the checkpoint guard)
        deliberately keeps reporting True for it."""
        if super().exit():
            return True
        if self.residual() < self.res_tol:
            print("Steady state converged!")
            return True
        return False
