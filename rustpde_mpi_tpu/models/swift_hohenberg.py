"""Swift–Hohenberg pattern-formation models (1-D and 2-D, periodic).

TPU rebuild of the reference's user-level "bring your own PDE" demos
(/root/reference/examples/swift_hohenberg_1d.rs, swift_hohenberg_2d.rs):

    du/dt = [r - (lap + 1)^2] u - u^3

integrated with the reference's IMEX scheme — the stiff linear operator
``(lap+1)^2 - r`` implicit (it is diagonal in Fourier space, so the implicit
solve is one elementwise divide), the cubic nonlinearity explicit:

    u_{n+1} = (u_n - dt * F[(F^-1 u_n)^3]) / (1 + dt*((1 - K^2)^2 - r))

with K^2 = (kx/Lx)^2 + (ky/Ly)^2.  The whole step is transforms + an
elementwise divide — on TPU that is MXU matmul transforms over the split
Re/Im representation (bases.Space1 / bases.BiPeriodicSpace2); there is no
complex arithmetic anywhere on that backend.

Reference-parity details kept: the 1-D model dealiases the cubic term and
does not pin the mean mode; the 2-D model pins the (0,0) mode and enforces
Hermitian symmetry of the ky=0 column each step (without which the implicit
update drifts unstable — swift_hohenberg_2d.rs enforce_hermitian_symmetry).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..bases import BiPeriodicSpace2, Space1, fourier_r2c
from ..utils.integrate import Integrate


def _h5():
    import h5py

    return h5py


class _SwiftHohenbergBase(Integrate):
    """Shared driver plumbing (time bookkeeping, scanned update_n, IO)."""

    def __init__(self, r: float, dt: float):
        self.r = r
        self.dt = dt
        self.time = 0.0
        self.write_intervall: float | None = None

    def _compile(self):
        from ..utils.jit import hoist_constants

        step = self._make_step()
        converted, consts = hoist_constants(step, self.theta)
        self._consts = consts

        @jax.jit
        def step_1(consts, theta):
            return converted(consts, theta)

        from functools import partial

        @partial(jax.jit, static_argnums=2)
        def step_n(consts, theta, n):
            return jax.lax.scan(
                lambda th, _: (converted(consts, th), None), theta, None, length=n
            )[0]

        self._step_1 = lambda th: step_1(self._consts, th)
        self._step_n = lambda th, n: step_n(self._consts, th, n)

    def update(self) -> None:
        self.theta = self._step_1(self.theta)
        self.time += self.dt

    def update_n(self, n: int) -> None:
        from ..utils.jit import run_scanned

        self.theta = run_scanned(self._step_n, self.theta, n)
        self.time += n * self.dt

    def get_time(self) -> float:
        return self.time

    def get_dt(self) -> float:
        return self.dt

    def norm(self) -> float:
        """|F|: coefficient-space L2 norm / complex mode count (the
        reference's norm_l2_c64 diagnostic, swift_hohenberg_2d.rs).  The
        split Re/Im representation stores |c|^2 as re^2 + im^2 across its two
        blocks, so the value is backend-independent."""
        a = np.asarray(self.theta)
        return float(np.sqrt(np.sum(np.abs(a) ** 2)) / self._norm_len)

    def exit(self) -> bool:
        return bool(np.any(np.isnan(np.asarray(self.theta))))

    def callback(self) -> None:
        import os

        print(f"Time = {self.time:6.2e}")
        os.makedirs("data", exist_ok=True)
        fname = f"data/flow{self.time:0>8.2f}.h5"
        self.write(fname)
        print(f"|F| = {self.norm():6.2e}")

    def write(self, filename: str) -> None:
        """Snapshot in the reference layout: ``temp/{v,vhat,x,dx,...}`` +
        scalars time/dt/r (swift_hohenberg_2d.rs _write)."""
        try:
            self._write(filename)
            print(f" ==> {filename}")
        except OSError as exc:
            print(f"Error while writing file {filename}: {exc}")

    def read(self, filename: str) -> None:
        with _h5().File(filename, "r") as f:
            g = f["temp"]
            if "vhat_re" in g:
                vhat_c = np.asarray(g["vhat_re"]) + 1j * np.asarray(g["vhat_im"])
            else:
                vhat_c = np.asarray(g["vhat"])
            s = self.space.vhat_from_complex(vhat_c)
            dtype = (
                config.complex_dtype()
                if np.iscomplexobj(s)
                else config.real_dtype()
            )
            self.theta = jnp.asarray(s, dtype=dtype)
            self.time = float(np.asarray(f["time"]))


class SwiftHohenberg1D(_SwiftHohenbergBase):
    """1-D Swift–Hohenberg on a periodic domain of length ``2*pi*length``
    (/root/reference/examples/swift_hohenberg_1d.rs)."""

    def __init__(self, nx: int, r: float, dt: float, length: float):
        super().__init__(r, dt)
        self.nx = nx
        self.space = Space1(fourier_r2c(nx))
        self.scale = (float(length),)
        self.x = [self.space.base.points * length]
        k = self.space.base.wavenumbers / length
        matl = 1.0 + dt * ((1.0 - k**2) ** 2 - r)
        self._matl = jnp.asarray(matl, dtype=config.real_dtype())
        self._dealias = jnp.asarray(
            self.space.dealias_mask(), dtype=config.real_dtype()
        )
        self.theta = self.space.ndarray_spectral()
        # complex mode count (the split representation has 2x real rows)
        base = self.space.base
        self._norm_len = base.m_complex if base.kind.is_split else base.m
        self.init_cos(1e-5)
        self._compile()

    def init_cos(self, c: float) -> None:
        """One-cosine disturbance over the domain span (reference init_cos)."""
        x = self.x[0]
        span = x[-1] - x[0]
        v = c * np.cos((x - x[0]) / span * 2.0 * np.pi)
        self.set_theta(v)

    def init_random(self, c: float, seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        self.set_theta(rng.uniform(-c, c, size=self.nx))

    def set_theta(self, values: np.ndarray) -> None:
        self.theta = self.space.forward(
            jnp.asarray(values, dtype=config.real_dtype())
        )

    def theta_physical(self) -> np.ndarray:
        return np.asarray(self.space.backward(self.theta))

    def _make_step(self):
        space, dt = self.space, self.dt
        matl, mask = self._matl, self._dealias

        def step(theta):
            v = space.backward(theta)
            cubic = space.forward(v * v * v) * mask
            return (theta - dt * cubic) / matl

        return step

    def _write(self, filename: str) -> None:
        from ..field import grid_deltas

        with _h5().File(filename, "w") as f:
            g = f.create_group("temp")
            g.create_dataset("v", data=self.theta_physical())
            vc = self.space.vhat_as_complex(self.theta)
            if np.iscomplexobj(vc):
                g.create_dataset("vhat_re", data=vc.real)
                g.create_dataset("vhat_im", data=vc.imag)
            else:
                g.create_dataset("vhat", data=vc)
            g.create_dataset("x", data=self.x[0])
            g.create_dataset("dx", data=grid_deltas(self.x[0], True))
            f.create_dataset("time", data=self.time)
            f.create_dataset("dt", data=self.dt)
            f.create_dataset("r", data=self.r)


class SwiftHohenberg2D(_SwiftHohenbergBase):
    """2-D Swift–Hohenberg on a doubly-periodic square of side
    ``2*pi*length`` (/root/reference/examples/swift_hohenberg_2d.rs;
    BASELINE.json config #5 at 2048^2)."""

    def __init__(self, nx: int, ny: int, r: float, dt: float, length: float):
        super().__init__(r, dt)
        self.nx, self.ny = nx, ny
        self.space = BiPeriodicSpace2(nx, ny)
        self.scale = (float(length), float(length))
        self.x = [p * length for p in self.space.coords()]
        kx = self.space.kx / length
        ky = self.space.ky / length
        k2 = kx[:, None] ** 2 + ky[None, :] ** 2
        matl = 1.0 + dt * ((1.0 - k2) ** 2 - r)
        self._matl = jnp.asarray(matl, dtype=config.real_dtype())
        self.theta = self.space.ndarray_spectral()
        self._norm_len = nx * self.space.my
        self.init_random(1e-1)
        self._compile()

    def init_random(self, c: float, seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        self.set_theta(rng.uniform(-c, c, size=(self.nx, self.ny)))

    def init_cos(self, c: float, kx: float, ky: float) -> None:
        x, y = self.x
        sx, sy = x[-1] - x[0], y[-1] - y[0]
        v = (
            c
            * np.cos((x[:, None] - x[0]) / sx * kx * np.pi)
            * np.cos((y[None, :] - y[0]) / sy * ky * np.pi)
        )
        self.set_theta(v)

    def set_theta(self, values: np.ndarray) -> None:
        self.theta = self.space.forward(
            jnp.asarray(values, dtype=config.real_dtype())
        )

    def theta_physical(self) -> np.ndarray:
        return np.asarray(self.space.backward(self.theta))

    def _make_step(self):
        space, dt = self.space, self.dt
        matl = self._matl

        def step(theta):
            v = space.backward(theta)
            cubic = space.forward(v * v * v)
            out = (theta - dt * cubic) / matl
            out = space.pin_zero_mode(out)
            return space.enforce_hermitian_x(out)

        return step

    def pattern_energy(self) -> float:
        """Domain-averaged theta^2 — the pattern-amplitude trace BASELINE
        config #5 records."""
        v = self.theta_physical()
        return float(np.mean(v**2))

    def _write(self, filename: str) -> None:
        from ..field import grid_deltas

        with _h5().File(filename, "w") as f:
            g = f.create_group("temp")
            g.create_dataset("v", data=self.theta_physical())
            vc = self.space.vhat_as_complex(self.theta)
            g.create_dataset("vhat_re", data=vc.real)
            g.create_dataset("vhat_im", data=vc.imag)
            for name, arr in (("x", self.x[0]), ("y", self.x[1])):
                g.create_dataset(name, data=arr)
                g.create_dataset("d" + name, data=grid_deltas(arr, True))
            f.create_dataset("time", data=self.time)
            f.create_dataset("dt", data=self.dt)
            f.create_dataset("r", data=self.r)
