"""Host-side tooling: particle tracer, XDMF/ParaView sidecars.

Rebuilds of the reference's standalone tool crates
(/root/reference/tools/{particle_tracer,create_xmf_crate}) — native C++ cores
where the reference's are native Rust, bound via ctypes."""

from .particle_tracer import ParticleSwarm, native_available  # noqa: F401
from .xdmf import create_xmf, sorted_h5_files  # noqa: F401
