"""Passive Lagrangian particle tracer.

Rebuild of the reference's ``particle_tracer`` crate
(/root/reference/tools/particle_tracer/src/lib.rs: ParticleSwarm, RK4 update,
bilinear interpolation, out-of-bounds freeze).  The hot loop is native C++
(tools/particle_tracer/tracer.cpp, built on demand with g++) bound through
ctypes; a vectorized numpy implementation provides the same semantics when no
compiler is available.  Both paths are tested for equality.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_TOOLS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tools",
    "particle_tracer",
)
_LIB_PATH = os.path.join(_TOOLS_DIR, "libtracer.so")
_lib = None
_lib_tried = False


def _load_native():
    """Load (building if needed) the C++ core; None if unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    src = os.path.join(_TOOLS_DIR, "tracer.cpp")
    stale = (
        os.path.exists(_LIB_PATH)
        and os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    )
    if stale or not os.path.exists(_LIB_PATH):
        if not os.path.exists(src):
            return None
        # build to a temp path + atomic rename: concurrent importers (MPI
        # ranks, parallel pytest) must never dlopen a half-written .so
        tmp = _LIB_PATH + f".build.{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", src, "-o", tmp],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, _LIB_PATH)
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if not os.path.exists(_LIB_PATH):
                return None
            # rebuild failed (e.g. no g++) but a previously-built library
            # exists: keep using it rather than silently dropping to numpy
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    dptr = ctypes.POINTER(ctypes.c_double)
    lib.advect_particles.restype = ctypes.c_long
    lib.advect_particles.argtypes = [
        dptr, ctypes.c_long, dptr, ctypes.c_long,
        dptr, dptr, dptr, dptr, ctypes.c_long,
        ctypes.c_double, ctypes.c_long,
    ]
    lib.sample_velocity.restype = None
    lib.sample_velocity.argtypes = [
        dptr, ctypes.c_long, dptr, ctypes.c_long,
        dptr, dptr, dptr, dptr, ctypes.c_long, dptr, dptr,
    ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load_native() is not None


def _as_c(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


# ---------------------------------------------------------------------------
# numpy fallback with identical semantics
# ---------------------------------------------------------------------------


def _bilinear(x, y, ux, uy, px, py):
    """Vectorized bilinear sample at (px, py); positions must be in bounds."""
    i = np.clip(np.searchsorted(x, px, side="right") - 1, 0, x.size - 2)
    j = np.clip(np.searchsorted(y, py, side="right") - 1, 0, y.size - 2)
    tx = (px - x[i]) / (x[i + 1] - x[i])
    ty = (py - y[j]) / (y[j + 1] - y[j])
    w00 = (1 - tx) * (1 - ty)
    w01 = (1 - tx) * ty
    w10 = tx * (1 - ty)
    w11 = tx * ty

    def samp(f):
        return (
            w00 * f[i, j] + w01 * f[i, j + 1] + w10 * f[i + 1, j] + w11 * f[i + 1, j + 1]
        )

    return samp(ux), samp(uy)


def _inside(x, y, px, py):
    return (px >= x[0]) & (px <= x[-1]) & (py >= y[0]) & (py <= y[-1])


def _advect_numpy(x, y, ux, uy, px, py, dt, n_steps):
    alive = _inside(x, y, px, py)
    for _ in range(n_steps):
        if not alive.any():
            break
        cx, cy = px.copy(), py.copy()
        k1x, k1y = _bilinear(x, y, ux, uy, cx, cy)
        mx, my = cx + 0.5 * dt * k1x, cy + 0.5 * dt * k1y
        alive &= _inside(x, y, mx, my)
        k2x, k2y = _bilinear(x, y, ux, uy, mx, my)
        mx, my = cx + 0.5 * dt * k2x, cy + 0.5 * dt * k2y
        alive &= _inside(x, y, mx, my)
        k3x, k3y = _bilinear(x, y, ux, uy, mx, my)
        mx, my = cx + dt * k3x, cy + dt * k3y
        alive &= _inside(x, y, mx, my)
        k4x, k4y = _bilinear(x, y, ux, uy, mx, my)
        nx_ = cx + dt / 6.0 * (k1x + 2 * k2x + 2 * k3x + k4x)
        ny_ = cy + dt / 6.0 * (k1y + 2 * k2y + 2 * k3y + k4y)
        alive &= _inside(x, y, nx_, ny_)
        px[alive] = nx_[alive]
        py[alive] = ny_[alive]
    return int((~alive).sum())


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


class ParticleSwarm:
    """Swarm of passive tracers on a 2-D tensor grid.

    API mirrors the reference (lib.rs ParticleSwarm): construct from explicit
    positions, a random rectangle, or a file; ``update`` advances through one
    velocity snapshot; ``trace_files`` replays a whole run of h5 snapshots.
    """

    def __init__(self, positions, x, y, timestep: float, backend: str = "auto"):
        self.x = np.ascontiguousarray(x, dtype=np.float64)
        self.y = np.ascontiguousarray(y, dtype=np.float64)
        positions = np.asarray(positions, dtype=np.float64)
        self.px = np.ascontiguousarray(positions[:, 0].copy())
        self.py = np.ascontiguousarray(positions[:, 1].copy())
        self.timestep = float(timestep)
        self.time = 0.0
        self.history: list[tuple[float, np.ndarray, np.ndarray]] = []
        if backend == "auto":
            backend = "native" if native_available() else "numpy"
        if backend == "native" and not native_available():
            raise RuntimeError("native tracer library unavailable (g++ build failed?)")
        self.backend = backend

    # -- constructors (reference lib.rs:78-140) ------------------------------

    @classmethod
    def from_rectangle(
        cls, x0, y0, range_, n, x, y, timestep, seed: int = 0, backend="auto"
    ):
        rng = np.random.default_rng(seed)
        pos = np.stack(
            [
                x0 + rng.uniform(-range_, range_, n),
                y0 + rng.uniform(-range_, range_, n),
            ],
            axis=1,
        )
        return cls(pos, x, y, timestep, backend=backend)

    @classmethod
    def from_file(cls, fname, x, y, timestep, backend="auto"):
        """Read ``time x y`` rows (the write() format)."""
        data = np.loadtxt(fname, ndmin=2)
        return cls(data[:, 1:3], x, y, timestep, backend=backend)

    # -- evolution ----------------------------------------------------------

    def positions(self) -> np.ndarray:
        return np.stack([self.px, self.py], axis=1)

    def update(self, ux, uy, n_steps: int = 1) -> int:
        """Advance ``n_steps`` RK4 steps through one (static) velocity field;
        returns the number of currently frozen (out-of-bounds) particles."""
        ux = np.ascontiguousarray(ux, dtype=np.float64)
        uy = np.ascontiguousarray(uy, dtype=np.float64)
        grid = (self.x.size, self.y.size)
        if ux.shape != grid or uy.shape != grid:
            raise ValueError(f"velocity shapes {ux.shape}/{uy.shape} != grid {grid}")
        if self.backend == "native":
            frozen = _load_native().advect_particles(
                _as_c(self.x), self.x.size, _as_c(self.y), self.y.size,
                _as_c(ux), _as_c(uy), _as_c(self.px), _as_c(self.py),
                self.px.size, self.timestep, n_steps,
            )
        else:
            frozen = _advect_numpy(
                self.x, self.y, ux, uy, self.px, self.py, self.timestep, n_steps
            )
        self.time += n_steps * self.timestep
        return int(frozen)

    def sample(self, ux, uy) -> tuple[np.ndarray, np.ndarray]:
        """Velocity at the current particle positions (0 outside)."""
        ux = np.ascontiguousarray(ux, dtype=np.float64)
        uy = np.ascontiguousarray(uy, dtype=np.float64)
        if self.backend == "native":
            out_u = np.empty_like(self.px)
            out_v = np.empty_like(self.py)
            _load_native().sample_velocity(
                _as_c(self.x), self.x.size, _as_c(self.y), self.y.size,
                _as_c(ux), _as_c(uy), _as_c(self.px), _as_c(self.py),
                self.px.size, _as_c(out_u), _as_c(out_v),
            )
            return out_u, out_v
        inside = _inside(self.x, self.y, self.px, self.py)
        u = np.zeros_like(self.px)
        v = np.zeros_like(self.py)
        if inside.any():
            su, sv = _bilinear(
                self.x, self.y, ux, uy, self.px[inside], self.py[inside]
            )
            u[inside], v[inside] = su, sv
        return u, v

    def record(self) -> None:
        self.history.append((self.time, self.px.copy(), self.py.copy()))

    def trace_files(
        self, files, snapshot_dt: float, ux_key="ux/v", uy_key="uy/v",
        record_every: int = 1,
    ) -> None:
        """Replay a run: for each snapshot file advance snapshot_dt worth of
        RK4 steps through its (frozen) velocity field, recording positions
        (the reference's main.rs driver loop)."""
        import h5py

        steps_per_file = max(1, round(snapshot_dt / self.timestep))
        self.record()
        for idx, fname in enumerate(files):
            with h5py.File(fname, "r") as f:
                ux = np.asarray(f[ux_key])
                uy = np.asarray(f[uy_key])
            self.update(ux, uy, steps_per_file)
            if (idx + 1) % record_every == 0:
                self.record()

    # -- IO (reference lib.rs write: "time x y" rows) ------------------------

    def write(self, fname: str) -> None:
        """Current positions, one ``time x y`` row per particle."""
        with open(fname, "w") as f:
            for xp, yp in zip(self.px, self.py):
                f.write(f"{self.time} {xp} {yp}\n")

    def write_history(self, fname: str) -> None:
        """Recorded trajectory: blocks of ``time x y`` per record call."""
        with open(fname, "w") as f:
            for t, xs, ys in self.history:
                for xp, yp in zip(xs, ys):
                    f.write(f"{t} {xp} {yp}\n")
