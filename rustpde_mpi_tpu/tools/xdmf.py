"""XDMF sidecar generator for ParaView visualization of HDF5 snapshots.

Rebuild of the reference's ``create_xmf_crate``
(/root/reference/tools/create_xmf_crate/src/{main,xdmf_writer,sort_files}.rs):
for every snapshot in a directory (sorted by the stored ``time`` scalar)
write an ``xmf######.xmf`` XML sidecar describing a curvilinear 2-D mesh plus
node-centered scalar attributes, and one shared ``cartesian.nc`` holding the
2-D meshgrid coordinates.  ParaView opens the .xmf files directly.

Coordinate lookup prefers this framework's snapshot layout (per-variable
groups, e.g. ``temp/x``) and falls back to top-level ``x``/``y`` datasets
(the layout the reference tool expects).
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np


def sorted_h5_files(root: str) -> list[tuple[float, str]]:
    """(time, path) for every .h5 in ``root``, sorted by the stored time
    scalar (files without one sort as time 0)
    (sort_files.rs sorted_list_of_h5_files)."""
    import h5py

    out = []
    for name in os.listdir(root):
        if not name.endswith(".h5"):
            continue
        path = os.path.join(root, name)
        t = 0.0
        try:
            with h5py.File(path, "r") as f:
                if "time" in f:
                    t = float(np.asarray(f["time"]))
        except OSError:
            continue
        out.append((t, path))
    out.sort(key=lambda p: p[0])
    return out


def _read_coords(path: str, attrs: Sequence[str]):
    import h5py

    with h5py.File(path, "r") as f:
        for g in (*attrs, None):
            xkey = f"{g}/x" if g else "x"
            ykey = f"{g}/y" if g else "y"
            if xkey in f and ykey in f:
                return np.asarray(f[xkey]), np.asarray(f[ykey])
        raise KeyError(f"no coordinate datasets found in {path}")


def _read_time(path: str):
    import h5py

    with h5py.File(path, "r") as f:
        return float(np.asarray(f["time"])) if "time" in f else None


class XdmfWriter:
    """One snapshot -> one .xmf sidecar (xdmf_writer.rs XdmfWriter)."""

    def __init__(
        self,
        fname: str,
        attrs: Sequence[str],
        variables: Sequence[str],
        xmfname: str | None = None,
    ):
        self.fname = fname
        self.attrs = list(attrs)
        self.variables = list(variables)
        x, y = _read_coords(fname, self.attrs)
        self.x, self.y = x, y
        self.nx, self.ny = x.size, y.size
        parent = os.path.dirname(fname)
        self.cname = os.path.join(parent, "cartesian.nc") if parent else "cartesian.nc"
        self.time = _read_time(fname)
        if xmfname is None:
            xmfname = (
                fname[:-3] + ".xmf" if fname.endswith(".h5") else "default.xmf"
            )
        self.xmfname = xmfname

    def create_cartesian(self, overwrite: bool = False) -> None:
        """Write the shared 2-D meshgrid file (xdmf_writer.rs
        create_cartesian)."""
        import h5py

        if not overwrite and os.path.exists(self.cname):
            return
        xx, yy = np.meshgrid(self.x, self.y, indexing="ij")
        with h5py.File(self.cname, "w") as f:
            f.create_dataset("x", data=xx)
            f.create_dataset("y", data=yy)

    def _geometry(self) -> str:
        cname = os.path.basename(self.cname)
        dims = f"{self.nx:6d}{self.ny:6d}"
        lines = ['<Geometry GeometryType="X_Y">']
        for axis in ("x", "y"):
            lines.append(
                f'<DataItem Dimensions="{dims}" NumberType="Float" '
                f'Precision="4" Format="HDF">{cname}:/{axis}</DataItem>'
            )
        lines.append("</Geometry>")
        return "\n".join(lines) + "\n"

    def _attribute(self, aname: str, vname: str) -> str:
        fname = os.path.basename(self.fname)
        dims = f"{self.nx:6d}{self.ny:6d}"
        return (
            self._geometry()
            + f'<Attribute Name="{aname}" AttributeType="Scalar" Center="Node">\n'
            + f'<DataItem Dimensions="{dims}" NumberType="Float" '
            + f'Precision="4" Format="HDF">{fname}:/{vname}</DataItem>\n'
            + "</Attribute>\n"
        )

    def write(self) -> None:
        with open(self.xmfname, "w") as f:
            f.write('<?xml version="1.0" ?>\n')
            f.write('<!DOCTYPE Xdmf SYSTEM "Xdmf.dtd" []>\n')
            f.write('<Xdmf Version="2.0">\n<Domain>\n')
            f.write('<Grid Name="Box" GridType="Uniform">\n')
            f.write(
                f'<Topology TopologyType="3DSMesh" '
                f'NumberOfElements="{self.nx:6d}{self.ny:6d}"/>\n'
            )
            for aname, vname in zip(self.attrs, self.variables):
                f.write(self._attribute(aname, vname))
            t = self.time if self.time is not None else 0.0
            f.write(f'<Time Value=" {t:12.10}" />\n')
            f.write("</Grid>\n</Domain>\n</Xdmf>\n")


def create_xmf(
    root: str,
    attrs: Sequence[str] = ("temp", "ux", "uy", "pres"),
    variables: Sequence[str] = ("temp/v", "ux/v", "uy/v", "pres/v"),
) -> list[str]:
    """Generate xmf sidecars for every snapshot under ``root``; returns the
    list of files written (main.rs create_xmf)."""
    written = []
    for i, (_, path) in enumerate(sorted_h5_files(root)):
        xmfname = os.path.join(root, f"xmf{i:06d}.xmf")
        w = XdmfWriter(path, attrs, variables, xmfname)
        w.create_cartesian(overwrite=False)
        w.write()
        written.append(xmfname)
        print(f"Created xmf for {path} => {xmfname}")
    return written
