"""Continuously-batched ensemble scheduler: the fault-isolated simulation
service.

The serving layer the ROADMAP's multi-tenant north star needs: a
persistent driver that accepts :class:`~.request.SimRequest` work through
the durable queue (serve/queue.py, plus the thin HTTP front in
serve/http_front.py), bucket-batches compatible requests into
:class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` slots, and
streams per-request observables back through PR-4 observable futures as
each request resolves.  The batching is LLM-style CONTINUOUS batching:

* requests bucket by :attr:`SimRequest.compat_key` (the operator constants
  one compiled vmapped step can serve: grid, Ra/Pr, dt, geometry, BC),
* a campaign opens one K-slot ensemble per bucket; each chunk advances
  every running slot together as ONE donated vmapped dispatch,
* the chunk length is ``min(remaining steps of any running slot,
  chunk_steps)``, so completions land exactly on chunk boundaries,
* a finished, diverged or idle slot is REFILLED from the queue at the
  boundary via ``set_member`` — the existing respawn machinery — without
  recompiling anything (equal keys share the jaxpr by construction).

Robustness is the spec, not a bolt-on:

* **per-request fault isolation** — one member's NaN freezes that member
  only (the ensemble's per-member finite mask); co-batched requests keep
  stepping bit-exactly like their solo runs (CI-asserted),
* **per-request retry** — a diverged request is re-queued at
  ``dt * request_dt_backoff`` (a different bucket: dt is an operator
  constant) with a bounded budget, then lands in the typed
  :class:`~.request.RequestFailed` terminal state,
* **admission control** — the queue bounds admissions and a submit past
  the bound is rejected with a reason (queue.py),
* **graceful drain** — SIGTERM (or :meth:`SimServer.request_drain`)
  finishes the in-flight chunk, checkpoints every slot via the sharded
  two-phase writer — WITH the slot table riding the manifest as
  digest-covered root data — re-enqueues unfinished requests and exits
  clean,
* **crash recovery** — on restart the queue re-enqueues whatever was
  ``running`` (accepted requests are never lost) and the campaign restore
  rebuilds the slot table from the newest valid checkpoint, so drained or
  killed requests resume mid-trajectory instead of restarting.

The device-facing machinery is the embedded
:class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner` (its
``session``/``advance``/``checkpoint_now`` surface): fault injection,
dispatch watchdogs, the async/sharded checkpoint pipeline and the journal
all come from there — the service adds scheduling, not a second harness.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import time

import numpy as np

from ..config import IOConfig, ServeConfig
from ..models.ensemble import NavierEnsemble
from ..telemetry import metrics as _tm
from ..telemetry import tracing as _tr
from ..telemetry.exporters import MetricsDumper
from ..utils import checkpoint
from ..workloads.registry import build_model_for_key
from ..utils.faults import FaultPlan, validate_fault_env
from ..utils.journal import JournalWriter, read_journal
from ..utils.resilience import ResilientRunner
from .queue import DurableQueue
from .request import AdmissionError, RequestFailed, SimRequest


class _ServedEnsemble(NavierEnsemble):
    """Ensemble whose checkpoints are self-describing for the scheduler:
    ``serve_meta`` (one dict per slot: request json + step target, None =
    idle) rides the sharded manifest as digest-covered root data, so a
    restore rebuilds the slot table from the checkpoint alone — no side
    file that could go stale against the state it describes."""

    def __init__(self, model, states):
        super().__init__(model, states)
        self.serve_meta: list[dict | None] = [None] * self.k
        self.restored_meta: list[dict | None] | None = None

    def snapshot_root_items(self) -> list:
        items = super().snapshot_root_items()
        blob = np.frombuffer(
            json.dumps(self.serve_meta).encode("utf-8"), np.uint8
        ).copy()
        items.append(("serve_slots", blob, "raw"))
        return items

    def apply_restored_state(self, updates, attrs, root) -> None:
        super().apply_restored_state(updates, attrs, root)
        if "serve_slots" in root:
            meta = json.loads(bytes(np.asarray(root["serve_slots"])).decode("utf-8"))
            self.serve_meta = meta
            self.restored_meta = meta


@dataclasses.dataclass
class _Slot:
    """One ensemble lane: IDLE (masked dead, waiting for work) or RUNNING
    a request toward ``target`` member-steps (``steps_done`` measured by
    the ensemble's own per-member counter)."""

    index: int
    req: SimRequest | None = None
    target: int = 0

    @property
    def running(self) -> bool:
        return self.req is not None


class SimServer:
    """The service front: durable queue + continuous-batching scheduler.

    Batch mode (``cfg.idle_exit=True``, the default) drains the queue and
    returns a summary; daemon mode keeps polling for new work (the HTTP
    front feeds the queue concurrently) until :meth:`request_drain` or
    SIGTERM.  One instance per process — it installs signal handlers while
    :meth:`serve` runs."""

    def __init__(self, cfg: ServeConfig | None = None, *, fault: str | None = None):
        self.cfg = cfg or ServeConfig()
        validate_fault_env()  # malformed chaos specs die here, not silently
        self.queue = DurableQueue(
            os.path.join(self.cfg.run_dir, "queue"), max_queue=self.cfg.max_queue
        )
        self.journal_path = os.path.join(self.cfg.run_dir, "journal.jsonl")
        self._journal_writer = JournalWriter(self.journal_path)
        self._fault = FaultPlan.from_spec(
            fault if fault is not None else os.environ.get("RUSTPDE_FAULT")
        )
        self._drain = False
        self._runner: ResilientRunner | None = None
        # bucket fairness: the key served by the previous campaign (the
        # round-robin cursor) + this campaign's claim budget consumption
        self._last_bucket: tuple | None = None
        self._campaign_claims = 0
        self._t0 = time.monotonic()
        self._global_step = 0  # member-chunk steps across campaigns
        self._member_steps = 0  # aggregate member-steps actually computed
        self._completed = 0
        self._failed = 0
        self._retried = 0
        self._pending_results: list[tuple] = []  # (obs_future, [(slot,req,..)])
        self._prev_handlers: dict = {}
        self._http = None
        # live serve telemetry (telemetry/metrics.py): slot occupancy of the
        # ACTIVE campaign, the member-rate mark for the steps/s + MFU gauges,
        # and the per-member step flops of the campaign model (trace-only
        # jaxpr count, computed once per campaign build)
        self._slots_state: tuple[int, int] = (0, int(self.cfg.slots))
        self._rate_mark: tuple[float, int] = (time.monotonic(), 0)
        self._flops_member: float | None = None

    # -- client surface -------------------------------------------------------

    def submit(self, req: SimRequest | dict) -> SimRequest:
        """Admit one request (validation + bounded-queue admission control;
        raises RequestError / AdmissionError).  Thread-safe — the HTTP
        front calls this from handler threads."""
        if isinstance(req, dict):
            req = SimRequest.from_dict(req)
        elif not isinstance(req, SimRequest):
            from .request import RequestError

            raise RequestError(
                f"request must be a dict or SimRequest, got {type(req).__name__}"
            )
        if req.amp is None:
            req.amp = float(self.cfg.default_amp)
        try:
            self.queue.submit(req, admit_open=not self._drain)
        except AdmissionError as exc:
            _tm.counter(
                "serve_admission_rejected_total",
                "submits rejected by admission control",
                reason=exc.reason,
            ).inc()
            raise
        queued = self.queue.counts()["queued"]
        _tm.counter("serve_requests_admitted_total", "requests admitted").inc()
        _tm.gauge("serve_queue_depth", "requests waiting in queued/").set(queued)
        self._journal(
            {
                "event": "request_admitted",
                "id": req.id,
                "key": list(req.compat_key),
                "steps": req.steps,
                "queued": queued,
            }
        )
        return req

    def status(self, request_id: str) -> dict | None:
        """Lifecycle state + record for one request id (None: unknown)."""
        found = self.queue.lookup(request_id)
        if found is None:
            return None
        state, record = found
        return {"id": request_id, "state": state, **record}

    def result(self, request_id: str) -> dict | None:
        """A done request's result record; raises the typed
        :class:`RequestFailed` for a terminally failed one; None while the
        request is still queued/running."""
        found = self.queue.lookup(request_id)
        if found is None:
            raise KeyError(f"unknown request id {request_id!r}")
        state, record = found
        if state == "done":
            return record["result"]
        if state == "failed":
            err = record["error"]
            raise RequestFailed(request_id, err["reason"], err.get("dts", ()))
        return None

    def request_drain(self) -> None:
        """Ask the service to drain: stop admitting, checkpoint in-flight
        slots, re-enqueue unfinished requests, return from serve()."""
        self._drain = True
        runner = self._runner
        if runner is not None:
            runner.request_drain()

    @property
    def draining(self) -> bool:
        """Public drain flag (the HTTP front's ``/healthz`` reads this —
        handlers must never reach into scheduler internals)."""
        return self._drain

    def slot_info(self) -> dict:
        """Occupancy of the ACTIVE campaign's ensemble lanes (between
        campaigns: 0 running over the configured slot count)."""
        running, total = self._slots_state
        return {
            "running": running,
            "total": total,
            "utilization": (running / total) if total else 0.0,
        }

    def stats(self) -> dict:
        return {
            "queue": self.queue.counts(),
            "completed": self._completed,
            "failed": self._failed,
            "retried": self._retried,
            "member_steps": self._member_steps,
            "wall_s": round(time.monotonic() - self._t0, 3),
            "draining": self._drain,
            "slots": self.slot_info(),
        }

    # -- service loop ---------------------------------------------------------

    def serve(self) -> dict:
        """Run the service until the queue drains (batch mode), or until a
        drain is requested (daemon mode).  Returns a summary dict."""
        self._install_signals()
        self._start_http()
        unclean = self._detect_unclean_shutdown()
        recovered = self.queue.recover()
        self._journal(
            {
                "event": "server_start",
                "slots": self.cfg.slots,
                "max_queue": self.cfg.max_queue,
                "recovered": recovered,
                "unclean_shutdown": unclean,
                "fault": dataclasses.asdict(self._fault) if self._fault else None,
            }
        )
        try:
            while not self._drain:
                key = self._next_bucket()
                if key is None:
                    if self.cfg.idle_exit:
                        break
                    time.sleep(self.cfg.poll_s)
                    continue
                self._run_campaign(key)
            if self._drain:
                self._journal({"event": "drain"})
        finally:
            import sys as _sys

            if _sys.exc_info()[0] is None:
                self._flush_results(force=True)
            elif self._pending_results:
                # an exception (DispatchHang above all) is propagating:
                # forcing the pending observable futures would device_get
                # against a possibly-wedged runtime with no watchdog and eat
                # the structured raise — drop them instead; the requests
                # stay claimed and queue.recover() re-runs them on restart
                self._journal(
                    {
                        "event": "results_abandoned",
                        "batches": len(self._pending_results),
                    }
                )
                self._pending_results = []
            summary = {
                "outcome": "drained" if self._drain else "idle",
                **self.stats(),
                "journal": self.journal_path,
            }
            self._journal({"event": "server_stop", **summary})
            # service-level metrics flush: one jsonl line at the service
            # root (campaign runners dump their own under campaigns/<key>)
            MetricsDumper(
                os.path.join(self.cfg.run_dir, "metrics.jsonl")
            ).dump(step=self._global_step)
            self._journal_writer.close()  # reopens lazily if used again
            self._stop_http()
            self._restore_signals()
        return summary

    def _detect_unclean_shutdown(self) -> bool:
        """True when the previous incarnation died without a server_stop —
        read through the torn-tail-tolerant reader, since the very crash
        being detected may have torn the final journal line."""
        events = [
            r.get("event")
            for r in read_journal(self.journal_path, on_error="skip")
            if r.get("event") in ("server_start", "server_stop")
        ]
        return bool(events) and events[-1] != "server_stop"

    # -- signals / http -------------------------------------------------------

    def _install_signals(self) -> None:
        def handler(signum, frame):
            self.request_drain()

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev_handlers[sig] = signal.signal(sig, handler)
        except ValueError:  # not the main thread
            self._prev_handlers = {}

    def _restore_signals(self) -> None:
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers = {}

    def _start_http(self) -> None:
        if self.cfg.http_port is None:
            return
        from .http_front import HttpFront

        self._http = HttpFront(self, self.cfg.http_host, self.cfg.http_port)
        self._http.start()
        self._journal({"event": "http_listen", "address": self._http.address})

    def _stop_http(self) -> None:
        if self._http is not None:
            self._http.stop()
            self._http = None

    @property
    def http_address(self) -> tuple[str, int] | None:
        return self._http.address if self._http is not None else None

    # -- journal --------------------------------------------------------------

    def _journal(self, event: dict) -> None:
        self._journal_writer.append(
            {"wall_s": round(time.monotonic() - self._t0, 3), **event}
        )

    # -- campaign -------------------------------------------------------------

    def _next_bucket(self) -> tuple | None:
        """Round-robin bucket selection (the fairness half of the ROADMAP
        item): buckets are ordered by their oldest queued request, and the
        pick ROTATES past the previously-served bucket — so under a
        daemon-mode mixed workload a hot bucket whose requests keep
        arriving cannot be re-picked while other buckets wait.  With one
        bucket (or none after it) this degrades to oldest-first."""
        order = self.queue.bucket_order()
        _tm.gauge(
            "serve_bucket_occupancy", "distinct compat buckets with queued work"
        ).set(len(order))
        if not order:
            return None
        if self._last_bucket in order and len(order) > 1:
            i = order.index(self._last_bucket)
            return order[(i + 1) % len(order)]
        return order[0]

    def _campaign_dir(self, key: tuple) -> str:
        tag = hashlib.sha1(repr(key).encode()).hexdigest()[:12]
        return os.path.join(self.cfg.run_dir, "campaigns", tag)

    def _build_runner(self, key: tuple) -> tuple[ResilientRunner, _ServedEnsemble]:
        # the bucket key IS the model spec: kind-prefixed, scenario-signed —
        # the workloads registry builds whatever physics the bucket needs
        # (DNS with/without modifiers, lnse, adjoint)
        model = build_model_for_key(key)
        model.write_intervall = float("inf")  # no flow-file callback IO
        # per-member step flops for the live MFU gauge: the trace-only jaxpr
        # dot count (no extra compile; the entry points were just built)
        try:
            from ..utils.profiling import step_flops

            self._flops_member = step_flops(model, method="jaxpr")
        except Exception:
            self._flops_member = None
        ens = _ServedEnsemble(model, [model.state] * int(self.cfg.slots))
        ens.mark_dead(range(ens.k))  # all lanes idle until a request lands
        rcfg = self.cfg.resilience
        runner = ResilientRunner.from_config(
            ens,
            rcfg,
            max_time=float("inf"),
            save_intervall=None,
            run_dir=self._campaign_dir(key),
            checkpoint_every_s=self.cfg.checkpoint_every_s,
            # divergence policy is PER REQUEST here (backoff re-queue);
            # whole-campaign checkpoint rollback stays the reactive last
            # resort behind it
            max_retries=getattr(rcfg, "max_retries", 3) if rcfg else 3,
            # serve checkpoints must carry the slot table in a manifest:
            # force the sharded two-phase format (single- or multi-process)
            io=IOConfig(sharded_checkpoints=True, overlap_dispatch=False),
            fault="",  # the server owns ONE plan across campaigns (below)
            # NO governor inside a campaign: its batch-wide set_dt would
            # silently rewrite every co-batched request's dt (dt is part of
            # the request contract AND the bucket key) — the per-request
            # dt-backoff retry is the serve-layer stability policy
            stability=None,
        )
        runner.fault = self._fault
        runner.step = self._global_step
        runner.set_journal(self._journal_writer)
        return runner, ens

    def _run_campaign(self, key: tuple) -> None:
        runner, ens = self._build_runner(key)
        self._runner = runner
        self._last_bucket = key  # round-robin cursor
        self._campaign_claims = 0  # fairness quantum consumption
        if self._drain:  # a signal raced the build
            runner.request_drain()
        try:
            with runner.session(install_signals=False, resume=False):
                self._try_resume(runner)
                slots = self._restore_slots(runner, ens, key)
                self._journal(
                    {
                        "event": "campaign_start",
                        "key": list(key),
                        "dir": runner.run_dir,
                        "restored": runner.resumed,
                        "slots_restored": sum(1 for s in slots if s.running),
                    }
                )
                self._fill_slots(runner, ens, slots, key)
                self._refresh_slot_state(slots, ens.k)
                self._campaign_loop(runner, ens, slots, key)
        finally:
            self._global_step = runner.step
            self._runner = None
            self._slots_state = (0, int(self.cfg.slots))

    def _try_resume(self, runner) -> None:
        """Campaign restore with graceful degradation: a checkpoint that no
        longer fits (slot-count/config change between incarnations — the
        sharded format is K-fixed) must NOT brick the service.  The
        incompatible checkpoints are swept (their slot geometry can never
        be restored by this server) and the campaign starts fresh — every
        request is still durably queued, so nothing is lost, only the
        drained progress."""
        try:
            runner.resumed = runner._maybe_resume()
        except checkpoint.CheckpointError as exc:
            self._journal(
                {
                    "event": "campaign_restore_failed",
                    "dir": runner.run_dir,
                    "error": str(exc),
                }
            )
            for path in checkpoint.checkpoint_files(runner.run_dir):
                checkpoint.remove_checkpoint(path)
            runner.resumed = False
            runner._last_ckpt_path = None

    def _restore_slots(self, runner, ens, key: tuple) -> list[_Slot]:
        """Rebuild the slot table after a checkpoint restore: a restored
        slot whose request is back in the queue (drain re-enqueued it, or
        crash recovery did) is RE-CLAIMED into its old lane — the member
        state is already sitting there, bit-equal — and continues from its
        checkpointed step counter.  Restored slots whose request is gone
        (completed after the checkpoint, durably recorded) go idle."""
        slots = [_Slot(i) for i in range(ens.k)]
        meta = ens.restored_meta if runner.resumed else None
        if not meta:
            return slots
        alive = ens.alive()
        for i, m in enumerate(meta[: ens.k]):
            if not m:
                continue
            if not alive[i]:
                # the member was dead in the checkpoint: leave the request
                # queued — a fresh lane (fresh IC) will claim it instead of
                # resuming a doomed trajectory
                ens.serve_meta[i] = None
                continue
            req = self.queue.claim_id(m["id"])
            if req is None:
                # the request resolved after this checkpoint was written
                # (durably recorded in done/): lane reverts to idle
                ens.serve_meta[i] = None
                ens.mark_dead([i])
                continue
            if req.compat_key != key:
                # same id, DIFFERENT bucket: the request diverged after this
                # checkpoint and was re-queued backed off to a new dt — the
                # old-dt member state must not resume it (the consumed retry
                # would never apply the backoff).  Leave it for its new
                # bucket's campaign.
                self.queue.requeue(req)
                ens.serve_meta[i] = None
                ens.mark_dead([i])
                continue
            slots[i].req = req
            slots[i].target = int(m["target"])
            self._journal(
                {
                    "event": "request_scheduled",
                    "id": req.id,
                    "slot": i,
                    "target": slots[i].target,
                    "restored": True,
                    "steps_done": int(np.asarray(ens.steps_done)[i]),
                }
            )
        return slots

    def _refresh_slot_state(self, slots: list[_Slot], total: int) -> None:
        """Keep ``slot_info()`` (/healthz) AND the Prometheus gauge honest
        the moment lanes are claimed/released — not just at chunk
        boundaries, where the first (compile-heavy) chunk would report 0
        running for many seconds and a post-settle sample would
        under-report lanes the refill is about to reclaim."""
        running = sum(1 for s in slots if s.running)
        self._slots_state = (running, total)
        _tm.gauge(
            "serve_slot_utilization", "running slots / campaign slot count"
        ).set((running / total) if total else 0.0)

    def _fill_slots(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        """Refill every idle lane from this bucket's queue (fresh IC via
        the template model's generator; ``set_member`` installs it without
        recompiling).

        Bucket fairness: one campaign visit claims at most
        ``cfg.bucket_quantum`` requests while OTHER buckets hold queued
        work — past the quantum the refill stops, the campaign drains its
        running slots and ends, and the round-robin pick serves the next
        bucket (this bucket's tail gets its next turn).  With no competing
        bucket the quantum is waived (no reason to cycle)."""
        if self._drain:
            return
        quantum = int(self.cfg.bucket_quantum)
        for slot in slots:
            if slot.running:
                continue
            if (
                quantum > 0
                and self._campaign_claims >= quantum
                and self.queue.other_bucket_waiting(key)
            ):
                self._journal(
                    {
                        "event": "bucket_quantum",
                        "key": list(key),
                        "claims": self._campaign_claims,
                    }
                )
                return
            req = self.queue.claim(key)
            if req is None:
                return
            self._campaign_claims += 1
            state = ens.fresh_member_state(req.seed, req.amp or self.cfg.default_amp)
            ens.set_member(slot.index, state)
            slot.req = req
            slot.target = req.steps
            ens.serve_meta[slot.index] = {"id": req.id, "target": slot.target,
                                          "req": json.loads(req.to_json())}
            self._journal(
                {
                    "event": "request_scheduled",
                    "id": req.id,
                    "slot": slot.index,
                    "target": slot.target,
                    "restored": False,
                    "step": runner.step,
                }
            )

    def _boundary_gauges(self) -> None:
        """Refresh the live queue/throughput gauges at one chunk boundary —
        host-side bookkeeping the scheduler already holds (slot occupancy
        is kept by :meth:`_refresh_slot_state` at claim/release time, so
        the gauge and ``slot_info()`` can never disagree)."""
        _tm.gauge("serve_queue_depth", "requests waiting in queued/").set(
            self.queue.counts()["queued"]
        )
        now = time.monotonic()
        mark_t, mark_steps = self._rate_mark
        if now > mark_t and self._member_steps > mark_steps:
            rate = (self._member_steps - mark_steps) / (now - mark_t)
            _tm.gauge(
                "serve_member_steps_per_sec",
                "aggregate member-steps/s across running slots",
            ).set(rate)
            if self._flops_member:
                from ..utils.profiling import PEAK_FLOPS, peak_flops_key

                _tm.gauge(
                    "serve_mfu", "model-flops utilization of the active campaign"
                ).set(self._flops_member * rate / PEAK_FLOPS[peak_flops_key()])
        self._rate_mark = (now, self._member_steps)

    def _campaign_loop(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        while True:
            running = [s for s in slots if s.running]
            if not running:
                break
            done = np.asarray(ens.steps_done)
            n = min(
                min(s.target - int(done[s.index]) for s in running),
                int(self.cfg.chunk_steps),
            )
            n = max(1, n)
            before = runner.step
            with _tr.span("serve_chunk", steps=n, slots=len(running)):
                runner.advance(n)
            advanced = runner.step - before
            self._member_steps += advanced * len(running)
            with _tr.span("serve_settle", step=runner.step):
                self._settle_boundary(runner, ens, slots, key)
            self._refresh_slot_state(slots, ens.k)
            self._boundary_gauges()
            # boundary housekeeping: deferred sharded commit + cadence
            # checkpoint + the drain/preemption flag — runner.on_boundary is
            # the same hook integrate() would drive
            if runner.on_boundary() or self._drain:
                self._drain = True
                self._drain_campaign(runner, ens, slots)
                return
            self._fill_slots(runner, ens, slots, key)
            self._refresh_slot_state(slots, ens.k)
            self._flush_results()
        self._flush_results(force=True)
        self._journal({"event": "campaign_end", "key": list(key),
                       "step": runner.step})
        # a cleanly finished campaign leaves no work to restore: settle the
        # async writer FIRST (a background shard write must never race the
        # sweep), then remove its checkpoints so a LATER campaign in this
        # bucket starts fresh instead of restoring a stale slot table
        runner._drain_io()
        for path in checkpoint.checkpoint_files(runner.run_dir):
            checkpoint.remove_checkpoint(path)

    def _settle_boundary(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        """Process completions and deaths at a chunk boundary.  The
        observables for every slot that finished here ride ONE vmapped
        async dispatch (PR-4 futures) captured BEFORE any lane is refilled,
        so the fetched values are the finished members' final states."""
        alive = ens.alive()
        done = np.asarray(ens.steps_done)
        # a member that stopped advancing via the model's SUCCESS criterion
        # (the adjoint finder's residual convergence) finished early — it is
        # a completion, not a death, even below its step target
        done_ok = ens.done_ok_members()
        finished = [
            s for s in slots
            if s.running and (
                (alive[s.index] and int(done[s.index]) >= s.target)
                or done_ok[s.index]
            )
        ]
        dead = [
            s for s in slots
            if s.running and not alive[s.index] and not done_ok[s.index]
        ]
        if finished:
            obs_fut = ens.get_observables_async()
            names = tuple(ens.observable_names)
            batch = []
            for s in finished:
                batch.append(
                    {
                        "slot": s.index,
                        "req": s.req,
                        "names": names,
                        "steps": int(done[s.index]),
                        "finished_wall": time.time(),
                        "step": runner.step,
                    }
                )
                self._release(ens, s)
            self._pending_results.append((obs_fut, batch))
        for s in dead:
            self._handle_death(runner, ens, s, int(done[s.index]))

    def _release(self, ens, slot: _Slot) -> None:
        """Lane back to idle (masked dead until refilled)."""
        ens.serve_meta[slot.index] = None
        ens.mark_dead([slot.index])
        slot.req = None
        slot.target = 0

    def _handle_death(self, runner, ens, slot: _Slot, steps_done: int) -> None:
        """Per-request divergence policy: bounded dt-backoff retry, then
        the typed terminal state.  The lane itself is immediately reusable
        — one member's NaN never perturbs its co-batched neighbours."""
        req = slot.req
        self._release(ens, slot)
        if req.retries < self.cfg.request_max_retries:
            retry = req.backed_off(self.cfg.request_dt_backoff)
            self.queue.requeue(retry)
            self._retried += 1
            _tm.counter(
                "serve_requests_retried_total", "diverged requests re-queued backed off"
            ).inc()
            self._journal(
                {
                    "event": "request_retry",
                    "id": req.id,
                    "slot": slot.index,
                    "steps_done": steps_done,
                    "dt": retry.dt,
                    "retries": retry.retries,
                }
            )
        else:
            reason = (
                f"diverged at member-step {steps_done}/{req.steps} and "
                f"exhausted {self.cfg.request_max_retries} retries"
            )
            self.queue.fail(req, reason)
            self._failed += 1
            _tm.counter(
                "serve_requests_failed_total", "requests in the typed terminal state"
            ).inc()
            self._journal(
                {
                    "event": "request_failed",
                    "id": req.id,
                    "slot": slot.index,
                    "reason": reason,
                    "dts": req.dts,
                }
            )

    def _flush_results(self, force: bool = False) -> None:
        """Resolve finished-request observable futures and write the done
        records.  Non-blocking by default (a future still in flight stays
        pending — the stream, not the device, waits); ``force`` resolves
        everything (campaign end / server stop)."""
        keep = []
        for fut, batch in self._pending_results:
            if not force and not fut.ready():
                keep.append((fut, batch))
                continue
            values = fut.result()
            for item in batch:
                req: SimRequest = item["req"]
                i = item["slot"]
                # result scalars carry the MODEL's observable vocabulary
                # (dns: nu/nuvol/re/div; lnse: energy/ke/te/div; adjoint:
                # res/res_u/res_t/div) — recorded under those names
                names = item["names"]
                result = {
                    name: float(vals[i]) for name, vals in zip(names, values)
                }
                result.update(
                    {
                        "model": str(req.model),
                        "steps": item["steps"],
                        "dt": float(req.dt),
                        "seed": int(req.seed),
                        # IC amplitude rides the record so solo-equivalence
                        # checks rerun the exact trajectory
                        "amp": float(req.amp) if req.amp else None,
                        "retries": int(req.retries),
                        "slot": i,
                        "latency_s": round(
                            item["finished_wall"] - req.submitted_s, 6
                        ),
                    }
                )
                self.queue.complete(req, result)
                self._completed += 1
                _tm.counter(
                    "serve_requests_completed_total", "requests resolved into done/"
                ).inc()
                _tm.histogram(
                    "serve_request_latency_seconds",
                    "submit-to-finish latency per completed request",
                ).observe(result["latency_s"])
                self._journal(
                    {
                        "event": "request_done",
                        "id": req.id,
                        "slot": i,
                        "steps": item["steps"],
                        names[0]: result[names[0]],
                        "latency_s": result["latency_s"],
                        "step": item["step"],
                    }
                )
        self._pending_results = keep

    def _drain_campaign(self, runner, ens, slots: list[_Slot]) -> None:
        """The graceful-drain path: flush resolved results, checkpoint the
        slot table + member states through the sharded two-phase writer,
        then re-enqueue every unfinished request (progress stamped for the
        record; the checkpoint is what actually restores it)."""
        self._flush_results(force=True)
        _tr.instant("drain", step=runner.step)
        running = [s for s in slots if s.running]
        path = None
        if running:
            path = runner.checkpoint_now("drain")
        done = np.asarray(ens.steps_done)
        for s in running:
            req = dataclasses.replace(s.req, progress=int(done[s.index]))
            self.queue.requeue(req)
            self._journal(
                {
                    "event": "request_requeued",
                    "id": req.id,
                    "slot": s.index,
                    "progress": req.progress,
                    "target": s.target,
                    "checkpoint": path,
                }
            )
        runner._drain_io()
        # the SIGTERM-drain incident ships with its timeline, like the
        # standalone runner's preempt path
        runner.incident_dump("drain")
