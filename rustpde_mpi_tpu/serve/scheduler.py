"""Continuously-batched ensemble scheduler: the fault-isolated simulation
service.

The serving layer the ROADMAP's multi-tenant north star needs: a
persistent driver that accepts :class:`~.request.SimRequest` work through
the durable queue (serve/queue.py, plus the thin HTTP front in
serve/http_front.py), bucket-batches compatible requests into
:class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` slots, and
streams per-request observables back through PR-4 observable futures as
each request resolves.  The batching is LLM-style CONTINUOUS batching:

* requests bucket by :attr:`SimRequest.compat_key` (the operator constants
  one compiled vmapped step can serve: grid, Ra/Pr, dt, geometry, BC),
* a campaign opens one K-slot ensemble per bucket; each chunk advances
  every running slot together as ONE donated vmapped dispatch,
* the chunk length is ``min(remaining steps of any running slot,
  chunk_steps)``, so completions land exactly on chunk boundaries,
* a finished, diverged or idle slot is REFILLED from the queue at the
  boundary via ``set_member`` — the existing respawn machinery — without
  recompiling anything (equal keys share the jaxpr by construction).

Robustness is the spec, not a bolt-on:

* **per-request fault isolation** — one member's NaN freezes that member
  only (the ensemble's per-member finite mask); co-batched requests keep
  stepping bit-exactly like their solo runs (CI-asserted),
* **per-request retry** — a diverged request is re-queued at
  ``dt * request_dt_backoff`` (a different bucket: dt is an operator
  constant) with a bounded budget, then lands in the typed
  :class:`~.request.RequestFailed` terminal state,
* **admission control** — the queue bounds admissions and a submit past
  the bound is rejected with a reason (queue.py),
* **graceful drain** — SIGTERM (or :meth:`SimServer.request_drain`)
  finishes the in-flight chunk, checkpoints every slot via the sharded
  two-phase writer — WITH the slot table riding the manifest as
  digest-covered root data — re-enqueues unfinished requests and exits
  clean,
* **crash recovery** — on restart the queue re-enqueues whatever was
  ``running`` (accepted requests are never lost) and the campaign restore
  rebuilds the slot table from the newest valid checkpoint, so drained or
  killed requests resume mid-trajectory instead of restarting.

The device-facing machinery is the embedded
:class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner` (its
``session``/``advance``/``checkpoint_now`` surface): fault injection,
dispatch watchdogs, the async/sharded checkpoint pipeline and the journal
all come from there — the service adds scheduling, not a second harness.

**Multihost campaigns** (root-coordinated scheduling): every process of a
multi-process mesh runs ``serve()`` together, but the durable queue, the
journal, the HTTP front and result flushing are ROOT-ONLY, and every
per-boundary decision the scheduler makes — bucket selection, slot
claim/refill assignments, completion/death verdicts, chunk length, the
dt-re-bucket plan, the drain flag — is computed on root and broadcast
(:func:`~rustpde_mpi_tpu.parallel.multihost.broadcast_obj`) BEFORE any
collective dispatch, exactly the treatment the runner's cadence decisions
already get.  Every host therefore executes the identical
``set_member``/``mark_dead``/``update_n`` sequence, ``sync_hosts`` fences
service start/stop and campaign open/close, and the two-phase slot-table
checkpoint carries the state.

**Elastic fleets**: a restart may resize ``cfg.slots``.  The scheduler
peeks the checkpoint's member count first, restores onto a fleet of THAT
size (topology-elastic restore reassembles the state onto whatever mesh
this incarnation has), then RE-PLANS onto the configured size: kept
requests move into the new lanes mid-trajectory (``set_member``), surplus
requests (shrink) are parked — member state held for the lane that will
next claim them — and re-enqueued at their checkpointed progress, grown
fleets refill the extra lanes from the queue, and a ``campaign_replanned``
journal event records old/new K.

**Governed campaign dt** (``cfg.stability``): per-request dt is part of
the request contract AND the bucket key, so the batch-wide governor stays
off; instead the on-device CFL sentinels are armed and a per-bucket
:class:`~rustpde_mpi_tpu.utils.governor.DtLadder` turns a ceiling catch
into a PROACTIVE re-bucket — the chunk was already rolled back in memory
while every member is still finite, the pinned requests are requeued WITH
their state at the next rung down (journal ``bucket_dt_adjust``), and the
reactive NaN + retry path remains the last resort underneath.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import threading
import time

import numpy as np

from .. import config as _config
from ..config import IOConfig, ServeConfig, env_get
from ..models.ensemble import NavierEnsemble
from ..parallel import submesh as _sm
from ..telemetry import compile_log as _cl
from ..telemetry import metrics as _tm
from ..telemetry import reqtrace as _rt
from ..telemetry import tracing as _tr
from ..telemetry.exporters import MetricsDumper
from ..utils import checkpoint
from ..workloads.registry import build_model_for_key
from ..utils.faults import FaultPlan, validate_fault_env
from ..utils.journal import JournalWriter, read_journal
from ..integrity import IntegrityError
from ..utils.resilience import DispatchHang, ResilientRunner
from .fleet.gang import GangMemberLost
from .queue import DurableQueue
from .request import AdmissionError, RequestFailed, SimRequest


class _ServedEnsemble(NavierEnsemble):
    """Ensemble whose checkpoints are self-describing for the scheduler:
    ``serve_meta`` (one dict per slot: request json + step target, None =
    idle) rides the sharded manifest as digest-covered root data, so a
    restore rebuilds the slot table from the checkpoint alone — no side
    file that could go stale against the state it describes."""

    def __init__(self, model, states):
        super().__init__(model, states)
        self.serve_meta: list[dict | None] = [None] * self.k
        self.restored_meta: list[dict | None] | None = None

    def snapshot_root_items(self) -> list:
        items = super().snapshot_root_items()
        blob = np.frombuffer(
            json.dumps(self.serve_meta).encode("utf-8"), np.uint8
        ).copy()
        items.append(("serve_slots", blob, "raw"))
        return items

    def apply_restored_state(self, updates, attrs, root) -> None:
        super().apply_restored_state(updates, attrs, root)
        if "serve_slots" in root:
            meta = json.loads(bytes(np.asarray(root["serve_slots"])).decode("utf-8"))
            self.serve_meta = meta
            self.restored_meta = meta


def _transport_death(exc: BaseException) -> bool:
    """A collective-transport failure that means a PEER process died (gloo
    connection reset, socket closed, coordination-service abort): the
    survivors' view of a gang member's death when it strikes mid-dispatch
    instead of at a gang barrier."""
    msg = str(exc).lower()
    return any(
        marker in msg
        for marker in (
            "connection reset",
            "connection refused",
            "socket closed",
            "gloo",
            "coordination service",
            "distributed service",
        )
    )


@dataclasses.dataclass
class _Slot:
    """One ensemble lane: IDLE (masked dead, waiting for work) or RUNNING
    a request toward ``target`` TOTAL member-steps.  ``base`` counts steps
    the trajectory completed in EARLIER lane assignments (an elastic
    re-plan or a dt re-bucket resets the ensemble's per-member counter via
    ``set_member``), and ``time_base`` the sim-time those steps covered —
    possibly at a different dt than the current bucket's — so total
    progress is ``base + steps_done[index]`` and completion is
    ``base + steps_done >= target``."""

    index: int
    req: SimRequest | None = None
    target: int = 0
    base: int = 0
    time_base: float = 0.0

    @property
    def running(self) -> bool:
        return self.req is not None


class SimServer:
    """The service front: durable queue + continuous-batching scheduler.

    Batch mode (``cfg.idle_exit=True``, the default) drains the queue and
    returns a summary; daemon mode keeps polling for new work (the HTTP
    front feeds the queue concurrently) until :meth:`request_drain` or
    SIGTERM.  One instance per process — it installs signal handlers while
    :meth:`serve` runs."""

    def __init__(self, cfg: ServeConfig | None = None, *, fault: str | None = None):
        self.cfg = cfg or ServeConfig()
        validate_fault_env()  # malformed chaos specs die here, not silently
        self.queue = DurableQueue(
            os.path.join(self.cfg.run_dir, "queue"), max_queue=self.cfg.max_queue
        )
        # fleet mode (cfg.fleet): this server is ONE replica of a fleet
        # sharing run_dir — its journal/campaigns/metrics move under
        # replicas/<id>/ (the queue + leases + parked continuations stay
        # shared), buckets are claimed through queue-level leases, and
        # parked member states persist durably.  fleet=None leaves every
        # path below byte-identical to the single-replica behavior.
        self._fleet = self.cfg.fleet
        self._lease = None  # the ACTIVE campaign's bucket lease (root)
        self._lease_mgr = None
        self._fenced = False  # lost our lease mid-campaign (root flag)
        self._claims_closed = False  # cross-bucket preemption: drain, don't refill
        self._hb_mark = 0.0
        self._cont_mark = 0.0  # cadence mark for running-slot continuations
        # lease liveness must not ride the campaign loop's cadence: a
        # model build or first-chunk compile stalls boundaries for many
        # seconds, which would read as replica death and thrash the
        # fleet with spurious breaks.  Root runs a daemon HEARTBEAT
        # THREAD instead (pure host-side file IO — never a collective):
        # process alive == lease renewed, exactly the failure-detector
        # semantics the sweep wants.  _hb_lock serializes the thread
        # against the main loop's claim/release/fence transitions.
        self._hb_lock = threading.Lock()
        self._hb_stop: threading.Event | None = None
        self._hb_thread: threading.Thread | None = None
        self._preempted = 0
        self._quota_rejected = 0
        self._leases_broken = 0
        self._continuations = 0
        if self._fleet is not None:
            self._replica_id = self._fleet.resolved_replica_id()
            self._replica_dir = os.path.join(
                self.cfg.run_dir, "replicas", self._replica_id
            )
            self.journal_path = os.path.join(self._replica_dir, "journal.jsonl")
        else:
            self._replica_id = ""
            self._replica_dir = self.cfg.run_dir
            self.journal_path = os.path.join(self.cfg.run_dir, "journal.jsonl")
        self._journal_writer = JournalWriter(self.journal_path)
        if self._fleet is not None:
            from .fleet.lease import LeaseManager

            self._lease_mgr = LeaseManager(
                os.path.join(self.cfg.run_dir, "queue", "leases"),
                self._replica_id,
                self._fleet.resolved_ttl(),
                journal=self._journal,
            )
        self._fault = FaultPlan.from_spec(
            fault if fault is not None else env_get("RUSTPDE_FAULT")
        )
        self._drain = False
        # preemption notice (RUSTPDE_PREEMPT_NOTICE_S, fleet mode): a
        # SIGTERM arms a monotonic deadline; the drain path then parks
        # running slots as durable continuations instead of the full
        # campaign checkpoint — sized to finish inside the window, with
        # the already-loss-free SIGKILL path as the clock-ran-out
        # fallback.  The handler only sets the deadline: journaling is
        # deferred to the next safe point (_log_preempt_notice).
        self._notice_s = float(env_get("RUSTPDE_PREEMPT_NOTICE_S") or 0.0)
        self._notice_deadline: float | None = None
        self._notice_logged = False
        # embedded fleet autoscaler (cfg.autoscale; None = nothing runs)
        self._autoscaler = None
        self._runner: ResilientRunner | None = None
        # bucket fairness: the key served by the previous campaign (the
        # round-robin cursor) + this campaign's claim budget consumption
        self._last_bucket: tuple | None = None
        self._campaign_claims = 0
        self._t0 = time.monotonic()
        self._global_step = 0  # member-chunk steps across campaigns
        self._member_steps = 0  # aggregate member-steps actually computed
        self._completed = 0
        self._failed = 0
        self._retried = 0
        self._pending_results: list[tuple] = []  # (obs_future, [(slot,req,..)])
        # sub-mesh campaign fence (multihost.set_device_fence): the active
        # campaign's ensemble plus every boundary dispatch whose future is
        # still unfetched — blocked on before any host-level collective so
        # full-device barriers cannot interleave with sub-mesh programs
        self._fence_ens = None
        self._inflight_futs: list = []
        self._prev_handlers: dict = {}
        self._http = None
        # live serve telemetry (telemetry/metrics.py): slot occupancy of the
        # ACTIVE campaign, the member-rate mark for the steps/s + MFU gauges,
        # and the per-member step flops of the campaign model (trace-only
        # jaxpr count, computed once per campaign build)
        self._slots_state: tuple[int, int] = (0, int(self.cfg.slots))
        self._rate_mark: tuple[float, int] = (time.monotonic(), 0)
        self._flops_member: float | None = None
        # compile/device attribution bookkeeping (telemetry/compile_log):
        # the active bucket's label, the campaign-open stamp the
        # time-to-first-chunk histogram measures from, and its one-shot flag
        self._bucket_tag = ""
        self._campaign_open = time.monotonic()
        self._first_chunk_done = True
        # parked mid-flight member states: request id -> (state pytree,
        # steps completed, sim time completed).  An elastic shrink or a dt
        # re-bucket releases a lane but keeps the trajectory — the next
        # lane to claim the id continues it instead of restarting.  Every
        # host holds the identical dict (parking decisions are broadcast;
        # the states are the same replicated/sharded device arrays).
        self._parked: dict[str, tuple] = {}
        self._replans = 0
        self._dt_adjusts = 0  # proactive bucket_dt_adjust events
        # two-level serving (cfg.submesh, parallel/submesh.py): the lazily
        # carved device plan, the mesh cache per carved slice, the ACTIVE
        # campaign's mesh + local-device share (telemetry), and the gang
        # chapter of the running campaign — placement resolved at model
        # build, lease group formed at open, fault scope bound for the
        # campaign's duration.  submesh=None leaves ALL of it inert: no
        # plan is carved, no gang row is journaled (CI-asserted).
        self._submesh = self.cfg.submesh
        # warm campaign pool (cfg.warm_profile, serve/warmpool.py): prebuilt
        # campaigns handed over at bucket-open; None = inert (the default)
        self._warm = None
        # admission canonicalization (cfg.canonicalize): the service-wide
        # dt ladder requests are snapped onto; None = exact-dt admission
        self._canon_ladder = None
        if self.cfg.canonicalize is not None:
            from ..utils.governor import DtLadder

            canon = self.cfg.canonicalize
            self._canon_ladder = DtLadder(
                canon.dt_anchor,
                ratio=canon.ladder_ratio,
                dt_min=canon.dt_min,
                dt_max=canon.dt_max,
            )
        self._submesh_plan: _sm.SubmeshPlan | None = None
        self._submesh_meshes: dict[int, object] = {}
        # flipped by _contain_integrity when a device of THIS replica is
        # quarantined: the heartbeat carries it so the fleet proxy routes
        # new work to healthy replicas (the autoscaler replaces us)
        self._integrity_unhealthy = False
        self._active_mesh = None
        self._active_share: tuple[int, int] | None = None
        self._gang_placement: tuple | None = None  # (Submesh, replanned)
        self._gang_active: dict | None = None
        self._gang_lease = None  # fate-shared lease group (root, fleet)
        self._gangs_formed = 0
        self._gang_members_lost = 0

    # -- multihost coordination ----------------------------------------------

    @staticmethod
    def _nproc() -> int:
        try:
            import jax

            return int(jax.process_count())
        except Exception:
            return 1

    @staticmethod
    def _is_root() -> bool:
        try:
            from ..parallel import multihost

            return multihost.is_root()
        except Exception:
            return True

    def _root_plan(self, build):
        """Compute one JSON-able scheduling decision on ROOT and broadcast
        it, so every host executes the identical collective sequence (the
        queue and the host-fetched counters may only be consulted inside
        ``build``, which runs on root alone).  Identity single-process."""
        if self._nproc() == 1:
            return build()
        from ..parallel import multihost

        return multihost.broadcast_obj(build() if multihost.is_root() else None)

    def _root_decides(self, local: bool) -> bool:
        """Root's flag, broadcast (drain/stop handshakes) — the shared
        :func:`~rustpde_mpi_tpu.parallel.multihost.root_decides` primitive
        the runner's cadence/preempt handshakes also ride."""
        from ..parallel import multihost

        return multihost.root_decides(local)

    def _sync(self, tag: str) -> None:
        """Cross-host fence (service start/stop, campaign open/close)."""
        if self._nproc() == 1:
            return
        from ..parallel import multihost

        multihost.sync_hosts(tag)

    def _device_fence(self) -> None:
        """Block until the active campaign's device dispatches complete —
        installed via :func:`~rustpde_mpi_tpu.parallel.multihost
        .set_device_fence` while a campaign occupies a PROPER sub-mesh.
        Host-level collectives run over EVERY device, so on a sub-mesh
        campaign their executables start immediately on the idle complement
        and the wire traffic interleaves nondeterministically with the
        campaign's in-flight collectives on the same transport pairs (gloo
        aborts with a size-mismatched op).  A full-mesh campaign never
        needs this — the barrier cannot start until the step program
        releases the devices — which is why the fence is armed only when
        ``cfg.submesh`` carves the fleet."""
        ens = self._fence_ens
        if ens is not None:
            ens.device_fence()
        futs, self._inflight_futs = self._inflight_futs, []
        for fut in futs:
            fut.result()

    def _arm_device_fence(self, ens) -> None:
        """Arm (or re-point, on a fleet swap/replan) the sub-mesh fence."""
        if self._submesh is None or self._nproc() == 1:
            return
        from ..parallel import multihost

        self._fence_ens = ens
        multihost.set_device_fence(self._device_fence)

    def _disarm_device_fence(self, drain: bool = True) -> None:
        """Remove the fence at campaign teardown.  ``drain=False`` on the
        gang-loss path: in-flight sub-mesh programs can never complete (a
        peer is dead), so containment must not block on them.  Every other
        exit drains first, so the campaign-close barrier and the next
        campaign's collectives start with an idle wire."""
        if self._fence_ens is None:
            return
        from ..parallel import multihost

        multihost.set_device_fence(None)
        if drain:
            try:
                self._device_fence()
            except Exception:
                pass  # poisoned buffers on an exceptional exit: disarm anyway
        self._fence_ens = None
        self._inflight_futs = []

    # -- client surface -------------------------------------------------------

    def submit(self, req: SimRequest | dict) -> SimRequest:
        """Admit one request (validation + bounded-queue admission control;
        raises RequestError / AdmissionError).  Thread-safe — the HTTP
        front calls this from handler threads."""
        if isinstance(req, dict):
            req = SimRequest.from_dict(req)
        elif not isinstance(req, SimRequest):
            from .request import RequestError

            raise RequestError(
                f"request must be a dict or SimRequest, got {type(req).__name__}"
            )
        if req.amp is None:
            req.amp = float(self.cfg.default_amp)
        if self._canon_ladder is not None:
            self._canonicalize(req)
        if (
            self.queue.dedupe_lookup(getattr(req, "idempotency_key", None))
            is not None
        ):
            # a retry of already-accepted work: admission policy (quota,
            # sub-mesh stamping, backpressure) must not re-judge it — the
            # queue replays the original submit's identity, nothing is
            # enqueued, and the front re-acks the first answer
            return self._ack_deduped(self.queue.submit(req))
        if self._submesh is not None:
            # two-level serving admission: stamp the sub-mesh shape the
            # grid needs (compat_key gains the stamp, so sharded buckets
            # never co-batch with vmapped ones) or reject TYPED — a grid
            # no configured shape fits is a 400 at POST time, never a
            # durable poison pill; a full sharded backlog is a 429 whose
            # Retry-After scales with the live queue depth
            from .fleet import qos as _qos

            try:
                self.queue.invalidate()
                pending = sum(
                    1
                    for _, r in self.queue.snapshot_queued()
                    if int(getattr(r, "submesh", 0)) > 0
                )
                req = _qos.admit_submesh(req, pending, self._submesh)
            except (AdmissionError, ValueError) as exc:
                reason = getattr(exc, "reason", None)
                if reason not in ("no_submesh", "capacity"):
                    raise
                _tm.counter(
                    "serve_admission_rejected_total",
                    "submits rejected by admission control",
                    reason=reason,
                ).inc()
                self._journal(
                    {
                        "event": "submesh_rejected",
                        "id": req.id,
                        "reason": reason,
                        "grid": [int(req.nx), int(req.ny)],
                    }
                )
                raise
        if self._fleet is not None:
            # the QoS quota half of the traffic contract: one tenant's
            # burst degrades into typed 429s before it can starve peers
            from .fleet import qos as _qos

            try:
                # refresh first: proxies + peer replicas write the shared
                # dir behind this process's listing cache, and a stale
                # census would under-count the tenant (the proxy path
                # invalidates before its quota check for the same reason)
                self.queue.invalidate()
                _qos.check_quota(req, self.queue.tenant_counts(), self._fleet)
            except AdmissionError as exc:
                self._quota_rejected += 1
                _tm.counter(
                    "serve_admission_rejected_total",
                    "submits rejected by admission control",
                    reason=exc.reason,
                ).inc()
                self._journal(
                    {
                        "event": "quota_rejected",
                        "id": req.id,
                        "tenant": req.tenant,
                        "priority": req.priority,
                    }
                )
                raise
        try:
            self.queue.submit(req, admit_open=not self._drain)
        except AdmissionError as exc:
            _tm.counter(
                "serve_admission_rejected_total",
                "submits rejected by admission control",
                reason=exc.reason,
            ).inc()
            raise
        if getattr(req, "deduped", False):
            # lost a concurrent same-key race inside queue.submit
            return self._ack_deduped(req)
        queued = self.queue.counts()["queued"]
        _tm.counter("serve_requests_admitted_total", "requests admitted").inc()
        _tm.gauge("serve_queue_depth", "requests waiting in queued/").set(queued)
        self._journal(
            {
                "event": "request_admitted",
                "id": req.id,
                "trace_id": req.trace_id,
                "key": list(req.compat_key),
                "steps": req.steps,
                "queued": queued,
            }
        )
        return req

    def _ack_deduped(self, req: SimRequest) -> SimRequest:
        """Journal + count one idempotent-retry hit; the returned request
        bears the ORIGINAL submit's id/trace (queue._dedupe_into)."""
        _tm.counter(
            "serve_requests_deduped_total",
            "retries answered from the idempotency index",
        ).inc()
        self._journal(
            {
                "event": "request_deduped",
                "id": req.id,
                "trace_id": req.trace_id,
                "idempotency_key": req.idempotency_key,
            }
        )
        return req

    def _canonicalize(self, req: SimRequest) -> None:
        """Admission canonicalization (cfg.canonicalize): snap ``req.dt``
        onto the service-wide dt ladder so the live compat-key space stays
        small enough for the warm pool to cover traffic.  The contract
        (README "Cold starts"): admission may move dt (within
        ``max_rel_dt_shift``, journaled ``request_canonicalized``, result
        within the documented rtol) but NEVER the simulated horizon —
        ``SimRequest.steps`` derives from horizon/dt, so the step count
        re-derives at the same physical end time — nor the physics of the
        key, seeds, priority, or deadlines.  An off-ladder dt outside the
        shift bound keeps its exact value and pays its own compile."""
        canon = self.cfg.canonicalize
        dt0 = float(req.dt)
        try:
            rung = self._canon_ladder.rung_for(dt0)
            dt1 = float(self._canon_ladder.dt(rung))
        except (ValueError, ZeroDivisionError):
            return
        if dt1 == dt0:
            return
        if abs(dt1 - dt0) / dt0 > float(canon.max_rel_dt_shift):
            return
        req.dt = dt1
        _tm.counter(
            "serve_requests_canonicalized_total",
            "requests whose dt admission snapped onto the service ladder",
        ).inc()
        self._journal(
            {
                "event": "request_canonicalized",
                "id": req.id,
                "dt_from": dt0,
                "dt_to": dt1,
                "rung": int(rung),
                "steps": req.steps,
            }
        )

    def _canonical_k(self) -> int:
        """The campaign slot count after canonicalization: ``cfg.slots``
        rounded UP to the nearest configured pool size (extra lanes start
        dead and refill from the queue like any other slot), so prebuilt
        warm-pool ensembles fit live campaigns."""
        k = int(self.cfg.slots)
        canon = self.cfg.canonicalize
        if canon is None or not canon.slot_sizes:
            return k
        sizes = sorted(int(s) for s in canon.slot_sizes)
        for size in sizes:
            if size >= k:
                return size
        return sizes[-1]

    def status(self, request_id: str) -> dict | None:
        """Lifecycle state + record for one request id (None: unknown)."""
        found = self.queue.lookup(request_id)
        if found is None:
            return None
        state, record = found
        return {"id": request_id, "state": state, **record}

    def result(self, request_id: str) -> dict | None:
        """A done request's result record; raises the typed
        :class:`RequestFailed` for a terminally failed one; None while the
        request is still queued/running."""
        found = self.queue.lookup(request_id)
        if found is None:
            raise KeyError(f"unknown request id {request_id!r}")
        state, record = found
        if state == "done":
            return record["result"]
        if state == "failed":
            err = record["error"]
            raise RequestFailed(request_id, err["reason"], err.get("dts", ()))
        return None

    def request_trace(self, request_id: str) -> dict | None:
        """One request's assembled Perfetto timeline (admission → queued →
        scheduled → chunks → re-bucket → done, across incarnations) from
        durable state alone — ``GET /requests/<id>/trace`` serves this.
        None for an unknown request; thread-safe (reads files only)."""
        return _rt.assemble_request_trace(self.cfg.run_dir, request_id)

    def profile_capture(self, seconds: float = 5.0) -> dict:
        """Start an on-demand ``jax.profiler`` capture into
        ``<run_dir>/profiles/`` (``POST /profile?seconds=N``); bounded by
        ``RUSTPDE_PROFILE_MAX_S``, single-flight (a second request while
        one runs is refused in the status payload)."""
        logdir = os.path.join(self.cfg.run_dir, "profiles", "manual")
        status = _cl.CAPTURE.start(logdir, seconds, reason="http")
        self._journal({"event": "profile_capture", **status})
        return status

    def request_drain(self) -> None:
        """Ask the service to drain: stop admitting, checkpoint in-flight
        slots, re-enqueue unfinished requests, return from serve()."""
        self._drain = True
        runner = self._runner
        if runner is not None:
            runner.request_drain()

    @property
    def draining(self) -> bool:
        """Public drain flag (the HTTP front's ``/healthz`` reads this —
        handlers must never reach into scheduler internals)."""
        return self._drain

    def slot_info(self) -> dict:
        """Occupancy of the ACTIVE campaign's ensemble lanes (between
        campaigns: 0 running over the configured slot count), plus the
        fleet shape — process count and mesh topology — so an operator
        probing ``/healthz`` sees WHAT is serving, not just that it is."""
        running, total = self._slots_state
        info = {
            "running": running,
            "total": total,
            "utilization": (running / total) if total else 0.0,
            "process_count": self._nproc(),
        }
        try:
            import jax

            info["devices"] = int(jax.device_count())
        except Exception:
            info["devices"] = 1
        mesh = self._campaign_mesh()
        info["mesh"] = (
            {
                "shape": [int(n) for n in mesh.devices.shape],
                "axes": [str(a) for a in mesh.axis_names],
            }
            if mesh is not None
            else None
        )
        return info

    def _campaign_mesh(self, key: tuple | None = None):
        """The mesh campaign models are built on.

        Single-level serving (``cfg.submesh=None``, the default): the
        global pencil mesh on a multi-process runtime (the scheduler's
        collective dispatches must span every host's devices), None
        single-controller — byte-identical to the pre-sub-mesh behavior.

        Two-level serving: the bucket ``key`` resolves through the carved
        :class:`~rustpde_mpi_tpu.parallel.submesh.SubmeshPlan` — a stamped
        (gang) bucket is PLACED onto its carved sub-mesh (elastically
        re-mapped when the fleet shrank under the stamp, recorded for the
        ``gang_replanned`` journal row), vmapped default traffic rides the
        remainder slice when its grid divides onto it.  ``key=None`` (the
        ``/healthz`` probe between builds) reports the ACTIVE campaign's
        mesh."""
        if self._submesh is None:
            if self._nproc() == 1:
                return None
            if not hasattr(self, "_mesh_cache"):
                from ..parallel import multihost

                self._mesh_cache = multihost.global_pencil_mesh()
            return self._mesh_cache
        if key is None:
            return self._active_mesh
        plan = self._carve_plan()
        shape = _sm.key_shape(key)
        self._gang_placement = None
        self._active_share = None
        if shape > 0:
            sub, replanned = plan.place(int(key[1]), int(key[2]), shape)
            if sub is None:
                # fleet too small for ANY carved slice: the default
                # remainder (or solo) serves it unsharded — the request
                # still resolves, only the sharding is waived
                sub, replanned = plan.default, plan.default is not None
            self._gang_placement = (sub, bool(replanned))
            self._active_mesh = self._submesh_mesh(sub)
            return self._active_mesh
        sub = plan.default
        if (
            sub is not None
            and self._nproc() > 1
            and _sm.grid_fits(int(key[1]), int(key[2]), len(sub.devices))
        ):
            self._active_mesh = self._submesh_mesh(sub)
        elif self._nproc() > 1:
            # the vmapped grid divides no carved remainder: fall back to
            # the whole-fleet pencil mesh (servability beats isolation
            # for unstamped traffic)
            if not hasattr(self, "_mesh_cache"):
                from ..parallel import multihost

                self._mesh_cache = multihost.global_pencil_mesh()
            self._active_mesh = self._mesh_cache
        else:
            self._active_mesh = None  # single-controller vmapped path
        return self._active_mesh

    def _carve_plan(self) -> _sm.SubmeshPlan:
        """The carved device plan, built once per incarnation.  Every
        process derives the IDENTICAL plan from the globally-consistent
        ``jax.devices()`` order — and from the root-broadcast quarantine
        verdict: devices the integrity ledger quarantined are excluded
        from the carve, so later campaigns route around suspect silicon.
        A restart after a fleet resize re-carves automatically (the
        elastic re-planner: stamped buckets re-place through
        ``plan.place``); an integrity quarantine drops the cached plan
        (:meth:`_contain_integrity`) to force the same re-carve."""
        if self._submesh_plan is None:
            try:
                import jax

                devices = jax.devices()
            except Exception:
                devices = []
            bad = self._quarantined_devices()
            if bad and devices:
                keep = [
                    d
                    for d in devices
                    if "%s:%s@proc%s"
                    % (
                        getattr(d, "platform", "cpu"),
                        getattr(d, "id", 0),
                        int(getattr(d, "process_index", 0)),
                    )
                    not in bad
                ]
                # never carve an EMPTY fleet: with every device struck the
                # quarantine is waived (servability beats suspicion) and
                # the journal row records the overridden verdict
                if keep and len(keep) < len(devices):
                    devices = keep
                self._journal(
                    {
                        "event": "carve_excluded_quarantined",
                        "devices": sorted(bad),
                        "kept": len(devices),
                        "waived": not keep,
                    }
                )
            self._submesh_plan = _sm.carve(
                devices, self._submesh.shapes, nproc=self._nproc()
            )
        return self._submesh_plan

    def _quarantined_devices(self) -> frozenset:
        """The durable quarantine verdict (integrity/ledger.py), read on
        ROOT and broadcast — the carve below must be identical on every
        host, and the ledger file lives in root's run dir."""

        def read():
            from ..integrity import QuarantineLedger

            icfg = self.cfg.integrity
            led = QuarantineLedger(
                self.cfg.run_dir,
                strikes=icfg.strikes if icfg else 2,
                strike_ttl_s=icfg.strike_ttl_s if icfg else 3600.0,
            )
            return list(led.quarantined())

        return frozenset(self._root_plan(read))

    def _submesh_mesh(self, sub):
        """The (cached) jax Mesh over one carved slice; None for an empty
        slice or a single-device slice on a single-controller runtime
        (the plain vmapped path needs no mesh)."""
        if sub is None or not sub.devices:
            return None
        if self._nproc() == 1 and len(sub.devices) <= 1:
            return None
        if sub.index not in self._submesh_meshes:
            self._submesh_meshes[sub.index] = sub.mesh()
        self._active_share = self._local_share(sub)
        return self._submesh_meshes[sub.index]

    def _local_share(self, sub) -> tuple[int, int] | None:
        """(this host's devices inside ``sub``, this host's total local
        devices) — the fleet-utilization gauges report the sub-mesh's
        share of the fleet, not all-or-nothing."""
        try:
            import jax

            pidx = int(jax.process_index())
            total = int(jax.local_device_count())
        except Exception:
            return None
        mine = sum(
            1
            for d in (sub.devices if sub is not None else ())
            if int(getattr(d, "process_index", 0)) == pidx
        )
        return (mine, total)

    def stats(self) -> dict:
        out = {
            "queue": self.queue.counts(),
            "completed": self._completed,
            "failed": self._failed,
            "retried": self._retried,
            "replans": self._replans,
            "bucket_dt_adjusts": self._dt_adjusts,
            "member_steps": self._member_steps,
            "wall_s": round(time.monotonic() - self._t0, 3),
            "draining": self._drain,
            "slots": self.slot_info(),
        }
        if self._submesh is not None:
            out["gangs"] = {
                "formed": self._gangs_formed,
                "members_lost": self._gang_members_lost,
            }
        if self._fleet is not None:
            out["fleet"] = {
                "replica": self._replica_id,
                "lease": self._lease.tag if self._lease else None,
                "leases_broken": self._leases_broken,
                "preempted": self._preempted,
                "quota_rejected": self._quota_rejected,
                "continuations_persisted": self._continuations,
            }
            if self._autoscaler is not None:
                out["fleet"]["autoscale"] = self._autoscaler.stats()
        return out

    # -- service loop ---------------------------------------------------------

    def serve(self) -> dict:
        """Run the service until the queue drains (batch mode), or until a
        drain is requested (daemon mode).  Returns a summary dict.

        On a multi-process runtime every host calls this together: root
        owns the queue/journal/HTTP/results, every scheduling decision is
        root-broadcast before the collective dispatch it leads into, and
        ``sync_hosts`` fences the service open/close."""
        root = self._is_root()
        # arm the persistent compile cache BEFORE the first model build and
        # before the autoscaler's launcher snapshots the environment, so
        # every restart/incarnation/elastic re-plan (and every replica this
        # service spawns) reloads serialized executables instead of
        # recompiling the fleet from scratch (RUSTPDE_COMPILE_CACHE=0 opts
        # out; see config.ensure_compile_cache)
        _config.ensure_compile_cache()
        self._install_signals()
        if root:
            self._start_http()
        unclean = self._detect_unclean_shutdown() if root else False
        # fleet mode NEVER runs the global running/ recovery: peer
        # replicas' live claims would be stolen.  Recovery is scoped by
        # lease instead — the sweep breaks stale leases (our own previous
        # incarnation's included, once their TTL lapses) and re-enqueues
        # exactly those buckets' requests.
        recovered = (
            self.queue.recover() if root and self._fleet is None else []
        )
        self._journal(
            {
                "event": "server_start",
                "slots": self.cfg.slots,
                "max_queue": self.cfg.max_queue,
                "processes": self._nproc(),
                "recovered": recovered,
                "unclean_shutdown": unclean,
                "replica": self._replica_id or None,
                "fault": dataclasses.asdict(self._fault) if self._fault else None,
            }
        )
        self._fleet_heartbeat(force=True)
        self._start_heartbeat_thread()
        self._start_autoscaler()
        self._start_warm_pool()
        self._sync("serve-start")
        try:
            while not self._drain_agreed():
                key = self._next_bucket_agreed()
                if key is None:
                    if self.cfg.idle_exit and self._idle_done_agreed():
                        break
                    time.sleep(self.cfg.poll_s)
                    continue
                self._run_campaign(key)
            if self._drain:
                self._log_preempt_notice()
                self._journal({"event": "drain"})
        finally:
            import sys as _sys

            if _sys.exc_info()[0] is None:
                if root:
                    self._flush_results(force=True)
            elif self._pending_results:
                # an exception (DispatchHang above all) is propagating:
                # forcing the pending observable futures would device_get
                # against a possibly-wedged runtime with no watchdog and eat
                # the structured raise — drop them instead; the requests
                # stay claimed and queue.recover() re-runs them on restart
                self._journal(
                    {
                        "event": "results_abandoned",
                        "batches": len(self._pending_results),
                    }
                )
                self._pending_results = []
            summary = {
                # an exception exit (DispatchHang after a peer died, a
                # wedged collective) is an ERROR outcome: requests may
                # still be claimed in running/ — the next incarnation must
                # see this as an unclean shutdown and recover them
                "outcome": (
                    "error"
                    if _sys.exc_info()[0] is not None
                    else ("drained" if self._drain else "idle")
                ),
                **self.stats(),
                "journal": self.journal_path,
            }
            self._journal({"event": "server_stop", **summary})
            if root:
                # service-level metrics flush: one jsonl line at the service
                # root (campaign runners dump their own under campaigns/<key>;
                # fleet replicas dump under replicas/<id>/ so peers sharing
                # the run_dir never interleave files)
                MetricsDumper(
                    os.path.join(self._replica_dir, "metrics.jsonl")
                ).dump(step=self._global_step)
            self._stop_warm_pool()
            self._stop_autoscaler()
            self._stop_heartbeat_thread()
            self._fleet_heartbeat(force=True, stopping=True)
            self._journal_writer.close()  # reopens lazily if used again
            self._stop_http()
            if _sys.exc_info()[0] is None:
                # clean close fences (an exception path must NOT barrier:
                # the peer that caused it may already be gone)
                self._sync("serve-stop")
            self._restore_signals()
        return summary

    def _drain_agreed(self) -> bool:
        """The service-level drain flag, root-decided: a drain request (or
        signal) lands on root; every host leaves the serve loop together.
        The broadcast verdict OVERWRITES the local flag — a stray signal on
        a non-root host must be ignored (the runner's preempt handshake
        rule), not let that host leave the loop alone and wedge the
        fleet's next collective."""
        self._drain = self._root_decides(self._drain)
        return self._drain

    def _idle_done_agreed(self) -> bool:
        """Is an idle-exit (batch mode) really DONE?  Single-replica:
        yes — an empty bucket scan means an empty queue.  Fleet mode: only
        once nothing is queued, nothing is running and no bucket lease
        exists — a peer may still be serving (its lease pins its work),
        and a DEAD peer's lease needs one observer TTL before the sweep
        may break it, so a batch replica must keep polling rather than
        exit under work it will be able to reclaim.  Root decides,
        broadcast (the queue and the lease dir are root's to read)."""
        if self._fleet is None:
            return True

        def decide():
            counts = self.queue.counts()
            if counts["queued"] or counts["running"]:
                return False
            return not self._lease_mgr.holders()

        return bool(self._root_plan(decide))

    def _next_bucket_agreed(self) -> tuple | None:
        """Root picks the bucket (the queue is root's); the key is
        broadcast so every host builds the identical campaign model."""
        from ..parallel import multihost

        def pick():
            key = self._next_bucket()
            return None if key is None else list(key)

        key = self._root_plan(pick)
        return multihost.tuplify(key) if key is not None else None

    def _detect_unclean_shutdown(self) -> bool:
        """True when the previous incarnation died without a server_stop —
        read through the torn-tail-tolerant reader, since the very crash
        being detected may have torn the final journal line.  A
        ``server_stop`` with ``outcome: "error"`` counts as UNCLEAN too:
        the root of a multihost fleet that lost a peer exits structured
        (watchdogged collective -> journaled stop) but leaves claimed
        requests behind exactly like a hard kill would."""
        records = [
            r
            for r in read_journal(self.journal_path, on_error="skip")
            if r.get("event") in ("server_start", "server_stop")
        ]
        if not records:
            return False
        last = records[-1]
        return last["event"] != "server_stop" or last.get("outcome") == "error"

    # -- signals / http -------------------------------------------------------

    def _install_signals(self) -> None:
        def handler(signum, frame):
            # flag-sets only: journaling from a signal handler could
            # deadlock on a writer lock the interrupted frame holds
            if (
                signum == signal.SIGTERM
                and self._notice_s > 0
                and self._fleet is not None
                and self._notice_deadline is None
            ):
                self._notice_deadline = time.monotonic() + self._notice_s
            self.request_drain()

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev_handlers[sig] = signal.signal(sig, handler)
        except ValueError:  # not the main thread
            self._prev_handlers = {}

    def _restore_signals(self) -> None:
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers = {}

    def _start_http(self) -> None:
        if self.cfg.http_port is None:
            return
        from .http_front import HttpFront

        self._http = HttpFront(self, self.cfg.http_host, self.cfg.http_port)
        self._http.start()
        self._journal({"event": "http_listen", "address": self._http.address})

    def _stop_http(self) -> None:
        if self._http is not None:
            self._http.stop()
            self._http = None

    @property
    def http_address(self) -> tuple[str, int] | None:
        return self._http.address if self._http is not None else None

    # -- journal --------------------------------------------------------------

    def _journal(self, event: dict) -> None:
        if not self._is_root():
            return  # run_dir is shared on multihost: one journal, root's
        self._journal_writer.append(
            {"wall_s": round(time.monotonic() - self._t0, 3), **event}
        )

    # -- campaign -------------------------------------------------------------

    def _next_bucket(self) -> tuple | None:
        """Round-robin bucket selection (the fairness half of the ROADMAP
        item): buckets are ordered by their oldest queued request, and the
        pick ROTATES past the previously-served bucket — so under a
        daemon-mode mixed workload a hot bucket whose requests keep
        arriving cannot be re-picked while other buckets wait.  With one
        bucket (or none after it) this degrades to oldest-first.

        Fleet mode replaces both halves: buckets order by the QoS
        contract (priority class, then deadline slack, then arrival) and
        a bucket is only returned once its LEASE is claimed — runs on
        root (inside the broadcast pick), like the queue scan itself."""
        if self._fleet is not None:
            return self._next_bucket_fleet()
        order = self.queue.bucket_order()
        _tm.gauge(
            "serve_bucket_occupancy", "distinct compat buckets with queued work"
        ).set(len(order))
        if not order:
            return None
        if self._last_bucket in order and len(order) > 1:
            i = order.index(self._last_bucket)
            return order[(i + 1) % len(order)]
        return order[0]

    def _next_bucket_fleet(self) -> tuple | None:
        """Fleet bucket pick (root): sweep-break stale peer leases and
        re-claim their requests, then walk the QoS-ordered buckets and
        return the first whose lease this replica wins.  A bucket leased
        to a live peer is skipped — two replicas can never own one bucket
        (the lease claim is an exclusive dirent creation)."""
        from ..parallel import multihost
        from .fleet import qos as _qos
        from .fleet.lease import bucket_tag

        self._fleet_heartbeat()
        self.queue.invalidate()  # proxies + peer replicas write behind us
        if self._submesh is not None:
            from .fleet import gang as _gang

            # fate-shared gang sweep FIRST: a stale gang breaks group-
            # then-members as a unit, so no member lease of a dead gang
            # ever looks live on its own.  The bucket lease underneath is
            # swept by the ordinary pass below, which re-enqueues the
            # bucket's requests.
            for rec in _gang.stale_gangs(self._lease_mgr):
                self._journal(
                    {
                        "event": "gang_swept",
                        "bucket": rec.get("bucket"),
                        "owner": rec.get("owner"),
                    }
                )
        for rec in self._lease_mgr.sweep():
            # the dead holder's claims come back: queued again, scoped to
            # exactly the broken bucket — live peers' claims are untouched
            self._leases_broken += 1
            _tm.counter(
                "serve_leases_broken_total",
                "stale peer leases broken by this replica",
            ).inc()
            key = rec.get("bucket")
            if key and key[0] in ("gang", "gang-member"):
                continue  # gang bookkeeping: fate-shared by the gang sweep
            if key:
                key = multihost.tuplify(key)
                ids = self.queue.recover_bucket(key)
                self._journal(
                    {
                        "event": "requests_reclaimed",
                        "bucket": bucket_tag(key),
                        "owner": rec.get("owner"),
                        "ids": ids,
                    }
                )
        order = _qos.bucket_order(self.queue.snapshot_queued())
        _tm.gauge(
            "serve_bucket_occupancy", "distinct compat buckets with queued work"
        ).set(len(order))
        for key in order:
            lease = self._lease_mgr.claim(key)
            if lease is not None:
                with self._hb_lock:
                    self._lease = lease
                return key
        return None

    def _fleet_heartbeat(self, force: bool = False, stopping: bool = False) -> None:
        """Root-only liveness publication: rewrite this replica's
        heartbeat file (the proxies' /stats source) and renew the held
        bucket lease.  Cadenced by ``FleetConfig.heartbeat_s``; pure
        host-side file IO, no collectives (safe anywhere on root).  A
        renewal that discovers the lease was broken + re-claimed marks
        this replica FENCED — the boundary fence check abandons the
        campaign before any further queue write."""
        if self._fleet is None or not self._is_root():
            return
        now = time.monotonic()
        if not force and (now - self._hb_mark) < self._fleet.resolved_heartbeat():
            return
        self._hb_mark = now
        from .fleet.lease import LeaseLost
        from .fleet.proxy import write_replica_heartbeat

        try:
            write_replica_heartbeat(
                self.cfg.run_dir,
                self._replica_id,
                {
                    "draining": self._drain,
                    "stopping": bool(stopping),
                    "unhealthy": self._integrity_unhealthy,
                    "slots": list(self._slots_state),
                    "completed": self._completed,
                    "failed": self._failed,
                    "queue": self.queue.counts(),
                },
            )
        except OSError:
            pass  # heartbeat loss degrades to lease staleness, not a crash
        with self._hb_lock:
            lease = self._lease
            if lease is not None:
                try:
                    lease.renew()
                except LeaseLost as exc:
                    self._journal(
                        {
                            "event": "lease_fenced",
                            "bucket": lease.tag,
                            "detail": str(exc),
                        }
                    )
                    self._lease = None
                    self._fenced = True
            # the gang lease group renews on the same heartbeat: group
            # lease first, then every member's fencing token (gang.py) —
            # losing ANY of them fences this replica exactly like losing
            # the bucket lease (the campaign is abandoned at the next
            # boundary, no further queue write)
            glease = self._gang_lease
            if glease is not None:
                try:
                    glease.renew()
                except LeaseLost as exc:
                    self._journal(
                        {
                            "event": "lease_fenced",
                            "bucket": glease.tag,
                            "gang": True,
                            "detail": str(exc),
                        }
                    )
                    self._gang_lease = None
                    self._fenced = True

    def _start_heartbeat_thread(self) -> None:
        """Root-only, fleet-only: renew the lease + replica heartbeat on
        a daemon thread so liveness never depends on how long a compile
        or a chunk keeps the main thread busy.  File IO only — the thread
        must never touch device state or collectives."""
        if self._fleet is None or not self._is_root():
            return
        self._hb_stop = threading.Event()

        def loop():
            while not self._hb_stop.wait(self._fleet.resolved_heartbeat()):
                try:
                    self._fleet_heartbeat(force=True)
                except Exception:  # noqa: BLE001 — liveness must not crash serve
                    pass

        self._hb_thread = threading.Thread(
            target=loop, name="fleet-heartbeat", daemon=True
        )
        self._hb_thread.start()

    def _stop_heartbeat_thread(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
            if self._hb_thread is not None:
                self._hb_thread.join(timeout=5.0)
            self._hb_thread = None
            self._hb_stop = None

    def _start_autoscaler(self) -> None:
        """Embedded fleet controller (``cfg.autoscale``; root + fleet
        only): an Autoscaler daemon thread driving a local-subprocess
        launcher — pure host-side file IO + process control, never a
        collective.  With ``autoscale=None`` (the default) NOTHING here
        runs: serve behavior stays byte-identical (CI-asserted)."""
        if (
            self.cfg.autoscale is None
            or self._fleet is None
            or not self._is_root()
        ):
            return
        from .fleet.autoscaler import Autoscaler
        from .fleet.launcher import LocalProcessLauncher

        self._autoscaler = Autoscaler(
            self.cfg.run_dir,
            LocalProcessLauncher(
                self.cfg.run_dir, notice_s=self.cfg.autoscale.notice_s
            ),
            self.cfg.autoscale,
            fleet=self._fleet,
            controller_id=f"autoscaler-{self._replica_id}",
        )
        self._autoscaler.start()

    def _stop_autoscaler(self) -> None:
        if self._autoscaler is not None:
            # the embedded controller dies with its host replica: retire
            # the replicas it launched (graceful drain — their running
            # slots park durably and their leases release) so a serve()
            # exit never orphans subprocesses
            self._autoscaler.stop(retire_fleet=True)
            self._autoscaler = None

    def _log_preempt_notice(self) -> None:
        """Journal the armed preemption notice ONCE, at the first safe
        point after the signal (never from the handler itself — the
        interrupted frame may hold the journal writer's lock)."""
        if self._notice_deadline is None or self._notice_logged:
            return
        self._notice_logged = True
        self._journal(
            {
                "event": "preempt_notice",
                "notice_s": self._notice_s,
                "remaining_s": round(
                    self._notice_deadline - time.monotonic(), 3
                ),
            }
        )

    def _campaign_dir(self, key: tuple) -> str:
        tag = hashlib.sha1(repr(key).encode()).hexdigest()[:12]
        # fleet replicas keep campaign checkpoints under their own
        # replicas/<id>/ subtree: two replicas must never rotate/sweep
        # each other's checkpoint files (cross-replica continuity rides
        # the SHARED parked/<id>/ continuation dirs instead)
        return os.path.join(self._replica_dir, "campaigns", tag)

    def _start_warm_pool(self) -> None:
        """Arm the warm campaign pool (cfg.warm_profile, serve/warmpool.py):
        resolve the traffic profile — the ``"journal"`` sentinel learns it
        from this run_dir's historical compile_build rows, anything else
        goes through ``warmpool.load_profile`` (durable JSON path or inline
        list) — and start the non-blocking background build.  Gated to
        single-process, non-submesh runtimes: a background model build on a
        mesh would run collectives off the agreed schedule and desync
        hosts.  ``warm_profile=None`` leaves all of it inert (no thread, no
        journal rows — byte-identical serve, CI-asserted)."""
        if self.cfg.warm_profile is None or self._warm is not None:
            return
        if self._nproc() != 1 or self._submesh is not None:
            return
        from . import warmpool as _wp

        source = self.cfg.warm_profile
        if isinstance(source, str) and source == "journal":
            entries = _wp.learn_profile(self.journal_path)
        else:
            entries = _wp.load_profile(source)
        if not entries:
            return
        self._warm = _wp.WarmPool(
            entries, self._warm_build, journal=self._journal
        )
        self._warm.start()

    def _stop_warm_pool(self) -> None:
        if self._warm is not None:
            self._warm.stop()

    def _warm_build(self, key: tuple, k: int | None):
        """Build one prebuilt campaign for the warm pool (background
        thread): EXACTLY the ``_build_runner`` arming — registry build
        (phase="aot" attribution), sentinels, stats, the K-member served
        ensemble with all lanes dead — plus the AOT chunk executables
        (``.lower().compile()`` for every static scan bucket of a
        ``chunk_steps`` dispatch) and a prewarmed observables dispatch.
        With sentinels/stats armed the dispatch rides their own jitted
        variants, so the AOT executables cover the plain path only — the
        handoff still skips the dominant model-build + entry-point cost.
        Returns None for buckets the pool must not prebuild."""
        key = tuple(key)
        model = build_model_for_key(key, mesh=None, phase="aot")
        model.write_intervall = float("inf")
        if self.cfg.stability is not None:
            model.set_stability(self.cfg.stability)
        if (
            self.cfg.stats is not None
            and getattr(model, "MODEL_KIND", "") == "dns"
        ):
            model.set_stats(self.cfg.stats)
        if self.cfg.integrity is not None:
            model.set_integrity(self.cfg.integrity)
        kk = int(k) if k else self._canonical_k()
        ens = _ServedEnsemble(model, [model.state] * kk)
        ens.mark_dead(range(ens.k))
        executables = ens.aot_compile(int(self.cfg.chunk_steps))
        try:
            # populate the vmapped-observables dispatch cache too (the
            # first-chunk path fetches observables right after the chunk)
            ens.get_observables()
        except Exception:
            pass
        ens._obs_cache = None
        return model, ens, executables

    def _build_runner(
        self, key: tuple, k: int | None = None
    ) -> tuple[ResilientRunner, _ServedEnsemble]:
        # the bucket key IS the model spec: kind-prefixed, scenario-signed —
        # the workloads registry builds whatever physics the bucket needs
        # (DNS with/without modifiers, lnse, adjoint); on a multi-process
        # runtime the model spans the global pencil mesh, so campaign
        # dispatches are the same collective SPMD programs the runner's
        # standalone multihost runs execute.  The build seam records the
        # per-compat-key compile attribution (telemetry/compile_log.py);
        # the journal rows here are the durable copies of that observation.
        # A warm-pool hit skips ALL of it: the prebuilt campaign (model +
        # ensemble + AOT chunk executables) is handed over as-is, and the
        # only row at bucket-open is warm_pool_hit — the recompile
        # accounting stays flat by construction.
        t_build = time.perf_counter()
        if k is None:
            # canonicalization's K rounding (no checkpoint pinning the
            # size): prebuilt warm-pool ensembles then fit live campaigns
            k = self._canonical_k()
        k = int(k)
        mesh = self._campaign_mesh(key)
        warm = (
            self._warm.take(key, k)
            if self._warm is not None and mesh is None
            else None
        )
        if warm is not None:
            model, ens = warm
        else:
            model = build_model_for_key(key, mesh=mesh)
            model.write_intervall = float("inf")  # no flow-file callback IO
            if self.cfg.stability is not None:
                # governed campaigns: arm the on-device sentinels BEFORE the
                # ensemble vmaps its entry points (per-member CFL + pinned
                # masks); the dt response is the scheduler's per-bucket ladder
                # (_settle_predivergence), never a batch-wide governor
                model.set_stability(self.cfg.stability)
            if (
                self.cfg.stats is not None
                and getattr(model, "MODEL_KIND", "") == "dns"
            ):
                # in-scan per-member physics stats (models/stats.py): armed
                # before the ensemble vmaps too; each done record then carries
                # the member's health summary.  A lane refill (set_member)
                # resets that member's averaging window — per-request stats
                # start at claim time.
                model.set_stats(self.cfg.stats)
            if self.cfg.integrity is not None:
                # SDC defense (integrity/): on-device state digests streamed
                # at every chunk boundary + sampled shadow re-execution
                # audits.  Armed before the ensemble vmaps so the digest
                # entry point compiles per-member; model-kind agnostic (the
                # digest folds whatever the state pytree holds).
                model.set_integrity(self.cfg.integrity)
            ens = _ServedEnsemble(model, [model.state] * k)
            ens.mark_dead(range(ens.k))  # all lanes idle until request lands
            # two phase-stamped compile_build rows cover the campaign build
            # window: "build" is the registry seam's model construction,
            # "entry_points" the campaign-level remainder (armed sentinels +
            # the K-member ensemble trace) — they SUM to the serving path's
            # real cold cost, so TTFC attribution adds up instead of ~2x
            builds = _cl.build_counts().get(_cl.key_tag(key), 1)
            wall_total = time.perf_counter() - t_build
            wall_build = min(_cl.last_build_wall(key), wall_total)
            base = {
                "event": "compile_build",
                "key": list(key),
                "key_tag": _cl.key_tag(key),
                "builds": builds,
                "k": ens.k,
            }
            self._journal(
                {
                    **base,
                    "phase": "build",
                    "wall_s": round(wall_build, 4),
                    "recompile": builds > 1,
                }
            )
            self._journal(
                {
                    **base,
                    "phase": "entry_points",
                    "wall_s": round(max(0.0, wall_total - wall_build), 4),
                    "recompile": False,
                }
            )
        # per-member step flops for the live MFU gauge: the trace-only jaxpr
        # dot count (no extra compile; the entry points were just built)
        try:
            from ..utils.profiling import step_flops

            self._flops_member = step_flops(model, method="jaxpr")
        except Exception:
            self._flops_member = None
        rcfg = self.cfg.resilience
        runner = ResilientRunner.from_config(
            ens,
            rcfg,
            max_time=float("inf"),
            save_intervall=None,
            run_dir=self._campaign_dir(key),
            checkpoint_every_s=self.cfg.checkpoint_every_s,
            # divergence policy is PER REQUEST here (backoff re-queue);
            # whole-campaign checkpoint rollback stays the reactive last
            # resort behind it
            max_retries=getattr(rcfg, "max_retries", 3) if rcfg else 3,
            # serve checkpoints must carry the slot table in a manifest:
            # force the sharded two-phase format (single- or multi-process)
            io=IOConfig(sharded_checkpoints=True, overlap_dispatch=False),
            fault="",  # the server owns ONE plan across campaigns (below)
            # NO governor inside a campaign: its batch-wide set_dt would
            # silently rewrite every co-batched request's dt (dt is part of
            # the request contract AND the bucket key) — the per-request
            # dt-backoff retry is the serve-layer stability policy
            stability=None,
        )
        # the constructor inherits armed sentinels from the model as its
        # stability config — pin it back off so session() never builds the
        # batch-wide governor (the sentinels stay armed; the scheduler's
        # per-bucket ladder consumes their statuses instead)
        runner.stability = None
        runner.fault = self._fault
        runner.step = self._global_step
        runner.set_journal(self._journal_writer)
        if self.cfg.integrity is not None:
            # the quarantine ledger lives at the SERVE root, not in the
            # per-bucket campaign dir the runner would default to: strikes
            # must accumulate across campaigns (and replicas sharing the
            # run dir) for the carve filter to ever see them
            from ..integrity import QuarantineLedger

            icfg = self.cfg.integrity
            runner._integ_ledger = QuarantineLedger(
                self.cfg.run_dir,
                strikes=icfg.strikes,
                strike_ttl_s=icfg.strike_ttl_s,
            )
        return runner, ens

    def _peek_checkpoint_members(self, run_dir: str) -> int | None:
        """The member count of the newest valid campaign checkpoint (root
        scans + broadcasts; None when no checkpoint exists or it carries no
        ensemble bookkeeping).  The fleet is BUILT at this size so the
        K-fixed sharded restore always fits, then re-planned onto the
        configured size (:meth:`_replan_fleet`)."""

        def peek():
            path = checkpoint.latest_checkpoint(run_dir)
            if path is None:
                return None
            try:
                root = checkpoint.read_root_data(path)
            except checkpoint.CheckpointError:
                return None
            if "members" not in root:
                return None
            return int(np.asarray(root["members"]))

        return self._root_plan(peek)

    def _run_campaign(self, key: tuple) -> None:
        # time-to-first-chunk clock starts at campaign open (model build
        # included — at production request rates compile time IS the p99)
        self._campaign_open = time.monotonic()
        self._first_chunk_done = False
        self._bucket_tag = _cl.key_tag(key)
        # discard request-trace events a PREVIOUS campaign failed to flush
        # (an exception skipped its campaign-close gather): carrying them
        # forward would misattribute that work to THIS campaign's file
        _rt.LOG.drain()
        ck_k = self._peek_checkpoint_members(self._campaign_dir(key))
        runner, ens = self._build_runner(key, k=ck_k)
        self._runner = runner
        self._arm_device_fence(ens)
        self._last_bucket = key  # round-robin cursor
        self._campaign_claims = 0  # fairness quantum consumption
        self._claims_closed = False  # re-opened per campaign
        if not self._open_gang(key):
            # gang formation lost its race (a stale generation still holds
            # the group lease until the sweep breaks it): hand the bucket
            # back and let a later pass retry — no campaign may run
            # half-gang.  Every host took this branch together (the
            # verdict is broadcast), so skipping the fences is aligned.
            self._release_bucket_lease()
            return
        if self._drain:  # a signal raced the build
            runner.request_drain()
        self._gang_fence("serve-campaign-open")
        slots: list[_Slot] = []
        try:
            with runner.session(install_signals=False, resume=False):
                self._try_resume(runner)
                if not runner.resumed and ens.k != int(self.cfg.slots):
                    # the peeked checkpoint was swept (restore failed): no
                    # state to carry — restart at the configured fleet size
                    runner, ens = self._swap_fleet(runner, ens)
                slots = self._restore_slots(runner, ens, key)
                if ens.k != int(self.cfg.slots):
                    runner, ens, slots = self._replan_fleet(
                        runner, ens, slots, key
                    )
                _tm.gauge(
                    "serve_fleet_size", "slot count of the active campaign"
                ).set(ens.k)
                self._journal(
                    {
                        "event": "campaign_start",
                        "key": list(key),
                        "dir": runner.run_dir,
                        "restored": runner.resumed,
                        "fleet": ens.k,
                        "slots_restored": sum(1 for s in slots if s.running),
                    }
                )
                self._fill_slots(runner, ens, slots, key)
                self._refresh_slot_state(slots, ens.k)
                self._campaign_loop(runner, ens, slots, key)
        except IntegrityError as exc:
            # SDC containment (integrity/): the runner detected corruption
            # it could not roll back past — a device crossed the quarantine
            # threshold, or no digest-verified state existed.  The raise is
            # collectively agreed (the quarantine verdict is root-broadcast
            # in the runner), so every host lands here together: requeue
            # the running slots from their durable parked progress (device
            # state is untrusted, never drained), drop the carve plan so
            # the next campaign excludes the quarantined device, and flag
            # the replica unhealthy.  Serve CONTINUES — unlike a gang
            # death, the collective runtime is intact.
            self._disarm_device_fence(drain=False)
            self._contain_integrity(key, slots, exc)
        except (GangMemberLost, DispatchHang) as exc:
            # gang fate-sharing: a dead member turned a barrier (typed
            # GangMemberLost from the gang watchdog) or a chunk dispatch
            # (DispatchHang) into a structured failure.  Containment is
            # HOST-LOCAL — the peer is gone, so no collective may run —
            # and only breaks THIS gang's lease: co-resident buckets'
            # requests requeue with their durable parked state and the
            # next incarnation reclaims them immediately, no TTL wait.
            # Non-gang campaigns keep the existing structured-exit path.
            self._disarm_device_fence(drain=False)
            if self._gang_active is not None:
                self._contain_gang_loss(key, slots, exc)
            raise
        except Exception as exc:
            # a gang member that dies MID-DISPATCH surfaces on the
            # survivors as the collective transport's runtime error (gloo
            # connection reset / socket closed), not as a gang barrier
            # timeout — same fate-sharing containment, same typed journal
            # row, so the bucket's requests requeue with their parked
            # progress immediately instead of waiting for the next
            # incarnation's lease sweep.
            if self._gang_active is not None and _transport_death(exc):
                self._disarm_device_fence(drain=False)
                info = self._gang_active
                self._contain_gang_loss(
                    key,
                    slots,
                    GangMemberLost(str(info.get("gang", "?")), None, str(exc)),
                )
            raise
        finally:
            self._global_step = runner.step
            self._runner = None
            self._slots_state = (0, int(self.cfg.slots))
            # host-local teardown only on this path (no collectives on a
            # possibly-exceptional exit): unbind the active trace ids and
            # zero the fleet + this bucket's MFU gauges between campaigns
            # (a labeled gauge left at its last in-flight value would read
            # as phantom utilization on every later scrape)
            _rt.clear_active()
            _tm.gauge(
                "serve_mfu",
                "model-flops utilization per compat bucket",
                bucket=self._bucket_tag,
            ).set(0.0)
            _tm.gauge(
                "serve_fleet_utilization",
                "running-slot fraction of the fleet (0 between campaigns)",
            ).set(0.0)
            _tm.gauge(
                "serve_fleet_devices_busy",
                "devices executing campaign work right now",
            ).set(0)
            self._close_gang()
            # hand the bucket lease back (root's file, host-local IO —
            # safe on the exception path too).  The release is ordered
            # AFTER every queue write of this campaign; a fenced lease
            # (LeaseLost) means a survivor already owns the bucket.
            self._release_bucket_lease()
            self._fenced = False
            self._disarm_device_fence()
        self._gang_fence("serve-campaign-close")

    def _release_bucket_lease(self) -> None:
        if self._fleet is None or self._lease is None:
            return
        from .fleet.lease import LeaseLost

        with self._hb_lock:
            lease, self._lease = self._lease, None
        if lease is not None:
            try:
                lease.release()
                self._journal(
                    {"event": "lease_released", "bucket": lease.tag}
                )
            except LeaseLost:
                self._journal(
                    {"event": "lease_fenced", "bucket": lease.tag}
                )

    # -- gang campaigns (two-level serving) -----------------------------------

    def _gang_fence(self, tag: str) -> None:
        """The campaign open/close fence: the plain sync for ordinary
        campaigns, the GANG barrier (its own watchdog,
        ``RUSTPDE_GANG_SYNC_TIMEOUT_S`` -> typed
        :class:`~rustpde_mpi_tpu.serve.fleet.gang.GangMemberLost`) while a
        gang campaign is open — a member SIGKILLed between fences surfaces
        structured instead of wedging every survivor."""
        if self._gang_active is None:
            self._sync(tag)
            return
        if self._nproc() == 1:
            return
        from .fleet import gang as _gang

        _gang.gang_sync(
            tag,
            str(self._gang_active["gang"]),
            member=self._gang_active.get("member"),
        )

    def _open_gang(self, key: tuple) -> bool:
        """Open the gang chapter of a sub-mesh campaign: resolve the
        placement the model build made, form the fate-shared lease group
        (fleet mode, root — one group lease + one fencing token per
        member), bind the fault-injection scope, journal ``gang_formed``
        (plus ``gang_replanned`` when the carve re-mapped a stamped
        bucket).  True for ordinary campaigns (nothing happens) and for a
        formed gang; False when formation lost the claim race — the
        verdict is root-broadcast, so every host refuses together."""
        self._gang_active = None
        shape = _sm.key_shape(key) if self._submesh is not None else 0
        if shape <= 0:
            return True
        sub, replanned = self._gang_placement or (None, False)
        gindex = int(sub.index) if sub is not None else 0
        try:
            import jax

            member = int(jax.process_index())
        except Exception:
            member = 0

        def plan_open():
            out = {"formed": True, "generation": None}
            if self._fleet is not None:
                from .fleet.gang import GangLease

                glease = GangLease.form(
                    self._lease_mgr, key, self._nproc()
                )
                if glease is None:
                    out["formed"] = False
                else:
                    with self._hb_lock:
                        self._gang_lease = glease
                    out["generation"] = glease.generation
            return out

        plan = self._root_plan(plan_open)
        if not plan["formed"]:
            self._journal(
                {"event": "gang_form_failed", "key": list(key), "gang": gindex}
            )
            return False
        self._gang_active = {
            "gang": gindex,
            "member": member,
            "shape": int(shape),
            "devices": int(len(sub.devices)) if sub is not None else 0,
            "generation": plan["generation"],
        }
        if self._fault is not None:
            self._fault.bind_gang(gindex, member)
        self._gangs_formed += 1
        _tm.counter(
            "serve_gangs_formed_total", "gang campaigns formed"
        ).inc()
        self._journal(
            {
                "event": "gang_formed",
                "key": list(key),
                "gang": gindex,
                "shape": int(shape),
                "devices": self._gang_active["devices"],
                "members": self._nproc(),
                "generation": plan["generation"],
            }
        )
        if replanned:
            # elastic re-carve: the fleet no longer holds the stamped
            # shape — the bucket was re-placed on what fits now
            self._journal(
                {
                    "event": "gang_replanned",
                    "key": list(key),
                    "gang": gindex,
                    "stamped": int(shape),
                    "devices": self._gang_active["devices"],
                }
            )
        return True

    def _close_gang(self) -> None:
        """Host-local gang teardown on every campaign exit path: unbind
        the fault scope, zero the per-gang gauges, release the lease
        group (LeaseLost = a survivor already broke us: fine, its
        cleanup is authoritative)."""
        if self._gang_active is None:
            return
        info, self._gang_active = self._gang_active, None
        if self._fault is not None:
            self._fault.bind_gang(None, None)
        _tm.gauge(
            "serve_gang_mfu",
            "model-flops utilization per gang sub-mesh",
            gang=str(info["gang"]),
        ).set(0.0)
        if self._fleet is None:
            return
        from .fleet.lease import LeaseLost

        with self._hb_lock:
            glease, self._gang_lease = self._gang_lease, None
        if glease is not None:
            try:
                glease.release()
            except (LeaseLost, OSError):
                pass  # broken by containment or a surviving peer

    def _contain_gang_loss(self, key: tuple, slots: list[_Slot], exc) -> None:
        """Gang-death containment, HOST-LOCAL ONLY — a member is dead, so
        not one collective may run here.  Root journals the typed loss,
        requeues every running slot WITH the progress its durable parked
        continuation carries (the cadence persist is the real resume
        state; the device runtime may be wedged and is never touched),
        and breaks ONLY this gang's lease group so the next incarnation
        reclaims immediately instead of waiting out a TTL.  Queue writes
        happen only under a live bucket lease (fencing: a survivor that
        broke us already requeued these requests itself)."""
        info = self._gang_active or {}
        self._gang_members_lost += 1
        _tm.counter(
            "serve_gang_members_lost_total",
            "gang members lost (barrier watchdog / dispatch hang)",
        ).inc()
        if not self._is_root():
            return
        self._journal(
            {
                "event": "gang_member_lost",
                "key": list(key),
                "gang": info.get("gang"),
                "member": getattr(exc, "member", None),
                "generation": info.get("generation"),
                "detail": str(exc),
            }
        )
        if self._fleet is not None:
            from .fleet.lease import LeaseLost

            with self._hb_lock:
                lease = self._lease
            try:
                if lease is not None:
                    lease.guard()
            except LeaseLost:
                return
        for s in slots:
            if not s.running:
                continue
            progress, parked = int(s.base), False
            meta = checkpoint.continuation_meta(
                checkpoint.continuation_dir(self.cfg.run_dir, s.req.id)
            )
            if meta is not None:
                progress, parked = int(meta[0]), True
            self.queue.requeue(
                dataclasses.replace(s.req, progress=progress)
            )
            self._journal(
                {
                    "event": "request_requeued",
                    "id": s.req.id,
                    "trace_id": s.req.trace_id,
                    "slot": s.index,
                    "progress": progress,
                    "target": s.target,
                    "parked": parked,
                    "checkpoint": None,
                    "gang": info.get("gang"),
                }
            )
        if self._fleet is not None:
            from .fleet.gang import break_gang

            break_gang(self._lease_mgr, key, self._nproc())
            with self._hb_lock:
                self._gang_lease = None

    def _contain_integrity(self, key: tuple, slots: list[_Slot], exc) -> None:
        """Silent-data-corruption containment: every host runs this
        together (the IntegrityError raise is collectively agreed).  The
        cached carve plan is dropped so the NEXT campaign excludes the
        quarantined device; root requeues every running slot with the
        progress its durable parked continuation carries — the live device
        state failed its digest audit and is never drained into a result —
        and the replica turns unhealthy in its fleet heartbeat."""
        self._submesh_plan = None
        self._submesh_meshes.clear()
        if getattr(exc, "device", None):
            self._integrity_unhealthy = True
        _tm.counter(
            "serve_integrity_contained_total",
            "campaigns abandoned on an unrecoverable integrity failure",
        ).inc()
        if not self._is_root():
            return
        self._journal(
            {
                "event": "integrity_contained",
                "key": list(key),
                "check": getattr(exc, "check", None),
                "step": getattr(exc, "step", None),
                "member": getattr(exc, "member", None),
                "device": getattr(exc, "device", None),
                "detail": str(exc),
            }
        )
        if self._fleet is not None:
            from .fleet.lease import LeaseLost

            with self._hb_lock:
                lease = self._lease
            try:
                if lease is not None:
                    lease.guard()
            except LeaseLost:
                return
        for s in slots:
            if not s.running:
                continue
            progress, parked = int(s.base), False
            meta = checkpoint.continuation_meta(
                checkpoint.continuation_dir(self.cfg.run_dir, s.req.id)
            )
            if meta is not None:
                progress, parked = int(meta[0]), True
            self.queue.requeue(
                dataclasses.replace(s.req, progress=progress)
            )
            self._journal(
                {
                    "event": "request_requeued",
                    "id": s.req.id,
                    "trace_id": s.req.trace_id,
                    "slot": s.index,
                    "progress": progress,
                    "target": s.target,
                    "parked": parked,
                    "checkpoint": None,
                    "integrity": True,
                }
            )
        self._fleet_heartbeat(force=True)

    def _try_resume(self, runner) -> None:
        """Campaign restore with graceful degradation: a checkpoint that no
        longer fits (slot-count/config change between incarnations — the
        sharded format is K-fixed) must NOT brick the service.  The
        incompatible checkpoints are swept (their slot geometry can never
        be restored by this server) and the campaign starts fresh — every
        request is still durably queued, so nothing is lost, only the
        drained progress."""
        try:
            runner.resumed = runner._maybe_resume()
        except checkpoint.CheckpointError as exc:
            self._journal(
                {
                    "event": "campaign_restore_failed",
                    "dir": runner.run_dir,
                    "error": str(exc),
                }
            )
            if self._is_root():
                for path in checkpoint.checkpoint_files(runner.run_dir):
                    checkpoint.remove_checkpoint(path)
            runner.resumed = False
            runner._last_ckpt_path = None

    def _restore_slots(self, runner, ens, key: tuple) -> list[_Slot]:
        """Rebuild the slot table after a checkpoint restore: a restored
        slot whose request is back in the queue (drain re-enqueued it, or
        crash recovery did) is RE-CLAIMED into its old lane — the member
        state is already sitting there, bit-equal — and continues from its
        checkpointed step counter.  Restored slots whose request is gone
        (completed after the checkpoint, durably recorded) go idle.

        The claims touch the queue, so ROOT builds the restore plan and
        broadcasts it; every host then applies the identical lane ops."""
        slots = [_Slot(i) for i in range(ens.k)]
        meta = ens.restored_meta if runner.resumed else None
        if not meta:
            return slots
        alive = ens.alive()  # replicated (K,) fetches: identical per host
        done = np.asarray(ens.steps_done)

        def plan_restore():
            plan = []
            for i, m in enumerate(meta[: ens.k]):
                if not m:
                    continue
                if not alive[i]:
                    # the member was dead in the checkpoint: leave the
                    # request queued — a fresh lane (fresh IC) will claim it
                    # instead of resuming a doomed trajectory
                    plan.append({"slot": i, "action": "dead"})
                    continue
                req = self.queue.claim_id(m["id"])
                if req is None:
                    # the request resolved after this checkpoint was
                    # written (durably recorded in done/): lane goes idle
                    plan.append({"slot": i, "action": "resolved"})
                    continue
                if req.compat_key != key:
                    # same id, DIFFERENT bucket: the request was re-queued
                    # at a new dt after this checkpoint (backoff retry or a
                    # dt re-bucket) — the old-dt member must not resume it;
                    # its new bucket's campaign will
                    self.queue.requeue(req)
                    plan.append({"slot": i, "action": "rebucketed"})
                    continue
                plan.append(
                    {
                        "slot": i,
                        "action": "resume",
                        "req": req.to_json(),
                        "target": int(m["target"]),
                        "base": int(m.get("base", 0)),
                        "time_base": float(m.get("time_base", 0.0)),
                    }
                )
            return plan

        for entry in self._root_plan(plan_restore):
            i = entry["slot"]
            if entry["action"] != "resume":
                ens.serve_meta[i] = None
                if entry["action"] in ("resolved", "rebucketed"):
                    ens.mark_dead([i])
                continue
            req = SimRequest.from_json(entry["req"])
            slots[i] = _Slot(
                i,
                req=req,
                target=entry["target"],
                base=entry["base"],
                time_base=entry["time_base"],
            )
            self._journal(
                {
                    "event": "request_scheduled",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": i,
                    "target": slots[i].target,
                    "restored": True,
                    "steps_done": entry["base"] + int(done[i]),
                }
            )
        return slots

    def _swap_fleet(self, runner, ens) -> tuple:
        """A fresh all-idle fleet at the configured size over the SAME
        campaign model (no state carried — used when the peeked checkpoint
        turned out unrestorable)."""
        model = ens.model
        new_ens = _ServedEnsemble(model, [model.state] * int(self.cfg.slots))
        new_ens.mark_dead(range(new_ens.k))
        new_ens.io_pipeline = getattr(ens, "io_pipeline", None)
        runner.pde = new_ens
        if self._fence_ens is not None:
            self._fence_ens = new_ens
        return runner, new_ens

    def _replan_fleet(
        self, runner, old_ens, old_slots: list[_Slot], key: tuple
    ) -> tuple:
        """Elastic fleet re-planning: the restored fleet's slot count
        differs from the configured one.  Restored mid-flight trajectories
        move into the new lanes (``set_member`` — no recompile, the model
        is shared); on a SHRINK the surplus trajectories are PARKED (member
        state held in memory for the next lane to claim them) and their
        requests re-enqueued at their checkpointed progress; on a GROW the
        extra lanes refill from the queue through the normal path.  A
        ``campaign_replanned`` journal event records old/new K, and a fresh
        checkpoint at the new geometry replaces the stale-K ones (which
        could never restore this fleet)."""
        want = int(self.cfg.slots)
        old_k = old_ens.k
        new_ens = _ServedEnsemble(old_ens.model, [old_ens.model.state] * want)
        new_ens.mark_dead(range(new_ens.k))
        new_ens.time = old_ens.time
        new_ens.io_pipeline = getattr(old_ens, "io_pipeline", None)
        done = np.asarray(old_ens.steps_done)
        running = [s for s in old_slots if s.running]  # identical per host

        def plan_replan():
            plan = []
            for j, s in enumerate(running):
                steps = s.base + int(done[s.index])
                tdone = s.time_base + int(done[s.index]) * float(s.req.dt)
                entry = {
                    "old": s.index,
                    "req": s.req.to_json(),
                    "target": int(s.target),
                    "base": steps,
                    "time_base": tdone,
                }
                if j < want:
                    entry.update(op="keep", new=j)
                else:
                    entry.update(op="park")
                plan.append(entry)
            return plan

        kept = parked = 0
        new_slots = [_Slot(i) for i in range(want)]
        for entry in self._root_plan(plan_replan):
            req = SimRequest.from_json(entry["req"])
            state = old_ens.member_state(entry["old"])  # device op, all hosts
            if entry["op"] == "keep":
                j = entry["new"]
                new_ens.set_member(j, state)
                new_slots[j] = _Slot(
                    j,
                    req=req,
                    target=entry["target"],
                    base=entry["base"],
                    time_base=entry["time_base"],
                )
                new_ens.serve_meta[j] = {
                    "id": req.id,
                    "target": entry["target"],
                    "base": entry["base"],
                    "time_base": entry["time_base"],
                    "req": json.loads(req.to_json()),
                }
                kept += 1
            else:
                # park: the trajectory stays continuable in this process
                # (and, fleet mode, durably in parked/<id>/ — a crash
                # before the park is re-claimed no longer restarts it)
                self._park_member(
                    req, state, int(entry["base"]), float(entry["time_base"])
                )
                parked += 1
                if self._is_root():
                    self.queue.requeue(
                        dataclasses.replace(req, progress=int(entry["base"]))
                    )
                self._journal(
                    {
                        "event": "request_requeued",
                        "id": req.id,
                        "trace_id": req.trace_id,
                        "slot": entry["old"],
                        "progress": entry["base"],
                        "target": entry["target"],
                        "parked": True,
                        "checkpoint": None,
                    }
                )
        runner.pde = new_ens
        if self._fence_ens is not None:
            self._fence_ens = new_ens
        self._replans += 1
        _tm.counter(
            "serve_replans_total", "elastic fleet re-plans across restarts"
        ).inc()
        _tm.gauge(
            "serve_fleet_size", "slot count of the active campaign"
        ).set(new_ens.k)
        self._journal(
            {
                "event": "campaign_replanned",
                "key": list(key),
                "old_slots": old_k,
                "new_slots": want,
                "kept": kept,
                "parked": parked,
            }
        )
        # anchor the new geometry, then sweep the stale-K checkpoints (a
        # reactive rollback must never hand this fleet an old-K manifest)
        path = runner.checkpoint_now("replan")
        if self._is_root():
            for p in checkpoint.checkpoint_files(runner.run_dir):
                if p != path:
                    checkpoint.remove_checkpoint(p)
        return runner, new_ens, new_slots

    def _refresh_slot_state(self, slots: list[_Slot], total: int) -> None:
        """Keep ``slot_info()`` (/healthz) AND the Prometheus gauge honest
        the moment lanes are claimed/released — not just at chunk
        boundaries, where the first (compile-heavy) chunk would report 0
        running for many seconds and a post-settle sample would
        under-report lanes the refill is about to reclaim."""
        running = sum(1 for s in slots if s.running)
        self._slots_state = (running, total)
        util = (running / total) if total else 0.0
        _tm.gauge(
            "serve_slot_utilization", "running slots / campaign slot count"
        ).set(util)
        try:
            import jax

            # LOCAL devices: gauges stay per-host in the fleet snapshot
            # (gather labels them host=<i>), so per-host values must sum
            # to the global count — the global count here would overcount
            # the fleet by nproc on any sum-over-hosts panel
            devices = int(jax.local_device_count())
        except Exception:
            devices = 1
        # fleet-level view (the mesh-sharded-serve item's gate gauges): a
        # single-level campaign spans every device (all-or-nothing); a
        # sub-mesh campaign reports only ITS slice's share of the fleet,
        # so co-resident gauges sum to the true fleet utilization
        fleet_util = util
        if self._active_share is not None:
            mine, local_total = self._active_share
            if local_total:
                fleet_util = util * (mine / local_total)
            devices = mine
        _tm.gauge(
            "serve_fleet_utilization",
            "running-slot fraction of the fleet (0 between campaigns)",
        ).set(fleet_util)
        _tm.gauge(
            "serve_fleet_devices_busy",
            "devices executing campaign work right now",
        ).set(devices if running else 0)

    def _fill_slots(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        """Refill every idle lane from this bucket's queue (fresh IC via
        the template model's generator; ``set_member`` installs it without
        recompiling).

        Bucket fairness: one campaign visit claims at most
        ``cfg.bucket_quantum`` requests while OTHER buckets hold queued
        work — past the quantum the refill stops, the campaign drains its
        running slots and ends, and the round-robin pick serves the next
        bucket (this bucket's tail gets its next turn).  With no competing
        bucket the quantum is waived (no reason to cycle)."""
        quantum = int(self.cfg.bucket_quantum)
        idle = [s.index for s in slots if not s.running]
        if not idle:  # identical slot tables on every host: consistent skip
            return
        if self._claims_closed:
            # a cross-bucket preemption closed this campaign: freed lanes
            # stay idle so the campaign drains (flag is derived from a
            # broadcast plan — identical on every host, consistent skip)
            return

        def plan_fill():
            plan = {"assign": [], "quantum": False, "claims": self._campaign_claims}
            if self._drain:  # lint-ok: RPD001 root-only plan closure; the returned plan is broadcast_obj'd before any host acts
                # drain check lives INSIDE the root plan: a host-local
                # early-return here would skip the broadcast on the host
                # the signal landed on while its peers enter it — one
                # collective out of phase, wedged fleet
                return plan
            if self._fleet is not None:
                self.queue.invalidate()  # proxies feed this bucket live
            for i in idle:
                if (
                    quantum > 0
                    and plan["claims"] >= quantum
                    and self.queue.other_bucket_waiting(key)
                ):
                    plan["quantum"] = True
                    break
                req = self.queue.claim(key, qos=self._fleet is not None)
                if req is None:
                    break
                if req.amp is None:
                    # proxy-admitted requests bypass SimServer.submit's
                    # default-amp stamping: stamp at claim so the done
                    # record names the IC amplitude solo reruns need
                    req.amp = float(self.cfg.default_amp)
                plan["claims"] += 1
                parked = req.id in self._parked
                durable = False
                base, tdone = 0, 0.0
                if parked:
                    _, base, tdone = self._parked[req.id]
                elif self._fleet is not None:
                    # cross-replica continuation: the park was persisted
                    # by a (possibly dead) peer — the manifest carries the
                    # progress accounting, the shards the member state
                    meta = checkpoint.continuation_meta(
                        checkpoint.continuation_dir(self.cfg.run_dir, req.id)
                    )
                    if meta is not None:
                        durable = True
                        base, tdone = meta
                if parked or durable:
                    # requeue-with-state continuation (elastic shrink / dt
                    # re-bucket / preemption): the remaining debt is the
                    # request's horizon minus the sim time already
                    # covered, at the CURRENT bucket's dt
                    target = base + max(
                        1, round((float(req.horizon) - tdone) / float(req.dt))
                    )
                else:
                    target = req.steps
                plan["assign"].append(
                    {
                        "slot": i,
                        "req": req.to_json(),
                        "parked": parked,
                        "durable": durable,
                        "base": base,
                        "time_base": tdone,
                        "target": target,
                    }
                )
            return plan

        plan = self._root_plan(plan_fill)
        self._campaign_claims = int(plan["claims"])
        if plan["quantum"]:
            self._journal(
                {
                    "event": "bucket_quantum",
                    "key": list(key),
                    "claims": self._campaign_claims,
                }
            )
        for a in plan["assign"]:
            req = SimRequest.from_json(a["req"])
            slot = slots[a["slot"]]
            if a["parked"]:
                # every host holds the identical parked entry (parking
                # decisions are broadcast) — a missing one is a bug, not a
                # fallback case
                state, _, _ = self._parked.pop(req.id)
            elif a.get("durable"):
                # a peer's durable park (it may be dead — that is the
                # point): restore mid-flight; a failed verification
                # degrades to a fresh trajectory with the debt reset —
                # by FLEET-AGREED verdict, so no host can restore while
                # a peer with a torn shard starts over
                state = self._load_continuation(req, ens, slot.index)
                if self._continuation_agreed(state is not None):
                    _tm.counter(
                        "serve_continuations_resumed_total",
                        "requests resumed mid-flight from durable parked state",
                    ).inc()
                    self._journal(
                        {
                            "event": "continuation_resumed",
                            "id": req.id,
                            "trace_id": req.trace_id,
                            "steps": int(a["base"]),
                            "time": float(a["time_base"]),
                        }
                    )
                else:
                    if state is not None:
                        self._journal(
                            {
                                "event": "continuation_restore_failed",
                                "id": req.id,
                                "error": "a peer host failed its shard read",
                            }
                        )
                    a = {**a, "base": 0, "time_base": 0.0, "target": req.steps}
                    state = ens.fresh_member_state(
                        req.seed, req.amp or self.cfg.default_amp
                    )
            else:
                state = ens.fresh_member_state(
                    req.seed, req.amp or self.cfg.default_amp
                )
            ens.set_member(slot.index, state)
            slot.req = req
            slot.target = int(a["target"])
            slot.base = int(a["base"])
            slot.time_base = float(a["time_base"])
            ens.serve_meta[slot.index] = {
                "id": req.id,
                "target": slot.target,
                "base": slot.base,
                "time_base": slot.time_base,
                "req": json.loads(req.to_json()),
            }
            self._journal(
                {
                    "event": "request_scheduled",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": slot.index,
                    "target": slot.target,
                    "restored": False,
                    "parked": bool(a["parked"]),
                    "base": slot.base,
                    "step": runner.step,
                }
            )

    def _boundary_gauges(self) -> None:
        """Refresh the live queue/throughput gauges at one chunk boundary —
        host-side bookkeeping the scheduler already holds (slot occupancy
        is kept by :meth:`_refresh_slot_state` at claim/release time, so
        the gauge and ``slot_info()`` can never disagree).  MFU is labeled
        PER BUCKET (``profiling.step_flops`` of this campaign's model ×
        measured member rate), and the per-device memory watermarks refresh
        here too (None-safe: CPU backends report nothing)."""
        _tm.gauge("serve_queue_depth", "requests waiting in queued/").set(
            self.queue.counts()["queued"]
        )
        now = time.monotonic()
        mark_t, mark_steps = self._rate_mark
        if now > mark_t and self._member_steps > mark_steps:
            rate = (self._member_steps - mark_steps) / (now - mark_t)
            _tm.gauge(
                "serve_member_steps_per_sec",
                "aggregate member-steps/s across running slots",
            ).set(rate)
            if self._flops_member:
                from ..utils.profiling import PEAK_FLOPS, peak_flops_key

                mfu = (
                    self._flops_member * rate / PEAK_FLOPS[peak_flops_key()]
                )
                _tm.gauge(
                    "serve_mfu",
                    "model-flops utilization per compat bucket",
                    bucket=self._bucket_tag,
                ).set(mfu)
                if self._gang_active is not None:
                    # the per-gang view of the same quantity: one labeled
                    # series per carved sub-mesh, zeroed at campaign close
                    _tm.gauge(
                        "serve_gang_mfu",
                        "model-flops utilization per gang sub-mesh",
                        gang=str(self._gang_active["gang"]),
                    ).set(mfu)
        self._rate_mark = (now, self._member_steps)
        _cl.update_device_memory_gauges()

    def _campaign_loop(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        root = self._is_root()
        while True:
            running = [s for s in slots if s.running]
            if not running:
                break
            done = np.asarray(ens.steps_done)  # replicated (K,): identical
            n = int(
                self._root_plan(
                    lambda: max(
                        1,
                        min(
                            min(
                                s.target - (s.base + int(done[s.index]))
                                for s in running
                            ),
                            int(self.cfg.chunk_steps),
                        ),
                    )
                )
            )
            before = runner.step
            # bind the on-device trace ids for this dispatch: flight spans
            # and incident dumps during the chunk are request-attributable
            _rt.bind_slots(
                {s.index: s.req.trace_id for s in running if s.req.trace_id}
            )
            t0_wall = time.time()
            with _tr.span("serve_chunk", steps=n, slots=len(running)):
                runner.advance(n)
            advanced = runner.step - before
            if self._first_chunk_done is False and advanced > 0:
                self._first_chunk_done = True
                self._journal(
                    {
                        **_cl.observe_first_chunk(
                            key, time.monotonic() - self._campaign_open
                        ),
                        "key": list(key),
                        "step": runner.step,
                    }
                )
            if _rt.enabled() and advanced > 0:
                dur = time.time() - t0_wall
                for s in running:
                    if s.req.trace_id:
                        _rt.chunk_span(
                            s.req.trace_id,
                            t0_wall,
                            dur,
                            slot=s.index,
                            steps=advanced,
                            step=runner.step,
                        )
            self._member_steps += advanced * len(running)
            if self.cfg.stability is not None and ens.pre_divergence_latched:
                # the chunk rolled back in memory while every member is
                # still finite: re-bucket the pinned requests down the
                # per-bucket dt ladder (proactive — no NaN, no checkpoint)
                self._settle_predivergence(runner, ens, slots, key)
            with _tr.span("serve_settle", step=runner.step):
                self._settle_boundary(runner, ens, slots, key)
            if self._fleet is not None:
                # fleet boundary work (config-aligned guard: every host
                # holds the same cfg, so the broadcasts inside stay in
                # lockstep): liveness heartbeat + lease renewal, the
                # fencing verdict, and deadline-driven preemption
                self._fleet_heartbeat()
                if self._fence_check(ens, slots, key):
                    return
                self._maybe_preempt(runner, ens, slots, key)
                self._persist_running_continuations(ens, slots)
            self._refresh_slot_state(slots, ens.k)
            self._boundary_gauges()
            # boundary housekeeping: deferred sharded commit + cadence
            # checkpoint + the drain/preemption flag — runner.on_boundary is
            # the same hook integrate() would drive, and its verdict is
            # root-broadcast (a local self._drain on root rides the
            # runner's interrupt flag via request_drain)
            if runner.on_boundary():
                self._drain = True
                self._drain_campaign(runner, ens, slots, key)
                return
            self._fill_slots(runner, ens, slots, key)
            self._refresh_slot_state(slots, ens.k)
            if root:
                self._flush_results()
        if root:
            self._flush_results(force=True)
        self._flush_reqtrace(runner, key)
        self._journal({"event": "campaign_end", "key": list(key),
                       "step": runner.step})
        # a cleanly finished campaign leaves no work to restore: settle the
        # async writer FIRST (a background shard write must never race the
        # sweep), then remove its checkpoints so a LATER campaign in this
        # bucket starts fresh instead of restoring a stale slot table
        runner._drain_io()
        if root:
            for path in checkpoint.checkpoint_files(runner.run_dir):
                checkpoint.remove_checkpoint(path)

    def _fence_check(self, ens, slots: list[_Slot], key: tuple) -> bool:
        """Fleet fencing at a chunk boundary: did a survivor break this
        replica's lease (we stalled past the TTL) and re-claim the bucket?
        Root's verdict is broadcast; a fenced campaign is ABANDONED — the
        lanes go idle in memory and NOT one queue write is made, because
        every request now durably belongs to the new lease holder (the
        breaker already re-enqueued them)."""
        fenced = bool(self._root_plan(lambda: self._fenced))
        if not fenced:
            return False
        for s in slots:
            if s.running:
                self._release(ens, s)
        # the in-memory parks are stale the moment we are fenced: the new
        # lease holder may progress/re-bucket those requests and write
        # NEWER durable continuations, which a surviving _parked entry
        # would shadow on a later re-claim (plan_fill prefers the memory
        # fast path).  Durable state is authoritative across a fence.
        self._parked.clear()
        self._journal({"event": "campaign_fenced", "key": list(key)})
        self._fenced = False
        return True

    def _maybe_preempt(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        """Deadline-driven preemption (the QoS contract's teeth): when a
        queued deadline request's slack runs below the configured
        threshold, park running best-effort lanes for it — through the
        SAME requeue-with-state machinery as an elastic shrink, now
        durable, so the preempted request loses nothing.  Root plans
        (queue scan + policy), the plan is broadcast, every host executes
        the identical lane ops."""
        if not self._fleet.preempt:
            return
        done = np.asarray(ens.steps_done)  # lint-ok: RPD005 replicated (K,) host-fetched counter, identical per host

        def decide():
            from .fleet import qos as _qos

            self.queue.invalidate()
            loaded = self.queue.snapshot_queued()
            at_risk = _qos.find_at_risk(
                loaded, float(self._fleet.preempt_slack_s)
            )
            if at_risk is None:
                return {"victims": [], "for": None}
            running = [(s.index, s.req) for s in slots if s.running]
            victims = _qos.preempt_victims(running, at_risk, key)
            by_index = {s.index: s for s in slots}
            return {
                "for": at_risk.id,
                "for_priority": at_risk.priority,
                # a CROSS-bucket emergency must also close this campaign's
                # claims: the parked victims land back in THIS bucket's
                # queue, and an open refill would re-claim them at the
                # same boundary — park/requeue churn forever, the urgent
                # bucket never reached
                "cross_bucket": tuple(at_risk.compat_key) != tuple(key),
                "victims": [
                    {
                        "slot": i,
                        "steps": by_index[i].base + int(done[i]),
                        "time": by_index[i].time_base
                        + int(done[i]) * float(by_index[i].req.dt),
                    }
                    for i in victims
                ],
            }

        plan = self._root_plan(decide)
        if plan["victims"] and plan.get("cross_bucket"):
            # every host computes this from the broadcast plan: the
            # campaign stops claiming, drains its remaining lanes, and
            # ends — the QoS-ordered bucket pick then takes the urgent one
            self._claims_closed = True
        for entry in plan["victims"]:
            s = slots[entry["slot"]]
            req = s.req
            state = ens.member_state(s.index)  # device op, all hosts
            self._release(ens, s)
            self._park_member(req, state, entry["steps"], entry["time"])
            if self._is_root():
                self.queue.requeue(
                    dataclasses.replace(req, progress=int(entry["steps"]))
                )
            self._preempted += 1
            _tm.counter(
                "serve_preemptions_total",
                "best-effort lanes parked for at-risk deadline requests",
            ).inc()
            self._journal(
                {
                    "event": "request_preempted",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": entry["slot"],
                    "priority": req.priority,
                    "steps_done": entry["steps"],
                    "preempted_for": plan["for"],
                }
            )

    def _flush_reqtrace(self, runner, key: tuple) -> None:
        """Gather every host's request-trace events for the closing
        campaign and write one Perfetto file next to its checkpoints
        (root-only write, allgather underneath — so the call sites are the
        campaign-close and drain paths, where the fleet is aligned; the
        env-pinned reqtrace flag makes the skip aligned too)."""
        path = _rt.write_campaign_trace(runner.run_dir, self._bucket_tag)
        if path is not None:
            self._journal(
                {"event": "campaign_trace", "key": list(key), "path": path}
            )

    def _settle_boundary(self, runner, ens, slots: list[_Slot], key: tuple) -> None:
        """Process completions and deaths at a chunk boundary.  The
        observables for every slot that finished here ride ONE vmapped
        async dispatch (PR-4 futures) captured BEFORE any lane is refilled,
        so the fetched values are the finished members' final states.

        Root decides who finished/died (broadcast); every host executes the
        identical release/refill lane ops and the observable dispatch."""
        alive = ens.alive()
        done = np.asarray(ens.steps_done)
        # a member that stopped advancing via the model's SUCCESS criterion
        # (the adjoint finder's residual convergence) finished early — it is
        # a completion, not a death, even below its step target.  The
        # done-ok probe is a device dispatch: EVERY host executes it (a
        # root-only dispatch would desynchronize the collective program
        # sequence on a multi-process mesh).
        done_ok = ens.done_ok_members()

        def decide():
            finished, dead = [], []
            for s in slots:
                if not s.running:
                    continue
                total = s.base + int(done[s.index])
                if (alive[s.index] and total >= s.target) or done_ok[s.index]:
                    finished.append({"slot": s.index, "steps": total})
                elif not alive[s.index]:
                    dead.append({"slot": s.index, "steps": total})
            return {"finished": finished, "dead": dead}

        plan = self._root_plan(decide)
        if plan["finished"]:
            obs_fut = ens.get_observables_async()  # one dispatch, all hosts
            names = tuple(ens.observable_names)
            # per-request physics-stats summary (cfg.stats armed): the
            # health readout is captured HERE, before any lane is released
            # or refilled (a refill zeroes that member's sums) — collective
            # dispatch on all hosts, like the observables
            stats_fut = stats_names = None
            if getattr(ens, "stats_armed", False):
                from ..models.stats import HEALTH_NAMES

                stats_fut = ens.stats_health_async()
                stats_names = HEALTH_NAMES
            # end-state digest per finished member (integrity armed):
            # captured with the observables, before any refill — the done
            # record carries it so the fleet proxy's cross-replica vote can
            # compare two replicas' results without shipping state
            dig_fut = None
            if getattr(ens, "integrity_armed", False):
                dig_fut = ens.state_digest_async()
            if self._fence_ens is not None:
                # EVERY host stashes the dispatch handles for the sub-mesh
                # fence (root alone keeps them in _pending_results): the
                # lanes refill right after this, so the ensemble's obs
                # cache rebinds and can no longer fence THESE programs
                self._inflight_futs.append(obs_fut)
                if stats_fut is not None:
                    self._inflight_futs.append(stats_fut)
                if dig_fut is not None:
                    self._inflight_futs.append(dig_fut)
            batch = []
            for d in plan["finished"]:
                s = slots[d["slot"]]
                batch.append(
                    {
                        "slot": s.index,
                        "req": s.req,
                        "names": names,
                        "stats_fut": stats_fut,
                        "stats_names": stats_names,
                        "dig_fut": dig_fut,
                        "steps": int(d["steps"]),
                        "finished_wall": time.time(),
                        "step": runner.step,
                    }
                )
                self._release(ens, s)
            if self._is_root():
                self._pending_results.append((obs_fut, batch))
        for d in plan["dead"]:
            self._handle_death(runner, ens, slots[d["slot"]], int(d["steps"]))

    def _release(self, ens, slot: _Slot) -> None:
        """Lane back to idle (masked dead until refilled)."""
        ens.serve_meta[slot.index] = None
        ens.mark_dead([slot.index])
        slot.req = None
        slot.target = 0
        slot.base = 0
        slot.time_base = 0.0

    def _park_member(self, req, state, base: int, time_base: float) -> None:
        """Park one mid-flight member state for later continuation (an
        elastic shrink, a dt re-bucket, a QoS preemption).  Always held in
        memory — the fast path for a park re-claimed by THIS process — and
        in fleet mode ALSO persisted through the two-phase continuation
        writer into the shared ``parked/<id>/`` dir, so requeue-with-state
        survives replica SIGKILL: any replica resumes the trajectory
        mid-flight instead of restarting it from step 0.  (On a
        multi-process replica the persist is collective, and every host
        reaches it through the same broadcast plan that parked the lane.)"""
        self._parked[req.id] = (state, int(base), float(time_base))
        if self._fleet is None or not self._fleet.durable_park:
            return
        self._write_continuation(req, state, int(base), float(time_base))

    def _write_continuation(self, req, state, base: int, time_base: float) -> bool:
        """Persist one member state into the shared ``parked/<id>/``
        continuation dir (two-phase; collective on multi-process — every
        host reaches this through a broadcast plan)."""
        cdir = checkpoint.continuation_dir(self.cfg.run_dir, req.id)
        try:
            checkpoint.write_continuation(
                cdir,
                state,
                base=int(base),
                time_base=float(time_base),
                meta={
                    "id": req.id,
                    "dt": float(req.dt),
                    # the sub-mesh stamp rides the manifest so a resuming
                    # gang can verify the parked shards' topology matches
                    # the bucket it re-forms under (checkpoint.
                    # continuation_record reads it back)
                    "submesh": int(getattr(req, "submesh", 0)),
                },
            )
        except (checkpoint.CheckpointError, OSError) as exc:
            # degrade to the PR-10 behavior (in-memory park + queued
            # record): the request survives, only the mid-flight resume
            # across a replica death is lost for this persist
            self._journal(
                {
                    "event": "continuation_persist_failed",
                    "id": req.id,
                    "error": str(exc),
                }
            )
            return False
        self._continuations += 1
        _tm.counter(
            "serve_continuations_persisted_total",
            "parked member states persisted into parked/<id>/ dirs",
        ).inc()
        self._journal(
            {
                "event": "continuation_persisted",
                "id": req.id,
                "trace_id": req.trace_id,
                "steps": int(base),
                "time": float(time_base),
            }
        )
        return True

    def _persist_running_continuations(self, ens, slots: list[_Slot]) -> None:
        """Fleet cadence persist: flow every RUNNING slot's member state
        into its ``parked/<id>/`` continuation dir, so a replica SIGKILL
        loses at most one cadence window of progress — the survivor that
        breaks our lease re-claims the requests and resumes them
        MID-FLIGHT from this state (campaign checkpoints cannot serve
        that role: they live under the dead replica's private subtree and
        restore only onto its exact slot geometry).  The cadence verdict
        is root-decided and broadcast (wall clocks are host-local); the
        per-slot work then executes identically everywhere."""
        running = [s for s in slots if s.running]
        if not running:
            return
        cadence = self._fleet.resolved_heartbeat()
        due = bool(
            self._root_plan(
                lambda: (time.monotonic() - self._cont_mark) > cadence
            )
        )
        if not due:
            return
        self._cont_mark = time.monotonic()
        done = np.asarray(ens.steps_done)  # lint-ok: RPD005 replicated (K,) host-fetched counter, identical per host
        for s in running:
            state = ens.member_state(s.index)  # device op, all hosts
            self._write_continuation(
                s.req,
                state,
                s.base + int(done[s.index]),
                s.time_base + int(done[s.index]) * float(s.req.dt),
            )

    def _load_continuation(self, req, ens, slot_index: int):
        """Restore one durable continuation for a claimed request (the
        cross-replica resume path: the park was made by a replica that is
        gone).  None on verification failure.  The caller must agree the
        use/degrade verdict ACROSS HOSTS before acting (a per-host fall
        back would hand different lanes different states) — so success is
        journaled there, not here."""
        cdir = checkpoint.continuation_dir(self.cfg.run_dir, req.id)
        rec = checkpoint.continuation_record(cdir)
        if rec is not None:
            # topology fence for gang parks: a SHARDED continuation only
            # resumes into a bucket of the same sub-mesh stamp — a fleet
            # that re-carved under the park degrades to a fresh
            # trajectory instead of reading shards at the wrong geometry
            want = int(getattr(req, "submesh", 0) or 0)
            got = int((rec.get("meta") or {}).get("submesh", 0) or 0)
            if got != want:
                self._journal(
                    {
                        "event": "continuation_restore_failed",
                        "id": req.id,
                        "error": (
                            f"sub-mesh stamp mismatch: parked at {got}, "
                            f"bucket wants {want}"
                        ),
                    }
                )
                return None
        template = ens.member_state(slot_index)
        try:
            state, _, _ = checkpoint.read_continuation(cdir, template)
        except checkpoint.CheckpointError as exc:
            self._journal(
                {
                    "event": "continuation_restore_failed",
                    "id": req.id,
                    "error": str(exc),
                }
            )
            return None
        return state

    def _continuation_agreed(self, ok: bool) -> bool:
        """Every host restored its continuation shard, fleet-agreed: the
        allgather makes the degrade verdict identical everywhere (one
        host's torn shard must not leave it on a fresh trajectory while
        its peers resume mid-flight).  Identity single-process."""
        if self._nproc() == 1:
            return ok
        from ..parallel import multihost

        flags = multihost.allgather_host(
            np.asarray([1 if ok else 0], np.uint8)
        )
        return bool(np.asarray(flags).all())  # lint-ok: RPD005 allgather output is host numpy already

    def _retire_continuation(self, req) -> None:
        """Root-only cleanup once a request terminally resolved (or
        discarded its trajectory): the parked continuation no longer
        describes anything resumable."""
        if self._fleet is None or not self._is_root():
            return
        checkpoint.remove_continuation(
            checkpoint.continuation_dir(self.cfg.run_dir, req.id)
        )

    def _settle_predivergence(
        self, runner, ens, slots: list[_Slot], key: tuple
    ) -> None:
        """Per-bucket governed dt (``cfg.stability``): the sentinel chunk
        tripped the hard CFL ceiling and was already rolled back in memory
        — every member is still FINITE.  Root sizes the drop on the
        bucket's :class:`~rustpde_mpi_tpu.utils.governor.DtLadder` (rung
        floats are exact, so every re-bucketed request lands in the SAME
        new bucket and co-batches there) and broadcasts the plan; the
        pinned requests are requeued WITH their state (parked, like an
        elastic shrink) at the new rung, journal-typed ``bucket_dt_adjust``.
        A ladder with no rung left falls back to the reactive per-request
        retry path — the proactive ladder sits ABOVE it, never replaces it."""
        status = ens.last_chunk_status
        stab = self.cfg.stability
        done = np.asarray(ens.steps_done)

        def decide():
            from ..utils.governor import DtLadder

            bucket_dt = float(ens.get_dt())
            pinned = [
                s
                for s in slots
                if s.running and status.pinned and status.pinned[s.index]
            ]
            new_dt = rung = None
            floor = stab.dt_min
            if floor is None or bucket_dt > floor * (1.0 + 1e-12):
                ladder = DtLadder(
                    bucket_dt,
                    ratio=stab.ladder_ratio,
                    dt_min=floor,
                    dt_max=bucket_dt,
                )
                down = ladder.rungs_to_target(status.cfl_max, stab.target_cfl)
                rung = ladder.clamp(-down)
                new_dt = ladder.dt(rung) if rung < 0 else None
            return {
                "new_dt": new_dt,
                "rung": rung,
                "cfl": float(status.cfl_max),
                "slots": [
                    {
                        "slot": s.index,
                        "steps": s.base + int(done[s.index]),
                        "time": s.time_base
                        + int(done[s.index]) * float(s.req.dt),
                    }
                    for s in pinned
                ],
            }

        plan = self._root_plan(decide)
        for entry in plan["slots"]:
            s = slots[entry["slot"]]
            if plan["new_dt"] is None:
                # ladder exhausted (dt_min floor): the reactive per-request
                # dt-backoff/terminal-failure policy takes over
                self._handle_death(runner, ens, s, int(entry["steps"]))
                continue
            req = s.req
            state = ens.member_state(s.index)  # finite: rolled-back chunk
            self._release(ens, s)
            self._park_member(req, state, int(entry["steps"]), float(entry["time"]))
            if self._is_root():
                self.queue.requeue(
                    req.rebucketed(plan["new_dt"], progress=int(entry["steps"]))
                )
            self._dt_adjusts += 1
            _tm.counter(
                "serve_bucket_dt_adjusts_total",
                "proactive per-bucket dt re-buckets",
            ).inc()
            _tm.gauge(
                "serve_bucket_dt_rung",
                "ladder rung of the latest dt re-bucket (relative, <0)",
            ).set(plan["rung"])
            self._journal(
                {
                    "event": "bucket_dt_adjust",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": entry["slot"],
                    "prev_dt": float(req.dt),
                    "dt": plan["new_dt"],
                    "rung": plan["rung"],
                    "cfl": plan["cfl"],
                    "steps_done": entry["steps"],
                }
            )
        ens.clear_pre_divergence()

    def _handle_death(self, runner, ens, slot: _Slot, steps_done: int) -> None:
        """Per-request divergence policy: bounded dt-backoff retry, then
        the typed terminal state.  The lane itself is immediately reusable
        — one member's NaN never perturbs its co-batched neighbours."""
        req = slot.req
        self._release(ens, slot)
        # a diverged trajectory is not worth resuming: whatever durable
        # continuation described it is poison for the retry (which
        # restarts from a fresh IC at a smaller dt) and noise after a
        # terminal failure — retire it either way
        self._retire_continuation(req)
        if req.retries < self.cfg.request_max_retries:
            retry = req.backed_off(self.cfg.request_dt_backoff)
            if self._is_root():
                self.queue.requeue(retry)
            self._retried += 1
            _tm.counter(
                "serve_requests_retried_total", "diverged requests re-queued backed off"
            ).inc()
            self._journal(
                {
                    "event": "request_retry",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": slot.index,
                    "steps_done": steps_done,
                    "dt": retry.dt,
                    "retries": retry.retries,
                }
            )
        else:
            reason = (
                f"diverged at member-step {steps_done}/{req.steps} and "
                f"exhausted {self.cfg.request_max_retries} retries"
            )
            if self._is_root():
                self.queue.fail(req, reason)
            self._failed += 1
            _tm.counter(
                "serve_requests_failed_total", "requests in the typed terminal state"
            ).inc()
            self._journal(
                {
                    "event": "request_failed",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": slot.index,
                    "reason": reason,
                    "dts": req.dts,
                }
            )

    def _flush_results(self, force: bool = False) -> None:
        """Resolve finished-request observable futures and write the done
        records.  Non-blocking by default (a future still in flight stays
        pending — the stream, not the device, waits); ``force`` resolves
        everything (campaign end / server stop).  Root-only: results and
        the queue belong to root."""
        if not self._is_root():
            return
        keep = []
        for fut, batch in self._pending_results:
            if not force and not fut.ready():
                keep.append((fut, batch))
                continue
            values = fut.result()
            for item in batch:
                req: SimRequest = item["req"]
                i = item["slot"]
                # result scalars carry the MODEL's observable vocabulary
                # (dns: nu/nuvol/re/div; lnse: energy/ke/te/div; adjoint:
                # res/res_u/res_t/div) — recorded under those names
                names = item["names"]
                result = {
                    name: float(vals[i]) for name, vals in zip(names, values)
                }
                result.update(
                    {
                        "model": str(req.model),
                        "steps": item["steps"],
                        "dt": float(req.dt),
                        # the QoS contract's accounting axes: per-class
                        # latency percentiles in the fleet bench read these
                        "tenant": str(req.tenant),
                        "priority": str(req.priority),
                        "deadline_s": (
                            float(req.deadline_s)
                            if req.deadline_s is not None
                            else None
                        ),
                        "seed": int(req.seed),
                        # IC amplitude rides the record so solo-equivalence
                        # checks rerun the exact trajectory
                        "amp": float(req.amp) if req.amp else None,
                        "retries": int(req.retries),
                        "slot": i,
                        "latency_s": round(
                            item["finished_wall"] - req.submitted_s, 6
                        ),
                    }
                )
                # the HA front-door gate metric: durable-queue enqueue to
                # the FIRST streamed observable for this request (the
                # result values just fetched are that first observable —
                # later than finished_wall, which only marks the device
                # reaching the step target)
                first_obs_s = max(
                    0.0, time.time() - (req.enqueued_s or req.submitted_s)
                )
                result["admission_to_first_observable_s"] = round(
                    first_obs_s, 6
                )
                # per-request physics-stats summary (cfg.stats): the
                # member's health vector at completion time — samples, Nu
                # estimators, budget residuals, spectral-tail fractions
                sfut = item.get("stats_fut")
                if sfut is not None:
                    svals = sfut.result()
                    result["stats"] = {
                        name: float(np.asarray(v).reshape(-1)[i])  # lint-ok: RPD005 future already converted to host numpy
                        for name, v in zip(item["stats_names"], svals)
                    }
                # end-state integrity digest (cfg.integrity): a content
                # fingerprint of the member's final spectral state — the
                # fleet proxy's cross-replica vote compares two replicas'
                # digests for the same request to catch SDC neither
                # replica's own audits saw
                dfut = item.get("dig_fut")
                if dfut is not None:
                    result["state_digest"] = int(
                        np.asarray(dfut.result()).reshape(-1)[i]  # lint-ok: RPD005 future already converted to host numpy
                    )
                self.queue.complete(req, result)
                self._completed += 1
                _tm.counter(
                    "serve_requests_completed_total", "requests resolved into done/"
                ).inc()
                _tm.histogram(
                    "serve_request_latency_seconds",
                    "submit-to-finish latency per completed request",
                ).observe(result["latency_s"])
                _tm.histogram(
                    "serve_admission_to_first_observable_seconds",
                    "durable enqueue to first streamed observable",
                ).observe(first_obs_s)
                # the per-class view of the same clock: the QoS contract's
                # gate metric (interactive p99 under mixed traffic)
                _tm.histogram(
                    "serve_class_latency_seconds",
                    "enqueue to first observable per QoS priority class",
                    **{"class": str(req.priority)},
                ).observe(first_obs_s)
                self._retire_continuation(req)
                self._journal(
                    {
                        "event": "request_done",
                        "id": req.id,
                        "trace_id": req.trace_id,
                        "slot": i,
                        "steps": item["steps"],
                        names[0]: result[names[0]],
                        "latency_s": result["latency_s"],
                        "first_observable_s": result[
                            "admission_to_first_observable_s"
                        ],
                        "step": item["step"],
                    }
                )
        self._pending_results = keep

    def _drain_campaign(self, runner, ens, slots: list[_Slot], key: tuple = ()) -> None:
        """The graceful-drain path: flush resolved results, checkpoint the
        slot table + member states through the sharded two-phase writer
        (collective — every host is here together, the drain verdict was
        root-broadcast), then re-enqueue every unfinished request on root
        (progress stamped for the record; the checkpoint is what actually
        restores it).

        Under an ARMED preemption notice (``RUSTPDE_PREEMPT_NOTICE_S``,
        fleet mode) the drain turns urgent — park everything, release
        leases, exit: running slots persist as durable per-request
        continuations (O(slots) small two-phase writes, the exact state
        a lease-breaking survivor resumes from) instead of the sharded
        campaign checkpoint the notice window may not afford, and the
        trace/incident flushes are skipped when the remaining clock is
        short.  Both verdicts ride one root plan so every host takes the
        same branch; if the window still runs out, the SIGKILL that
        follows is the already-loss-free path."""

        def _plan():
            if self._notice_deadline is None:
                return [0, 1]
            remaining = self._notice_deadline - time.monotonic()
            return [1, 1 if remaining > 1.0 else 0]

        urgent, full_io = (
            (bool(v) for v in self._root_plan(_plan))
            if self._fleet is not None
            else (False, True)
        )
        self._log_preempt_notice()
        self._flush_results(force=True)
        _tr.instant("drain", step=runner.step)
        running = [s for s in slots if s.running]
        done = np.asarray(ens.steps_done)
        path = None
        if running and not urgent:
            path = runner.checkpoint_now("drain")
        if running and urgent:
            for s in running:
                state = ens.member_state(s.index)
                self._write_continuation(
                    s.req,
                    state,
                    s.base + int(done[s.index]),
                    s.time_base + int(done[s.index]) * float(s.req.dt),
                )
            if self._gang_active is not None:
                # the gang's SHARDED state just went through the same
                # two-phase continuation writer (one shard per member):
                # the whole gang parks as a unit inside the notice window
                self._journal(
                    {
                        "event": "gang_parked",
                        "key": list(key),
                        "gang": self._gang_active.get("gang"),
                        "generation": self._gang_active.get("generation"),
                        "slots": len(running),
                    }
                )
        for s in running:
            req = dataclasses.replace(
                s.req, progress=s.base + int(done[s.index])
            )
            if self._is_root():
                self.queue.requeue(req)
            self._journal(
                {
                    "event": "request_requeued",
                    "id": req.id,
                    "trace_id": req.trace_id,
                    "slot": s.index,
                    "progress": req.progress,
                    "target": s.target,
                    "checkpoint": path,
                    **({"parked": True} if urgent else {}),
                }
            )
        runner._drain_io()
        if full_io:
            # the drained campaign's request-trace events must land durably
            # NOW (this incarnation is about to exit — the gather is
            # collective and every host reaches this drain path together)
            self._flush_reqtrace(runner, key)
            # the SIGTERM-drain incident ships with its timeline, like the
            # standalone runner's preempt path
            runner.incident_dump("drain")
