"""Library entry point for launcher-spawned replicas: ``python -m
rustpde_mpi_tpu.serve.fleet.replica_main --run-dir <dir> --replica-id
<rid> [--daemon] ...`` builds a fleet-mode :class:`SimServer` and serves
until drained (or signalled).  This is what
:class:`~rustpde_mpi_tpu.serve.fleet.launcher.LocalProcessLauncher`
execs — the examples drivers stay thin wrappers over the same flags.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run-dir", required=True, help="shared fleet run_dir")
    p.add_argument("--replica-id", required=True, help="stable replica id")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--chunk-steps", type=int, default=4)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--ckpt-every-s", type=float, default=None)
    p.add_argument("--lease-ttl-s", type=float, default=None)
    p.add_argument("--heartbeat-s", type=float, default=None)
    p.add_argument("--quota", type=int, default=None)
    p.add_argument("--preempt-slack-s", type=float, default=30.0)
    p.add_argument(
        "--daemon",
        action="store_true",
        help="keep serving after the queue drains (idle_exit=False)",
    )
    p.add_argument("--fault", default=None, help="chaos spec (RUSTPDE_FAULT)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from ... import config as _config
    from ...config import FleetConfig, ServeConfig
    from ..scheduler import SimServer

    # arm the persistent compile cache before the first jit: a spawned
    # (scale-out) replica inherits the fleet's cache dir from the launcher
    # env and boots warm against the serialized executables
    _config.ensure_compile_cache()

    cfg = ServeConfig(
        run_dir=args.run_dir,
        slots=args.slots,
        chunk_steps=args.chunk_steps,
        max_queue=args.max_queue,
        checkpoint_every_s=args.ckpt_every_s,
        idle_exit=not args.daemon,
        http_port=None,
        fleet=FleetConfig(
            replica_id=args.replica_id,
            lease_ttl_s=args.lease_ttl_s,
            heartbeat_s=args.heartbeat_s,
            default_quota=args.quota,
            preempt_slack_s=args.preempt_slack_s,
        ),
    )
    summary = SimServer(cfg, fault=args.fault).serve()
    print(
        json.dumps(
            {
                "replica": args.replica_id,
                "outcome": summary.get("outcome"),
                "completed": summary.get("completed"),
                "failed": summary.get("failed"),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
