"""Gang scheduling: fate-shared lease groups for sub-mesh campaigns.

A GANG is the unit that serves one pencil-sharded bucket: K ensemble
members × one sharded grid, gang-scheduled onto a carved sub-mesh
(parallel/submesh.py).  Its failure contract is fate-sharing — the gang
runs as a whole and dies as a whole:

* **One gang lease** over the bucket (key ``("gang",) + serve_key``)
  authorizes the campaign; **per-member leases** (``("gang-member", i) +
  serve_key``) carry individual fencing tokens so a survivor that breaks
  the gang fences EVERY member's writes, not just the root's.  All token
  escrows are per-tag and never move backward (lease.py), so member
  tokens stay monotonic across gang GENERATIONS — generation = the gang
  lease's own fencing token.
* **Formation is all-or-nothing**: if any member lease cannot be claimed
  the partial claims are rolled back and :meth:`GangLease.form` reports
  failure — there is never a half-formed gang holding real capacity.
* **Breaking is gang-first**: :func:`break_gang` breaks the GROUP lease
  before any member lease.  The group break is the linearization point
  (``os.replace`` — exactly one breaker wins); member breaks after it
  are cleanup, and a member mid-renew loses to the breaker through the
  ordinary escrow fence (`Lease.renew`'s post-write re-check).

The other half of fate-sharing is the BARRIER: a sharded step is a
collective, and a dead member turns every survivor's next collective
into a silent forever-hang.  :func:`gang_sync` is the campaign barrier
with its own (tighter) watchdog — ``RUSTPDE_GANG_SYNC_TIMEOUT_S`` — that
converts the hang into a typed :class:`GangMemberLost` the scheduler can
contain: break own gang lease, requeue-with-state, keep co-resident
sub-meshes streaming.
"""

from __future__ import annotations

from ...config import env_get
from ...parallel import multihost
from .lease import Lease, LeaseLost, LeaseManager, bucket_tag


class GangMemberLost(RuntimeError):
    """A gang member stopped participating (missed the gang barrier or
    was fenced): the GANG is dead as a unit.  The holder must park what
    it can host-locally, break only its own gang lease, and requeue the
    bucket's requests — co-resident sub-meshes are untouched."""

    def __init__(self, tag: str, member: int | None, detail: str):
        who = f"member {member}" if member is not None else "a member"
        super().__init__(f"gang {tag}: {who} lost: {detail}")
        self.tag = tag
        self.member = member
        self.detail = detail


def gang_key(key: tuple) -> tuple:
    """The gang (group) lease key for one serve bucket."""
    return ("gang",) + tuple(key)


def member_key(key: tuple, member: int) -> tuple:
    """The per-member lease key: distinct tag per member, so each member
    carries its own fencing token under the shared gang generation."""
    return ("gang-member", int(member)) + tuple(key)


class GangLease:
    """One formed gang: the group lease plus K member leases, claimed and
    released as a unit through a shared :class:`LeaseManager`.

    The scheduler holds exactly one of these per gang campaign; every
    heartbeat renews group-then-members (:meth:`renew`), and any
    :class:`LeaseLost` from any constituent lease is raised as-is — the
    caller treats it exactly like a bucket-lease fence today."""

    def __init__(self, mgr: LeaseManager, key: tuple, group: Lease,
                 members: list[Lease]):
        self.mgr = mgr
        self.key = tuple(key)
        self.tag = bucket_tag(gang_key(key))
        self.group = group
        self.members = list(members)

    @property
    def generation(self) -> int:
        """The gang generation = the group lease's fencing token: strictly
        increases every time the gang is re-formed (escrow-monotonic)."""
        return self.group.token

    @classmethod
    def form(cls, mgr: LeaseManager, key: tuple, k: int) -> "GangLease | None":
        """All-or-nothing formation: claim the group lease, then every
        member lease.  Any failure rolls the partial claims back (release,
        not break — our own tokens go to escrow so the next generation's
        tokens still advance) and returns None."""
        group = mgr.claim(gang_key(key))
        if group is None:
            return None
        members: list[Lease] = []
        for i in range(int(k)):
            m = mgr.claim(member_key(key, i))
            if m is None:
                for held in members:
                    try:
                        held.release()
                    except (LeaseLost, OSError):
                        pass
                try:
                    group.release()
                except (LeaseLost, OSError):
                    pass
                return None
            members.append(m)
        return cls(mgr, key, group, members)

    def renew(self) -> None:
        """Heartbeat the whole gang, GROUP FIRST: if a survivor broke the
        gang, the group renew fences before any member write happens —
        members never outlive their gang by even one heartbeat."""
        self.group.renew()
        for m in self.members:
            m.renew()

    def renew_member(self, member: int) -> None:
        """Renew one member under the gang's authority: guard the group
        lease first (a broken gang fences the member immediately), then
        renew the member's own lease.  In the break-vs-renew race exactly
        one side wins: the breaker's ``os.replace`` or this renew's
        escrow re-check decides, never both."""
        self.group.guard()
        self.members[int(member)].renew()

    def guard(self) -> None:
        """Fencing check over the whole gang (cheap reads, no writes)."""
        self.group.guard()
        for m in self.members:
            m.guard()

    def release(self) -> None:
        """Clean hand-back, members first then group — the group lease is
        the last thing standing, so an observer never sees a groupless
        member.  Escrow advances for every tag (token monotonicity)."""
        err: Exception | None = None
        for m in self.members:
            try:
                m.release()
            except LeaseLost as exc:
                err = exc
        try:
            self.group.release()
        except LeaseLost as exc:
            err = exc
        if err is not None:
            raise err


def break_gang(mgr: LeaseManager, key: tuple, k: int) -> dict | None:
    """Break a dead gang as a unit, group lease FIRST: the group break is
    the single linearization point (one winner), then every member lease
    is broken as cleanup — their escrows advance so the next generation's
    member tokens are strictly greater.  Returns the broken group record,
    or None when a peer won the break race (the peer does the member
    cleanup too)."""
    rec = mgr.break_lease(bucket_tag(gang_key(key)))
    if rec is None:
        return None
    for i in range(int(k)):
        mgr.break_lease(bucket_tag(member_key(key, i)))
    return rec


def stale_gangs(mgr: LeaseManager, max_members: int = 64) -> list[dict]:
    """Sweep helper: break every stale GANG lease (group-first fate
    sharing) and return the broken group records.  Member leases of a
    broken gang are broken unconditionally — a live-looking member of a
    dead gang is still dead (fate-sharing is the contract)."""
    broken = []
    for tag, rec in mgr.holders().items():
        bucket = rec.get("bucket") or []
        if not (isinstance(bucket, list) and bucket[:1] == ["gang"]):
            continue
        if not mgr.stale(tag):
            continue
        got = mgr.break_lease(tag)
        if got is None:
            continue
        key = multihost.tuplify(bucket[1:])
        for i in range(int(max_members)):
            mtag = bucket_tag(member_key(key, i))
            if mtag not in mgr.holders():
                break
            mgr.break_lease(mtag)
        broken.append(got)
    return broken


def gang_sync_timeout_s() -> float:
    """The gang-barrier watchdog deadline: ``RUSTPDE_GANG_SYNC_TIMEOUT_S``
    (seconds; 0 = disabled, fall back to the job-wide sync behavior)."""
    return float(env_get("RUSTPDE_GANG_SYNC_TIMEOUT_S", "0") or 0.0)


def gang_sync(tag: str, gang_tag: str, member: int | None = None) -> None:
    """The gang barrier: a cross-host sync fence with the GANG watchdog
    armed.  A peer that never arrives (SIGKILLed member) trips the
    watchdog and surfaces as a typed :class:`GangMemberLost` instead of a
    wedged collective — the difference between one dead sub-mesh and a
    wedged fleet."""
    timeout = gang_sync_timeout_s()
    from ...utils.resilience import DispatchHang

    try:
        multihost.sync_hosts(tag, timeout_s=timeout if timeout > 0 else None)
    except DispatchHang as exc:
        raise GangMemberLost(
            gang_tag, member, f"barrier {tag!r} timed out: {exc}"
        ) from exc
