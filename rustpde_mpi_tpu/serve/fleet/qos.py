"""The fleet traffic contract: tenants, priority classes, deadlines.

Pure host-side policy — no device work, no collectives, no file writes.
The scheduler calls these helpers from inside its root-plan closures (the
decisions are broadcast like every other scheduling verdict) and the
stateless proxy calls them at admission; both journal the outcomes
themselves.

Four levers:

* **sub-mesh admission** — :func:`admit_submesh` stamps sharded-grid
  requests with the sub-mesh shape they gang onto (two-level serving)
  and converts the two mismatch shapes into typed rejects: permanently
  unservable grids into ``reason="no_submesh"`` 400s, transient sharded
  backlog into ``reason="capacity"`` 429s with a queue-depth-derived
  ``Retry-After``,
* **per-tenant quotas** — :func:`check_quota` bounds one tenant's
  queued+running footprint; past it the submit is rejected with the typed
  ``reason="quota"`` :class:`~rustpde_mpi_tpu.serve.AdmissionError`
  (HTTP: 429 + ``Retry-After`` + the live queue depth), so one noisy
  tenant degrades into clean backpressure instead of starving the fleet,
* **priority-ordered scheduling** — :func:`bucket_order` replaces the
  single-replica FIFO/round-robin bucket pick: buckets sort by the best
  priority class waiting in them, then by the tightest deadline slack,
  then by arrival.  Within a bucket the queue's ``claim(qos=True)``
  applies the same order to individual requests,
* **deadline-driven preemption** — :func:`find_at_risk` flags the queued
  interactive request whose remaining slack dropped below the configured
  threshold; :func:`preempt_victims` picks the running best-effort lanes
  to park for it (requeue-WITH-state through the durable continuation
  machinery, so preemption is loss-free).  Only strictly-lower classes
  are ever victims: interactive preempts best-effort, batch preempts
  nothing and is preempted by nothing.
"""

from __future__ import annotations

import dataclasses
import time

from ...parallel import submesh as _submesh
from ..request import AdmissionError, RequestError, SimRequest

#: wall-step threshold for the deadline clock: steps smaller than this are
#: ordinary NTP slew/drift the deadline math can absorb; larger ones are
#: corrections that would blow every queued deadline at once
CLOCK_STEP_THRESHOLD_S = 30.0


def qos_now() -> float:
    """The deadline clock: wall time, with forward steps compensated for
    the detecting scan (fleet/clock.py — one-shot ``clock_skew`` warning,
    then the step is absorbed as the new normal).  Without this, an NTP
    forward correction would flag every queued deadline as at-risk in the
    same boundary and preemption would evict the whole best-effort tier
    for requests that were comfortably on time a second earlier."""
    from .clock import MONITOR

    now = time.time()
    skew = MONITOR.check(CLOCK_STEP_THRESHOLD_S, where="qos_deadlines")
    return now - skew if skew > 0.0 else now


def admit_submesh(
    req: SimRequest, pending_sharded: int, cfg
) -> SimRequest:
    """Two-level-serving admission (parallel/submesh.py): stamp ``req``
    with the sub-mesh device count its grid needs, or reject it typed.

    ``cfg`` is the service's :class:`~rustpde_mpi_tpu.config.SubmeshConfig`
    (None = feature off: the request passes through untouched, byte-
    identical default).  Small grids stay unstamped (vmapped traffic).  A
    grid at/above the sharding threshold that fits NO configured shape is
    a permanent mismatch for this service — typed ``reason="no_submesh"``
    :class:`RequestError` (HTTP 400) at POST time, not a durable poison
    pill that wedges every later serve pass.  A grid that DOES fit but
    finds the sharded backlog at ``max_pending`` is a transient capacity
    reject — ``reason="capacity"`` :class:`AdmissionError` (HTTP 429)
    whose ``Retry-After`` scales with the live sharded queue depth.
    ``pending_sharded`` is the caller's census of queued stamped requests.
    """
    if cfg is None:
        return req
    shape = _submesh.shape_for(int(req.nx), int(req.ny), cfg)
    if shape == 0:
        return req
    if shape < 0:
        raise RequestError(
            f"grid {req.nx}x{req.ny} needs sharding (>= {cfg.shard_min_nx}"
            f" points) but fits none of the configured sub-mesh shapes "
            f"{tuple(cfg.shapes)}",
            reason="no_submesh",
        )
    pending = int(pending_sharded)
    if pending >= int(cfg.max_pending):
        raise AdmissionError(
            "capacity",
            f"{pending} sharded requests already queued "
            f"(max_pending={cfg.max_pending}); retry once gangs drain",
            retry_after_s=2.0 * max(1, pending),
        )
    if int(req.submesh) == shape:
        return req
    return dataclasses.replace(req, submesh=shape)


def check_quota(req: SimRequest, tenant_counts: dict, fleet_cfg) -> None:
    """Raise the typed quota rejection when ``req``'s tenant is at its
    bound (``tenant_counts`` is the queue's queued+running census)."""
    quota = fleet_cfg.resolved_quota(req.tenant)
    if quota is None:
        return
    held = int(tenant_counts.get(req.tenant, 0))
    if held >= quota:
        raise AdmissionError(
            "quota",
            f"tenant {req.tenant!r} holds {held}/{quota} queued+running "
            "requests; retry after some resolve",
            retry_after_s=2.0,
        )


def bucket_order(loaded: list, now: float | None = None) -> list[tuple]:
    """Distinct bucket keys ordered by the QoS contract: best waiting
    priority class first, tightest deadline slack second, oldest arrival
    third.  ``loaded`` is the queue's ``(name, SimRequest)`` scan (names
    sort by enqueue time by construction)."""
    now = qos_now() if now is None else now
    best: dict[tuple, list] = {}
    for name, req in loaded:
        cand = [req.class_rank, req.deadline_slack(now), name]
        cur = best.get(req.compat_key)
        if cur is None or cand < cur:
            best[req.compat_key] = cand
    return [k for k, _ in sorted(best.items(), key=lambda kv: kv[1])]


def find_at_risk(
    loaded: list, slack_s: float, now: float | None = None
) -> SimRequest | None:
    """The most urgent queued deadline-carrying request whose remaining
    slack is below ``slack_s`` — the preemption trigger.  None when every
    deadline still has room (the common case: preemption stays idle)."""
    now = qos_now() if now is None else now
    at_risk = [
        req
        for _, req in loaded
        if req.deadline_s is not None and req.deadline_slack(now) < slack_s
    ]
    if not at_risk:
        return None
    return min(at_risk, key=lambda r: (r.class_rank, r.deadline_slack(now)))


def preempt_victims(
    running: list, at_risk: SimRequest, current_key: tuple
) -> list[int]:
    """Slot indices to park for ``at_risk``: only lanes running a STRICTLY
    lower class are candidates (best-effort under an interactive emergency
    — batch is never preempted).  Same-bucket emergencies free exactly one
    lane (the at-risk request refills it this boundary); cross-bucket ones
    park every candidate lane, so the campaign drains toward its end and
    the priority-ordered bucket pick takes the urgent bucket next.
    ``running`` is ``[(slot_index, SimRequest), ...]``."""
    if at_risk.class_rank > 0:
        # only the interactive class may preempt: a late BATCH deadline
        # is a scheduling miss, not an emergency worth evicting for
        return []
    now = qos_now()
    victims = sorted(
        (
            (req.class_rank, req.deadline_slack(now), i)
            for i, req in running
            if req.class_rank > at_risk.class_rank
            and req.class_rank >= 2  # only the best-effort lane is fair game
        ),
        reverse=True,  # worst class first, then MOST slack (the lane best
    )  # able to absorb a park) — never the one nearest its own deadline
    if not victims:
        return []
    if tuple(at_risk.compat_key) == tuple(current_key):
        return [victims[0][2]]
    return [v[2] for v in victims]
