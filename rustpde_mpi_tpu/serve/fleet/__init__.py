"""Fleet layer: N stateless proxies + M ``SimServer`` replicas over ONE
shared durable queue — the highly-available front the ROADMAP's
"replicated front door" item asks for, built the way an LLM-serving
stack would and coordinated entirely through the queue's fsynced
atomic-rename lifecycle (no consensus service):

* :class:`~.proxy.FleetProxy` — stateless HTTP fronts: any number of
  them accept/answer against durable state, so reads and admission
  survive any single process death,
* :class:`~.lease.LeaseManager` / :class:`~.lease.Lease` — queue-level
  bucket leases with fencing tokens and observer-monotonic heartbeat
  staleness: a replica that stops heartbeating past the TTL has its
  leases broken by survivors, who re-claim its requests,
* :mod:`~.qos` — the traffic contract: per-tenant quotas (429 +
  Retry-After), priority classes ordering bucket selection, deadline
  slack, and loss-free preemption of best-effort lanes,
* durable parked continuations live in
  :mod:`rustpde_mpi_tpu.utils.checkpoint` (``write_continuation`` /
  ``read_continuation``): requeue-with-state survives replica SIGKILL.

Enable per replica via ``ServeConfig(fleet=FleetConfig(...))``; with
``fleet=None`` (the default) none of this machinery runs — zero extra
journal rows, zero extra collectives.
"""

from .autoscaler import Autoscaler  # noqa: F401
from .launcher import (  # noqa: F401
    LocalProcessLauncher,
    ReplicaHandle,
    ReplicaLauncher,
)
from .lease import Lease, LeaseLost, LeaseManager, bucket_tag  # noqa: F401
from .proxy import (  # noqa: F401
    FleetProxy,
    read_replica_status,
    write_replica_heartbeat,
)
from . import qos  # noqa: F401
