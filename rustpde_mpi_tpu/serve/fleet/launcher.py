"""Pluggable replica launchers: how the autoscaler turns a scale
decision into a running ``SimServer`` replica process (and back).

The interface is deliberately tiny — ``spawn`` / ``retire`` / ``kill`` /
``alive`` / ``reap`` over opaque :class:`ReplicaHandle` records — so a
cloud backend (spot VM APIs, a k8s ReplicaSet patch) can slot in behind
the same :class:`~rustpde_mpi_tpu.serve.fleet.autoscaler.Autoscaler`
control loop.  The shipped :class:`LocalProcessLauncher` runs replicas
as local subprocesses over ``python -m
rustpde_mpi_tpu.serve.fleet.replica_main`` — the backend the chaos soaks
and the examples drive.

Retirement is a SIGTERM, never a SIGKILL: the replica's own drain path
(durable park of running slots, lease release, clean exit — urgent when
``RUSTPDE_PREEMPT_NOTICE_S`` arms the notice window) is the loss-free
mechanism; the launcher only delivers the signal.  ``kill`` exists for
chaos injection and last-resort cleanup.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field


@dataclass
class ReplicaHandle:
    """One launched replica as the launcher tracks it: identity, the
    backend's process (None for remote backends), and bookkeeping the
    autoscaler's spawn-grace window reads."""

    replica_id: str
    pid: int | None = None
    proc: object = None  # subprocess.Popen for the local backend
    spawned_mono: float = field(default_factory=time.monotonic)
    retired: bool = False


class ReplicaLauncher:
    """Backend interface the autoscaler drives.  Implementations own the
    mechanics of replica creation/destruction; the control law, journal
    and gauges stay in the autoscaler."""

    def spawn(self, replica_id: str) -> ReplicaHandle:
        """Start one replica under ``replica_id``; return its handle."""
        raise NotImplementedError

    def retire(self, handle: ReplicaHandle) -> None:
        """Ask one replica to drain and exit (graceful — the replica
        parks its running slots and releases its leases itself)."""
        raise NotImplementedError

    def kill(self, handle: ReplicaHandle) -> None:
        """Hard-stop one replica (chaos / cleanup; loss-free only
        because the fleet's lease-break + continuation machinery is)."""
        raise NotImplementedError

    def alive(self, handle: ReplicaHandle) -> bool:
        """Is the replica's backend process still running?"""
        raise NotImplementedError

    def reap(self) -> list[ReplicaHandle]:
        """Collect exited replicas; return their handles."""
        raise NotImplementedError

    # -- gang-shaped capacity (two-level serving) -------------------------------

    def spawn_gang(self, replica_ids: list[str]) -> list[ReplicaHandle]:
        """Spawn a fate-shared replica group ALL-OR-NOTHING: either every
        id comes up or the partial gang is killed and the spawn failure
        re-raised.  A lone gang member is worse than no gang — it claims
        a member lease and then wedges the sub-mesh collective its
        missing peers never join — so partial success is never returned
        (the same rollback contract ``GangLease.form`` makes for
        leases)."""
        handles: list[ReplicaHandle] = []
        try:
            for rid in replica_ids:
                handles.append(self.spawn(rid))
        except Exception:
            for handle in handles:
                try:
                    self.kill(handle)
                except Exception:  # noqa: BLE001 — rollback is best effort
                    pass
            raise
        return handles

    def retire_gang(self, handles: list[ReplicaHandle]) -> None:
        """Retire a whole gang together: every member gets the drain
        signal in one pass, so the gang parks as a unit (sharded state
        through the two-phase continuation writer) instead of one member
        draining while its peers block on the next collective."""
        for handle in handles:
            self.retire(handle)


class LocalProcessLauncher(ReplicaLauncher):
    """Local-subprocess backend: each replica is ``python -m
    rustpde_mpi_tpu.serve.fleet.replica_main --run-dir <run_dir>
    --replica-id <rid> --daemon`` inheriting this process's environment
    (JAX platform pins ride along).  ``serve_args`` appends extra CLI
    flags (slots, chunk-steps, lease-ttl-s, ...); ``notice_s`` arms
    ``RUSTPDE_PREEMPT_NOTICE_S`` in the child so a retire SIGTERM drains
    urgently inside the notice window; ``log_dir`` captures per-replica
    stdout/stderr files for post-mortems."""

    def __init__(
        self,
        run_dir: str,
        *,
        serve_args: list[str] | None = None,
        notice_s: float | None = None,
        env: dict | None = None,
        log_dir: str | None = None,
        python: str | None = None,
    ):
        self.run_dir = run_dir
        self.serve_args = list(serve_args or [])
        self.notice_s = notice_s
        self.env = dict(os.environ if env is None else env)
        if notice_s is not None:
            self.env["RUSTPDE_PREEMPT_NOTICE_S"] = str(float(notice_s))
        # replicas must share the fleet's persistent compile cache: a
        # scale-out spawn then deserializes the executables peers already
        # built instead of recompiling them (cold-start elimination) —
        # seed the arming vars into any custom ``env`` snapshot that lacks
        # them (an env=None copy of os.environ already carries them when
        # the parent armed the cache before constructing the launcher)
        from ... import config as _config

        for name, val in _config.compile_cache_env().items():
            self.env.setdefault(name, val)
        self.log_dir = log_dir
        self.python = python or sys.executable
        self._handles: dict[str, ReplicaHandle] = {}

    def handles(self) -> list[ReplicaHandle]:
        """Live view of every handle this launcher still tracks."""
        return list(self._handles.values())

    def spawn(self, replica_id: str) -> ReplicaHandle:
        argv = [
            self.python,
            "-m",
            "rustpde_mpi_tpu.serve.fleet.replica_main",
            "--run-dir",
            self.run_dir,
            "--replica-id",
            replica_id,
            "--daemon",
            *self.serve_args,
        ]
        stdout = stderr = subprocess.DEVNULL
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = stderr = open(  # noqa: SIM115 — owned by the child
                os.path.join(self.log_dir, f"{replica_id}.log"), "ab"
            )
        proc = subprocess.Popen(
            argv, env=self.env, stdout=stdout, stderr=stderr
        )
        if stdout is not subprocess.DEVNULL:
            stdout.close()  # the child holds its own descriptor now
        handle = ReplicaHandle(replica_id=replica_id, pid=proc.pid, proc=proc)
        self._handles[replica_id] = handle
        return handle

    def retire(self, handle: ReplicaHandle) -> None:
        handle.retired = True
        if handle.proc is not None and handle.proc.poll() is None:
            try:
                handle.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass  # already gone: reap() collects it

    def kill(self, handle: ReplicaHandle) -> None:
        handle.retired = True
        if handle.proc is not None and handle.proc.poll() is None:
            try:
                handle.proc.kill()
            except OSError:
                pass

    def alive(self, handle: ReplicaHandle) -> bool:
        return handle.proc is not None and handle.proc.poll() is None

    def reap(self) -> list[ReplicaHandle]:
        gone = [
            h for h in self._handles.values() if not self.alive(h)
        ]
        for h in gone:
            if h.proc is not None:
                h.proc.wait()  # immediate: poll() already returned
            del self._handles[h.replica_id]
        return gone

    def shutdown(self, timeout_s: float = 30.0) -> None:
        """Retire every tracked replica and wait for clean exits,
        escalating to kill at the deadline — the controller's own
        teardown path (SIGTERM on the controller retires its fleet)."""
        for h in self.handles():
            self.retire(h)
        deadline = time.monotonic() + float(timeout_s)
        for h in self.handles():
            if h.proc is None:
                continue
            remaining = deadline - time.monotonic()
            try:
                h.proc.wait(timeout=max(0.1, remaining))
            except subprocess.TimeoutExpired:
                self.kill(h)
                h.proc.wait()
        self.reap()
