"""Stateless HTTP proxy tier: the fleet's replicated front door.

PR 10 left HTTP ingestion root-only — one process, one crash, no door.
A :class:`FleetProxy` is a front-door process that holds NO request
state: every endpoint reads or writes the shared durable queue (the
fsynced atomic-rename lifecycle IS the coordination substrate) plus the
replica heartbeat files, so any number of proxies can run behind a dumb
TCP load-balancer and any single process death loses nothing::

    POST /requests        validate + QoS quota check + fsynced enqueue
                          -> 202 {"id","steps","trace_id"}; 429 + a
                          jittered queue-depth-derived Retry-After header
                          on rejection (queue_full / quota), 400
                          malformed, 413 big; when a bearer-token
                          allowlist is configured (``RUSTPDE_PROXY_TOKENS``
                          or ``auth_tokens=``), 401 ``auth_missing`` /
                          403 ``auth_invalid`` with constant-time compares
    GET  /requests/<id>   lifecycle record from durable state (404)
    GET  /requests/<id>/trace
                          cross-replica Perfetto timeline: proxy
                          admission + every replica's lifecycle rows +
                          campaign chunk spans stitched from the
                          ``replicas/<rid>/`` journals (one process lane
                          per journal source)
    GET  /stats           queue counts + per-tenant census + bucket
                          leases + replica heartbeat aggregation
    GET  /healthz         {"ok", "proxy", "queue", "replicas"} — a
                          proxy is healthy whenever the queue dir is;
                          replica liveness rides along for orchestrators
    GET  /metrics         Prometheus exposition of this proxy's registry

A submit is acknowledged only after the queue fsynced the request file —
the same durability contract the root front makes — and the ack is valid
even if every replica is momentarily dead: a replica that comes back (or
a survivor that breaks the dead one's leases) finds the request in the
shared queue.

**Replica heartbeats** (how stateless fronts answer "who is serving"):
each fleet-mode replica atomically rewrites
``<run_dir>/replicas/<id>.json`` every heartbeat with its stats
snapshot; :func:`read_replica_status` aggregates them with a staleness
verdict, and the proxy serves the aggregate on /stats and /healthz.
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import json
import os
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ...config import env_get
from ...telemetry import metrics as _tm
from ...telemetry.exporters import PROMETHEUS_CONTENT_TYPE, prometheus_text
from ...telemetry.reqtrace import assemble_fleet_request_trace
from ...utils.fsutil import atomic_write_text
from ...utils.journal import JournalWriter
from ..http_front import read_body, rejection_payload, reply_json, reply_text
from ..queue import DurableQueue
from ..request import AdmissionError, RequestError, SimRequest
from . import qos as _qos
from .lease import LeaseManager


def replicas_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "replicas")


def write_replica_heartbeat(run_dir: str, replica_id: str, payload: dict) -> None:
    """Atomically publish one replica's liveness + stats snapshot (tmp +
    rename + dirsync, like every durable write): proxies aggregate these
    files, so the write must never be observable half-done."""
    root = replicas_dir(run_dir)
    os.makedirs(root, exist_ok=True)
    record = {
        "replica": replica_id,
        "hb_unix": time.time(),
        "hb_mono": time.monotonic(),
        "pid": os.getpid(),
        **payload,
    }
    atomic_write_text(
        os.path.join(root, f"{replica_id}.json"),
        json.dumps(record, sort_keys=True),
    )


def read_replica_status(
    run_dir: str, ttl_s: float, journal=None
) -> list[dict]:
    """Every replica's last heartbeat, staleness-marked: ``stale`` is true
    when the heartbeat file has not been rewritten for ``ttl_s`` (file
    mtime vs this process's clock — display-grade; the authoritative
    failure detector is the lease sweep's observer-monotonic window).

    A heartbeat file that exists but won't parse (torn/truncated JSON —
    a crashed writer, a reader racing a non-atomic copy tool) is NOT a
    missing replica: it surfaces as a ``stale`` + ``torn`` entry with a
    warning, so autoscalers and dashboards see a sick replica instead of
    silently forgetting one.  Files that vanish mid-scan (replica
    retirement unlinking its heartbeat) are still skipped.

    **Clock-step hardening** (fleet/clock.py): a wall clock that stepped
    FORWARD past the staleness window since the last scan would mark
    every replica stale at once — an NTP correction read as a fleet-wide
    death.  The shared :data:`~rustpde_mpi_tpu.serve.fleet.clock.MONITOR`
    detects the step against monotonic time, journals a one-shot
    ``clock_skew`` row (``journal`` optional), and this scan compensates
    ages by the step instead of mass-expiring; a BACKWARD step (mtimes
    ahead of our clock) clamps ages to zero rather than going negative."""
    from .clock import MONITOR

    root = replicas_dir(run_dir)
    out = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    now = time.time()
    skew = MONITOR.check(
        float(ttl_s), journal=journal, where="replica_heartbeats"
    )
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(root, name)
        try:
            age = now - os.stat(path).st_mtime
        except OSError:
            continue  # unlinked between listdir and stat
        if skew > 0.0:
            age -= skew  # forward step inflated every age by the step
        age = max(0.0, age)  # backward step / writer clock ahead of ours
        try:
            with open(path, encoding="utf-8") as fh:
                rec = json.load(fh)
        except OSError:
            continue
        except ValueError:
            warnings.warn(
                f"torn replica heartbeat {path}: treating as stale",
                RuntimeWarning,
                stacklevel=2,
            )
            out.append(
                {
                    "replica": name[: -len(".json")],
                    "torn": True,
                    "hb_age_s": round(age, 3),
                    "stale": True,
                }
            )
            continue
        rec["hb_age_s"] = round(age, 3)
        rec["stale"] = age > float(ttl_s)
        out.append(rec)
    return out


class FleetProxy:
    """One stateless front-door process over a shared fleet ``run_dir``.

    ``fleet`` (a :class:`~rustpde_mpi_tpu.config.FleetConfig`) supplies
    the QoS quotas and the staleness TTL for replica reporting; ``None``
    serves without quotas (pure pass-through admission).  ``start()``
    binds (port 0 = ephemeral, see ``address``), ``stop()`` shuts down.
    Thread-safe by construction: handlers touch only the (locked) queue
    object and read-only durable state.

    ``auth_tokens`` is the bearer-token allowlist for MUTATING endpoints
    (POST /requests): any presented token must match one entry under a
    constant-time compare.  ``None`` defaults from the comma-separated
    ``RUSTPDE_PROXY_TOKENS`` knob; an empty list serves open (the
    pre-auth behavior, and the right call behind a trusted LB).  Reads
    (/stats, /healthz, /metrics, GET /requests/*) stay open: they expose
    no tenant payloads and orchestrator probes must not need secrets."""

    def __init__(
        self,
        run_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        max_queue: int = 256,
        fleet=None,
        registry=None,
        auth_tokens: list[str] | None = None,
        submesh=None,
        vote_rate: float | None = None,
    ):
        self.run_dir = run_dir
        self.fleet = fleet
        self.submesh = submesh
        if vote_rate is None:
            try:
                vote_rate = float(env_get("RUSTPDE_VOTE_RATE") or "0")
            except ValueError:
                vote_rate = 0.0
        # cross-replica voting (the integrity tentpole's fleet check):
        # the fraction of admitted requests double-assigned as an
        # independent ".vote" twin whose done-record state digest is
        # compared against the original's (check_votes)
        self.vote_rate = min(1.0, max(0.0, float(vote_rate)))
        self._votes_seen: set[str] = set()
        if auth_tokens is None:
            raw = env_get("RUSTPDE_PROXY_TOKENS") or ""
            auth_tokens = [t.strip() for t in raw.split(",") if t.strip()]
        self.auth_tokens = tuple(auth_tokens)
        self.queue = DurableQueue(
            os.path.join(run_dir, "queue"), max_queue=int(max_queue)
        )
        pid = (
            fleet.resolved_replica_id()
            if fleet is not None
            else f"{os.getpid()}"
        )
        self.proxy_id = f"proxy-{pid}"
        self.ttl_s = fleet.resolved_ttl() if fleet is not None else 15.0
        self.registry = registry if registry is not None else _tm.default_registry()
        self._journal_writer = JournalWriter(
            os.path.join(replicas_dir(run_dir), self.proxy_id, "journal.jsonl")
        )
        self._leases = LeaseManager(
            os.path.join(run_dir, "queue", "leases"), self.proxy_id, self.ttl_s
        )
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-proxy", daemon=True
        )
        self._thread.start()
        self._journal(
            {"event": "proxy_listen", "address": list(self.address)}
        )

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._journal_writer.close()

    def _journal(self, event: dict) -> None:
        self._journal_writer.append({"proxy": self.proxy_id, **event})

    # -- the admission path (shared by every proxy endpoint handler) ----------

    def auth_check(self, headers) -> tuple[int, dict, dict] | None:
        """Bearer-token gate for mutating endpoints: ``None`` admits,
        else ``(status, payload, extra_headers)`` — 401 ``auth_missing``
        (no/malformed Authorization header, with a ``WWW-Authenticate``
        challenge) or 403 ``auth_invalid`` (well-formed but unknown
        token).  Every configured token is compared via
        :func:`hmac.compare_digest`, and ALL of them are always checked,
        so response timing leaks neither prefix matches nor which slot
        matched.  No tokens configured = open admission."""
        if not self.auth_tokens:
            return None
        presented = ""
        header = headers.get("Authorization") or ""
        if header.startswith("Bearer "):
            presented = header[len("Bearer ") :].strip()
        if not presented:
            code, reason = 401, "auth_missing"
            extra = {"WWW-Authenticate": "Bearer"}
        else:
            ok = False
            for token in self.auth_tokens:
                ok |= hmac.compare_digest(presented, token)
            if ok:
                return None
            code, reason, extra = 403, "auth_invalid", {}
        self.registry.counter(
            "fleet_auth_rejected_total",
            "mutating requests rejected by the proxy bearer-token gate",
            reason=reason,
        ).inc()
        self._journal({"event": "auth_rejected", "reason": reason})
        return code, {"error": "unauthorized", "reason": reason}, extra

    def submit(self, data: dict) -> SimRequest:
        """Validate + QoS-admit + durably enqueue one request.  The proxy
        NEVER talks to a replica: the fsynced queue file is the handoff.
        Raises RequestError (malformed) / AdmissionError (backpressure or
        quota)."""
        if not isinstance(data, dict):
            raise RequestError(
                f"request must be a JSON object, got {type(data).__name__}"
            )
        req = SimRequest.from_dict(data)
        req.validate()
        if (
            self.queue.dedupe_lookup(getattr(req, "idempotency_key", None))
            is not None
        ):
            # idempotent retry: skip quota/sub-mesh re-judgement, replay
            # the original submit's identity (queue._dedupe_into via
            # queue.submit — nothing is enqueued)
            req = self.queue.submit(req)
            return self._ack_deduped(req)
        if self.submesh is not None:
            # stamp sharded grids with their sub-mesh shape at the DOOR, so
            # every proxy and the root front bucket the same grid the same
            # way; permanent shape mismatches die here as typed 400s
            # instead of poisoning the durable queue
            self.queue.invalidate()
            pending = sum(
                1
                for _, queued in self.queue.snapshot_queued()
                if int(getattr(queued, "submesh", 0)) > 0
            )
            try:
                req = _qos.admit_submesh(req, pending, self.submesh)
            except (AdmissionError, ValueError) as exc:
                reason = getattr(exc, "reason", None)
                if reason not in ("no_submesh", "capacity"):
                    raise
                _tm.counter(
                    "fleet_submesh_rejected_total",
                    "submits rejected by sub-mesh admission",
                    reason=reason,
                ).inc()
                self._journal(
                    {
                        "event": "submesh_rejected",
                        "id": req.id,
                        "reason": reason,
                        "grid": [int(req.nx), int(req.ny)],
                    }
                )
                raise
        if self.fleet is not None:
            # stale cache is fine for a QUOTA (it only over/under-counts
            # by the race window), but refresh so peer-proxy submits count
            self.queue.invalidate()
            try:
                _qos.check_quota(req, self.queue.tenant_counts(), self.fleet)
            except AdmissionError as exc:
                _tm.counter(
                    "fleet_quota_rejected_total",
                    "submits rejected by per-tenant quota",
                    tenant=req.tenant,
                ).inc()
                self._journal(
                    {
                        "event": "quota_rejected",
                        "id": req.id,
                        "tenant": req.tenant,
                        "reason": exc.reason,
                    }
                )
                raise
        self.queue.submit(req)
        if getattr(req, "deduped", False):
            # lost a concurrent same-key race inside queue.submit
            return self._ack_deduped(req)
        _tm.counter(
            "fleet_proxy_admitted_total", "requests admitted via this proxy"
        ).inc()
        self._journal(
            {
                "event": "request_admitted",
                "id": req.id,
                "trace_id": req.trace_id,
                "tenant": req.tenant,
                "priority": req.priority,
                "key": list(req.compat_key),
                "via": "proxy",
            }
        )
        if self._vote_sampled(req):
            self._assign_vote(req)
        return req

    def _ack_deduped(self, req: SimRequest) -> SimRequest:
        _tm.counter(
            "fleet_proxy_deduped_total",
            "retries answered from the idempotency index via this proxy",
        ).inc()
        self._journal(
            {
                "event": "request_deduped",
                "id": req.id,
                "trace_id": req.trace_id,
                "idempotency_key": req.idempotency_key,
                "via": "proxy",
            }
        )
        return req

    # -- cross-replica voting (integrity/) ------------------------------------

    def _vote_sampled(self, req: SimRequest) -> bool:
        """Deterministic per-id sampling at ``vote_rate`` (never a vote of
        a vote): every proxy derives the same verdict from the id, so a
        retry routed through a different front cannot double-vote."""
        if self.vote_rate <= 0.0 or req.id.endswith(".vote"):
            return False
        h = int(hashlib.sha256(req.id.encode("utf-8")).hexdigest()[:8], 16)
        return (h / float(0xFFFFFFFF)) < self.vote_rate

    def _assign_vote(self, req: SimRequest) -> None:
        """Double-assign one sampled request: an independent ``.vote``
        twin (same physics, seed, and dt — a deterministic executable
        yields a bit-equal end state) is enqueued as ordinary work.  When
        both done-records exist, :meth:`check_votes` compares their state
        digests: a disagreement is silent corruption that BOTH executions'
        own audits missed — the strongest end-to-end check the fleet has.
        Best-effort: a twin the queue rejects (backpressure) is dropped,
        the original request is never affected."""
        twin = dataclasses.replace(
            req,
            id=f"{req.id}.vote",
            idempotency_key=None,
            trace=None,  # __post_init__ mints the twin its own trace
            dts=list(req.dts),
        )
        try:
            self.queue.submit(twin)
        except AdmissionError:
            return
        _tm.counter(
            "fleet_votes_assigned_total",
            "sampled requests double-assigned for digest voting",
        ).inc()
        self._journal(
            {"event": "vote_assigned", "id": req.id, "vote_id": twin.id}
        )

    def _done_record(self, rid: str) -> dict | None:
        path = os.path.join(self.run_dir, "queue", "done", f"{rid}.json")
        try:
            with open(path, encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def check_votes(self) -> list[dict]:
        """Resolve completed vote pairs: for every ``<id>.vote`` done
        record whose original is also done, compare the two
        ``state_digest`` values and journal the verdict —
        ``integrity_vote`` always, ``integrity_vote_mismatch`` on
        disagreement (match=None when either record carries no digest:
        the service ran without integrity armed).  Incomplete pairs wait
        for a later scan; each pair is verdicted once per proxy process.
        Called from ``stats()`` so any scrape advances the votes."""
        done_dir = os.path.join(self.run_dir, "queue", "done")
        try:
            names = os.listdir(done_dir)
        except OSError:
            return []
        out = []
        for name in sorted(names):
            if not name.endswith(".vote.json"):
                continue
            vid = name[: -len(".json")]
            rid = vid[: -len(".vote")]
            if rid in self._votes_seen:
                continue
            rec_v = self._done_record(vid)
            rec_o = self._done_record(rid)
            if rec_v is None or rec_o is None:
                continue  # pair incomplete — a later scan resolves it
            self._votes_seen.add(rid)
            d_orig = (rec_o.get("result") or {}).get("state_digest")
            d_vote = (rec_v.get("result") or {}).get("state_digest")
            match = (
                None
                if d_orig is None or d_vote is None
                else bool(int(d_orig) == int(d_vote))
            )
            verdict = {
                "id": rid,
                "vote_id": vid,
                "match": match,
                "digests": [d_orig, d_vote],
            }
            _tm.counter(
                "fleet_votes_resolved_total",
                "vote pairs verdicted by digest comparison",
                match=str(match).lower(),
            ).inc()
            self._journal({"event": "integrity_vote", **verdict})
            if match is False:
                self._journal(
                    {"event": "integrity_vote_mismatch", **verdict}
                )
            out.append(verdict)
        return out

    def stats(self) -> dict:
        self.queue.invalidate()  # other processes write the shared dir
        self.check_votes()  # advance pending digest votes on every scrape
        return {
            "proxy": self.proxy_id,
            "votes_checked": len(self._votes_seen),
            "queue": self.queue.counts(),
            "tenants": self.queue.tenant_counts(),
            "leases": self._leases.holders(),
            "replicas": read_replica_status(
                self.run_dir, 2.0 * self.ttl_s, journal=self._journal
            ),
        }

    def _make_handler(self):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            timeout = 30.0

            def log_message(self, fmt, *args):  # journal is the log
                pass

            def do_GET(self):
                proxy.registry.counter(
                    "fleet_proxy_requests_total",
                    "HTTP requests served by the proxy tier",
                    method="GET",
                ).inc()
                if self.path == "/healthz":
                    replicas = read_replica_status(
                        proxy.run_dir, 2.0 * proxy.ttl_s
                    )
                    return reply_json(
                        self,
                        200,
                        {
                            "ok": True,
                            "proxy": proxy.proxy_id,
                            "queue": proxy.queue.counts(),
                            "replicas_alive": sum(
                                1 for r in replicas if not r["stale"]
                            ),
                            "replicas": replicas,
                        },
                    )
                if self.path == "/metrics":
                    return reply_text(
                        self,
                        200,
                        prometheus_text(proxy.registry),
                        PROMETHEUS_CONTENT_TYPE,
                    )
                if self.path == "/stats":
                    return reply_json(self, 200, proxy.stats())
                if self.path.startswith("/requests/") and self.path.endswith(
                    "/trace"
                ):
                    rid = self.path.strip("/").split("/")[-2]
                    payload = assemble_fleet_request_trace(proxy.run_dir, rid)
                    if payload is None:
                        return reply_json(
                            self, 404, {"error": "unknown request id"}
                        )
                    return reply_json(self, 200, payload)
                if self.path.startswith("/requests/"):
                    rid = self.path.strip("/").split("/")[-1]
                    proxy.queue.invalidate()  # replicas mutate behind us
                    found = proxy.queue.lookup(rid)
                    if found is None:
                        return reply_json(
                            self, 404, {"error": "unknown request id"}
                        )
                    state, record = found
                    return reply_json(
                        self, 200, {"id": rid, "state": state, **record}
                    )
                return reply_json(self, 404, {"error": "unknown endpoint"})

            def do_POST(self):
                proxy.registry.counter(
                    "fleet_proxy_requests_total",
                    "HTTP requests served by the proxy tier",
                    method="POST",
                ).inc()
                if self.path != "/requests":
                    return reply_json(self, 404, {"error": "unknown endpoint"})
                denied = proxy.auth_check(self.headers)
                if denied is not None:
                    code, payload, extra = denied
                    return reply_json(self, code, payload, extra)
                body, err = read_body(self)
                if err is not None:
                    code, message = err
                    return reply_json(self, code, {"error": message})
                try:
                    req = proxy.submit(json.loads(body or b"{}"))
                except AdmissionError as exc:
                    proxy.queue.invalidate()
                    payload, headers = rejection_payload(
                        exc, proxy.queue.counts()["queued"]
                    )
                    # storage_full is a 503 (the queue volume hit ENOSPC:
                    # service impairment, not client backpressure) so load
                    # balancers fail the proxy over instead of retrying it
                    code = 503 if exc.reason == "storage_full" else 429
                    return reply_json(self, code, payload, headers)
                except (RequestError, ValueError, TypeError) as exc:
                    payload = {"error": str(exc)}
                    reason = getattr(exc, "reason", None)
                    if reason:
                        payload["reason"] = reason
                    return reply_json(self, 400, payload)
                payload = {
                    "id": req.id,
                    "steps": req.steps,
                    "trace_id": req.trace_id,
                }
                if getattr(req, "deduped", False):
                    payload["deduped"] = True
                    return reply_json(self, 200, payload)
                return reply_json(self, 202, payload)

        return Handler
