"""SLO-holding fleet autoscaler: the controller that lets the fleet run
on preemptible capacity.

PR 15 made replica death loss-free (queue leases + fencing tokens,
durable parked continuations); this controller exploits it.  It consumes
ONLY signals the fleet already exports — queue depth and the queued
snapshot from :class:`~rustpde_mpi_tpu.serve.queue.DurableQueue`,
deadline slack from the QoS request contract, replica heartbeats via
:func:`~rustpde_mpi_tpu.serve.fleet.proxy.read_replica_status` — and
drives a pluggable
:class:`~rustpde_mpi_tpu.serve.fleet.launcher.ReplicaLauncher`.

The control law lives in :class:`~rustpde_mpi_tpu.config.AutoscaleConfig`
(scale-out on deadline-slack pressure / sustained queue depth / capacity
repair below the floor; scale-in only from a sustained fully-idle fleet,
by SIGTERM through the replica's own park-and-release drain).  Every
evaluation that acts — and every verdict transition — is journaled as a
typed ``autoscale_decision`` row under
``<run_dir>/replicas/<controller>/journal.jsonl``, with
``replica_spawned`` / ``replica_retired`` rows for the actions and live
``autoscale_*`` gauges for dashboards.

Pure host-side file IO + subprocess control: the controller never touches
device state or collectives, so it can ride a daemon thread inside a
root ``SimServer`` (``ServeConfig.autoscale``) or run standalone
(``examples/navier_rbc_autoscale.py``).
"""

from __future__ import annotations

import os
import threading
import time

from ...config import AutoscaleConfig, env_get
from ...telemetry import metrics as _tm
from ...utils.journal import JournalWriter
from ..queue import DurableQueue
from .launcher import ReplicaLauncher
from .proxy import read_replica_status


class Autoscaler:
    """One controller over one fleet ``run_dir``.

    ``step()`` is a single observe → decide → act evaluation (pure,
    deterministic given the injected clocks — the unit-test surface);
    ``start()``/``stop()`` wrap it in a daemon thread at
    ``cfg.decide_s`` cadence.  ``mono``/``wall`` inject clocks for
    tests."""

    def __init__(
        self,
        run_dir: str,
        launcher: ReplicaLauncher,
        cfg: AutoscaleConfig | None = None,
        *,
        fleet=None,
        controller_id: str = "",
        registry=None,
        mono=time.monotonic,
        wall=time.time,
    ):
        self.run_dir = run_dir
        self.launcher = launcher
        self.cfg = cfg or AutoscaleConfig()
        self._mono = mono
        self._wall = wall
        if fleet is not None:
            self._ttl = float(fleet.resolved_ttl())
        else:
            self._ttl = float(env_get("RUSTPDE_LEASE_TTL_S", "15"))
        self.controller_id = controller_id or f"autoscaler-{os.getpid()}"
        self.registry = registry if registry is not None else _tm.default_registry()
        self._journal_writer = JournalWriter(
            os.path.join(
                run_dir, "replicas", self.controller_id, "journal.jsonl"
            )
        )
        self.queue = DurableQueue(
            os.path.join(run_dir, "queue"), max_queue=1 << 30
        )
        # sustain-window marks (None = the pressure is not present) and
        # the elective-action cooldown anchor
        self._high_since: float | None = None
        self._idle_since: float | None = None
        self._last_action_mono: float | None = None
        self._seq = 0
        self._last_logged: tuple | None = None
        self.decisions = 0  # acted decisions (scale_out + scale_in)
        self.spawned = 0
        self.retired = 0
        self._stop_evt: threading.Event | None = None
        self._thread: threading.Thread | None = None

    # -- observe ---------------------------------------------------------------

    def observe(self) -> dict:
        """One snapshot of every control input, all from durable state:
        queue census, tightest deadline slack among queued requests,
        heartbeat-fresh replicas, and the launcher's own spawn ledger
        (a just-spawned replica has no heartbeat yet — it counts toward
        capacity for ``spawn_grace_s`` so a slow interpreter start cannot
        read as missing capacity and storm spawns)."""
        self.queue.invalidate()  # proxies + replicas write behind us
        counts = self.queue.counts()
        now_wall = self._wall()
        min_slack = float("inf")
        for _, req in self.queue.snapshot_queued():
            slack = req.deadline_slack(now_wall)
            if slack < min_slack:
                min_slack = slack
        self.launcher.reap()
        status = read_replica_status(self.run_dir, self._ttl)
        fresh = {
            r.get("replica"): r
            for r in status
            if not r.get("stale") and not r.get("stopping")
        }
        now = self._mono()
        pending = 0
        for h in getattr(self.launcher, "handles", list)():
            if h.retired or h.replica_id in fresh:
                continue
            if self.launcher.alive(h) and (
                now - h.spawned_mono
            ) < self.cfg.spawn_grace_s:
                pending += 1
        return {
            "queued": counts["queued"],
            "running": counts["running"],
            "alive": len(fresh),
            "pending": pending,
            "min_slack_s": min_slack,
            "replicas": fresh,
        }

    # -- decide ----------------------------------------------------------------

    def _in_cooldown(self, now: float) -> bool:
        return (
            self._last_action_mono is not None
            and (now - self._last_action_mono) < self.cfg.cooldown_s
        )

    def _pick_victims(self, obs: dict, n: int) -> list:
        """Scale-in victims: launcher-owned, heartbeat-fresh, not-yet-
        retired replicas with the fewest occupied slots (the cheapest
        drains).  Returns exactly ``n`` handles or ``[]`` — gang-shaped
        capacity (``gang_size > 1``) retires a whole gang or nothing,
        and the controller never signals replicas it did not launch."""
        victims = []
        for h in getattr(self.launcher, "handles", list)():
            if h.retired or not self.launcher.alive(h):
                continue
            rec = obs["replicas"].get(h.replica_id)
            if rec is None or rec.get("draining"):
                continue
            occupied = (rec.get("slots") or [0])[0]
            victims.append((occupied, h.replica_id, h))
        if len(victims) < n:
            return []
        victims.sort(key=lambda v: (v[0], v[1]))
        return [v[2] for v in victims[:n]]

    def decide(self, obs: dict) -> dict:
        """Apply the control law to one observation.  Returns the typed
        decision record (the ``autoscale_decision`` journal row body);
        ``action`` is ``scale_out`` / ``scale_in`` / ``hold``."""
        cfg = self.cfg
        # gang-shaped capacity: every scale action moves `unit` replicas
        # as one fate-shared group (1 = the pre-gang control law)
        unit = max(1, int(getattr(cfg, "gang_size", 1)))
        now = self._mono()
        capacity = obs["alive"] + obs["pending"]
        busy = obs["queued"] > 0 or obs["running"] > 0

        # sustain windows first: they must advance on every evaluation,
        # whatever the verdict, or pressure could never accumulate
        if obs["queued"] > cfg.queue_high:
            if self._high_since is None:
                self._high_since = now
        else:
            self._high_since = None
        if not busy:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        action, reason, victims = "hold", "steady", []
        if capacity < cfg.min_replicas:
            # capacity repair (a preempted replica died): immediate and
            # cooldown-exempt — replacement is not elective growth
            action, reason = "scale_out", "below_min"
        elif capacity > cfg.max_replicas:
            action, reason = "scale_in", "above_max"
            victims = self._pick_victims(obs, unit)
        elif (
            obs["min_slack_s"] < cfg.slack_low_s
            and capacity + unit <= cfg.max_replicas
        ):
            if self._in_cooldown(now):
                action, reason = "hold", "cooldown"
            else:
                action, reason = "scale_out", "deadline_slack"
        elif (
            self._high_since is not None
            and (now - self._high_since) >= cfg.sustain_s
        ):
            if capacity + unit > cfg.max_replicas:
                action, reason = "hold", "at_max"
            elif self._in_cooldown(now):
                action, reason = "hold", "cooldown"
            else:
                action, reason = "scale_out", "queue_depth"
        elif (
            self._idle_since is not None
            and (now - self._idle_since) >= cfg.idle_sustain_s
            and capacity - unit >= cfg.min_replicas
        ):
            if self._in_cooldown(now):
                action, reason = "hold", "cooldown"
            else:
                action, reason = "scale_in", "idle"
                victims = self._pick_victims(obs, unit)
                if not victims:
                    action, reason = "hold", "no_owned_victim"
        elif self._high_since is not None:
            action, reason = "hold", "pressure_building"
        elif (
            self._idle_since is not None
            and capacity - unit >= cfg.min_replicas
        ):
            action, reason = "hold", "idle_building"

        desired = capacity
        if action == "scale_out":
            desired = min(
                capacity + unit, max(cfg.max_replicas, cfg.min_replicas)
            )
        elif action == "scale_in":
            desired = max(capacity - unit, cfg.min_replicas)
        return {
            "action": action,
            "reason": reason,
            "desired": desired,
            "alive": obs["alive"],
            "pending": obs["pending"],
            "queued": obs["queued"],
            "running": obs["running"],
            "min_slack_s": (
                None
                if obs["min_slack_s"] == float("inf")
                else round(obs["min_slack_s"], 3)
            ),
            "victim": victims[0].replica_id if victims else None,
            "victims": [h.replica_id for h in victims],
            "_victim_handles": victims,
        }

    # -- act -------------------------------------------------------------------

    def _journal(self, event: dict) -> None:
        self._journal_writer.append(
            {"controller": self.controller_id, **event}
        )

    def _log_decision(self, decision: dict) -> None:
        """Journal the decision.  Actions always land; holds land only on
        a verdict TRANSITION (action/reason/desired changed) so a
        long-lived steady controller does not grow the journal without
        bound while every state change stays on the record."""
        key = (decision["action"], decision["reason"], decision["desired"])
        if decision["action"] == "hold" and key == self._last_logged:
            return
        self._last_logged = key
        row = {k: v for k, v in decision.items() if not k.startswith("_")}
        self._journal({"event": "autoscale_decision", **row})

    def act(self, decision: dict) -> None:
        cfg = self.cfg
        unit = max(1, int(getattr(cfg, "gang_size", 1)))
        if decision["action"] == "scale_out":
            rids = []
            for _ in range(unit):
                self._seq += 1
                rids.append(f"{cfg.replica_prefix}-{os.getpid()}-{self._seq}")
            if unit > 1:
                # all-or-nothing: a spawn failure rolls the partial gang
                # back inside the launcher and re-raises
                handles = self.launcher.spawn_gang(rids)
            else:
                handles = [self.launcher.spawn(rids[0])]
            self.spawned += len(handles)
            self.decisions += 1
            if decision["reason"] != "below_min":
                self._last_action_mono = self._mono()
            for handle in handles:
                row = {
                    "event": "replica_spawned",
                    "replica": handle.replica_id,
                    "pid": handle.pid,
                    "reason": decision["reason"],
                }
                if unit > 1:
                    row["gang"] = rids
                self._journal(row)
                self.registry.counter(
                    "autoscale_spawned_total",
                    "replicas spawned by the autoscaler",
                ).inc()
        elif decision["action"] == "scale_in":
            handles = decision.get("_victim_handles") or []
            if not handles:
                return
            if len(handles) > 1:
                self.launcher.retire_gang(handles)
            else:
                self.launcher.retire(handles[0])
            self.retired += len(handles)
            self.decisions += 1
            self._last_action_mono = self._mono()
            for handle in handles:
                row = {
                    "event": "replica_retired",
                    "replica": handle.replica_id,
                    "pid": handle.pid,
                    "reason": decision["reason"],
                }
                if len(handles) > 1:
                    row["gang"] = [h.replica_id for h in handles]
                self._journal(row)
                self.registry.counter(
                    "autoscale_retired_total",
                    "replicas retired (drained) by the autoscaler",
                ).inc()

    def step(self) -> dict:
        """One control evaluation: observe → decide → journal → act →
        gauges.  Returns the decision record."""
        obs = self.observe()
        decision = self.decide(obs)
        self._log_decision(decision)
        self.act(decision)
        self.registry.gauge(
            "autoscale_desired_replicas", "controller's current fleet target"
        ).set(decision["desired"])
        self.registry.gauge(
            "autoscale_alive_replicas", "heartbeat-fresh replicas observed"
        ).set(obs["alive"])
        self.registry.gauge(
            "autoscale_pending_spawns",
            "spawned replicas inside the grace window, no heartbeat yet",
        ).set(obs["pending"])
        return decision

    def stats(self) -> dict:
        return {
            "controller": self.controller_id,
            "decisions": self.decisions,
            "spawned": self.spawned,
            "retired": self.retired,
        }

    # -- daemon ----------------------------------------------------------------

    def start(self) -> None:
        """Run the control loop on a daemon thread (file IO + subprocess
        control only — safe inside a root SimServer next to the
        heartbeat thread)."""
        if self._thread is not None:
            return
        self._stop_evt = threading.Event()

        def loop():
            while not self._stop_evt.wait(self.cfg.decide_s):
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — control must not crash serve
                    pass

        self._thread = threading.Thread(
            target=loop, name="fleet-autoscale", daemon=True
        )
        self._thread.start()

    def stop(self, retire_fleet: bool = False, timeout_s: float = 30.0) -> None:
        """Stop the control loop; ``retire_fleet`` additionally drains
        every launcher-owned replica (the embedded-controller teardown —
        a standalone controller's driver owns that choice itself)."""
        if self._stop_evt is not None:
            self._stop_evt.set()
            if self._thread is not None:
                self._thread.join(timeout=10.0)
            self._thread = None
            self._stop_evt = None
        if retire_fleet:
            shutdown = getattr(self.launcher, "shutdown", None)
            if shutdown is not None:
                shutdown(timeout_s=timeout_s)
        self._journal_writer.close()
