"""Queue-level bucket leases: how N replicas share one durable queue.

A fleet of :class:`~rustpde_mpi_tpu.serve.SimServer` replicas coordinates
through lease files next to the queue they share — no consensus service,
the same fsynced atomic-dirent lifecycle the queue itself rides::

    <root>/<tag>.json            the live lease for bucket <tag>
    <root>/<tag>.gen             token escrow: highest fencing token ever
                                 issued for the bucket (survives lease-file
                                 deletion, so tokens stay monotonic)
    <root>/<tag>.json.breaking.* a break in progress (crash-tolerant
                                 intermediate; adopted by the next claim)

The protocol, one atomic dirent operation per transition:

* **claim** — write the new lease to a unique tmp file (fsynced), then
  ``os.link`` it to the lease path: dirent creation is atomic and
  EXCLUSIVE, so when two replicas race one bucket exactly one link
  succeeds and the loser sees EEXIST.  The fencing token is
  ``escrow + 1`` — strictly greater than every token the bucket has ever
  issued.
* **renew** (heartbeat) — the owner atomically rewrites the lease file
  (tmp + ``os.replace``) with a bumped sequence number, after verifying
  the on-disk ``(owner, token)`` still match its own: a mismatch means a
  survivor broke this lease while the owner stalled — the owner is FENCED
  and must stop writing (:class:`LeaseLost`).
* **break** — a survivor that observed a stale heartbeat renames the
  lease file away (``os.replace`` of a shared source: the loser of a
  break race gets FileNotFoundError — exactly one breaker wins), writes
  the broken token into the escrow, and removes the intermediate.  The
  bucket's queued+running requests are then re-claimable.
* **release** — the clean-shutdown path: verify ownership, park the
  token in the escrow, remove the lease file.

**Clock robustness** (the NTP-step satellite): staleness is never
computed as ``wall_now - heartbeat_stamp``.  The observer remembers, per
lease, the last *observed change* ``(token, seq, mtime_ns)`` and its own
``time.monotonic()`` at that observation; a lease is stale only when the
observation has not changed for ``ttl`` of OBSERVER-monotonic time.  Any
change — including an mtime that jumps BACKWARDS after a clock step —
resets the window, so a skewed-clock heartbeat reads as live for one
extra TTL instead of being instantly broken.  Heartbeats carry
``(hb_unix, hb_mono)`` pairs for diagnosis, not for the verdict.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ...utils.fsutil import atomic_write_text as _atomic_write
from ...utils.fsutil import fsync_dir


class LeaseLost(RuntimeError):
    """This process's lease was broken and possibly re-claimed by a peer:
    every write it was about to make is FENCED (the on-disk token moved
    past ours).  The holder must drop the bucket — its requests already
    belong to whoever holds the new token."""

    def __init__(self, tag: str, detail: str):
        super().__init__(f"lease {tag} lost: {detail}")
        self.tag = tag


def bucket_tag(key: tuple) -> str:
    """Stable 12-hex tag for one compat bucket (matches the scheduler's
    campaign-dir tagging)."""
    return hashlib.sha1(repr(tuple(key)).encode()).hexdigest()[:12]


def _read_json(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


class Lease:
    """One held bucket lease.  All methods are fencing-checked: they
    verify the on-disk ``(owner, token)`` before acting and raise
    :class:`LeaseLost` when a survivor broke + re-claimed the bucket."""

    def __init__(self, mgr: "LeaseManager", key: tuple, token: int):
        self.mgr = mgr
        self.key = tuple(key)
        self.tag = bucket_tag(key)
        self.token = int(token)
        self.owner = mgr.owner
        self._seq = 0

    @property
    def path(self) -> str:
        return os.path.join(self.mgr.root, f"{self.tag}.json")

    def _on_disk(self) -> dict | None:
        return _read_json(self.path)

    def _escrow_fenced(self) -> bool:
        """True when the token escrow has advanced TO OR PAST our token:
        some survivor broke (or we released) this lease at some point, so
        our authority is gone even if the lease file currently shows us —
        the defense against the guard-then-write resurrection race (a
        holder that stalls between its ownership read and its rewrite
        would otherwise recreate a broken lease over the new owner's)."""
        rec = _read_json(self.mgr._gen_path(self.tag)) or {}
        return int(rec.get("token", 0)) >= self.token

    def guard(self) -> None:
        """Fencing check (cheap reads): raise :class:`LeaseLost` unless
        this process still owns the bucket AND the token escrow has not
        moved past our token — called before every queue write the lease
        is supposed to authorize."""
        rec = self._on_disk()
        if (
            rec is None
            or rec.get("owner") != self.owner
            or int(rec.get("token", -1)) != self.token
        ):
            raise LeaseLost(
                self.tag,
                f"on-disk holder is {rec and rec.get('owner')!r} token "
                f"{rec and rec.get('token')}, we hold token {self.token}",
            )
        if self._escrow_fenced():
            # a record bearing our owner+token past the escrow can only
            # be our own resurrection (the legit new holder's token is
            # strictly greater): retract it so the bucket frees NOW
            # instead of after another observer TTL
            self._retract()
            raise LeaseLost(
                self.tag,
                f"token escrow reached {self.token}: this lease was broken "
                "while we stalled",
            )

    def _retract(self) -> None:
        """Best-effort removal of a lease file WE resurrected after being
        broken (only when it still bears our owner+token — never touch a
        legitimate newer holder's record)."""
        rec = self._on_disk()
        if (
            rec is not None
            and rec.get("owner") == self.owner
            and int(rec.get("token", -1)) == self.token
        ):
            try:
                os.remove(self.path)
                fsync_dir(self.mgr.root)
            except OSError:
                pass

    def renew(self) -> None:
        """Heartbeat: atomically rewrite the lease with a bumped sequence
        (mtime + content both advance, so observers see the change).
        Fencing-checked before AND after the write: a break that lands
        inside the guard→write window is caught by the escrow re-check,
        and the resurrected file is retracted — the zombie stands down
        within one heartbeat instead of fencing the legitimate owner."""
        self.guard()
        self._seq += 1
        _atomic_write(self.path, json.dumps(self.mgr._record(self, self._seq)))
        if self._escrow_fenced():
            self._retract()
            raise LeaseLost(
                self.tag,
                "broken during renewal (escrow advanced mid-write); "
                "resurrected record retracted",
            )

    def release(self) -> None:
        """Clean hand-back: escrow our token (monotonicity across the
        file's deletion), then remove the lease."""
        self.guard()
        self.mgr._escrow(self.tag, self.token)
        try:
            os.remove(self.path)
            fsync_dir(self.mgr.root)
        except OSError:
            pass


class LeaseManager:
    """Claim / renew / break / sweep over one lease directory.

    ``journal`` is an optional callable receiving event dicts
    (``lease_claimed`` / ``lease_broken`` / ``lease_released`` rows ride
    the replica's run journal).  ``ttl_s`` is the break threshold in
    observer-monotonic seconds (see module docstring)."""

    def __init__(
        self,
        root: str,
        owner: str,
        ttl_s: float,
        journal=None,
        mono_fn=time.monotonic,
    ):
        self.root = root
        self.owner = str(owner)
        self.ttl_s = float(ttl_s)
        self.journal = journal
        self._mono = mono_fn
        # observer bookkeeping: tag -> ((token, seq, mtime_ns), mono_seen)
        self._seen: dict[str, tuple[tuple, float]] = {}
        os.makedirs(root, exist_ok=True)

    # -- record helpers -------------------------------------------------------

    def _record(self, lease: Lease, seq: int) -> dict:
        return {
            "bucket": list(lease.key),
            "owner": lease.owner,
            "token": lease.token,
            "seq": int(seq),
            # monotonic-epoch PAIR: wall time for humans, the writer's
            # monotonic clock for skew diagnosis — neither is the
            # staleness verdict (that is observer-side, see sweep)
            "hb_unix": time.time(),
            "hb_mono": self._mono(),
        }

    def _gen_path(self, tag: str) -> str:
        return os.path.join(self.root, f"{tag}.gen")

    def _escrow(self, tag: str, token: int) -> None:
        """Advance the token escrow to at least ``token`` (never backward:
        a crashed breaker may have left it behind the broken lease)."""
        cur = _read_json(self._gen_path(tag)) or {}
        if int(cur.get("token", 0)) < int(token):
            _atomic_write(
                self._gen_path(tag), json.dumps({"token": int(token)})
            )

    def _next_token(self, tag: str) -> int:
        """escrow + 1, also adopting any crashed break's intermediate file
        (its token may exceed the escrow the breaker never wrote)."""
        best = int((_read_json(self._gen_path(tag)) or {}).get("token", 0))
        try:
            names = os.listdir(self.root)
        except OSError:
            names = []
        for name in names:
            if name.startswith(f"{tag}.json.breaking."):
                rec = _read_json(os.path.join(self.root, name)) or {}
                best = max(best, int(rec.get("token", 0)))
                self._escrow(tag, int(rec.get("token", 0)))
                try:
                    os.remove(os.path.join(self.root, name))
                    fsync_dir(self.root)
                except OSError:
                    pass
        return best + 1

    # -- the protocol ---------------------------------------------------------

    def claim(self, key: tuple) -> Lease | None:
        """Try to claim one bucket.  None when a lease file already exists
        (held — maybe stale: that is sweep's business, never claim's) or
        when we lost the creation race by one dirent."""
        tag = bucket_tag(key)
        path = os.path.join(self.root, f"{tag}.json")
        if os.path.exists(path):
            return None
        lease = Lease(self, key, self._next_token(tag))
        tmp = f"{path}.{self.owner}.{os.getpid()}.claimtmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(self._record(lease, 0)))
            fh.flush()
            os.fsync(fh.fileno())
        try:
            # atomic EXCLUSIVE dirent creation: exactly one racer links
            os.link(tmp, path)
        except FileExistsError:
            return None
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        fsync_dir(self.root)
        self._note(tag, path)
        if self.journal:
            self.journal(
                {
                    "event": "lease_claimed",
                    "bucket": tag,
                    "key": list(key),
                    "owner": self.owner,
                    "token": lease.token,
                }
            )
        return lease

    def _observe(self, tag: str, path: str) -> tuple | None:
        """(token, seq, mtime_ns) of the on-disk lease, None when gone."""
        try:
            mtime_ns = os.stat(path).st_mtime_ns
        except OSError:
            return None
        rec = _read_json(path)
        if rec is None:
            return None
        return (int(rec.get("token", 0)), int(rec.get("seq", 0)), mtime_ns)

    def _note(self, tag: str, path: str) -> None:
        obs = self._observe(tag, path)
        if obs is not None:
            self._seen[tag] = (obs, self._mono())

    def holders(self) -> dict[str, dict]:
        """tag -> lease record for every live lease file (introspection:
        the proxy's /stats aggregates this next to replica heartbeats)."""
        out = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(self.root, name))
            if rec is not None:
                out[name[: -len(".json")]] = rec
        return out

    def stale(self, tag: str) -> bool:
        """True when ``tag``'s lease observation has not changed for a
        full TTL of observer-monotonic time.  ANY change — token, seq, or
        an mtime that moved in EITHER direction (an mtime jumping
        backwards is a clock step, not a death) — restarts the window, so
        the verdict never rides the wall clock."""
        path = os.path.join(self.root, f"{tag}.json")
        obs = self._observe(tag, path)
        if obs is None:
            self._seen.pop(tag, None)
            return False
        seen = self._seen.get(tag)
        if seen is None or seen[0] != obs:
            self._seen[tag] = (obs, self._mono())
            return False
        return (self._mono() - seen[1]) > self.ttl_s

    def break_lease(self, tag: str) -> dict | None:
        """Break one stale lease: rename it away (exactly one breaker wins
        — the source dirent vanishes for the loser), escrow its token,
        clean up.  Returns the broken record, or None when a peer raced us
        to it (or the holder revived and renewed first — the rename is the
        linearization point either way)."""
        path = os.path.join(self.root, f"{tag}.json")
        breaking = f"{path}.breaking.{self.owner}.{os.getpid()}"
        try:
            os.replace(path, breaking)
        except FileNotFoundError:
            return None
        fsync_dir(self.root)
        rec = _read_json(breaking) or {}
        self._escrow(tag, int(rec.get("token", 0)))
        try:
            os.remove(breaking)
            fsync_dir(self.root)
        except OSError:
            pass
        self._seen.pop(tag, None)
        if self.journal:
            self.journal(
                {
                    "event": "lease_broken",
                    "bucket": tag,
                    "key": rec.get("bucket"),
                    "owner": rec.get("owner"),
                    "token": rec.get("token"),
                    "breaker": self.owner,
                }
            )
        return rec

    def sweep(self) -> list[dict]:
        """Break every stale lease in the directory; returns the broken
        records (each carries the bucket key the caller re-claims requests
        for).  Run between campaigns — survivors are the failure detector,
        there is no central one."""
        broken = []
        for tag in list(self.holders()):
            if self.stale(tag):
                rec = self.break_lease(tag)
                if rec is not None:
                    broken.append(rec)
        return broken
