"""Wall-clock step detection for the fleet's wall-time consumers.

The lease layer never trusts the wall clock (observer-monotonic windows,
see lease.py) — but two fleet surfaces still read ``time.time()`` against
on-disk stamps: the replica-heartbeat staleness display
(:func:`~rustpde_mpi_tpu.serve.fleet.proxy.read_replica_status`) and the
QoS deadline math (qos.py).  An NTP step on the reading host would make
every heartbeat look dead and every deadline look blown at once.

:class:`ClockMonitor` detects the step by comparing wall-clock progress
against ``time.monotonic()`` progress since an anchor: the difference is
the accumulated wall adjustment.  A step past the caller's threshold is
reported ONCE (``clock_skew`` journal row + RuntimeWarning), compensated
for the detecting scan, and then absorbed by re-anchoring — a permanent
NTP correction becomes the new normal after one grace scan instead of
mass-expiring state that was alive a second ago.
"""

from __future__ import annotations

import threading
import time
import warnings


class ClockMonitor:
    """One wall-vs-monotonic drift tracker (clocks injectable for tests).

    ``check(threshold_s)`` returns the detected step size (0.0 in the
    steady state): positive = the wall clock jumped FORWARD, negative =
    backward.  Detection re-anchors, so each step is reported once."""

    def __init__(self, wall=time.time, mono=time.monotonic):
        self._wall = wall
        self._mono = mono
        self._lock = threading.Lock()
        self._anchor: tuple[float, float] | None = None
        self._latched = False

    def check(self, threshold_s: float, journal=None, where: str = "") -> float:
        """Detect a wall-clock step larger than ``threshold_s`` since the
        last anchor.  Returns the step in seconds for the caller to
        compensate its CURRENT scan by; journals/warns one-shot per
        process (the first step is the news — later ones ride the same
        root cause)."""
        w, m = self._wall(), self._mono()
        with self._lock:
            if self._anchor is None:
                self._anchor = (w, m)
                return 0.0
            aw, am = self._anchor
            skew = (w - aw) - (m - am)
            if abs(skew) <= float(threshold_s):
                return 0.0
            self._anchor = (w, m)  # absorb: the step is the new normal
            latched, self._latched = self._latched, True
        if not latched:
            row = {
                "event": "clock_skew",
                "skew_s": round(skew, 3),
                "where": where,
            }
            if journal is not None:
                try:
                    journal(row)
                except Exception:  # noqa: BLE001 — diagnosis must not crash
                    pass
            warnings.warn(
                f"wall clock stepped {skew:+.1f}s ({where or 'fleet'}): "
                "compensating this scan instead of mass-expiring state",
                RuntimeWarning,
                stacklevel=3,
            )
        return skew


#: process-wide monitor: every fleet wall-time consumer shares one anchor,
#: so a single NTP step is detected (and journaled) once, not per module
MONITOR = ClockMonitor()
