"""Warm campaign pools + AOT bucket executables (the cold-start killer).

At production request rates compile time IS the p99: every novel
``compat_key`` pays a full model build + jit at admission, and the journal
already measures it (per-key ``compile_build`` rows,
``serve_time_to_first_chunk_seconds{key}``) without closing the loop.  This
module closes it, the same shape every LLM serving stack ships:

* a **traffic profile** — the expected (model kind × grid × K × dt-rung)
  matrix, either seeded explicitly via ``ServeConfig.warm_profile`` (a path
  to a durable JSON or an inline ``[{"key": [...], "k": int}, ...]`` list)
  or learned from the journal's historical ``compile_build`` rows
  (:func:`learn_profile` / the ``"journal"`` sentinel),
* a **background builder** — a daemon thread that walks the profile at
  service start and builds each entry through the scheduler-supplied build
  callback (the SAME arming ``_build_runner`` performs: registry build,
  sentinels, stats, the K-member ensemble trace) and AOT-compiles the
  chunked dispatch executables via ``.lower().compile()``
  (``NavierEnsemble.aot_compile``) — service start is never serialized
  behind the matrix,
* a **warm pool** — prebuilt campaigns keyed by ``compat_key``; the
  scheduler's ``_build_runner`` takes a matching entry at bucket-open and
  admission-to-first-chunk skips the jit entirely (journaled
  ``warm_pool_hit``, accounting in telemetry/compile_log.py).

The pool is gated to single-process runtimes by the scheduler: a
background model build on a multihost mesh would desync collectives.
``ServeConfig.warm_profile=None`` keeps all of it inert — no thread, no
journal rows, byte-identical serve behavior (CI-asserted).
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..telemetry import compile_log as _cl

#: default bound on live pool entries (oldest evicted past it): the pool
#: holds whole device-resident ensembles, so it must stay small
MAX_ENTRIES = 8


def freeze_key(key) -> tuple:
    """Deep list->tuple normalization: compat keys round-trip through JSON
    (profiles, journal rows) as nested lists, and the pool/attribution tag
    is ``repr``-based — one canonical tuple form on every path."""
    if isinstance(key, (list, tuple)):
        return tuple(freeze_key(x) for x in key)
    return key


def load_profile(source) -> list[dict]:
    """Normalize a ``ServeConfig.warm_profile`` value into
    ``[{"key": tuple, "k": int | None}, ...]``: a path reads the durable
    JSON (missing/corrupt -> empty, the service must still boot), an inline
    list passes through.  Entries without a usable key are dropped."""
    if source is None:
        return []
    entries = source
    if isinstance(source, (str, os.PathLike)):
        try:
            with open(source) as fh:
                entries = json.load(fh)
        except (OSError, ValueError):
            return []
    out = []
    for ent in entries or []:
        try:
            key = freeze_key(ent["key"])
            k = ent.get("k")
            k = int(k) if k else None
        except (TypeError, KeyError, ValueError):
            continue
        if not isinstance(key, tuple) or not key:
            continue
        out.append({"key": key, "k": k})
    return out


def learn_profile(journal_path: str, max_entries: int = MAX_ENTRIES) -> list[dict]:
    """Learn a traffic profile from a serve journal: every live-path
    ``compile_build`` row (phase ``build``/``entry_points``, or legacy rows
    without a phase — never ``aot``, the pool must not learn from itself)
    votes for its key; entries come back most-built-first with the row's
    campaign ``k`` when recorded."""
    counts: dict[tuple, dict] = {}
    try:
        with open(journal_path) as fh:
            for line in fh:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get("event") != "compile_build" or "key" not in row:
                    continue
                if row.get("phase") == "aot":
                    continue
                key = freeze_key(row["key"])
                ent = counts.setdefault(key, {"n": 0, "k": None})
                ent["n"] += 1
                if row.get("k"):
                    ent["k"] = int(row["k"])
    except OSError:
        return []
    ranked = sorted(counts.items(), key=lambda kv: -kv[1]["n"])
    return [
        {"key": key, "k": ent["k"]} for key, ent in ranked[:max_entries]
    ]


def save_profile(path: str, entries: list[dict]) -> None:
    """Atomically persist a learned profile as the durable JSON
    ``ServeConfig.warm_profile`` accepts (lists for the tuple keys)."""
    payload = [
        {"key": list(freeze_key(e["key"])), "k": e.get("k")} for e in entries
    ]
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, default=str)
    os.replace(tmp, path)


class WarmPool:
    """Prebuilt campaign pool: profile entries are built in a background
    daemon thread through ``build_fn(key, k) -> (model, ens, executables)``
    and held keyed by compat key until the scheduler takes them at
    bucket-open.  ``take`` transfers OWNERSHIP — a taken entry is gone (the
    campaign mutates the ensemble in place), so a second campaign for the
    same key is a miss by design.  Hit/miss/eviction accounting rides
    telemetry/compile_log so tests and the bench read one source of truth;
    ``journal`` (when given) gets the durable copies."""

    def __init__(
        self,
        entries: list[dict],
        build_fn,
        journal=None,
        max_entries: int = MAX_ENTRIES,
    ):
        self._profile = list(entries)
        self._build_fn = build_fn
        self._journal = journal
        self._max_entries = int(max_entries)
        self._pool: dict[str, dict] = {}  # key_tag -> entry
        self._order: list[str] = []  # insertion order (eviction)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # profile tags not yet built: take() WAITS on these instead of
        # cold-building the same model the builder already has in flight
        # (the background build started earlier, so waiting is strictly
        # cheaper than a duplicate inline build)
        self._pending: set[str] = {
            _cl.key_tag(freeze_key(e["key"])) for e in entries
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.built = 0
        self.build_errors = 0

    # -- background build ----------------------------------------------------

    def start(self) -> "WarmPool":
        """Begin the non-blocking warmup (idempotent)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._build_all, name="warm-pool", daemon=True
            )
            self._thread.start()
        return self

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the warmup pass finished (tests/bench); True when
        the builder thread is done."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self) -> None:
        """Ask the builder to wind down (service drain); in-flight build
        finishes, remaining profile entries are skipped, waiters wake."""
        self._stop.set()
        with self._cond:
            self._pending.clear()
            self._cond.notify_all()

    def _build_all(self) -> None:
        for ent in self._profile:
            if self._stop.is_set():
                break
            key, k = ent["key"], ent.get("k")
            tag = _cl.key_tag(freeze_key(key))
            t0 = time.perf_counter()
            try:
                built = self._build_fn(key, k)
            except Exception as exc:  # a bad profile entry must not kill warmup
                self.build_errors += 1
                self._emit(
                    _cl.observe_warm_pool(
                        "error", key=key, error=f"{type(exc).__name__}: {exc}"
                    )
                )
                built = None
            if built is not None:
                model, ens, executables = built
                self.built += 1
                self.put(key, model, ens)
                self._emit(
                    _cl.observe_warm_pool(
                        "aot",
                        key=key,
                        k=ens.k,
                        executables=int(executables),
                        wall_s=round(time.perf_counter() - t0, 4),
                    )
                )
            with self._cond:
                self._pending.discard(tag)
                self._cond.notify_all()
        with self._cond:  # entries skipped by stop() must not strand waiters
            self._pending.clear()
            self._cond.notify_all()

    # -- pool ------------------------------------------------------------------

    def put(self, key, model, ens) -> None:
        tag = _cl.key_tag(freeze_key(key))
        evicted = []
        with self._lock:
            if tag in self._pool:
                self._order.remove(tag)
            self._pool[tag] = {"key": freeze_key(key), "model": model, "ens": ens}
            self._order.append(tag)
            while len(self._order) > self._max_entries:
                old = self._order.pop(0)
                evicted.append(self._pool.pop(old))
        for ent in evicted:
            self.evictions += 1
            self._emit(
                _cl.observe_warm_pool(
                    "evict", key=ent["key"], k=ent["ens"].k, reason="capacity"
                )
            )

    def take(self, key, k: int | None = None):
        """Pop the prebuilt campaign for ``key`` (``(model, ens)``), or None
        on a miss.  A key the builder still has IN FLIGHT is waited for
        first — the background build started earlier, so waiting beats a
        duplicate inline build.  A K mismatch is a miss AND an eviction —
        the prebuilt ensemble's member count is baked into its trace, so
        it cannot serve a differently-sized campaign."""
        tag = _cl.key_tag(freeze_key(key))
        with self._cond:
            while tag in self._pending and tag not in self._pool:
                self._cond.wait()
            ent = self._pool.pop(tag, None)
            if ent is not None:
                self._order.remove(tag)
        if ent is None:
            self.misses += 1
            self._emit(_cl.observe_warm_pool("miss", key=key))
            return None
        if k is not None and int(k) != int(ent["ens"].k):
            self.misses += 1
            self.evictions += 1
            self._emit(
                _cl.observe_warm_pool(
                    "evict", key=key, k=ent["ens"].k, reason="k_mismatch"
                )
            )
            return None
        self.hits += 1
        self._emit(_cl.observe_warm_pool("hit", key=key, k=ent["ens"].k))
        return ent["model"], ent["ens"]

    def counts(self) -> dict:
        """Accounting snapshot (tests + the bench payload)."""
        with self._lock:
            pooled = len(self._pool)
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "built": self.built,
            "build_errors": self.build_errors,
            "pooled": pooled,
        }

    def _emit(self, payload: dict) -> None:
        if self._journal is not None:
            try:
                self._journal(payload)
            except Exception:
                pass  # accounting must never kill the builder/scheduler
