"""Fault-isolated simulation service: continuously-batched ensemble serving.

The layer that accepts simulation work from the outside and survives the
failures multi-tenancy produces (see serve/scheduler.py for the design):

* :class:`SimServer` — durable-queue + continuous-batching scheduler over
  :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` slots,
* :class:`SimRequest` — the unit of work (Ra/Pr/resolution/geometry/
  horizon), bucketed by operator-constant compatibility key,
* :class:`DurableQueue` — crash-safe on-disk request lifecycle,
* :class:`RequestFailed` / :class:`AdmissionError` / :class:`RequestError`
  — the typed failure surface (terminal divergence, bounded-queue
  backpressure, malformed work),
* :class:`HttpFront` — optional thin stdlib HTTP front,
* :mod:`~rustpde_mpi_tpu.serve.fleet` — the HA fleet layer: stateless
  :class:`FleetProxy` front doors over the shared queue, queue-level
  bucket leases with fencing (:class:`LeaseManager` / :class:`LeaseLost`),
  durable parked continuations, and the QoS traffic contract
  (tenants / priority classes / deadlines / preemption).
"""

from .fleet import (  # noqa: F401
    FleetProxy,
    Lease,
    LeaseLost,
    LeaseManager,
)
from .http_front import HttpFront  # noqa: F401
from .queue import DurableQueue  # noqa: F401
from .request import (  # noqa: F401
    AdmissionError,
    RequestError,
    RequestFailed,
    SimRequest,
)
from .scheduler import SimServer  # noqa: F401
