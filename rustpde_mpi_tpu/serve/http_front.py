"""Thin HTTP front for the simulation service (stdlib only).

A :class:`~http.server.ThreadingHTTPServer` on a daemon thread, speaking a
six-endpoint JSON protocol over the :class:`~.scheduler.SimServer`'s
thread-safe surface::

    POST /requests        {"ra":1e4,"horizon":0.1,...}  -> 202 {"id", "steps",
                          "trace_id"} — the trace id names the request's
                          whole lifecycle across restarts
                          an "idempotency_key" field makes retries safe:
                          a repeat submit with a seen key replays the
                          original ack as 200 {...,"deduped":true}
                          instead of enqueueing duplicate work
                          429 {"error","reason","queue_depth",
                          "retry_after_s"} + a Retry-After header on
                          admission rejection (queue_full / draining /
                          quota), so clients back off intelligently;
                          503 + Retry-After when the queue volume is out
                          of space (reason="storage_full")
                          400 on a malformed request body / bad
                          Content-Length / truncated body, 413 oversized
    GET  /requests/<id>   lifecycle record               (404 unknown)
    GET  /requests/<id>/trace  the request's assembled Perfetto timeline
                          (admission -> queued -> scheduled -> chunks ->
                          re-bucket -> done, across incarnations) — load it
                          straight into ui.perfetto.dev  (404 unknown)
    GET  /stats           queue counts + throughput counters
    GET  /healthz         {"ok", "draining", "queue", "slots"} — liveness
                          plus queue depth and slot utilization, so an
                          orchestrator can see back-pressure, not just "up"
    GET  /metrics         Prometheus text exposition of the live registry
                          (telemetry/exporters.py) — point a scraper here
    POST /profile?seconds=N   on-demand jax.profiler capture into
                          <run_dir>/profiles (RUSTPDE_PROFILE_MAX_S cap,
                          single-flight: 409 while one runs, 400 bad args)
    POST /drain           ask the service to drain       -> 202

Durability lives BELOW this layer: a submit is acknowledged only after the
queue fsynced the request file, so an accepted 202 survives any crash.
The front is deliberately minimal — no auth, no TLS, bind it to loopback
(the default) and put a real proxy in front for anything public.
"""

from __future__ import annotations

import json
import random
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..telemetry import metrics as _tm
from ..telemetry.exporters import PROMETHEUS_CONTENT_TYPE, prometheus_text
from .request import AdmissionError, RequestError

#: request bodies past this are rejected with 413 before any parse — a
#: SimRequest is a handful of scalars; megabyte bodies are abuse or bugs
MAX_BODY_BYTES = 1 << 20


def reply_json(handler, code: int, payload: dict, headers: dict | None = None) -> None:
    """One JSON reply, shared by every front (the root server's handler
    and the fleet proxy's): Content-Length framed, optional extra headers
    (the 429 path's ``Retry-After``)."""
    body = json.dumps(payload).encode("utf-8")
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for name, value in (headers or {}).items():
        handler.send_header(name, str(value))
    handler.end_headers()
    handler.wfile.write(body)


def reply_text(handler, code: int, text: str, content_type: str) -> None:
    body = text.encode("utf-8")
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def read_body(handler):
    """Validated request body, or (code, error) on a broken frame:
    non-integer/negative Content-Length -> 400, oversized -> 413,
    truncated (client hung up early) -> 400.  Never trusts the header for
    the read — the socket read is capped and the byte count re-checked."""
    raw = handler.headers.get("Content-Length", "0")
    try:
        length = int(raw)
    except (TypeError, ValueError):
        return None, (400, f"bad Content-Length: {raw!r}")
    if length < 0:
        return None, (400, f"bad Content-Length: {raw!r}")
    if length > MAX_BODY_BYTES:
        return None, (
            413,
            f"request body of {length} bytes exceeds the "
            f"{MAX_BODY_BYTES}-byte limit",
        )
    body = handler.rfile.read(length)
    if len(body) != length:
        return None, (
            400,
            f"truncated body: Content-Length {length}, got {len(body)} bytes",
        )
    return body, None


#: jitter stream for 429 Retry-After values — module-level so tests can
#: pin it (seed_retry_jitter) and every front in the process shares one
#: sequence; NOT the random module's global state, which user code owns
_retry_jitter = random.Random()


def seed_retry_jitter(seed) -> None:
    """Re-seed the Retry-After jitter stream.  Tests pin this for
    deterministic backoff assertions; production leaves it entropy-seeded
    so a rejected client herd doesn't re-arrive in lockstep."""
    _retry_jitter.seed(seed)


def rejection_payload(exc: AdmissionError, queue_depth: int):
    """The 429 body + headers for one admission rejection: machine-
    readable reason, the live queue depth, and a ``Retry-After`` both in
    the JSON and as the standard header — so clients can back off
    intelligently instead of hammering a full queue.

    The advice is queue-depth-derived and JITTERED: the base grows with
    the live backlog (a deep queue needs longer than the exception's
    floor to drain) and a ±50% multiplicative jitter de-synchronizes the
    herd — N clients rejected in the same burst must not all come back
    on the same second.  Invariants the clients rely on: the value is an
    integer ≥ 1 and the header always equals the JSON field."""
    base_s = float(exc.retry_after_s) + 0.25 * max(0, int(queue_depth))
    retry_after = max(1, int(round(base_s * _retry_jitter.uniform(0.5, 1.5))))
    payload = {
        "error": str(exc),
        "reason": exc.reason,
        "queue_depth": int(queue_depth),
        "retry_after_s": retry_after,
    }
    return payload, {"Retry-After": retry_after}


class HttpFront:
    """Lifecycle wrapper: ``start()`` binds (port 0 = ephemeral, see
    ``address``), ``stop()`` shuts the listener down.  Handlers call the
    server's thread-safe methods only.  ``registry`` defaults to the
    process-wide telemetry registry rendered by ``GET /metrics``."""

    def __init__(self, sim_server, host: str = "127.0.0.1", port: int = 0,
                 registry=None):
        self.sim = sim_server
        self.registry = registry if registry is not None else _tm.default_registry()
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _make_handler(self):
        sim = self.sim
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            # socket timeout (socketserver applies it in setup()): a client
            # that promises a body and then goes SILENT — without hanging
            # up — must not wedge a handler thread forever; the 400/413
            # checks below only cover malformed/oversized/EOF frames
            timeout = 30.0

            def log_message(self, fmt, *args):  # quiet: the journal is the log
                pass

            def _reply(self, code: int, payload: dict, headers: dict | None = None) -> None:
                reply_json(self, code, payload, headers)

            def _reply_text(self, code: int, text: str, content_type: str) -> None:
                reply_text(self, code, text, content_type)

            def do_GET(self):
                registry.counter(
                    "http_requests_total", "HTTP requests served", method="GET"
                ).inc()
                if self.path == "/healthz":
                    # enriched liveness: queue depth + slot utilization ride
                    # along, so "up but drowning" is visible to the prober
                    return self._reply(
                        200,
                        {
                            "ok": True,
                            "draining": sim.draining,
                            "queue": sim.queue.counts(),
                            "slots": sim.slot_info(),
                        },
                    )
                if self.path == "/metrics":
                    return self._reply_text(
                        200, prometheus_text(registry), PROMETHEUS_CONTENT_TYPE
                    )
                if self.path == "/stats":
                    return self._reply(200, sim.stats())
                if self.path.startswith("/requests/"):
                    parts = self.path.strip("/").split("/")
                    if len(parts) == 3 and parts[2] == "trace":
                        trace = sim.request_trace(parts[1])
                        if trace is None:
                            return self._reply(
                                404, {"error": "unknown request id (or no "
                                              "trace recorded for it)"}
                            )
                        return self._reply(200, trace)
                    status = sim.status(parts[-1])
                    if status is None:
                        return self._reply(404, {"error": "unknown request id"})
                    return self._reply(200, status)
                return self._reply(404, {"error": "unknown endpoint"})

            def _read_body(self):
                return read_body(self)

            def do_POST(self):
                registry.counter(
                    "http_requests_total", "HTTP requests served", method="POST"
                ).inc()
                if self.path == "/drain":
                    sim.request_drain()
                    return self._reply(202, {"draining": True})
                if self.path.split("?", 1)[0] == "/profile":
                    from urllib.parse import parse_qs, urlsplit

                    query = parse_qs(urlsplit(self.path).query)
                    seconds = (query.get("seconds") or ["5"])[0]
                    try:
                        seconds = float(seconds)
                    except ValueError:
                        return self._reply(
                            400, {"error": f"bad seconds {seconds!r}"}
                        )
                    status = sim.profile_capture(seconds)
                    if status.get("started"):
                        return self._reply(202, status)
                    code = 409 if "already running" in status.get("error", "") else 400
                    return self._reply(code, status)
                if self.path != "/requests":
                    return self._reply(404, {"error": "unknown endpoint"})
                body, err = self._read_body()
                if err is not None:
                    code, message = err
                    return self._reply(code, {"error": message})
                try:
                    data = json.loads(body or b"{}")
                    req = sim.submit(data)
                except AdmissionError as exc:
                    # 429 with a Retry-After header + the live queue depth
                    # in the body: clients see WHY and for HOW LONG, not a
                    # bare reason string.  A storage_full reject is a 503:
                    # the SERVICE is impaired (the queue volume hit
                    # ENOSPC), not the client over a bound — load
                    # balancers fail over on 5xx, which is the right call
                    payload, headers = rejection_payload(
                        exc, sim.queue.counts()["queued"]
                    )
                    code = 503 if exc.reason == "storage_full" else 429
                    return self._reply(code, payload, headers)
                except (RequestError, ValueError, TypeError) as exc:
                    # typed malformed-request rejects (e.g. the sub-mesh
                    # admission's "no_submesh") carry a machine-readable
                    # reason alongside the human-readable message
                    payload = {"error": str(exc)}
                    reason = getattr(exc, "reason", None)
                    if reason:
                        payload["reason"] = reason
                    return self._reply(400, payload)
                payload = {
                    "id": req.id,
                    "steps": req.steps,
                    "trace_id": req.trace_id,
                }
                if getattr(req, "deduped", False):
                    # idempotent retry: replay the ORIGINAL ack (200, not
                    # 202 — nothing new was accepted) with the marker
                    payload["deduped"] = True
                    return self._reply(200, payload)
                return self._reply(202, payload)

        return Handler
