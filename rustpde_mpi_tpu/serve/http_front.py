"""Thin HTTP front for the simulation service (stdlib only).

A :class:`~http.server.ThreadingHTTPServer` on a daemon thread, speaking a
five-endpoint JSON protocol over the :class:`~.scheduler.SimServer`'s
thread-safe surface::

    POST /requests        {"ra":1e4,"horizon":0.1,...}  -> 202 {"id": ...}
                          429 {"error","reason"} on admission rejection
                          400 on a malformed request body
    GET  /requests/<id>   lifecycle record               (404 unknown)
    GET  /stats           queue counts + throughput counters
    GET  /healthz         {"ok": true, "draining": ...}
    POST /drain           ask the service to drain       -> 202

Durability lives BELOW this layer: a submit is acknowledged only after the
queue fsynced the request file, so an accepted 202 survives any crash.
The front is deliberately minimal — no auth, no TLS, bind it to loopback
(the default) and put a real proxy in front for anything public.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .request import AdmissionError, RequestError


class HttpFront:
    """Lifecycle wrapper: ``start()`` binds (port 0 = ephemeral, see
    ``address``), ``stop()`` shuts the listener down.  Handlers call the
    server's thread-safe methods only."""

    def __init__(self, sim_server, host: str = "127.0.0.1", port: int = 0):
        self.sim = sim_server
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _make_handler(self):
        sim = self.sim

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: the journal is the log
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._reply(
                        200, {"ok": True, "draining": sim._drain}
                    )
                if self.path == "/stats":
                    return self._reply(200, sim.stats())
                if self.path.startswith("/requests/"):
                    status = sim.status(self.path.rsplit("/", 1)[-1])
                    if status is None:
                        return self._reply(404, {"error": "unknown request id"})
                    return self._reply(200, status)
                return self._reply(404, {"error": "unknown endpoint"})

            def do_POST(self):
                if self.path == "/drain":
                    sim.request_drain()
                    return self._reply(202, {"draining": True})
                if self.path != "/requests":
                    return self._reply(404, {"error": "unknown endpoint"})
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    data = json.loads(self.rfile.read(length) or b"{}")
                    req = sim.submit(data)
                except AdmissionError as exc:
                    return self._reply(
                        429, {"error": str(exc), "reason": exc.reason}
                    )
                except (RequestError, ValueError, TypeError) as exc:
                    return self._reply(400, {"error": str(exc)})
                return self._reply(202, {"id": req.id, "steps": req.steps})

        return Handler
