"""Simulation requests: the unit of work the service admits, batches,
retries and resolves.

A :class:`SimRequest` names everything a campaign needs to reproduce the
run — grid, physics parameters, dt, geometry, horizon, IC seed — plus the
bookkeeping the robustness contract rides on (retry budget and count, dt
trajectory, progress at the last drain).  Its :meth:`compat_key` mirrors
:attr:`~rustpde_mpi_tpu.models.navier.Navier2D.compat_key`: requests with
equal keys share one compiled ensemble step and can co-batch / refill each
other's slots without recompiling.

Lifecycle (the queue directories in serve/queue.py map 1:1)::

    queued ── claim ──> running ── complete ──> done
      ^                   │ │
      │   requeue (drain/ │ └─ fail (retries exhausted) ──> failed
      └── crash/dt-retry)─┘

Every transition is an atomic file rename, so a crash at any point leaves
each request in exactly one state and restart-time recovery re-enqueues
whatever was ``running`` — accepted requests are never lost.
"""

from __future__ import annotations

import dataclasses
import json
import time
import uuid


class RequestError(ValueError):
    """A submitted request is malformed (bad grid/dt/horizon/bc): rejected
    at admission, before it can poison a batch.  ``reason`` optionally
    names a machine-readable rejection class the HTTP fronts surface in
    the 400 body (``"no_submesh"``: a sharded grid fits none of the
    configured sub-mesh shapes — permanently unservable here, distinct
    from the retryable 429 capacity reject)."""

    def __init__(self, message: str, reason: str | None = None):
        super().__init__(message)
        self.reason = reason


class AdmissionError(RuntimeError):
    """The service refused to admit a request — bounded-queue backpressure
    (``reason="queue_full"``), a draining/stopped service
    (``reason="draining"``), a tenant over its QoS quota
    (``reason="quota"``), or a queue volume with no space left
    (``reason="storage_full"`` — the durable-enqueue write hit ENOSPC;
    admitting without the fsynced file would break the never-lost
    contract, so the reject is typed and the HTTP front answers 503).
    Typed reject-with-reason instead of an unbounded backlog: the client
    backs off or routes elsewhere.  ``retry_after_s`` is the back-off
    hint the HTTP 429/503 surfaces as a ``Retry-After`` header."""

    def __init__(self, reason: str, detail: str, retry_after_s: float = 5.0):
        super().__init__(f"request rejected ({reason}): {detail}")
        self.reason = reason
        self.retry_after_s = float(retry_after_s)


#: QoS priority classes, best first — the rank orders bucket selection
#: and decides who may preempt whom (interactive preempts best-effort;
#: batch neither preempts nor is preempted by batch)
PRIORITY_CLASSES = ("interactive", "batch", "best-effort")


def priority_rank(priority: str) -> int:
    """0 = most urgent.  Unknown classes sort last (defensive: validation
    rejects them at admission, but durable files outlive code)."""
    try:
        return PRIORITY_CLASSES.index(priority)
    except ValueError:
        return len(PRIORITY_CLASSES)


class RequestFailed(RuntimeError):
    """Terminal per-request failure: the request diverged (or was killed)
    and exhausted its retry budget.  Carries the request id, the journaled
    dt trajectory it was retried along, and the terminal reason — the
    per-request analogue of
    :class:`~rustpde_mpi_tpu.utils.resilience.DivergenceError`."""

    def __init__(self, request_id: str, reason: str, dt_trajectory=()):
        super().__init__(
            f"request {request_id} failed terminally ({reason}); "
            f"dt trajectory: {list(dt_trajectory)}"
        )
        self.request_id = request_id
        self.reason = reason
        self.dt_trajectory = list(dt_trajectory)


@dataclasses.dataclass
class SimRequest:
    """One simulation request (model kind + Ra/Pr/resolution/geometry/horizon).

    ``model`` names the physics through the workloads registry (``"dns"``
    DNS, ``"lnse"`` linearized eigenmode run, ``"adjoint"`` steady-state
    find) — it PREFIXES :attr:`compat_key`, so mixed-model traffic buckets
    into separate campaigns by construction.  ``scenario`` optionally adds
    DNS step modifiers (``coriolis`` / ``passive_scalar`` /
    ``scalar_kappa`` — workloads/modifiers.ScenarioConfig.to_dict()); the
    modifier terms are operator constants, so the scenario signature joins
    the bucket key too.

    ``horizon`` is sim-time; the scheduler converts it to a step count at
    admission (``steps = max(1, round(horizon / dt))``).  ``dt`` may be
    rewritten by the per-request divergence retry (backoff re-queues the
    request at a smaller dt — a different compatibility bucket); ``dts``
    records the trajectory for the terminal :class:`RequestFailed` report.
    ``progress`` carries steps already completed in a drained campaign
    whose checkpoint will restore the member state on resume."""

    ra: float
    horizon: float
    pr: float = 1.0
    nx: int = 129
    ny: int = 129
    dt: float = 2e-3
    aspect: float = 1.0
    bc: str = "rbc"
    periodic: bool = False
    model: str = "dns"  # workloads-registry kind
    scenario: dict | None = None  # DNS step modifiers (compat-key signed)
    # QoS traffic contract (serve/fleet/qos.py): the tenant the quota is
    # charged to, the priority class (PRIORITY_CLASSES) ordering bucket
    # selection + preemption, and an optional soft deadline in seconds
    # from submission — a queued interactive request whose deadline slack
    # runs low preempts a running best-effort lane.  None of these joins
    # compat_key: requests of different tenants/classes co-batch freely.
    tenant: str = "default"
    priority: str = "batch"
    deadline_s: float | None = None
    # client-chosen idempotency key (serve/queue.py dedupe index): a retry
    # of an acked-but-unobserved submit (timeout, dropped 202, LB failover
    # to another proxy) carrying the same key is answered with the ORIGINAL
    # request's id instead of enqueueing duplicate work.  Never joins
    # compat_key; None (the default) opts out entirely.
    idempotency_key: str | None = None
    seed: int = 0
    amp: float | None = None  # IC amplitude (None: ServeConfig.default_amp)
    # sub-mesh stamp (two-level serving, parallel/submesh.py): 0 = vmapped
    # default traffic (compat_key stays the bare 10-tuple — byte-identical
    # to a service without SubmeshConfig); >0 = the device count of the
    # sub-mesh this sharded request is gang-scheduled onto, stamped at
    # admission from the configured shapes so every front buckets equal
    # grids identically.  Clients never set it; admission owns the stamp.
    submesh: int = 0
    id: str = ""
    submitted_s: float = 0.0  # unix time at admission (latency accounting)
    enqueued_s: float = 0.0  # unix time of the FIRST durable enqueue
    retries: int = 0  # divergence retries consumed
    dts: list = dataclasses.field(default_factory=list)  # dt trajectory
    progress: int = 0  # steps completed before the last drain/requeue
    # distributed trace context (telemetry/reqtrace.mint): trace_id names
    # the request's whole lifecycle across retries/re-buckets/incarnations;
    # riding the durable request file it survives exactly what the id does
    trace: dict | None = None

    def __post_init__(self):
        if not self.id:
            self.id = uuid.uuid4().hex[:12]
        if not self.submitted_s:
            self.submitted_s = time.time()
        if not self.dts:
            self.dts = [float(self.dt)]
        if self.trace is None:
            from ..telemetry import reqtrace

            self.trace = reqtrace.mint(self.id)

    @property
    def trace_id(self) -> str | None:
        """The lifecycle trace id (journal rows and chunk spans carry it)."""
        return (self.trace or {}).get("trace_id")

    def validate(self) -> "SimRequest":
        """Admission-time sanity: reject malformed work before it costs a
        compile or poisons a batch.  Raises :class:`RequestError`."""
        if self.bc not in ("rbc", "hc"):
            raise RequestError(f"bc must be 'rbc' or 'hc', got {self.bc!r}")
        if not (self.nx >= 4 and self.ny >= 4):
            raise RequestError(f"grid too small: {self.nx}x{self.ny}")
        if not (self.dt > 0.0):
            raise RequestError(f"dt must be positive, got {self.dt}")
        if not (self.horizon > 0.0):
            raise RequestError(f"horizon must be positive, got {self.horizon}")
        if not (self.ra > 0.0 and self.pr > 0.0):
            raise RequestError(f"Ra/Pr must be positive, got {self.ra}/{self.pr}")
        if self.priority not in PRIORITY_CLASSES:
            raise RequestError(
                f"priority must be one of {PRIORITY_CLASSES}, "
                f"got {self.priority!r}"
            )
        if not self.tenant or not isinstance(self.tenant, str):
            raise RequestError(f"tenant must be a non-empty string, got {self.tenant!r}")
        if self.deadline_s is not None and not (float(self.deadline_s) > 0.0):
            raise RequestError(
                f"deadline_s must be positive (or null), got {self.deadline_s}"
            )
        if int(self.submesh) < 0:
            raise RequestError(
                f"submesh stamp must be >= 0, got {self.submesh}"
            )
        if self.idempotency_key is not None:
            if (
                not isinstance(self.idempotency_key, str)
                or not self.idempotency_key.strip()
            ):
                raise RequestError(
                    "idempotency_key must be a non-empty string (or null), "
                    f"got {self.idempotency_key!r}"
                )
            if len(self.idempotency_key) > 256:
                raise RequestError(
                    "idempotency_key longer than 256 characters "
                    f"({len(self.idempotency_key)})"
                )
        from ..workloads.registry import model_kinds

        if self.model not in model_kinds():
            raise RequestError(
                f"unknown model kind {self.model!r}; known: {list(model_kinds())}"
            )
        if self.scenario is not None:
            if self.model != "dns":
                raise RequestError(
                    "scenario modifiers are a DNS axis (model='dns')"
                )
            known = {"coriolis", "passive_scalar", "scalar_kappa"}
            unknown = set(self.scenario) - known
            if unknown:
                raise RequestError(
                    f"unknown scenario fields: {sorted(unknown)}"
                )
            # VALUE validation: the signature computation must succeed —
            # compat_key is evaluated after admission (journal, bucket
            # ordering), so a bad-typed value admitted here would become a
            # durable poison pill that crashes every serve() pass
            from ..models.navier import scenario_signature

            try:
                scenario_signature(self.scenario)
            except (TypeError, ValueError) as exc:
                raise RequestError(f"bad scenario values: {exc}") from exc
        return self

    @property
    def compat_key(self) -> tuple:
        """Operator-constant bucket key — equal keys co-batch (mirrors
        :attr:`~rustpde_mpi_tpu.models.campaign.CampaignModelBase.compat_key`:
        model kind first, canonical scenario signature last)."""
        from ..models.navier import scenario_signature

        key = (
            str(self.model),
            int(self.nx),
            int(self.ny),
            float(self.ra),
            float(self.pr),
            float(self.dt),
            float(self.aspect),
            str(self.bc),
            bool(self.periodic),
            scenario_signature(self.scenario),
        )
        # gang traffic gains the sub-mesh stamp as an 11th element so
        # sharded buckets never co-batch with vmapped ones; unstamped
        # requests keep the bare 10-tuple (byte-identical default)
        if int(self.submesh) > 0:
            key = key + (int(self.submesh),)
        return key

    @property
    def steps(self) -> int:
        """Total steps this request needs at its current dt."""
        return max(1, round(float(self.horizon) / float(self.dt)))

    @property
    def steps_remaining(self) -> int:
        """Steps still owed after any drained-campaign progress."""
        return max(0, self.steps - int(self.progress))

    @property
    def class_rank(self) -> int:
        """QoS priority rank (0 = interactive, most urgent)."""
        return priority_rank(self.priority)

    def deadline_slack(self, now: float) -> float:
        """Seconds of deadline slack left at wall time ``now`` (may be
        negative: already late); +inf for deadline-free requests."""
        if self.deadline_s is None:
            return float("inf")
        return (self.submitted_s + float(self.deadline_s)) - float(now)

    def backed_off(self, factor: float) -> "SimRequest":
        """The retry copy: dt shrunk, retry counted, progress DISCARDED —
        a diverged trajectory is not worth resuming — and the dt recorded
        on the trajectory."""
        new_dt = float(self.dt) * float(factor)
        return dataclasses.replace(
            self,
            dt=new_dt,
            retries=self.retries + 1,
            dts=self.dts + [new_dt],
            progress=0,
        )

    def rebucketed(self, new_dt: float, progress: int = 0) -> "SimRequest":
        """The PROACTIVE dt re-bucket copy (per-bucket stability ladder,
        serve/scheduler): dt moved to a new ladder rung, the dt recorded on
        the trajectory, progress PRESERVED — the member state was finite
        when the CFL sentinel tripped, so the scheduler parks it and the
        trajectory continues at the new rung.  Unlike :meth:`backed_off`
        this consumes no retry: nothing failed, the governor acted early."""
        new_dt = float(new_dt)
        return dataclasses.replace(
            self,
            dt=new_dt,
            dts=self.dts + [new_dt],
            progress=int(progress),
        )

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "SimRequest":
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise RequestError(f"unknown request fields: {sorted(unknown)}")
        return cls(**data)

    @classmethod
    def from_dict(cls, data: dict) -> "SimRequest":
        return cls.from_json(json.dumps(data))
