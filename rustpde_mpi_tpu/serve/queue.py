"""Durable on-disk request queue with admission control.

One JSON file per request, one directory per lifecycle state::

    <root>/queued/<seq>-<id>.json     FIFO order rides the seq prefix
    <root>/running/<id>.json          claimed by a campaign slot
    <root>/done/<id>.json             request + result record
    <root>/failed/<id>.json           request + terminal RequestFailed record

Every transition is ``os.replace`` of a file that was fsynced at admission
— atomic on POSIX — so a crash at ANY point leaves each request in exactly
one state: the durability story is the filesystem's rename atomicity, not
a database.  The parent lifecycle DIRECTORY is fsynced after each rename
too: ``os.replace`` alone leaves the new directory entry in the page cache,
so a power loss right after an acknowledged submit (or a claim) could
silently undo the rename — the request-never-lost guarantee needs the
directory inode durable, not just the file bytes.  Restart-time :meth:`recover` re-enqueues whatever was left in
``running/`` (the campaign that claimed it died), which is the "accepted
requests are never lost" half of the serve contract; the scheduler's
checkpoint + journal restore the *progress* half.

Admission control is the queue's job too: :meth:`submit` rejects — with a
typed :class:`~rustpde_mpi_tpu.serve.request.AdmissionError` naming the
reason — once ``max_queue`` requests are waiting, so a client burst
degrades into clean 429-style rejections instead of an OOM or an unbounded
latency tail.  All public methods are thread-safe (the HTTP front submits
from handler threads while the scheduler claims from the campaign loop).
"""

from __future__ import annotations

import bisect
import errno
import hashlib
import json
import os
import threading
import time

from .request import AdmissionError, RequestError, SimRequest
from ..utils.fsutil import atomic_write_text, fsync_dir

_STATES = ("queued", "running", "done", "failed")


# shared durability primitives (utils/fsutil): os.replace alone leaves
# the new dirent in page cache — the request-never-lost guarantee would
# rest on the filesystem journaling renames by luck
_fsync_dir = fsync_dir
_atomic_write = atomic_write_text


class DurableQueue:
    """The on-disk request queue (see module docstring)."""

    def __init__(self, root: str, max_queue: int = 256):
        self.root = root
        self.max_queue = int(max_queue)
        self._lock = threading.RLock()
        self._seq = 0  # in-process tiebreak under one time.time_ns() tick
        # queued-dir scan cache: the scheduler consults the queue several
        # times per chunk boundary (bucket order, fairness probe, claims)
        # and each consult was an O(all files) listdir + a JSON parse per
        # file — a 10k-deep queue taxed every boundary.  The listing is
        # kept incrementally coherent across own mutations (enqueue
        # inserts, claim/recover evict, a lost claim race invalidates);
        # queued files are immutable once placed (a requeue writes a NEW
        # seq-name), so parsed requests are cached by name too.  External
        # writers (fleet proxies, peer replicas over the shared dir) are
        # handled by invalidate() + the claim-race eviction path.
        self._listing: list[str] | None = None
        self._req_cache: dict[str, SimRequest] = {}
        for state in _STATES:
            os.makedirs(os.path.join(root, state), exist_ok=True)

    def _dir(self, state: str) -> str:
        return os.path.join(self.root, state)

    # -- admission ------------------------------------------------------------

    def submit(self, req: SimRequest, *, admit_open: bool = True) -> SimRequest:
        """Validate + admit one request into ``queued/``.

        Raises :class:`RequestError` (malformed — never admitted) or
        :class:`AdmissionError` (``queue_full`` backpressure, ``draining``
        when the owning service flipped ``admit_open`` off, or
        ``storage_full`` when the durable write itself hit ENOSPC — an
        un-fsyncable admission must never be acknowledged).
        Returns the request with its id/submit-time stamped.

        **Idempotent retries**: a request carrying an ``idempotency_key``
        already present in the durable dedupe index is NOT re-enqueued —
        the returned request bears the ORIGINAL submit's id/trace and
        ``req.deduped`` is set, so the front replays the original ack.
        The dedupe check runs BEFORE every admission bound: a retry of
        already-accepted work must get its ack back even through a full
        queue or a draining service."""
        req.validate()
        with self._lock:
            key = getattr(req, "idempotency_key", None)
            if key:
                prior = self.dedupe_lookup(key)
                if prior is not None:
                    return self._dedupe_into(req, prior)
            if not admit_open:
                raise AdmissionError(
                    "draining",
                    "the service is draining and admits no new work",
                    retry_after_s=30.0,
                )
            if len(self._queued_files()) >= self.max_queue:
                raise AdmissionError(
                    "queue_full",
                    f"{self.max_queue} requests already queued; retry with "
                    "backoff",
                    retry_after_s=5.0,
                )
            try:
                self._enqueue(req)
            except OSError as exc:
                if exc.errno == errno.ENOSPC:
                    raise AdmissionError(
                        "storage_full",
                        "the queue volume has no space left; admission "
                        "refused until storage is reclaimed",
                        retry_after_s=30.0,
                    ) from exc
                raise
            if key:
                winner = self._idem_claim(req)
                if winner is not None and winner.get("id") != req.id:
                    # lost a concurrent same-key race by one dirent:
                    # withdraw our duplicate and answer with the winner
                    self._withdraw_queued(req.id)
                    return self._dedupe_into(req, winner)
        return req

    # -- idempotency (the dedupe index) ---------------------------------------

    def _idem_dir(self) -> str:
        return os.path.join(self.root, "idempotency")

    def _idem_path(self, key: str) -> str:
        digest = hashlib.sha256(key.encode("utf-8")).hexdigest()[:40]
        return os.path.join(self._idem_dir(), f"{digest}.json")

    def dedupe_lookup(self, key) -> dict | None:
        """The durable index record for one idempotency key — ``{"id",
        "trace_id", "key"}`` of the submit that claimed it — or None for
        an unseen (or falsy) key."""
        if not key:
            return None
        try:
            with open(self._idem_path(key), encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _idem_claim(self, req: SimRequest) -> dict | None:
        """Claim ``req``'s key in the index via O_EXCL dirent creation —
        exactly one of N racing same-key submits wins.  Returns None on a
        win, the winner's record on a loss.  The index is written AFTER
        the enqueue: a crash between the two degrades to at-least-once
        (the retry re-runs the physics — a dup result, never a lost or
        ghost request), which is the right failure direction.  An index
        write that itself fails is swallowed the same way."""
        path = self._idem_path(req.idempotency_key)
        record = {
            "id": req.id,
            "trace_id": req.trace_id,
            "key": req.idempotency_key,
        }
        try:
            os.makedirs(self._idem_dir(), exist_ok=True)
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
        except FileExistsError:
            return self.dedupe_lookup(req.idempotency_key)
        except OSError:
            return None  # degraded: no index entry, dedupe waived
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                fh.write(json.dumps(record, sort_keys=True))
                fh.flush()
                os.fsync(fh.fileno())
            _fsync_dir(self._idem_dir())
        except OSError:
            pass
        return None

    def _dedupe_into(self, req: SimRequest, prior: dict) -> SimRequest:
        """Rewrite ``req`` into the original submit's identity so the
        caller's ack (id/steps/trace_id) replays the first answer; the
        ``deduped`` marker tells fronts to journal ``request_deduped``
        instead of admitting."""
        req.deduped = True
        req.id = prior.get("id") or req.id
        if prior.get("trace_id"):
            req.trace = {"trace_id": prior["trace_id"]}
        return req

    def _withdraw_queued(self, request_id: str) -> None:
        """Remove our just-enqueued file for ``request_id`` (the loser of
        an idempotency race): the winner's copy is the one true submit."""
        for name in list(self._queued_files()):
            if name.endswith(f"-{request_id}.json"):
                try:
                    os.remove(os.path.join(self._dir("queued"), name))
                except OSError:
                    pass
                self._evict(name)

    def _enqueue(self, req: SimRequest) -> None:
        """Write the queued file (caller holds the lock).  The FIRST durable
        enqueue stamps ``enqueued_s`` — the admission-to-first-observable
        histogram's clock start; requeues (drain/retry/re-bucket) keep it."""
        if not req.enqueued_s:
            req.enqueued_s = time.time()
        self._seq += 1
        name = f"{time.time_ns():020d}{self._seq:04d}-{req.id}.json"
        _atomic_write(os.path.join(self._dir("queued"), name), req.to_json())
        if self._listing is not None:
            bisect.insort(self._listing, name)
        self._req_cache[name] = req

    def _state_files(self, state: str) -> list[str]:
        """Committed request files only: a crash inside ``_atomic_write``
        can leave ``*.tmp`` corpses next to them, which must never count
        toward admission, scheduling or the lifecycle totals."""
        try:
            return sorted(
                n for n in os.listdir(self._dir(state)) if n.endswith(".json")
            )
        except OSError:
            return []

    def _queued_files(self) -> list[str]:
        if self._listing is None:
            self._listing = self._state_files("queued")
            self._req_cache = {
                n: r for n, r in self._req_cache.items() if n in set(self._listing)
            }
        return self._listing

    def _evict(self, name: str) -> None:
        """Drop one name from the cached listing (claimed/raced away)."""
        if self._listing is not None:
            try:
                self._listing.remove(name)
            except ValueError:
                pass
        self._req_cache.pop(name, None)

    def invalidate(self) -> None:
        """Forget the cached queued-dir listing: the next scan re-lists.
        Fleet replicas call this once per scheduler boundary — proxies and
        peer replicas write the shared dir behind this process's back."""
        with self._lock:
            self._listing = None

    # -- scheduling -----------------------------------------------------------

    def _load_queued(self) -> list[tuple[str, SimRequest]]:
        out = []
        for name in list(self._queued_files()):
            req = self._req_cache.get(name)
            if req is not None:
                out.append((name, req))
                continue
            path = os.path.join(self._dir("queued"), name)
            try:
                with open(path, encoding="utf-8") as fh:
                    req = SimRequest.from_json(fh.read())
            except FileNotFoundError:
                # a peer replica claimed it between our listdir and this
                # read (fleet mode: the shared dir has other writers)
                self._evict(name)
                continue
            except (OSError, ValueError, RequestError):
                # unreachable in practice: submit() fsyncs before the
                # atomic rename and .tmp corpses are filtered out — but a
                # truly unreadable file must not wedge scheduling forever
                continue
            self._req_cache[name] = req
            out.append((name, req))
        return out

    def snapshot_queued(self) -> list[tuple[str, SimRequest]]:
        """The queued scan as ``(name, request)`` pairs (names sort by
        enqueue order) — the fleet QoS planner's input; served from the
        listing/request caches like every other consult."""
        with self._lock:
            return list(self._load_queued())

    def buckets(self) -> dict[tuple, int]:
        """Pending request count per compatibility bucket, FIFO-weighted:
        the scheduler opens a campaign for the bucket holding the OLDEST
        queued request (no starvation), refilling slots from that bucket."""
        with self._lock:
            counts: dict[tuple, int] = {}
            for _, req in self._load_queued():
                counts.setdefault(req.compat_key, 0)
                counts[req.compat_key] += 1
            return counts

    def oldest_bucket(self) -> tuple | None:
        with self._lock:
            for _, req in self._load_queued():
                return req.compat_key
        return None

    def bucket_order(self) -> list[tuple]:
        """Distinct pending bucket keys ordered by their OLDEST queued
        request (FIFO over buckets) — the scheduler's round-robin rotation
        walks this list so no bucket waits more than one full cycle."""
        with self._lock:
            order: list[tuple] = []
            for _, req in self._load_queued():
                if req.compat_key not in order:
                    order.append(req.compat_key)
            return order

    def other_bucket_waiting(self, key: tuple) -> bool:
        """True when some OTHER bucket holds queued work (the fairness
        quantum only caps a campaign while someone is actually waiting)."""
        with self._lock:
            for _, req in self._load_queued():
                if req.compat_key != key:
                    return True
        return False

    def _claim_name(self, name: str, req: SimRequest) -> bool:
        """Move one queued file into ``running/``; False when a peer
        replica raced the claim (the source vanished under us — fleet
        mode's shared dir), in which case the stale cache is dropped."""
        src = os.path.join(self._dir("queued"), name)
        dst = os.path.join(self._dir("running"), f"{req.id}.json")
        try:
            os.replace(src, dst)
        except FileNotFoundError:
            self._evict(name)
            return False
        _fsync_dir(self._dir("running"))
        _fsync_dir(self._dir("queued"))
        self._evict(name)
        return True

    def claim(self, key: tuple | None = None, qos: bool = False) -> SimRequest | None:
        """Atomically move the oldest queued request (matching ``key`` when
        given) into ``running/`` and return it; None when nothing matches.
        ``qos=True`` picks by (priority class, deadline slack, FIFO)
        instead of pure FIFO — the fleet traffic contract's claim order."""
        with self._lock:
            candidates = [
                (name, req)
                for name, req in self._load_queued()
                if key is None or req.compat_key == key
            ]
            if qos:
                now = time.time()
                candidates.sort(
                    key=lambda nr: (
                        nr[1].class_rank,
                        nr[1].deadline_slack(now),
                        nr[0],
                    )
                )
            for name, req in candidates:
                if self._claim_name(name, req):
                    return req
        return None

    def claim_id(self, request_id: str) -> SimRequest | None:
        """Claim one SPECIFIC queued request by id (the campaign-restore
        path: the slot table names the request whose member state the
        checkpoint restored).  None when the id is not queued — e.g. it
        completed after the checkpoint was written."""
        with self._lock:
            for name, req in self._load_queued():
                if req.id != request_id:
                    continue
                if self._claim_name(name, req):
                    return req
        return None

    # -- resolution -----------------------------------------------------------

    def _resolve(self, req: SimRequest, state: str, record: dict) -> str:
        with self._lock:
            path = os.path.join(self._dir(state), f"{req.id}.json")
            _atomic_write(path, json.dumps(record, sort_keys=True))
            running = os.path.join(self._dir("running"), f"{req.id}.json")
            try:
                os.remove(running)
                _fsync_dir(self._dir("running"))
            except OSError:
                pass  # recovery may already have re-enqueued it
            return path

    def complete(self, req: SimRequest, result: dict) -> str:
        """Move a running request to ``done/`` with its result record."""
        return self._resolve(req, "done", {"request": json.loads(req.to_json()), "result": result})

    def fail(self, req: SimRequest, reason: str) -> str:
        """Move a running request to its terminal ``failed/`` state."""
        record = {
            "request": json.loads(req.to_json()),
            "error": {"type": "RequestFailed", "reason": reason, "dts": req.dts},
        }
        return self._resolve(req, "failed", record)

    def requeue(self, req: SimRequest) -> None:
        """Put a running request back on the queue (drain, crash recovery,
        or a dt-backoff retry — the caller updates the request first).
        Requeues bypass the admission bound: the work was already
        accepted."""
        with self._lock:
            self._enqueue(req)
            running = os.path.join(self._dir("running"), f"{req.id}.json")
            try:
                os.remove(running)
                _fsync_dir(self._dir("running"))
            except OSError:
                pass

    def recover(self) -> list[str]:
        """Re-enqueue every ``running/`` request (startup: whatever claimed
        them died before resolving).  Progress is NOT reset here — the
        scheduler restores it from the campaign checkpoint when it can.
        Returns the recovered ids."""
        recovered = []
        with self._lock:
            for name in self._state_files("running"):
                path = os.path.join(self._dir("running"), name)
                try:
                    with open(path, encoding="utf-8") as fh:
                        req = SimRequest.from_json(fh.read())
                except (OSError, ValueError, RequestError):
                    continue
                self._enqueue(req)
                os.remove(path)
                recovered.append(req.id)
            if recovered:
                _fsync_dir(self._dir("running"))
        return recovered

    def recover_bucket(self, key: tuple) -> list[str]:
        """Re-enqueue the ``running/`` requests of ONE compat bucket — the
        fleet lease-break path: a dead replica's claims are scoped by the
        bucket lease the survivor just broke, never the whole running dir
        (peer replicas' live claims must not be stolen).  Returns the
        recovered ids."""
        recovered = []
        with self._lock:
            for name in self._state_files("running"):
                path = os.path.join(self._dir("running"), name)
                try:
                    with open(path, encoding="utf-8") as fh:
                        req = SimRequest.from_json(fh.read())
                except (OSError, ValueError, RequestError):
                    continue
                if req.compat_key != key:
                    continue
                self._enqueue(req)
                try:
                    os.remove(path)
                except OSError:
                    pass  # queued copy wins either way: duplicate beats lost
                recovered.append(req.id)
            if recovered:
                _fsync_dir(self._dir("running"))
        return recovered

    def tenant_counts(self) -> dict[str, int]:
        """Waiting + in-flight request count per tenant — the QoS quota
        denominator (done/failed are resolved: they no longer charge)."""
        with self._lock:
            counts: dict[str, int] = {}
            for _, req in self._load_queued():
                counts[req.tenant] = counts.get(req.tenant, 0) + 1
            for name in self._state_files("running"):
                path = os.path.join(self._dir("running"), name)
                try:
                    with open(path, encoding="utf-8") as fh:
                        req = SimRequest.from_json(fh.read())
                except (OSError, ValueError, RequestError):
                    continue
                counts[req.tenant] = counts.get(req.tenant, 0) + 1
            return counts

    # -- introspection --------------------------------------------------------

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {
                state: len(self._state_files(state)) for state in _STATES
            }

    def lookup(self, request_id: str) -> tuple[str, dict] | None:
        """(state, record) for one id; queued records are the bare request."""
        with self._lock:
            for state in ("running", "done", "failed"):
                path = os.path.join(self._dir(state), f"{request_id}.json")
                if os.path.exists(path):
                    with open(path, encoding="utf-8") as fh:
                        data = json.load(fh)
                    return state, (data if state != "running" else {"request": data})
            for name, req in self._load_queued():
                if req.id == request_id:
                    return "queued", {"request": json.loads(req.to_json())}
        return None
