"""Per-model solo-vs-ensemble parity deltas (the cross-model drift probe).

One tiny campaign per registered model kind: K=2 members stepped as a
vmapped ensemble vs the same trajectories stepped solo, with the maximum
relative state-leaf deviation recorded per kind.  ``scripts/record_tests.py``
runs this and lands the numbers in PARITY.json (`"workloads"` key) so a
vmap/scan/refactor regression in ANY model's batched path shows up as a
per-PR delta next to the existing Nu-parity numbers — not months later in
a campaign.
"""

from __future__ import annotations

import numpy as np

#: tiny shapes: parity is about code paths, not physics
_DEFAULTS = dict(nx=17, ny=17, ra=1e4, pr=1.0, aspect=1.0, bc="rbc")


def _build(kind: str, dt: float):
    from .registry import build_model

    return build_model(
        kind,
        _DEFAULTS["nx"],
        _DEFAULTS["ny"],
        _DEFAULTS["ra"],
        _DEFAULTS["pr"],
        dt,
        _DEFAULTS["aspect"],
        _DEFAULTS["bc"],
        False,
    )


def _seed(model, kind: str, seed: int) -> None:
    if kind == "adjoint":
        model.set_temperature(0.3 + 0.1 * seed, 1.0, 1.0)
        model.set_velocity(0.3 + 0.1 * seed, 1.0, 1.0)
    else:
        model.init_random(1e-2, seed=seed)


def solo_ensemble_parity(kinds=("dns", "lnse", "adjoint"), steps: int = 8) -> dict:
    """``{kind: {"max_rel_diff", "steps", "k"}}`` — max relative deviation
    of every state leaf between a K=2 vmapped ensemble and the member-wise
    solo runs after ``steps`` steps (identical ICs, identical dt)."""
    from ..models.ensemble import NavierEnsemble

    out = {}
    for kind in kinds:
        dt = 5e-3 if kind == "adjoint" else 1e-2
        model = _build(kind, dt)
        members = []
        for seed in (0, 1):
            _seed(model, kind, seed)
            members.append(model.state)
        ens = NavierEnsemble(model, members)
        ens.update_n(steps)
        worst = 0.0
        for i, seed in enumerate((0, 1)):
            # fresh model per member: seeding only rewrites the IC fields,
            # and a reused model would leak the previous run's pres/pseu
            solo = _build(kind, dt)
            _seed(solo, kind, seed)
            solo.update_n(steps)
            for got, want in zip(ens.member_state(i), solo.state):
                got = np.asarray(got)
                want = np.asarray(want)
                scale = float(np.max(np.abs(want)))
                if scale == 0.0 or not np.isfinite(scale):
                    continue
                worst = max(worst, float(np.max(np.abs(got - want))) / scale)
        out[kind] = {"max_rel_diff": worst, "steps": int(steps), "k": 2}
    return out
