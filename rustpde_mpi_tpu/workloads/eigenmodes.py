"""Eigenmode-sweep campaign: linear-stability analysis as a batched,
governed, checkpointed workload.

The linearized model (:class:`~rustpde_mpi_tpu.models.lnse.Navier2DLnse`)
evolves a perturbation about a base state; after transients the energy of
the leading eigenmode behaves as ``E(t) ~ e^{2 sigma t}``, so the leading
growth rate falls out of a log-linear fit over the energy trajectory the
campaign observables already stream at chunk boundaries.  This module runs
that as a CampaignModel workload:

* one vmapped :class:`~rustpde_mpi_tpu.models.ensemble.NavierEnsemble` per
  Rayleigh number, with K members seeded on DIFFERENT horizontal
  wavenumbers (``modes``) — the sweep over the dispersion relation
  ``sigma(m; Ra)`` rides the batch axis, the Ra axis maps to buckets
  (Ra is an operator constant: the implicit solvers factorize it),
* driven through :class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner`
  (sharded checkpoints + auto-resume: a killed sweep continues mid-sweep),
* growth rates fitted per member from the second half of the sampled
  ``ln E`` trajectory; :func:`critical_rayleigh` interpolates the sign
  change of the leading rate — for the rigid-rigid layer (periodic-x at
  the critical wavelength) the analytic answer is Ra_c = 1707.76 at
  ``k_c = 3.117`` (Chandrasekhar), which the workload gate reproduces
  within discretization tolerance (tests/test_workloads.py).

Notably this reuses the unsharded banded-scan solve path that deliberately
kept reverse-mode differentiability — the same model also serves the
optimal-control gradients (models/lnse.py), so stability analysis and
adjoint optimization share one operator stack.
"""

from __future__ import annotations

import math
import os

import numpy as np

#: Chandrasekhar's rigid-rigid critical wavenumber a_c = k_c * d (d = layer
#: depth); the model's layer depth is 2 (Chebyshev wall-to-wall), so the
#: periodic box must put mode m at k = m / aspect = A_C / 2.
RAC_RIGID = 1707.762
AC_RIGID = 3.117


def critical_aspect(mode: int = 1) -> float:
    """Aspect ratio placing horizontal mode ``mode`` exactly at the
    rigid-rigid critical wavenumber (layer depth 2 -> k_c = a_c / 2)."""
    return float(mode) / (AC_RIGID / 2.0)


def build_eigenmode_ensemble(
    *,
    nx: int,
    ny: int,
    ra: float,
    pr: float = 1.0,
    dt: float = 0.05,
    aspect: float | None = None,
    bc: str = "rbc",
    periodic: bool = True,
    modes=(1,),
    amp: float = 1e-4,
    mesh=None,
):
    """One Ra bucket of the sweep: K = len(modes) members of the linearized
    model, member ``i`` seeded on horizontal mode ``modes[i]`` (velocity +
    temperature eigenmode shape — close enough to the true eigenfunction
    that the transient is short)."""
    from ..models.ensemble import NavierEnsemble
    from .registry import build_model

    if aspect is None:
        aspect = critical_aspect(1)
    model = build_model(
        "lnse", nx, ny, ra, pr, dt, aspect, bc, periodic, mesh=mesh
    )
    members = []
    for m in modes:
        model.set_velocity(amp, float(m), 1.0)
        model.set_temperature(amp, float(m), 1.0)
        members.append(model.state)
    return NavierEnsemble(model, members)


def growth_rates(times, energies, fit_fraction: float = 0.5) -> np.ndarray:
    """Per-member leading growth rates from sampled energies: least-squares
    slope of ``ln E`` over the LAST ``fit_fraction`` of the samples (the
    transient lives in the first part), divided by 2 (energy grows at twice
    the amplitude rate).  Members whose energy went non-finite report NaN."""
    times = np.asarray(times, dtype=np.float64)
    energies = np.asarray(energies, dtype=np.float64)  # (samples, K)
    n = len(times)
    start = max(0, min(n - 2, int(round(n * (1.0 - fit_fraction)))))
    t = times[start:]
    out = np.full(energies.shape[1], np.nan)
    for i in range(energies.shape[1]):
        e = energies[start:, i]
        if not (np.isfinite(e).all() and (e > 0).all()):
            continue
        slope = np.polyfit(t, np.log(e), 1)[0]
        out[i] = 0.5 * slope
    return out


def eigenmode_sweep(
    ras,
    *,
    nx: int = 8,
    ny: int = 17,
    pr: float = 1.0,
    dt: float = 0.05,
    aspect: float | None = None,
    bc: str = "rbc",
    periodic: bool = True,
    modes=(1,),
    amp: float = 1e-4,
    horizon: float = 40.0,
    samples: int = 16,
    run_dir: str | None = None,
    checkpoint_every_s: float | None = None,
    stability=None,
    fault: str | None = None,
    mesh=None,
) -> list[dict]:
    """Sweep the leading growth rate over ``ras``.

    Each Ra runs as a governed/checkpointed ensemble campaign under
    ``ResilientRunner``: with a ``run_dir``, checkpoints + auto-resume are
    on per Ra — a mid-sweep kill resumes where it died — and a COMPLETED
    Ra run removes its (spent) checkpoints, so a later sweep over the same
    directory starts fresh instead of "resuming" past its own sampling
    window.  ``run_dir=None`` runs checkpoint-free in a temporary
    directory.  Energies are sampled at ``samples`` chunk boundaries over
    ``horizon`` time units and fitted by :func:`growth_rates`.

    Returns one dict per Ra: ``{"ra", "modes", "sigma" (per member),
    "sigma_max", "times", "energies", "resumed"}``."""
    import shutil
    import tempfile

    from ..config import IOConfig
    from ..utils import checkpoint
    from ..utils.resilience import ResilientRunner

    results = []
    steps_total = max(samples, int(round(horizon / dt)))
    chunk = max(1, steps_total // samples)
    tmp_root = None
    if run_dir is None:
        tmp_root = tempfile.mkdtemp(prefix="eigenmode_sweep_")
    for ra in ras:
        ens = build_eigenmode_ensemble(
            nx=nx, ny=ny, ra=float(ra), pr=pr, dt=dt, aspect=aspect, bc=bc,
            periodic=periodic, modes=modes, amp=amp, mesh=mesh,
        )
        runner = ResilientRunner(
            ens,
            max_time=float("inf"),
            run_dir=os.path.join(tmp_root or run_dir, f"ra{float(ra):g}"),
            checkpoint_every_s=checkpoint_every_s,
            stability=stability,
            fault=fault if fault is not None else "",
            resume=tmp_root is None,
            # the slot-table-free sharded format restores bit-equal onto
            # the same K (the sweep geometry is fixed per Ra directory)
            io=IOConfig(sharded_checkpoints=True, overlap_dispatch=False),
        )
        times, energies = [], []
        drained = False
        with runner.session(install_signals=False):
            # a resumed run re-enters mid-trajectory: skip what is done
            while runner.step < steps_total:
                n = min(chunk, steps_total - runner.step)
                before = runner.step
                runner.advance(n)
                if runner.step == before:
                    break  # governor re-plan made no progress; next loop
                times.append(float(ens.get_time()))
                energies.append(np.asarray(ens.get_observables()[0]))
                if runner.drain_requested():
                    drained = True
                    runner.checkpoint_now("preempt")
                    break
            if runner.step >= steps_total and not drained:
                # the campaign is DONE and its growth rates extracted: the
                # checkpoints were kill-insurance, now spent — sweep them
                # so a rerun measures fresh instead of resuming complete
                # (with zero samples, hence NaN rates)
                runner.drain_io()
                for path in checkpoint.checkpoint_files(runner.run_dir):
                    checkpoint.remove_checkpoint(path)
        sigma = (
            growth_rates(times, np.stack(energies))
            if len(times) >= 2
            else np.full(len(tuple(modes)), np.nan)
        )
        results.append(
            {
                "ra": float(ra),
                "modes": list(modes),
                "sigma": [float(s) for s in sigma],
                "sigma_max": (
                    float(np.nanmax(sigma)) if np.isfinite(sigma).any()
                    else float("nan")
                ),
                "steps": int(runner.step),
                "times": [float(t) for t in times],
                "energies": [[float(v) for v in row] for row in energies],
                "resumed": bool(runner.resumed),
            }
        )
    if tmp_root is not None:
        shutil.rmtree(tmp_root, ignore_errors=True)
    return results


def critical_rayleigh(results) -> float:
    """Interpolated zero crossing of the leading growth rate over the sweep
    (linear in Ra — exact near onset, where sigma(Ra) is linear).  Raises
    ``ValueError`` when the sweep does not bracket the sign change."""
    rows = sorted(
        (r for r in results if math.isfinite(r["sigma_max"])),
        key=lambda r: r["ra"],
    )
    for lo, hi in zip(rows, rows[1:]):
        s0, s1 = lo["sigma_max"], hi["sigma_max"]
        if s0 <= 0.0 <= s1:
            if s1 == s0:
                return 0.5 * (lo["ra"] + hi["ra"])
            return lo["ra"] - s0 * (hi["ra"] - lo["ra"]) / (s1 - s0)
    raise ValueError(
        "sweep does not bracket the growth-rate sign change: "
        + ", ".join(f"Ra={r['ra']:g}: sigma={r['sigma_max']:.3e}" for r in rows)
    )
