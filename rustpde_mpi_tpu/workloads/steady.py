"""Steady-state finder campaign: adjoint descent as a batched, resilient
workload.

:class:`~rustpde_mpi_tpu.models.steady_adjoint.Navier2DAdjoint` descends
the smoothed-residual norm toward a steady state; as a CampaignModel its
residual norms ride the state carry, so residual CONVERGENCE is the
chunk's compiled early-exit (``_scan_ok``): a member that reaches
``res_tol`` freezes at its converged state mid-chunk — no wasted GEMMs, no
host round-trip per iteration.  This module drives K seed-decorrelated
finds as one vmapped ensemble under
:class:`~rustpde_mpi_tpu.utils.resilience.ResilientRunner`: sharded
checkpoints on a cadence, auto-resume (a mid-find SIGTERM/kill resumes the
descent from the newest valid checkpoint — exercised by the workload gate
in tests/test_workloads.py), and per-member fault isolation (one diverged
IC cannot kill its co-batched finds).
"""

from __future__ import annotations

import numpy as np


def build_steady_ensemble(
    *,
    nx: int,
    ny: int,
    ra: float,
    pr: float = 1.0,
    dt: float = 5e-3,
    aspect: float = 1.0,
    bc: str = "rbc",
    periodic: bool = False,
    res_tol: float | None = None,
    k: int = 1,
    amp: float = 0.5,
    seeds=None,
    mesh=None,
):
    """K member adjoint finders: member 0 is seeded on the large-scale
    circulation mode (the reference's IC, steady_adjoint.rs doc example),
    further members on random ICs (``seeds``, default 1..K-1) — basins of
    attraction differ, so a batch explores several candidate states."""
    from ..models.ensemble import NavierEnsemble
    from ..models.steady_adjoint import RES_TOL
    from .registry import build_model

    model = build_model(
        "adjoint", nx, ny, ra, pr, dt, aspect, bc, periodic, mesh=mesh,
        scenario={"res_tol": float(res_tol if res_tol is not None else RES_TOL)},
    )
    members = []
    model.set_temperature(amp, 1.0, 1.0)
    model.set_velocity(amp, 1.0, 1.0)
    members.append(model.state)
    seeds = list(seeds) if seeds is not None else list(range(1, k))
    for seed in seeds[: max(0, k - 1)]:
        model.init_random(amp, seed=int(seed))
        members.append(model.state)
    return NavierEnsemble(model, members)


def steady_state_find(
    *,
    nx: int = 17,
    ny: int = 17,
    ra: float = 100.0,
    pr: float = 1.0,
    dt: float = 1e-3,
    aspect: float = 1.0,
    bc: str = "rbc",
    periodic: bool = False,
    res_tol: float = 1e-7,
    k: int = 1,
    amp: float = 0.5,
    seeds=None,
    max_iters: int = 20000,
    chunk: int = 200,
    run_dir: str = "data/steady_find",
    checkpoint_every_s: float | None = None,
    checkpoint_every_iters: int | None = None,
    fault: str | None = None,
    stability=None,
    mesh=None,
    install_signals: bool = True,
) -> dict:
    """Run a K-member steady-state find to convergence (or ``max_iters``).

    The exit sentinel is the residual: each chunk's per-member residuals
    arrive with the (already-dispatched) observables, members freeze
    on-device at convergence, and the campaign ends when every member is
    converged or dead.  With ``run_dir`` checkpoints + auto-resume are on:
    re-invoking after a kill CONTINUES the find mid-descent.

    Returns ``{"converged" (per member), "residuals", "nu", "iterations",
    "resumed", "checkpoint"}``."""
    from ..config import IOConfig
    from ..utils.resilience import ResilientRunner

    ens = build_steady_ensemble(
        nx=nx, ny=ny, ra=ra, pr=pr, dt=dt, aspect=aspect, bc=bc,
        periodic=periodic, res_tol=res_tol, k=k, amp=amp, seeds=seeds,
        mesh=mesh,
    )
    runner = ResilientRunner(
        ens,
        max_time=float("inf"),
        run_dir=run_dir,
        checkpoint_every_s=checkpoint_every_s,
        stability=stability,
        fault=fault if fault is not None else "",
        io=IOConfig(sharded_checkpoints=True, overlap_dispatch=False),
    )
    preempted = False
    with runner.session(install_signals=install_signals):
        last_ckpt_step = runner.step
        while runner.step < max_iters:
            res = np.asarray(ens.get_observables()[0])
            done = ens.done_ok_members()
            # a member is finished when converged (done) or dead (NaN
            # residual/field); the pristine +inf residual means "not yet"
            if bool((done | np.isnan(res) | (res < res_tol)).all()):
                break
            before = runner.step
            runner.advance(min(chunk, max_iters - runner.step))
            if runner.step == before:
                break  # no progress (all members frozen inside the chunk)
            if runner.on_boundary() or runner.drain_requested():
                preempted = True
                break  # drain/preempt: checkpoint-then-exit below
            if (
                checkpoint_every_iters
                and runner.step - last_ckpt_step >= checkpoint_every_iters
            ):
                runner.checkpoint_now("cadence_iters")
                last_ckpt_step = runner.step
        final_res = np.asarray(ens.get_observables()[0])
        converged = np.isfinite(final_res) & (final_res < res_tol)
        if converged.any() or preempted:
            # the converged state is the ANSWER (and a preempted descent
            # must resume mid-trajectory): persist it durably
            runner.checkpoint_now("preempt" if preempted else "final")
    nus = []
    for i in range(ens.k):
        try:
            # Nusselt of each member's final iterate (DNS vocabulary)
            member = ens.member_state(i)
            ens.model.state = ens.model.state._replace(
                temp=member.temp, velx=member.velx, vely=member.vely,
                pres=member.pres, pseu=member.pseu,
            )
            ens.model._obs_cache = None
            nus.append(float(ens.model.eval_nu()))
        except Exception:
            nus.append(float("nan"))
    return {
        "converged": [bool(c) for c in converged],
        "residuals": [float(r) for r in final_res],
        "nu": nus,
        "iterations": int(runner.step),
        "preempted": preempted,
        "resumed": bool(runner.resumed),
        "checkpoint": runner.last_checkpoint,
    }
