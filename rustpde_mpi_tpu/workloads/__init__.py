"""Multi-model workload subsystem: campaign drivers over the CampaignModel
contract (models/campaign.py).

* ``registry`` — one table mapping model kinds (``dns`` / ``lnse`` /
  ``adjoint``) to campaign-model builders; the serve scheduler and every
  workload driver build models through it,
* ``eigenmodes`` — lnse eigenmode sweeps (leading growth rates, critical
  Rayleigh number) as governed, checkpointed, vmapped ensembles,
* ``steady`` — adjoint steady-state finds with residual convergence as the
  compiled exit sentinel, kill/resume-safe under ``ResilientRunner``,
* ``modifiers`` — the scenario axis: config-carried step modifiers
  (rotating frame, passive scalar) and the vmapped solid-mask geometry
  sweep,
* ``parity`` — per-model solo-vs-ensemble drift probe (PARITY.json).
"""

from .eigenmodes import (  # noqa: F401
    AC_RIGID,
    RAC_RIGID,
    build_eigenmode_ensemble,
    critical_aspect,
    critical_rayleigh,
    eigenmode_sweep,
    growth_rates,
)
from .modifiers import (  # noqa: F401
    ScenarioConfig,
    geometry_sweep,
    penalization_factors,
)
from .parity import solo_ensemble_parity  # noqa: F401
from .registry import (  # noqa: F401
    build_model,
    build_model_for_key,
    model_kinds,
    register_model_kind,
    validate_campaign_model,
)
from .steady import build_steady_ensemble, steady_state_find  # noqa: F401
