"""Model-kind registry: one table of campaign-model builders.

The multi-model half of the workload subsystem: every physics model that
satisfies the :class:`~rustpde_mpi_tpu.models.campaign.CampaignModelBase`
contract registers a builder under its ``MODEL_KIND``, and everything
downstream — the serve scheduler's campaign construction, the workload
drivers, the parity recorder — builds models through :func:`build_model`
instead of hard-wiring ``Navier2D``.  A request's ``compat_key`` starts
with the kind, so mixed-model traffic buckets correctly by construction.

Built-in kinds:

* ``dns`` — :class:`~rustpde_mpi_tpu.models.navier.Navier2D` (full DNS,
  scenario modifiers allowed),
* ``lnse`` — :class:`~rustpde_mpi_tpu.models.lnse.Navier2DLnse` linearized
  about the analytic conduction base state (eigenmode sweeps),
* ``adjoint`` — :class:`~rustpde_mpi_tpu.models.steady_adjoint.Navier2DAdjoint`
  (steady-state finds by adjoint descent).
"""

from __future__ import annotations

from ..models.campaign import CAMPAIGN_MODEL_ATTRS

_REGISTRY: dict[str, callable] = {}


def register_model_kind(kind: str, builder) -> None:
    """Register ``builder(nx, ny, ra, pr, dt, aspect, bc, periodic, *,
    mesh=None, scenario=None) -> CampaignModel`` under ``kind``."""
    _REGISTRY[str(kind)] = builder


def model_kinds() -> tuple:
    """The registered kinds (sorted, for stable error messages/docs)."""
    return tuple(sorted(_REGISTRY))


def build_model(
    kind: str,
    nx: int,
    ny: int,
    ra: float,
    pr: float,
    dt: float,
    aspect: float,
    bc: str,
    periodic: bool,
    *,
    mesh=None,
    scenario=None,
):
    """Build a campaign model of ``kind`` (raises ``KeyError`` naming the
    registered kinds for an unknown one)."""
    try:
        builder = _REGISTRY[str(kind)]
    except KeyError:
        raise KeyError(
            f"unknown model kind {kind!r}; registered: {list(model_kinds())}"
        ) from None
    return builder(
        nx, ny, ra, pr, dt, aspect, bc, periodic, mesh=mesh, scenario=scenario
    )


def build_model_for_key(key: tuple, *, mesh=None, phase: str = "build"):
    """Build the campaign model one compat-key bucket needs (the serve
    scheduler's campaign constructor): ``key`` is the 10-tuple
    ``(kind, nx, ny, ra, pr, dt, aspect, bc, periodic, scenario_sig)``,
    or the 11-tuple SERVE key with the sub-mesh stamp appended
    (two-level serving) — the stamp selects the mesh upstream and is
    stripped here; the model's own compat key stays the 10-tuple.

    This is THE model-build/jit seam for every bucket, so compile
    attribution hangs here: build wall time and the recompile count are
    recorded per compat key (telemetry/compile_log.py) — the cold-start
    ROADMAP item's baseline numbers.  ``phase`` stamps the attribution row
    ("build" for live campaign opens, "aot" when the warm pool builds
    ahead of traffic)."""
    import time as _time

    from ..telemetry import compile_log

    t0 = _time.perf_counter()
    key = tuple(key)
    if len(key) == 11:
        key = key[:10]
    kind, nx, ny, ra, pr, dt, aspect, bc, periodic, scenario_sig = key
    scenario = dict(scenario_sig) if scenario_sig else None
    if scenario and "passive_scalar" in scenario:
        # the signature packs the kappa into the value slot (0.0 = thermal)
        kappa = scenario.pop("passive_scalar")
        scenario["passive_scalar"] = True
        scenario["scalar_kappa"] = kappa or None
    if scenario and kind == "dns":
        from ..models.navier import scenario_signature

        if scenario_signature(scenario) != tuple(scenario_sig):
            raise ValueError(f"non-canonical scenario signature {scenario_sig}")
    model = build_model(
        kind, nx, ny, ra, pr, dt, aspect, bc, periodic,
        mesh=mesh, scenario=scenario,
    )
    if model.compat_key != tuple(key):
        raise ValueError(
            f"registry builder for {kind!r} produced compat_key "
            f"{model.compat_key} for requested key {tuple(key)}"
        )
    compile_log.observe_build(
        key, _time.perf_counter() - t0, kind=str(kind), phase=phase
    )
    return model


def validate_campaign_model(model) -> list:
    """The protocol check: every attribute/method of the CampaignModel
    contract (models/campaign.CAMPAIGN_MODEL_ATTRS) must be present.
    Returns the list of missing names (empty = conforms)."""
    return [name for name in CAMPAIGN_MODEL_ATTRS if not hasattr(model, name)]


# -- built-in kinds -----------------------------------------------------------


def _build_dns(nx, ny, ra, pr, dt, aspect, bc, periodic, *, mesh=None, scenario=None):
    from ..models.navier import Navier2D

    return Navier2D(
        nx, ny, ra, pr, dt, aspect, bc, periodic=periodic, mesh=mesh,
        scenario=scenario,
    )


def _build_lnse(nx, ny, ra, pr, dt, aspect, bc, periodic, *, mesh=None, scenario=None):
    from ..models.lnse import Navier2DLnse
    from ..models.meanfield import MeanFields

    if scenario:
        raise ValueError("scenario modifiers are a DNS axis (model='dns')")
    # deterministic analytic base state (no mean.h5 file dependency): the
    # conduction profile for rbc, the cos-bottom parabola for hc
    mean = (
        MeanFields.new_hc(nx, ny, periodic)
        if bc == "hc"
        else MeanFields.new_rbc(nx, ny, periodic)
    )
    return Navier2DLnse(
        nx, ny, ra, pr, dt, aspect, bc, periodic=periodic, mean=mean, mesh=mesh
    )


def _build_adjoint(
    nx, ny, ra, pr, dt, aspect, bc, periodic, *, mesh=None, scenario=None
):
    from ..models.steady_adjoint import RES_TOL, Navier2DAdjoint

    res_tol = RES_TOL
    if scenario:
        extra = dict(
            scenario if isinstance(scenario, dict) else dict(scenario)
        )
        # the adjoint's variant slot carries its convergence tolerance
        # (compiled into the chunk's exit sentinel, hence part of the key)
        res_tol = float(extra.pop("res_tol", res_tol))
        if extra:
            raise ValueError(
                f"unsupported adjoint variant fields: {sorted(extra)}"
            )
    return Navier2DAdjoint(
        nx, ny, ra, pr, dt, aspect, bc, periodic=periodic, mesh=mesh,
        res_tol=res_tol,
    )


register_model_kind("dns", _build_dns)
register_model_kind("lnse", _build_lnse)
register_model_kind("adjoint", _build_adjoint)
