"""Scenario step modifiers + the vmapped solid-mask geometry sweep.

The scenario axis of the DNS: config-carried terms compiled into the
:class:`~rustpde_mpi_tpu.models.navier.Navier2D` step (so they are operator
constants and sign into ``compat_key`` — see
:func:`~rustpde_mpi_tpu.models.navier.scenario_signature`):

* **rotating frame** — the f-plane Coriolis force ``(+f v, -f u)`` added
  explicitly to the momentum equations.  Analytic validation: in exactly
  incompressible 2-D flow this force is irrotational (its curl is
  ``-f div(u) = 0``) and therefore absorbed ENTIRELY by the pressure — the
  velocity/temperature trajectory matches the non-rotating run while the
  pressure carries the geostrophic correction (tests/test_workloads.py).
* **passive scalar** — an advected-diffused scalar leaf riding the
  temperature's composite space and BC lift, at its own diffusivity
  (``scalar_kappa``; defaults to the thermal one).  Exact validation: at
  matched diffusivity a scalar released equal to the temperature stays
  identically equal for all time (one-way coupling; the scalar sees the
  same advection-diffusion operator + boundary forcing).

The **geometry sweep** extends the batching axis to solid obstacles: the
Brinkman penalization is an elementwise post-step map on
``(temp, velx, vely)`` (the step applies it after the projection, and the
pressure update never reads the penalized fields), so
``step_solid = penalize ∘ step_plain`` EXACTLY — which means one compiled
plain step serves every geometry, with the per-member penalization factors
vmapped as runtime inputs instead of baked constants.  K obstacle
geometries advance as one donated vmapped scan, and each member is
bit-identical to a solo ``set_solid`` run of the same mask.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class ScenarioConfig:
    """Config-carried scenario step modifiers for ``Navier2D`` (pass as the
    model's ``scenario=`` ctor arg, ``NavierConfig.scenario``, or carry the
    equivalent dict on a :class:`~rustpde_mpi_tpu.serve.SimRequest`).

    * ``coriolis`` — rotating-frame f-plane rate ``f`` (0 = off); adds
      ``(+f v, -f u)`` to the momentum equations,
    * ``passive_scalar`` — add the advected scalar state leaf,
    * ``scalar_kappa`` — scalar diffusivity (None: the thermal diffusivity,
      the matched configuration whose scalar mirrors the temperature)."""

    coriolis: float = 0.0
    passive_scalar: bool = False
    scalar_kappa: float | None = None

    @property
    def signature(self) -> tuple:
        """The canonical compat-key signature (models/navier.py)."""
        from ..models.navier import scenario_signature

        return scenario_signature(self)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def penalization_factors(model, mask, value=None, eta: float | None = None):
    """The pointwise implicit-Brinkman factors ``(fac, temp_add)`` for one
    obstacle — literally :func:`~rustpde_mpi_tpu.models.navier.brinkman_factors`,
    the SAME implementation ``Navier2D.set_solid`` bakes into its step (the
    sweep's bit-match-solo guarantee rests on never forking it)."""
    from ..models.navier import brinkman_factors

    return brinkman_factors(model, mask, value, eta)


def geometry_sweep(model, geometries, steps: int, states=None):
    """Advance K obstacle geometries as ONE vmapped donated scan.

    ``model`` — a plain (no ``set_solid``) :class:`Navier2D` whose hoisted
    step jaxpr is shared by every member; ``geometries`` — a list of
    ``(mask, value)`` pairs (models/solid_masks.py builders) or ``mask``
    arrays; ``states`` — optional per-member initial states (default: K
    copies of ``model.state``).

    Returns ``(stacked_state, observables)`` where ``observables`` is the
    model's ``(K,)``-shaped observable tuple of the final states.  Each
    member equals a solo ``set_solid(mask, value)`` run EXACTLY (the
    penalize-after-step factoring is an identity, not an approximation —
    asserted in tests/test_workloads.py)."""
    import jax
    import jax.numpy as jnp

    if getattr(model, "_solid", None) is not None:
        raise ValueError(
            "geometry_sweep needs a plain template model; the sweep itself "
            "supplies the per-member penalization (set_solid(None) first)"
        )
    pairs = []
    for geom in geometries:
        mask, value = geom if isinstance(geom, tuple) else (geom, None)
        pairs.append(penalization_factors(model, mask, value))
    if not pairs:
        raise ValueError("geometry_sweep needs at least one geometry")
    facs = jnp.stack([p[0] for p in pairs])
    adds = jnp.stack([p[1] for p in pairs])
    k = len(pairs)
    if states is None:
        members = [model.state] * k
    else:
        members = list(states)
        if len(members) != k:
            raise ValueError(f"{len(members)} states for {k} geometries")
    with model._scope():
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *members)

    step_cc = model._step_cc
    consts = model._step_consts
    sp_u, sp_v, sp_t = model.velx_space, model.vely_space, model.temp_space

    def member_step(state, fac, add):
        new = step_cc(consts, state)
        # the exact set_solid composition: penalize (temp, velx, vely) of
        # the stepped state; pres/pseu are untouched by the penalization
        return new._replace(
            velx=sp_u.forward(sp_u.backward(new.velx) * fac),
            vely=sp_v.forward(sp_v.backward(new.vely) * fac),
            temp=sp_t.forward(sp_t.backward(new.temp) * fac + add),
        )

    vstep = jax.vmap(member_step, in_axes=(0, 0, 0))

    def sweep(stacked, facs, adds, n: int):
        def body(carry, _):
            return vstep(carry, facs, adds), None

        return jax.lax.scan(body, stacked, None, length=int(n))[0]

    sweep_jit = jax.jit(sweep, static_argnames=("n",), donate_argnums=(0,))
    with model._scope():
        final = sweep_jit(stacked, facs, adds, n=int(steps))
        obs = jax.jit(jax.vmap(model._obs_cc, in_axes=(None, 0)))(
            model._obs_consts, final
        )
    return final, tuple(np.asarray(v) for v in obs)
