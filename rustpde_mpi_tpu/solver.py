"""Composite Helmholtz / Poisson solvers over Galerkin spectral spaces.

TPU rebuild of the reference solver layer (/root/reference/src/solver/):

* :class:`HholtzAdi` — ``(I - c*D2) u = f`` by alternating-direction-implicit
  1-D solves per axis (same O(dt*c) splitting as the reference,
  /root/reference/src/solver/hholtz_adi.rs:12-16).
* :class:`TensorSolver` — the `FdmaTensor` analog: eigen-diagonalize axis 0,
  leaving a banded family along axis 1
  (/root/reference/src/solver/fdma_tensor.rs:36-71 documents the math).
  Two deliberate departures from the reference: (a) the per-eigenvalue banded
  factorizations are computed ONCE at build time (host numpy) instead of per
  solve call; (b) axis 0 is diagonalized through the *weak-form* (Galerkin)
  pencil ``(S^T W D2 S, S^T W S)`` whose spectrum is exactly real for all
  composite Chebyshev bases — the reference diagonalizes the quasi-inverse-
  preconditioned pencil and silently drops imaginary parts
  (/root/reference/src/solver/utils.rs:84-86), which is ill-defined for the
  Neumann (pressure) operator where that pencil has genuinely complex pairs.
* :class:`Poisson` / :class:`Hholtz` — pressure Poisson (alpha=0, singular
  mode regularized) and exact Helmholtz (alpha=1).

All device work is GEMMs (MXU) + one batched banded substitution scan.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import config
from .bases import Base, BaseKind, Space2  # noqa: F401
from .ops.banded import BandedSolver, DenseSolver, DiagSolver
from .ops.transforms import apply_diag, apply_matrix

_P, _Q = 2, 4  # lower/upper bandwidth of every preconditioned Chebyshev operator


def ingredients_for_hholtz(space: Space2, axis: int):
    """(mat_a, mat_b, precond) per axis — the contract of
    /root/reference/src/field.rs:195-216:

    Chebyshev axes: precondition with the restricted quasi-inverse so the
    Helmholtz operator ``mat_a - c*mat_b`` becomes banded; Fourier axes are
    already diagonal."""
    base = space.bases[axis]
    if base.kind.is_chebyshev:
        peye = base.laplace_inv_eye()
        pinv = peye @ base.laplace_inv()
        S = base.mass()
        if base.kind == BaseKind.CHEBYSHEV:
            S = S[:, 2:]
        return pinv @ S, peye @ S, pinv
    mass = np.eye(base.m)
    lap = base.laplace()
    return mass, lap, None


def ingredients_for_poisson(space: Space2, axis: int):
    mat_a, mat_b, precond = ingredients_for_hholtz(space, axis)
    is_diag = space.bases[axis].kind.is_periodic
    return mat_a, mat_b, precond, is_diag


def _sorted_real_eig(x: np.ndarray):
    """Eigendecomposition with eigenvalues sorted descending by real part
    (matching the reference's utils::eig ordering so the singular mode lands
    at index 0, /root/reference/src/solver/utils.rs:88-95)."""
    lam, q = np.linalg.eig(x)
    if np.abs(lam.imag).max() > 1e-8 * max(np.abs(lam.real).max(), 1.0):
        raise ValueError("tensor-solver eigenvalues are significantly complex")
    order = np.argsort(lam.real)[::-1]
    lam = lam.real[order]
    q = q.real[:, order] if np.iscomplexobj(q) else q[:, order]
    return lam, q


def weak_form_matrices(base: Base):
    """Galerkin weak-form pair (G_A, G_B) = (S^T W D2 S, S^T W S) and the
    ortho->weak projection S^T W for one Chebyshev base."""
    from .ops import chebyshev as chb

    S = base.stencil
    if base.kind == BaseKind.CHEBYSHEV:
        S = S[:, 2:]
    W = np.diag(chb.cheb_weights(base.n))
    D2 = chb.diff_matrix(base.n, 2)
    return S.T @ W @ D2 @ S, S.T @ W @ S, S.T @ W


class _AxisSolver:
    """1-D solver for one axis: banded (Chebyshev) or diagonal (Fourier)."""

    def __init__(self, mat: np.ndarray, kind: BaseKind, method: str):
        if kind.is_periodic:
            self.solver = DiagSolver(np.diag(mat))
        elif method == "dense":
            self.solver = DenseSolver(mat)
        else:
            self.solver = BandedSolver(mat, _P, _Q)

    def solve(self, b, axis: int):
        return self.solver.solve(b, axis)


def default_method() -> str:
    """Execution path for the 1-D axis solves: sequential banded substitution
    is exact O(n) and fast on CPU, but its lax.scan serializes on TPU (one
    tiny dispatch per mode); the precomputed dense-inverse GEMM keeps the MXU
    busy instead."""
    return "dense" if config.is_tpu_like() else "banded"


class HholtzAdi:
    """ADI Helmholtz: ``(I - c*D2) vhat = A f`` solved axis-by-axis.

    ``method``: "banded" (scan substitution, exact O(n)) or "dense"
    (precomputed inverse GEMMs; fastest on TPU).  Default auto-selects.
    """

    def __init__(self, space: Space2, c, method: str | None = None):
        method = method or default_method()
        self.space = space
        self.matvec = []
        self.solvers = []
        for axis, ci in enumerate(c):
            mat_a, mat_b, precond = ingredients_for_hholtz(space, axis)
            mat = mat_a - ci * mat_b
            kind = space.base_kind(axis)
            self.solvers.append(_AxisSolver(mat, kind, method))
            self.matvec.append(
                jnp.asarray(precond, dtype=config.real_dtype()) if precond is not None else None
            )

    def solve(self, rhs):
        """rhs in ortho space -> solution in composite space.

        Under a parallel mesh the axis solves run on the pencil whose solve
        axis is local (the reference's HholtzAdiMpi transpose pattern,
        /root/reference/src/solver_mpi/hholtz_adi.rs:105-145); the pencil
        flips are sharding constraints, XLA inserts the all-to-alls."""
        from .parallel.mesh import PHYS, SPEC, constrain

        out = constrain(rhs, SPEC)
        if self.matvec[0] is not None:
            out = apply_matrix(self.matvec[0], out, 0)
        out = constrain(out, PHYS)
        if self.matvec[1] is not None:
            out = apply_matrix(self.matvec[1], out, 1)
        out = self.solvers[1].solve(out, 1)  # axis-1 recurrence, lanes = axis 0
        out = constrain(out, SPEC)
        out = self.solvers[0].solve(out, 0)  # axis-0 recurrence, lanes = axis 1
        return constrain(out, SPEC)


class TensorSolver:
    """2-D tensor-product solver: ``[(A_x x C_y) + (C_x x A_y) + alpha (C_x x
    C_y)] u = f``; axis 0 diagonalized (weak-form pencil eig, or
    already-diagonal Fourier), axis 1 a batch of banded systems factored at
    build time.

    ``fwd`` maps the axis-0 *ortho-space* rhs into eigenspace (it folds the
    Galerkin projection in), so no separate axis-0 preconditioner matvec is
    applied when ``fwd`` is present."""

    def __init__(self, a, c, is_diag, alpha: float, weak0=None, fix_singular=False):
        dt = config.real_dtype()
        if is_diag[0]:
            lam = np.diag(a[0]).copy()
            self.fwd = self.bwd = None
        else:
            g_a, g_b, proj = weak0
            lam, q = _sorted_real_eig(np.linalg.solve(g_b, g_a))
            self.fwd = jnp.asarray(
                np.linalg.solve(q, np.linalg.solve(g_b, proj)), dtype=dt
            )
            self.bwd = jnp.asarray(q, dtype=dt)
        if fix_singular and abs(lam[0]) < 1e-10:
            # pure-Neumann problems: nudge the zero mode so the banded
            # factorization exists (/root/reference/src/solver/poisson.rs:84-87)
            lam = lam - 1e-10
        self.lam = lam
        self.alpha = alpha
        self._a1, self._c1 = a[1], c[1]
        # (A_y + (lam_i + alpha) C_y) factored for every eigenvalue lane i
        self._refactor()

    def _refactor(self):
        mats = (
            self._a1[None, :, :]
            + (self.lam[:, None, None] + self.alpha) * self._c1[None, :, :]
        )
        self.banded = BandedSolver(mats, _P, _Q)

    def update_lam(self, lam):
        """Re-factor after an eigenvalue shift (singularity regularization)."""
        self.lam = lam
        self._refactor()

    def solve(self, rhs):
        """Under a parallel mesh: GEMMs run on the x-pencil (axis 0 local),
        the per-eigenvalue banded solves on the y-pencil where the eigenvalue
        lanes (axis 0) are sharded — the reference's PoissonMpi lam-slicing
        (/root/reference/src/solver_mpi/poisson.rs:139-187)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        out = constrain(rhs, SPEC)
        if self.fwd is not None:
            out = apply_matrix(self.fwd, out, 0)
        out = self.banded.solve(constrain(out, PHYS), 1)
        out = constrain(out, SPEC)
        if self.bwd is not None:
            out = apply_matrix(self.bwd, out, 0)
        return constrain(out, SPEC)


class FastDiag:
    """Fast-diagonalisation 2-D solver: ``[c0 D2_x + c1 D2_y] u (+ alpha u) =
    f`` with BOTH axes eigendecomposed through their weak-form (Galerkin)
    pencils, so the device solve is 4 GEMMs + 1 elementwise divide — pure MXU
    work, no sequential recurrence.  This is the TPU-native answer to the
    reference's FdmaTensor (eig axis 0 + per-eigenvalue banded sweeps along
    axis 1, /root/reference/src/solver/fdma_tensor.rs:36-71): same discrete
    solution, but the O(n) Thomas recurrence the reference parallelises with
    rayon lanes would serialise a TPU, while matmuls saturate it.

    Fourier axes are already modal (diagonal), so their fwd/bwd maps are
    identity and their eigenvalues are -k^2.
    """

    def __init__(self, space: Space2, c, alpha: float, negate_lap: bool, fix_singular=False):
        dt = config.real_dtype()
        sign = -1.0 if negate_lap else 1.0
        self.fwd, self.bwd, lams = [], [], []
        for axis, ci in enumerate(c):
            base = space.bases[axis]
            if base.kind.is_periodic:
                lam = sign * ci * (-(base.wavenumbers**2))
                self.fwd.append(None)
                self.bwd.append(None)
            else:
                g_a, g_b, proj = weak_form_matrices(base)
                lam, q = _sorted_real_eig(np.linalg.solve(g_b, g_a))
                self.fwd.append(
                    jnp.asarray(np.linalg.solve(q, np.linalg.solve(g_b, proj)), dtype=dt)
                )
                self.bwd.append(jnp.asarray(q, dtype=dt))
                lam = sign * ci * lam
            lams.append(lam)
        if fix_singular and abs(lams[0][0]) < 1e-10:
            # pure-Neumann zero mode: same nudge as the reference
            # (/root/reference/src/solver/poisson.rs:84-87)
            lams[0] = lams[0].copy()
            lams[0][0] -= 1e-10
        denom = lams[0][:, None] + lams[1][None, :] + alpha
        self.denom = jnp.asarray(denom, dtype=dt)

    def solve(self, rhs):
        """rhs in ortho space -> solution in composite space.  Pencil flips
        sit between the axis-0 and axis-1 contractions."""
        from .parallel.mesh import PHYS, SPEC, constrain

        out = constrain(rhs, SPEC)
        if self.fwd[0] is not None:
            out = apply_matrix(self.fwd[0], out, 0)
        out = constrain(out, PHYS)
        if self.fwd[1] is not None:
            out = apply_matrix(self.fwd[1], out, 1)
        out = out / self.denom.astype(out.dtype)
        if self.bwd[1] is not None:
            out = apply_matrix(self.bwd[1], out, 1)
        out = constrain(out, SPEC)
        if self.bwd[0] is not None:
            out = apply_matrix(self.bwd[0], out, 0)
        return constrain(out, SPEC)


class _TensorBased:
    """Shared assembly for Poisson/Hholtz: fast-diagonalisation on TPU,
    eig-axis0 + banded-axis1 tensor solver elsewhere (both solve the same
    discrete system)."""

    def __init__(
        self,
        space: Space2,
        c,
        alpha: float,
        negate_lap: bool,
        fix_singular=False,
        method: str | None = None,
    ):
        method = method or ("fd" if config.is_tpu_like() else "banded")
        if method == "fd":
            self._fd = FastDiag(space, c, alpha, negate_lap, fix_singular)
            return
        self._fd = None
        self.space = space
        sign = -1.0 if negate_lap else 1.0
        laps, masses, is_diags, self.matvec = [], [], [], []
        weak0 = None
        for axis, ci in enumerate(c):
            mat_a, mat_b, precond, is_diag = ingredients_for_poisson(space, axis)
            laps.append(sign * ci * mat_b)
            masses.append(mat_a)
            is_diags.append(is_diag)
            # axis 0 rhs projection is folded into the tensor fwd matrix for
            # Chebyshev axes; only axis 1 keeps an explicit precond matvec
            if axis == 1 and precond is not None:
                self.matvec.append(jnp.asarray(precond, dtype=config.real_dtype()))
            else:
                self.matvec.append(None)
        if not is_diags[0]:
            g_a, g_b, proj = weak_form_matrices(space.bases[0])
            weak0 = (sign * c[0] * g_a, g_b, proj)
        self.tensor = TensorSolver(
            laps, masses, is_diags, alpha, weak0=weak0, fix_singular=fix_singular
        )

    def solve(self, rhs):
        if self._fd is not None:
            return self._fd.solve(rhs)
        from .parallel.mesh import PHYS, constrain

        out = rhs
        if self.matvec[1] is not None:
            out = apply_matrix(self.matvec[1], constrain(out, PHYS), 1)
        return self.tensor.solve(out)


class Poisson(_TensorBased):
    """Pressure Poisson ``c * D2 u = A f`` with singular-mode regularization
    (lam -= 1e-10, /root/reference/src/solver/poisson.rs:84-87)."""

    def __init__(self, space: Space2, c, **kw):
        super().__init__(space, c, alpha=0.0, negate_lap=False, fix_singular=True, **kw)


class Hholtz(_TensorBased):
    """Exact (non-ADI) Helmholtz ``(I - c*D2) u = A f`` via the tensor solver
    with alpha=1 (/root/reference/src/solver/hholtz.rs:63-100)."""

    def __init__(self, space: Space2, c, **kw):
        super().__init__(space, c, alpha=1.0, negate_lap=True, **kw)
