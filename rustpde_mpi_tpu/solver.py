"""Composite Helmholtz / Poisson solvers over Galerkin spectral spaces.

TPU rebuild of the reference solver layer (/root/reference/src/solver/):

* :class:`HholtzAdi` — ``(I - c*D2) u = f`` by alternating-direction-implicit
  1-D solves per axis (same O(dt*c) splitting as the reference,
  /root/reference/src/solver/hholtz_adi.rs:12-16).
* :class:`TensorSolver` — the `FdmaTensor` analog: eigen-diagonalize axis 0
  through the B2-preconditioned pencil ``(pinv S)^-1 (peye S)``, leaving a
  banded family along axis 1
  (/root/reference/src/solver/fdma_tensor.rs:36-71 documents the math).
  One deliberate departure from the reference: the per-eigenvalue banded
  factorizations are computed ONCE at build time (host numpy) instead of per
  solve call (poisson.rs:226-228 re-sweeps every step).
* :class:`FastDiag` — both axes eigen-diagonalized through the same pencils;
  solves the *identical* discrete system as :class:`TensorSolver` (tested),
  but as pure GEMMs + one elementwise divide — the MXU-native path.
* :class:`Poisson` / :class:`Hholtz` — pressure Poisson (alpha=0, singular
  mode regularized) and exact Helmholtz (alpha=1).

The discretization is reference-exact: the truncated quasi-inverse
(ops/chebyshev.quasi_inverse_b2) reproduces the reference's embedded pypde
golden solutions (tests/test_golden.py) and makes the pencil spectrum exactly
real for every composite base — the imaginary parts the reference's
utils::eig silently drops (/root/reference/src/solver/utils.rs:84-86) are
structurally zero under this convention.

All device work is GEMMs (MXU) + one batched banded substitution scan.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from . import config
from .bases import Base, BaseKind, Space2  # noqa: F401
from .ops.banded import BandedSolver, DenseSolver, DiagSolver
from .ops.folded import FoldedMatrix

_P, _Q = 2, 4  # lower/upper bandwidth of every preconditioned Chebyshev operator


def ingredients_for_hholtz(space: Space2, axis: int):
    """(mat_a, mat_b, precond) per axis — the contract of
    /root/reference/src/field.rs:195-216:

    Chebyshev axes: precondition with the restricted quasi-inverse so the
    Helmholtz operator ``mat_a - c*mat_b`` becomes banded; Fourier axes are
    already diagonal."""
    base = space.bases[axis]
    if base.kind.is_chebyshev:
        peye = base.laplace_inv_eye()
        pinv = peye @ base.laplace_inv()
        S = base.mass()
        if base.kind == BaseKind.CHEBYSHEV:
            S = S[:, 2:]
        return pinv @ S, peye @ S, pinv
    mass = np.eye(base.m)
    lap = base.laplace()
    return mass, lap, None


def hholtz_axis_solve_matrix(space: Space2, axis: int, ci: float) -> np.ndarray:
    """Dense equivalent of ONE :class:`HholtzAdi` axis factor, in natural
    (split-form for periodic) order: ``A = (mat_a - ci*mat_b)^-1 @ precond``
    — the full 2-D ADI solve is ``A0 @ rhs @ A1^T``.  This is the public
    modal contract the fused step kernels (ops/pallas_step.py) build their
    stage matrices from: the banded recurrence, the precomputed dense
    inverse, and this explicit inverse factor all solve the identical 1-D
    system (machine-precision agreement in f64).

    Periodic axes return the diagonal ``1/(1 + ci*k^2)`` in the split Re/Im
    convention over ``2*(n//2+1)`` rows (each eigenvalue twice — complex r2c
    bases get the duplication here, split bases already carry it), matching
    ``Base.axis_operator``'s split-matrix form."""
    base = space.bases[axis]
    mat_a, mat_b, precond = ingredients_for_hholtz(space, axis)
    mat = mat_a - ci * mat_b
    if base.kind.is_periodic:
        d = 1.0 / np.diag(mat)
        if not base.kind.is_split:
            d = np.concatenate([d, d])
        return np.diag(d)
    return np.linalg.solve(mat, precond)


def modal_data_split(space: Space2, axis: int, ci: float, sign: float = 1.0):
    """Public :func:`_axis_modal_data` in the split-real convention of the
    fused step kernels: ``(lam, fwd, bwd)`` with periodic-axis eigenvalues
    duplicated over the Re/Im blocks (complex r2c bases carry each
    eigenvalue once; split bases already twice).  ``fwd``/``bwd`` are None
    for periodic axes (already modal); eigenvalues come back in natural
    order — sep-storage callers apply ``parity_perm`` themselves, exactly
    like :class:`FastDiag`."""
    lam, fwd, bwd = _axis_modal_data(space, axis, ci, sign)
    base = space.bases[axis]
    if base.kind.is_periodic and not base.kind.is_split:
        lam = np.concatenate([lam, lam])
    return lam, fwd, bwd


def _checker_shift(m: np.ndarray) -> int | None:
    """Shift s in {0, 1} such that ``m[i, j] == 0`` (exactly) whenever
    ``(i + j + s)`` is odd; None if neither holds.  The pure-Chebyshev solver
    ingredients are products of even-offset banded matrices, so their
    checkerboard zeros are *exact* floating-point zeros — no tolerance."""
    r, c = m.shape
    i = np.arange(r)[:, None]
    j = np.arange(c)[None, :]
    for s in (0, 1):
        if not np.any(m[(i + j + s) % 2 == 1]):
            return s
    return None


def _real_eig_desc(x: np.ndarray):
    """Real eigendecomposition sorted by descending eigenvalue."""
    lam, q = np.linalg.eig(x)
    if np.abs(lam.imag).max(initial=0.0) > 1e-8 * max(np.abs(lam.real).max(), 1.0):
        raise ValueError("tensor-solver eigenvalues are significantly complex")
    lam = lam.real
    q = q.real if np.iscomplexobj(q) else q
    order = np.argsort(lam)[::-1]
    return lam[order], q[:, order]


# bump when the modal decomposition code or the stored (lam, fwd, q)
# semantics change — the disk-cache key hashes only the ingredient matrices
_MODAL_CACHE_VERSION = "v1"


def _axis_modal_data(space: Space2, axis: int, ci: float, sign: float):
    """Modal diagonalization of one axis of the preconditioned operator.

    Returns ``(lam, fwd, bwd)``: ``lam`` scaled by ``sign * ci``; ``fwd``
    maps the axis's *ortho-space* rhs into eigenspace (it folds the B2
    preconditioner in: ``Q^-1 C^-1 pinv``), ``bwd = Q`` maps the eigenspace
    solution back to composite coefficients.  Fourier axes are already modal:
    ``lam = sign*ci*(-k^2)``, no maps.  This is the pencil the reference's
    FdmaTensor diagonalizes (/root/reference/src/solver/fdma_tensor.rs:106-154);
    under the truncated quasi-inverse its spectrum is exactly real."""
    base = space.bases[axis]
    if base.kind.is_periodic:
        return sign * ci * (-(base.wavenumbers**2)), None, None
    mat_c, mat_a, precond = ingredients_for_hholtz(space, axis)
    # host-eig disk cache (SURVEY S7 "cache to disk for big N"): the
    # nonsymmetric parity-block eigendecompositions dominate build time at
    # the flagship sizes (~tens of seconds at 2049); exact f64 npz
    # round-trips, keyed by the INGREDIENT CONTENT (cheap O(n^2) hash of the
    # matrices actually decomposed) plus ci/sign.  The content hash does NOT
    # see this function's code: the _MODAL_CACHE_VERSION salt below must be
    # bumped whenever the decomposition algorithm or the stored (lam, fwd,
    # q) semantics change (ADVICE r4).  Gated to n >= 512: below that the
    # eig costs less than the IO.
    cache_path = None
    if base.n >= 512:
        import hashlib

        h = hashlib.blake2b(digest_size=12)
        for m in (mat_c, mat_a, precond):
            h.update(np.ascontiguousarray(m).tobytes())
        cache_path = os.path.join(
            config.host_cache_dir(),
            f"modal_{_MODAL_CACHE_VERSION}_{base.kind.value}_{base.n}_"
            f"{float(ci):.17g}_{sign:g}_{h.hexdigest()}.npz",
        )
        try:
            with np.load(cache_path) as z:
                return z["lam"], z["fwd"], z["q"]
        except Exception:  # missing/corrupt/format-drift: recompute
            pass
    if (
        _checker_shift(mat_c) == 0
        and _checker_shift(mat_a) == 0
        and _checker_shift(precond) == 0
    ):
        # Parity-blocked eigendecomposition: the pencil preserves parity, so
        # solve the even and odd subproblems independently and assemble with
        # eigen indices interleaved (evens at even positions).  The modal
        # maps are then checkerboard with *exact* zeros — a full-matrix eig
        # leaves O(1e-7)-relative off-parity noise at n >= 1025, which
        # silently defeated fold detection (and the noise is itself error:
        # the true eigenvectors have definite parity).
        m = mat_c.shape[0]
        n_cols = precond.shape[1]
        lam = np.empty(m)
        q = np.zeros((m, m))
        fwd = np.zeros((m, n_cols))
        for par in (0, 1):
            sl = slice(par, None, 2)
            c_b = mat_c[sl, sl]
            lam_b, q_b = _real_eig_desc(np.linalg.solve(c_b, mat_a[sl, sl]))
            fwd_b = np.linalg.solve(q_b, np.linalg.solve(c_b, precond[sl, sl]))
            lam[sl] = lam_b
            q[sl, sl] = q_b
            fwd[sl, sl] = fwd_b
        return _modal_cache_store(cache_path, sign * ci * lam, fwd, q)
    # non-parity-preserving pencils (mixed Dirichlet-Neumann base): plain
    # descending eigen order, as in the reference (solver/utils.rs:88-95)
    lam, q = _real_eig_desc(np.linalg.solve(mat_c, mat_a))
    fwd = np.linalg.solve(q, np.linalg.solve(mat_c, precond))
    return _modal_cache_store(cache_path, sign * ci * lam, fwd, q)


def _modal_cache_store(path, lam, fwd, q):
    if path is not None:
        config.host_cache_store(path, lambda tmp: np.savez(tmp, lam=lam, fwd=fwd, q=q))
    return lam, fwd, q


class _AxisSolver:
    """1-D solver for one axis: banded/dense/pallas (Chebyshev) or diagonal
    (Fourier).  ``sep``: the axis uses the parity-separated spectral layout —
    the dense inverse handles it natively (block GEMMs, ops/folded.py); the
    sequential banded/Pallas recurrences are wrapped with explicit
    permutations (ops/banded.SepWrapped, the CPU correctness fallback)."""

    def __init__(self, mat: np.ndarray, kind: BaseKind, method: str, sep: bool = False):
        from .ops.banded import SepWrapped

        if kind.is_periodic:
            assert not sep, "sep layout is not defined for Fourier axes"
            self.solver = DiagSolver(np.diag(mat))
        elif method == "dense":
            self.solver = DenseSolver(mat, sep=sep)
        elif method == "pallas":
            from .ops.pallas_banded import PallasBandedSolver

            self.solver = PallasBandedSolver(mat, _P, _Q)
            if sep:
                self.solver = SepWrapped(self.solver, mat.shape[-1])
        else:
            self.solver = BandedSolver(mat, _P, _Q)
            if sep:
                self.solver = SepWrapped(self.solver, mat.shape[-1])

    def solve(self, b, axis: int):
        return self.solver.solve(b, axis)


def default_method() -> str:
    """Execution path for the 1-D axis solves.  Measured on v5e at the
    1025^2 shapes (ops/pallas_banded.bench_banded_paths, BASELINE.md): the
    precomputed dense-inverse GEMM (~1.10 ms/solve fused) beats both the
    Pallas VMEM recurrence (~1.38 ms) and by 3 orders of magnitude the
    lax.scan substitution — the MXU wins despite O(n/(p+q)) more flops.  The
    same holds in emulated f64 (129^2 ADI: dense 1.6 ms vs scan 2.5 ms;
    Pallas has no Mosaic f64 support).  On CPU the O(n) banded scan wins.
    Override per-solver with ``method="banded"|"dense"|"pallas"``."""
    return "dense" if config.is_tpu_like() else "banded"


class HholtzAdi:
    """ADI Helmholtz: ``(I - c*D2) vhat = A f`` solved axis-by-axis.

    ``method``: "banded" (scan substitution, exact O(n)) or "dense"
    (precomputed inverse GEMMs; fastest on TPU).  Default auto-selects.
    """

    def __init__(self, space: Space2, c, method: str | None = None):
        method = method or default_method()
        self.space = space
        sep = getattr(space, "sep", (False, False))
        self.matvec = []
        self.solvers = []
        for axis, ci in enumerate(c):
            mat_a, mat_b, precond = ingredients_for_hholtz(space, axis)
            mat = mat_a - ci * mat_b
            kind = space.base_kind(axis)
            self.solvers.append(_AxisSolver(mat, kind, method, sep=sep[axis]))
            # the B2 precond is checkerboard parity-foldable like every
            # pure-Chebyshev operator (ops/folded.py) -> two half GEMMs
            self.matvec.append(
                FoldedMatrix(
                    precond,
                    lambda m: jnp.asarray(m, dtype=config.real_dtype()),
                    sep_in=sep[axis],
                    sep_out=sep[axis],
                )
                if precond is not None
                else None
            )

    def solve(self, rhs):
        """rhs in ortho space -> solution in composite space.  Extra leading
        dims are batch (identical-operator fields solved in one dispatch).

        Under a parallel mesh the axis solves run on the pencil whose solve
        axis is local (the reference's HholtzAdiMpi transpose pattern,
        /root/reference/src/solver_mpi/hholtz_adi.rs:105-145); the pencil
        flips are sharding constraints, XLA inserts the all-to-alls."""
        from .parallel.mesh import PHYS, SPEC, constrain

        if rhs.ndim < 2:
            raise ValueError(
                f"2-D tensor solver needs rhs.ndim >= 2, got {rhs.ndim} "
                "(a rank-1 rhs would silently solve both axes over the same "
                "axis; batch dims go in front)"
            )
        ax = rhs.ndim - 2
        out = constrain(rhs, SPEC)
        if self.matvec[0] is not None:
            out = self.matvec[0].apply(out, ax)
        out = constrain(out, PHYS)
        if self.matvec[1] is not None:
            out = self.matvec[1].apply(out, ax + 1)
        out = self.solvers[1].solve(out, ax + 1)  # axis-1 recurrence
        out = constrain(out, SPEC)
        out = self.solvers[0].solve(out, ax)  # axis-0 recurrence
        return constrain(out, SPEC)


class TensorSolver:
    """2-D tensor-product solver: ``[(A_x x C_y) + (C_x x A_y) + alpha (C_x x
    C_y)] u = B2 f``; axis 0 diagonalized through the preconditioned pencil
    (or already-diagonal Fourier), axis 1 a batch of banded systems factored
    at build time (the reference re-sweeps per solve,
    /root/reference/src/solver/poisson.rs:226-228).

    ``modal0 = (lam0, fwd0, bwd0)`` from :func:`_axis_modal_data` — ``fwd0``
    maps the axis-0 *ortho-space* rhs into eigenspace (preconditioner folded
    in), so no separate axis-0 matvec is applied."""

    def __init__(
        self, modal0, a1, c1, precond1, alpha: float, fix_singular=False,
        sep=(False, False),
    ):
        from .ops.banded import SepWrapped
        from .ops.folded import parity_perm

        dt = config.real_dtype()
        lam, fwd0, bwd0 = modal0
        s0 = sep[0] and fwd0 is not None  # Fourier axes are never sep
        to_dev = lambda m: jnp.asarray(m, dtype=dt)  # noqa: E731
        self.fwd = (
            FoldedMatrix(fwd0, to_dev, sep_in=s0, sep_out=s0)
            if fwd0 is not None
            else None
        )
        self.bwd = (
            FoldedMatrix(bwd0, to_dev, sep_in=s0, sep_out=s0)
            if bwd0 is not None
            else None
        )
        if fix_singular and abs(lam[0]) < 1e-10:
            # pure-Neumann problems: nudge the zero mode so the banded
            # factorization exists (/root/reference/src/solver/poisson.rs:84-87)
            lam = lam.copy()
            lam -= 1e-10
        if s0:
            # eigenvalue lanes live on the sep-ordered axis 0
            lam = lam[parity_perm(len(lam))]
        self.lam = lam
        self.alpha = alpha
        self.matvec1 = (
            FoldedMatrix(precond1, to_dev, sep_in=sep[1], sep_out=sep[1])
            if precond1 is not None
            else None
        )
        # (A_y + (lam_i + alpha) C_y) factored for every eigenvalue lane i
        mats = a1[None, :, :] + (lam[:, None, None] + alpha) * c1[None, :, :]
        self.banded = BandedSolver(mats, _P, _Q)
        if sep[1]:
            # the banded recurrence runs in natural axis-1 order
            self.banded = SepWrapped(self.banded, a1.shape[-1])

    def solve(self, rhs):
        """Under a parallel mesh: GEMMs run on the x-pencil (axis 0 local),
        the per-eigenvalue banded solves on the y-pencil where the eigenvalue
        lanes (axis 0) are sharded — the reference's PoissonMpi lam-slicing
        (/root/reference/src/solver_mpi/poisson.rs:139-187).  Extra leading
        dims are batch (the per-eigenvalue factors broadcast against them)."""
        from .parallel.mesh import PHYS, SPEC, constrain

        if rhs.ndim < 2:
            raise ValueError(
                f"2-D tensor solver needs rhs.ndim >= 2, got {rhs.ndim} "
                "(a rank-1 rhs would silently solve both axes over the same "
                "axis; batch dims go in front)"
            )
        ax = rhs.ndim - 2
        out = constrain(rhs, SPEC)
        if self.matvec1 is not None:
            out = self.matvec1.apply(constrain(out, PHYS), ax + 1)
        out = constrain(out, SPEC)
        if self.fwd is not None:
            out = self.fwd.apply(out, ax)
        out = self.banded.solve(constrain(out, PHYS), ax + 1)
        out = constrain(out, SPEC)
        if self.bwd is not None:
            out = self.bwd.apply(out, ax)
        return constrain(out, SPEC)


class FastDiag:
    """Fast-diagonalisation 2-D solver: BOTH axes eigendecomposed through the
    preconditioned pencils, so the device solve is 4 GEMMs + 1 elementwise
    divide — pure MXU work, no sequential recurrence.  This is the TPU-native
    answer to the reference's FdmaTensor (eig axis 0 + per-eigenvalue banded
    sweeps along axis 1, /root/reference/src/solver/fdma_tensor.rs:36-71):
    the *identical* discrete solution (same pencils, tested against
    :class:`TensorSolver`), but the O(n) Thomas recurrence the reference
    parallelises with rayon lanes would serialise a TPU, while matmuls
    saturate it.

    Fourier axes are already modal (diagonal), so their fwd/bwd maps are
    identity and their eigenvalues are -k^2.
    """

    def __init__(self, modal0, modal1, alpha: float, fix_singular=False, sep=(False, False)):
        from .ops.folded import parity_perm

        dt = config.real_dtype()
        lams, self.fwd, self.bwd = [], [], []
        to_dev = lambda m: jnp.asarray(m, dtype=dt)  # noqa: E731
        for si, (lam, fwd, bwd) in zip(sep, (modal0, modal1)):
            si = si and fwd is not None  # Fourier axes are never sep
            self.fwd.append(
                FoldedMatrix(fwd, to_dev, sep_in=si, sep_out=si)
                if fwd is not None
                else None
            )
            self.bwd.append(
                FoldedMatrix(bwd, to_dev, sep_in=si, sep_out=si)
                if bwd is not None
                else None
            )
            lams.append(lam[parity_perm(len(lam))] if si else lam)
        if fix_singular and abs(lams[0][0]) < 1e-10:
            # pure-Neumann zero mode: same nudge as the reference
            # (/root/reference/src/solver/poisson.rs:84-87)
            lams[0] = lams[0].copy()
            lams[0] -= 1e-10
        denom = lams[0][:, None] + lams[1][None, :] + alpha
        self.denom = jnp.asarray(denom, dtype=dt)

    def solve(self, rhs):
        """rhs in ortho space -> solution in composite space (extra leading
        dims are batch).  Pencil flips sit between the two contractions."""
        from .parallel.mesh import PHYS, SPEC, constrain

        if rhs.ndim < 2:
            raise ValueError(
                f"2-D tensor solver needs rhs.ndim >= 2, got {rhs.ndim} "
                "(a rank-1 rhs would silently solve both axes over the same "
                "axis; batch dims go in front)"
            )
        ax = rhs.ndim - 2
        out = constrain(rhs, SPEC)
        if self.fwd[0] is not None:
            out = self.fwd[0].apply(out, ax)
        out = constrain(out, PHYS)
        if self.fwd[1] is not None:
            out = self.fwd[1].apply(out, ax + 1)
        out = out / self.denom.astype(out.dtype)
        if self.bwd[1] is not None:
            out = self.bwd[1].apply(out, ax + 1)
        out = constrain(out, SPEC)
        if self.bwd[0] is not None:
            out = self.bwd[0].apply(out, ax)
        return constrain(out, SPEC)


class _TensorBased:
    """Shared assembly for Poisson/Hholtz: fast-diagonalisation on TPU,
    eig-axis0 + banded-axis1 tensor solver elsewhere.  Both backends
    diagonalize the same preconditioned pencils, so they solve the same
    discrete system (tests/test_golden.py asserts equality to machine
    precision)."""

    def __init__(
        self,
        space: Space2,
        c,
        alpha: float,
        negate_lap: bool,
        fix_singular=False,
        method: str | None = None,
    ):
        method = method or ("fd" if config.is_tpu_like() else "banded")
        sign = -1.0 if negate_lap else 1.0
        sep = getattr(space, "sep", (False, False))
        modal0 = _axis_modal_data(space, 0, c[0], sign)
        if method == "fd":
            modal1 = _axis_modal_data(space, 1, c[1], sign)
            self._solver = FastDiag(modal0, modal1, alpha, fix_singular, sep=sep)
        else:
            # mat_c1 = preconditioned mass (pinv S, or I for Fourier),
            # mat_a1 = preconditioned laplacian (peye S, or diag(-k^2))
            mat_c1, mat_a1, precond1 = ingredients_for_hholtz(space, 1)
            self._solver = TensorSolver(
                modal0,
                sign * c[1] * mat_a1,
                mat_c1,
                precond1,
                alpha,
                fix_singular=fix_singular,
                sep=sep,
            )

    def solve(self, rhs):
        return self._solver.solve(rhs)


class Poisson(_TensorBased):
    """Pressure Poisson ``c * D2 u = A f`` with singular-mode regularization
    (lam -= 1e-10, /root/reference/src/solver/poisson.rs:84-87)."""

    def __init__(self, space: Space2, c, **kw):
        super().__init__(space, c, alpha=0.0, negate_lap=False, fix_singular=True, **kw)


class Hholtz(_TensorBased):
    """Exact (non-ADI) Helmholtz ``(I - c*D2) u = A f`` via the tensor solver
    with alpha=1 (/root/reference/src/solver/hholtz.rs:63-100)."""

    def __init__(self, space: Space2, c, **kw):
        super().__init__(space, c, alpha=1.0, negate_lap=True, **kw)
