"""rustpde_mpi_tpu — a TPU-native spectral-method PDE framework.

A from-scratch JAX/XLA rebuild with the capabilities of the Rust
``rustpde-mpi`` framework (2-D Navier–Stokes / Rayleigh–Bénard convection with
Chebyshev/Fourier spectral-Galerkin discretisation; serial, single-chip and
mesh-sharded multi-chip execution).  See SURVEY.md for the component map.

Public API vocabulary mirrors the reference (``/root/reference/src/lib.rs``):
bases, Field2/Space2, solvers (Poisson/Hholtz/HholtzAdi), Navier2D models and
an ``integrate`` driver — redesigned functionally for XLA: states are pytrees,
steps are pure jitted functions, parallelism is `jax.sharding` over a Mesh.
"""

from . import config  # noqa: F401  (must import first: enables x64)
from .bases import (  # noqa: F401
    Base,
    BaseKind,
    Space2,
    cheb_dirichlet,
    cheb_dirichlet_neumann,
    cheb_neumann,
    chebyshev,
    fourier_c2c,
    fourier_r2c,
    fourier_r2c_split,
)
from .bases import BiPeriodicSpace2, Space1  # noqa: F401
from .field import Field1, Field2, average, average_axis, norm_l2  # noqa: F401
from .models.ensemble import NavierEnsemble  # noqa: F401
from .models.lnse import Navier2DLnse, Navier2DNonLin  # noqa: F401
from .models.meanfield import MeanFields  # noqa: F401
from .models.navier import Navier2D, NavierState  # noqa: F401
from .models.opt_routines import steepest_descent_energy_constrained  # noqa: F401
from .models.statistics import Statistics  # noqa: F401
from .models.stats import StatsEngine, StatsState, export_stats  # noqa: F401
from .models.steady_adjoint import Navier2DAdjoint  # noqa: F401
from .models.swift_hohenberg import SwiftHohenberg1D, SwiftHohenberg2D  # noqa: F401
from .utils.governor import (  # noqa: F401
    ChunkStatus,
    DtLadder,
    RunHealth,
    StabilityGovernor,
)
from .utils.integrate import Integrate, integrate  # noqa: F401
from .utils.io_pipeline import (  # noqa: F401
    AsyncWriteError,
    IOPipeline,
    ObservableFuture,
)
from .models.campaign import CampaignModelBase  # noqa: F401
from .serve import (  # noqa: F401
    AdmissionError,
    RequestFailed,
    SimRequest,
    SimServer,
)
from .workloads import (  # noqa: F401
    ScenarioConfig,
    build_model,
    critical_rayleigh,
    eigenmode_sweep,
    geometry_sweep,
    model_kinds,
    register_model_kind,
    steady_state_find,
    validate_campaign_model,
)
from . import telemetry  # noqa: F401
from .telemetry import (  # noqa: F401
    MetricsRegistry,
    ThroughputMonitor,
)
from .parallel.sanitizer import CollectiveDesyncError  # noqa: F401
from .utils.checkpoint import CheckpointError  # noqa: F401
from .utils.faults import FaultSpecError  # noqa: F401
from .utils.resilience import (  # noqa: F401
    DispatchHang,
    DivergenceError,
    ResilientRunner,
)
from .utils.vorticity import (  # noqa: F401
    vorticity_auto,
    vorticity_from_file,
    vorticity_from_file_periodic,
)

__version__ = "0.1.0"
