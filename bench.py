"""Benchmark harness: the 5 BASELINE.json configs + MFU estimate.

Prints ONE JSON line whose required fields are
``{"metric", "value", "unit", "vs_baseline"}`` (primary metric: timesteps/sec
of the confined 2-D RBC DNS at 1025^2, BASELINE config #4); the same object
carries the full config matrix under ``"configs"`` and an ``"mfu"`` estimate,
and the matrix is also written to BENCH_FULL.json.

Environment knobs:

    RUSTPDE_BENCH_CONFIGS  comma list / "all" (default) /
                           names: rbc129, periodic, poisson1025,
                                  poisson1025_f64, rbc1025, rbc1025_f64,
                                  sh2048, rbc2049, rbc2049_f64, rbc129_f64,
                                  ensemble129, resilience129, governor129,
                                  pipeline129, shardedio129, serve129,
                                  workloads129
    RUSTPDE_BENCH_STEPS    timed window for the primary config (default 64;
                           rates are slope-timed over windows L and 4L, see
                           utils/profiling.benchmark_steps)
    RUSTPDE_X64            1 for f64 parity mode (default 0 here)
    RUSTPDE_BENCH_STARVE_LIMIT  consecutive budget-skips a config may
                           accumulate before the run FAILS (default 3; the
                           payload lists current counters in
                           "starved_configs", persisted in BENCH_FULL.json
                           and reset by any fresh measurement)

``vs_baseline``: the reference publishes no numbers and cannot be built in
this container (no Rust toolchain), so the denominator is this framework's
own CPU path (f64, banded solvers — algorithmically the reference's serial
configuration) measured on this host at the same 1025^2 config; see
BASELINE.md "Measured stand-in baseline".
"""

import json
import os
import sys
import time

os.environ.setdefault("RUSTPDE_X64", "0")
_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

# Persistent XLA compilation cache (works through the axon relay; measured
# 39 s -> 9 s step-compile, 67 s -> 10 s model build at 1025^2): each bench
# entry point calls config.enable_compilation_cache(), which also exports the
# env so the f64 subprocess runs inherit it.

# where the f32/f64 short-horizon shadow states meet (see shadow gate below)
_SHADOW_DIR = os.path.join(_REPO, "data")
_SHADOW_STEPS = 8


def _shadow_path(tag: str) -> str:
    return os.path.join(_SHADOW_DIR, f"bench_shadow_{tag}.npy")

# CPU f64 banded-path steps/s at 1025^2 Ra=1e9 measured on this container's
# host CPU, 2026-07-29 (BASELINE.md "Measured stand-in baseline").
CPU_BASELINE_STEPS_PER_SEC = 0.188

# primary config first: with a driver-side timeout or the RUSTPDE_BENCH_BUDGET_S
# cutoff, whatever completes still yields the primary metric line
DEFAULT_CONFIGS = [
    "rbc1025",
    "rbc1025_f64",
    "rbc2049",
    "periodic1024",
    "sh2048",
    "rbc129",
    "ensemble129",
    "resilience129",
    "governor129",
    "pipeline129",
    "shardedio129",
    "serve129",
    "autoscale129",
    "serve_submesh129",
    "coldstart129",
    "workloads129",
    "stats129",
    "integrity129",
    "pallasconv",
    "bandedsolve",
    "periodic",
    "poisson1025",
    "poisson1025_f64",
    "rbc129_f64",
    "rbc2049_f64",
]
# always run first, in this order, when selected: the two flagship sizes and
# the f64 shadow anchor must be fresh at HEAD in every driver capture
# (VERDICT r3 weak #2); the rest rotate least-recently-measured first
PINNED = ("rbc1025", "rbc1025_f64", "rbc2049")

# shared by the live payload (main) and the degraded payload (_emit_degraded)
# so the two lines cannot drift apart in the driver's record
METRIC_NAMES = {
    "rbc1025": "2D RBC confined 1025x1025 Ra=1e9",
    "rbc1025_f64": "2D RBC confined 1025x1025 Ra=1e9",
    "rbc2049": "2D RBC confined 2049x2049 Ra=1e9",
    "rbc2049_f64": "2D RBC confined 2049x2049 Ra=1e9",
    "rbc129": "2D RBC confined 129x129 Ra=1e7",
    "rbc129_f64": "2D RBC confined 129x129 Ra=1e7",
    "ensemble129": "2D RBC ensemble 129x129 Ra=1e7 K=1/8/32 (member-steps/s)",
    "resilience129": "2D RBC confined 129x129 Ra=1e7 NaN-fault recovery",
    "governor129": "2D RBC confined 129x129 Ra=1e7 stability governor (sentinel overhead + spike catch)",
    "pipeline129": "2D RBC confined 129x129 Ra=1e7 overlapped I/O pipeline (async checkpoints + dispatch double-buffering)",
    "shardedio129": "2D RBC sharded two-phase checkpoints, 2-proc CPU harness (sharded vs gathered write + elastic-restore gate)",
    "serve129": "2D RBC simulation service 129x129 Ra=1e7, 200 requests / 8 slots soak (drain+NaN chaos; member-steps/s + latency percentiles)",
    "autoscale129": "autoscaling fleet chaos soak 17x17 CPU (controller + launcher under Poisson notice-SIGTERM/SIGKILL preemptions; zero-lost + reclaimed-with-state + admission p99 gates)",
    "serve_submesh129": "gang-scheduled sub-mesh serving chaos soak, 2-proc CPU harness (34^2 gang-sharded + 18^2 vmapped co-resident traffic; gang-member SIGKILL mid-campaign: zero-lost + gang-reclaimed-with-state + rtol-1e-9 solo parity + co-resident latency gates)",
    "coldstart129": "cold-start elimination 17x17 CPU (persistent compile cache + warm campaign pool + admission canonicalization: never-seen-key TTFC and restart-to-first-result cold vs warm, zero-jit warm admission, recompile-flat drain/restart/re-plan cycle, canonicalized-vs-direct parity gates)",
    "workloads129": "multi-model workloads 129x129 (dns/lnse/adjoint member-steps/s per kind + solo-vs-ensemble parity + lnse onset-sign gate)",
    "stats129": "2D RBC confined 129x129 Ra=1e7 in-scan physics stats (stats-on vs stats-off matched governed windows: bit-equal trajectory + <=5% overhead + budget-closure gates)",
    "integrity129": "2D RBC confined 129x129 Ra=1e7 SDC defense (digests-on vs off matched windows: bit-equal trajectory + <=2% digest-stream overhead + injected-bitflip caught/rolled-back/bit-equal gates)",
    "pallasconv": "fused Pallas convection + solve megakernels vs unfused dense (RUSTPDE_CONV_KERNEL / RUSTPDE_STEP_KERNEL A/B: ms/step + MFU + bit-tolerance + HBM-traffic deltas; 129x129 min, flagship rows on-chip)",
    "bandedsolve": "lane-parallel Pallas banded substitution vs dense-inverse GEMM vs lax.scan recurrence (ops/pallas_banded.bench_banded_paths: sec/solve per path at 1023x1025)",
    "periodic": "2D RBC periodic 128x65 Ra=1e6",
    "periodic1024": "2D RBC periodic 1024x1025 Ra=1e9",
    "poisson1025": "Poisson standalone 1025x1025",
    "poisson1025_f64": "Poisson standalone 1025x1025",
    "sh2048": "Swift-Hohenberg 2048x2048",
}
PRIMARY = "rbc1025"


def _metric_string(primary_name, unit, x64, platform, stale_note=""):
    return (
        f"{'timesteps' if unit == 'steps/s' else 'solves'}/sec, "
        f"{METRIC_NAMES.get(primary_name, primary_name)} "
        f"({'f64' if x64 else 'f32'}, {platform}{stale_note})"
    )


def bench_navier(nx, ny, ra, dt, steps, periodic=False, x64=None, shadow_path=None):
    """Model step rate (slope-timed; see profiling.benchmark_steps).

    ``shadow_path``: run _SHADOW_STEPS steps from the deterministic IC first
    and save the temperature field there — the f32 and f64 runs of the same
    config produce comparable snapshots for the short-horizon shadowing gate.
    """
    import numpy as np

    from rustpde_mpi_tpu import Navier2D, config
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps, mfu_estimate

    config.enable_compilation_cache()
    ctor = Navier2D.new_periodic if periodic else Navier2D.new_confined
    model = ctor(nx, ny, ra, 1.0, dt, 1.0, "rbc")
    shadow = None
    if shadow_path:
        # smooth deterministic IC for the shadowing window: the default
        # random-noise IC is a stiff transient (high-k diffusive decay ~0.23
        # per step at 1025^2 Ra=1e9) where f32 roundoff amplifies to ~1e-1
        # field drift within 8 steps; from a smooth IC the measured f32-vs-
        # f64 drift is 3.8e-6 — the gate tests the numerics, not the IC
        model.set_velocity(0.1, 2.0, 2.0)
        model.set_temperature(0.1, 2.0, 2.0)
        model.update_n(_SHADOW_STEPS)
        temp = np.asarray(model.get_field("temp"), dtype=np.float64)
        os.makedirs(os.path.dirname(shadow_path), exist_ok=True)
        np.save(shadow_path, temp)
        shadow = {"steps": _SHADOW_STEPS, "nu": model.eval_nu(), "path": shadow_path}
    res = benchmark_steps(model, steps)
    nu, _, _, div = model.get_observables()
    res["nu"] = nu
    res["finite"] = bool(nu == nu and div == div)
    res["mfu"] = mfu_estimate(model, res["steps_per_sec"])
    if shadow:
        res["shadow"] = shadow
    return res


def bench_poisson(n, solves=32):
    """Standalone Poisson solve rate + MMS max error (BASELINE config #3,
    /root/reference/examples/poisson_mpi.rs analog)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rustpde_mpi_tpu import Space2, cheb_neumann, config
    from rustpde_mpi_tpu.solver import Poisson

    config.enable_compilation_cache()

    space = Space2(cheb_neumann(n), cheb_neumann(n))
    solver = Poisson(space, (1.0, 1.0))
    xs, ys = (b.points for b in space.bases)
    # Neumann-compatible zero-mean MMS mode (tests/test_solver.py convention)
    u = np.cos(np.pi * xs)[:, None] * np.cos(np.pi * ys)[None, :]
    f = -2.0 * np.pi**2 * u
    fhat_ortho = space.to_ortho(space.forward(jnp.asarray(f)))

    solve = jax.jit(solver.solve)
    out = solve(fhat_ortho)
    got = np.array(space.backward(out))
    got -= got.mean() - u.mean()  # defined up to a constant
    err = float(np.abs(got - u).max())
    np.asarray(out[:1, :1])
    t0 = time.perf_counter()
    for _ in range(solves):
        out = solve(fhat_ortho)
    np.asarray(out[:1, :1])
    elapsed = time.perf_counter() - t0
    return {"solves_per_sec": solves / elapsed, "max_error": err, "n": n}


def bench_sh(nx, steps=128):
    from rustpde_mpi_tpu import SwiftHohenberg2D, config
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps

    config.enable_compilation_cache()
    model = SwiftHohenberg2D(nx, nx, r=0.35, dt=0.02, length=20.0)
    e_start = model.pattern_energy()
    res = benchmark_steps(model, steps)
    e_end = model.pattern_energy()
    res["pattern_energy_start"] = e_start
    res["pattern_energy"] = e_end
    # r=0.35 is supercritical: from the small random IC the pattern must have
    # GROWN over the executed steps (or already saturated at O(r) amplitude);
    # a zero/shrinking energy means a vacuous run (VERDICT r3 weak #6)
    res["pattern_grew"] = bool(e_end > max(e_start, 1e-10))
    res["finite"] = bool(not model.exit() and res["pattern_grew"])
    return res


def bench_ensemble(nx, ny, ra, dt, steps, ks=(1, 8, 32)):
    """Ensemble throughput-scaling curve (models/ensemble.py): K member
    states stepped by one vmapped dispatch, K in ``ks``.  Reports per-K
    slope-timed rates; the headline ``steps_per_sec`` is the AGGREGATE
    member-steps/s at the largest K (the number that compares against K solo
    runs), and ``k8_vs_k1_member_rate`` records the batching speedup (only
    when both K=1 and K=8 were measured; informational — the red/green gate
    is per-member liveness, which hardware-dependent scaling is not).  One
    template model serves every K (shared operator constants)."""
    import numpy as np

    from rustpde_mpi_tpu import Navier2D, NavierEnsemble, config
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps, mfu_estimate

    config.enable_compilation_cache()
    model = Navier2D.new_confined(nx, ny, ra, 1.0, dt, 1.0, "rbc")
    curve = {}
    finite = True
    for k in ks:
        ens = NavierEnsemble.from_seeds(model, seeds=range(k))
        r = benchmark_steps(ens, steps)
        nu = np.asarray(ens.eval_nu())
        # liveness comes from the mask, NOT isfinite(Nu): a member that
        # diverges mid-run is frozen at its last FINITE state (graceful
        # degradation), so its stale Nu still reads finite
        alive = np.asarray(ens.alive())
        r["members_alive"] = int(alive.sum())
        r["nu_mean"] = float(nu[alive].mean()) if alive.any() else None
        r["mfu"] = mfu_estimate(ens, r["steps_per_sec"])["mfu"]
        finite = finite and bool(alive.all())
        curve[str(k)] = {
            key: r[key]
            for key in (
                "steps_per_sec",
                "ms_per_step",
                "member_steps_per_sec",
                "fixed_overhead_ms",
                "members_alive",
                "nu_mean",
                "mfu",
            )
        }
    k1 = curve.get("1", {}).get("member_steps_per_sec")
    k8 = curve.get("8", {}).get("member_steps_per_sec")
    return {
        "ks": list(ks),
        "curve": curve,
        # aggregate member throughput at the largest K (see docstring)
        "steps_per_sec": curve[str(ks[-1])]["member_steps_per_sec"],
        "unit_note": "steps_per_sec = aggregate member-steps/s at max K",
        "k8_vs_k1_member_rate": (k8 / k1) if (k8 and k1) else None,
        "finite": finite,
    }


def bench_governor(nx, ny, ra, dt, steps):
    """Stability-governor config (utils/governor.py), two legs:

    (1) **sentinel overhead** — the same slope-timed window stepped by the
    plain chain and by the sentinel-armed chain (on-device CFL/KE/|div|
    reductions riding the scan carry).  Gate: <5% per-chunk overhead — the
    sentinels only reduce arrays the step already materializes.  Min-of-reps
    slopes (not medians): host noise on a shared box dwarfs the real delta.

    (2) **spike recovery** — a deterministic velocity spike at the midpoint
    (``spike@<step>``), sized from the measured baseline CFL so the spiked
    flow lands ~3x over the target.  Governed: the pre-divergence sentinel
    catches it BEFORE NaNs, rollback happens in memory, dt descends the
    rung-cached ladder, and the run finishes with ZERO reactive checkpoint
    restores.  Ungoverned: the same spike grows into NaN and needs the
    checkpoint-rollback path.  Red/green gate: governed done with
    retries==0 and >=1 rollback avoided while ungoverned retries>=1 (or
    dies), plus the overhead gate."""
    import shutil
    import tempfile

    import numpy as np

    from rustpde_mpi_tpu import DivergenceError, Navier2D, ResilientRunner, config
    from rustpde_mpi_tpu.config import StabilityConfig

    config.enable_compilation_cache()

    def build(stab=None):
        model = Navier2D(nx, ny, ra, 1.0, dt, 1.0, "rbc", periodic=False)
        model.set_velocity(0.1, 2.0, 2.0)
        model.set_temperature(0.1, 2.0, 2.0)
        model.write_intervall = 1e9
        if stab is not None:
            model.set_stability(stab)
        return model

    # sentinel overhead via INTERLEAVED slope timing: plain and sentinel
    # windows alternate rep by rep, so slow host weather (this box is a
    # shared 2-core container with ±10% drift over minutes — far above the
    # 5% gate) hits both chains alike; min-of-reps slopes estimate the true
    # per-step cost of each chain.  benchmark_steps times one model per
    # call, which bakes minutes of drift into the comparison.
    import jax as _jax

    m_plain, m_sent = build(), build(StabilityConfig())
    L = max(16, int(steps))
    for m in (m_plain, m_sent):  # compile + warm both window lengths
        m.update_n(L)
        m.update_n(4 * L)
        _jax.block_until_ready(m.state)
    slopes = {"plain": [], "sent": []}
    for _ in range(5):
        for key, m in (("plain", m_plain), ("sent", m_sent)):
            t0 = time.perf_counter()
            m.update_n(L)
            _jax.block_until_ready(m.state)
            t_l = time.perf_counter() - t0
            t0 = time.perf_counter()
            m.update_n(4 * L)
            _jax.block_until_ready(m.state)
            t_4l = time.perf_counter() - t0
            slopes[key].append((t_4l - t_l) / (3 * L))
    ms_plain = min(slopes["plain"]) * 1e3
    ms_sent = min(slopes["sent"]) * 1e3
    overhead = ms_sent / ms_plain - 1.0
    r_plain = {"steps_per_sec": 1e3 / ms_plain}
    r_sent = {"steps_per_sec": 1e3 / ms_sent}

    # telemetry overhead gate (PR 8): metrics+tracing ON vs OFF through the
    # RUNNER advance path (where the spans/counters/SLO live — bare
    # update_n never touches telemetry).  Unlike the sentinel leg there is
    # no differing fixed cost to cancel — ON and OFF execute the IDENTICAL
    # dispatch path, only the telemetry branches differ — so no slope
    # timing: one large matched window per rep (16 sub-chunks of L steps =
    # one telemetry round per sub-chunk, the production cadence),
    # interleaved, min-of-reps.  Gates: <=2% wall overhead AND bit-equal
    # observables (telemetry records host scalars the run already fetched;
    # it must never perturb the traced programs).
    from rustpde_mpi_tpu import ResilientRunner as _Runner
    from rustpde_mpi_tpu import telemetry

    tel_window = 16 * L  # 16 telemetry rounds per timed window
    tel_dirs = [tempfile.mkdtemp(prefix="bench_tel_") for _ in range(2)]
    try:
        runners = {}
        for key, d in (("on", tel_dirs[0]), ("off", tel_dirs[1])):
            runners[key] = _Runner(
                build(StabilityConfig()),
                max_time=float("inf"),
                run_dir=d,
                checkpoint_every_s=None,
                max_chunk_steps=L,  # one span/counter round per L steps
            )
        # save/restore each layer's own flag: restoring both from the
        # metrics flag would re-enable tracing a user pinned off via
        # RUSTPDE_TRACE=0.  The reqtrace layer rides the same master
        # switch, and a fake slot binding keeps the span-annotator path
        # HOT through the ON legs — the 2% gate covers the reqtrace path,
        # not just bare spans (ISSUE 13 extension of the PR-8 contract).
        from rustpde_mpi_tpu.telemetry import reqtrace as _reqtrace

        tel_prev = (
            telemetry.metrics_enabled(),
            telemetry.tracing_enabled(),
            telemetry.reqtrace_enabled(),
        )
        tel_walls = {"on": [], "off": []}
        try:
            for key, r in runners.items():  # compile + warm the chunk shapes
                telemetry.set_enabled(key == "on")
                _reqtrace.bind_slots({0: "benchtrace0000"} if key == "on" else {})
                r.advance(tel_window)
                _jax.block_until_ready(r.pde.state)
            for _ in range(5):
                for key, r in runners.items():
                    telemetry.set_enabled(key == "on")
                    _reqtrace.bind_slots(
                        {0: "benchtrace0000"} if key == "on" else {}
                    )
                    t0 = time.perf_counter()
                    r.advance(tel_window)
                    _jax.block_until_ready(r.pde.state)
                    tel_walls[key].append(time.perf_counter() - t0)
        finally:
            _reqtrace.clear_active()
            telemetry.set_metrics_enabled(tel_prev[0])
            telemetry.set_tracing_enabled(tel_prev[1])
            telemetry.set_reqtrace_enabled(tel_prev[2])
        tel_overhead = min(tel_walls["on"]) / min(tel_walls["off"]) - 1.0
        # bit-equality: both runners stepped the identical IC the identical
        # number of steps — telemetry must not have changed a single bit
        nu_on = float(runners["on"].pde.eval_nu())
        nu_off = float(runners["off"].pde.eval_nu())
        tel_bit_equal = bool(nu_on == nu_off)
    finally:
        for d in tel_dirs:
            shutil.rmtree(d, ignore_errors=True)
    tel_ok = bool(tel_overhead <= 0.02)

    # collective-sequence sanitizer overhead gate (PR 12): RUSTPDE_SANITIZE
    # armed vs off through the identical runner advance path (the per-
    # boundary root_decides handshakes are the recorded collective entry
    # points on a single process), same matched-window min-of-reps shape as
    # the telemetry leg.  Gates: <=2% wall overhead armed AND bit-equal
    # observables (the sanitizer is host-side only — it must never perturb
    # the traced programs).
    from rustpde_mpi_tpu.parallel import sanitizer as _sanitizer

    san_dirs = [tempfile.mkdtemp(prefix="bench_san_") for _ in range(2)]
    try:
        runners = {}
        for key, d in (("on", san_dirs[0]), ("off", san_dirs[1])):
            runners[key] = _Runner(
                build(StabilityConfig()),
                max_time=float("inf"),
                run_dir=d,
                checkpoint_every_s=None,
                max_chunk_steps=L,
            )
        san_prev = _sanitizer.enabled()
        san_walls = {"on": [], "off": []}
        try:
            for key, r in runners.items():  # compile + warm the chunk shapes
                _sanitizer.set_enabled(key == "on")
                r.advance(tel_window)
                _jax.block_until_ready(r.pde.state)
            for _ in range(5):
                for key, r in runners.items():
                    _sanitizer.set_enabled(key == "on")
                    t0 = time.perf_counter()
                    r.advance(tel_window)
                    _jax.block_until_ready(r.pde.state)
                    san_walls[key].append(time.perf_counter() - t0)
        finally:
            _sanitizer.set_enabled(san_prev)
        san_overhead = min(san_walls["on"]) / min(san_walls["off"]) - 1.0
        san_records = _sanitizer.stats()["records"]
        nu_on = float(runners["on"].pde.eval_nu())
        nu_off = float(runners["off"].pde.eval_nu())
        san_bit_equal = bool(nu_on == nu_off)
    finally:
        for d in san_dirs:
            shutil.rmtree(d, ignore_errors=True)
    # the armed leg must have RECORDED something, or the gate is vacuous
    san_ok = bool(san_overhead <= 0.02 and san_records > 0)

    # probe the CFL the flow will have AT the spike step (the early flow is
    # far calmer than the developed one the overhead window ends in), then
    # size the spike WITH MARGIN — 8x the ceiling, not a value that lands
    # near 1x where roundoff in the spike's decay through the step's
    # velocity recomputation decides whether the sentinel trips at all
    # (PR 8 observed governed_retries flipping 0<->1 leg to leg): violently
    # nonlinear, so an ungoverned run NaNs within the remaining horizon,
    # while a governed one descends the ladder proactively.  The CATCH
    # WINDOW is derived from the same probe: the governed leg's sub-chunk
    # cap is sized so the sentinel evaluates within a few steps of the
    # spike — far inside the steps-to-NaN horizon — instead of at whatever
    # boundary the horizon happened to leave.
    spike_steps = max(32, min(steps, 64))
    spike_at = max(4, spike_steps // 4)
    max_time = spike_steps * dt
    probe = build(StabilityConfig())
    probe.update_n(spike_at)
    cfl_base = probe.last_chunk_status.cfl_max
    spike_factor = 8.0 / max(cfl_base, 1e-9)
    catch_window = max(2, min(8, spike_at // 2))

    run_dir = tempfile.mkdtemp(prefix="bench_governor_")
    try:
        governed = ResilientRunner(
            build(),
            max_time,
            None,
            run_dir=run_dir,
            checkpoint_every_s=None,
            max_retries=2,
            fault=f"spike@{spike_at}",
            spike_factor=spike_factor,
            stability=StabilityConfig(),
            max_chunk_steps=catch_window,
        )
        t0 = time.perf_counter()
        g_summary = governed.run()
        governed_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)

    run_dir = tempfile.mkdtemp(prefix="bench_governor_ungov_")
    ungoverned_retries = None
    ungoverned_outcome = "diverged"
    try:
        ungoverned = ResilientRunner(
            build(),
            max_time,
            None,
            run_dir=run_dir,
            checkpoint_every_s=None,
            max_retries=3,
            fault=f"spike@{spike_at}",
            spike_factor=spike_factor,
        )
        try:
            u_summary = ungoverned.run()
            ungoverned_retries = u_summary["retries"]
            ungoverned_outcome = u_summary["outcome"]
        except DivergenceError:
            ungoverned_retries = ungoverned.attempt
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)

    health = g_summary["health"]
    # the gate asserts the INVARIANT, not an exact retry count (the old
    # `retries == 0` flipped 0<->1 with box weather when the spike landed
    # near the sentinel threshold): the governed trajectory COMPLETES with
    # finite physics, the sentinels actually caught the spike pre-NaN at
    # least once, and the governed run needed NO MORE reactive checkpoint
    # rollbacks than the ungoverned one (strictly fewer whenever the
    # ungoverned run suffered at all, which the spike sizing guarantees)
    ungoverned_rollbacks = (
        ungoverned_retries
        if ungoverned_retries is not None
        else governed.max_retries
    )
    recovered = bool(
        g_summary["outcome"] == "done"
        and health["pre_divergence_catches"] >= 1
        and health["rollbacks_avoided"] >= 1
        and g_summary["retries"] <= ungoverned_rollbacks
        and g_summary["nu"] is not None
        and np.isfinite(g_summary["nu"])
    )
    ungoverned_suffered = bool(
        ungoverned_outcome == "diverged" or (ungoverned_retries or 0) >= 1
    )
    overhead_ok = bool(overhead < 0.05)
    return {
        "steps_per_sec": r_sent["steps_per_sec"],
        "plain_steps_per_sec": r_plain["steps_per_sec"],
        "sentinel_overhead_x": 1.0 + overhead,
        "sentinel_overhead_ok": overhead_ok,
        "telemetry_overhead_x": 1.0 + tel_overhead,
        "telemetry_overhead_ok": tel_ok,
        "telemetry_bit_equal": tel_bit_equal,
        "sanitizer_overhead_x": 1.0 + san_overhead,
        "sanitizer_overhead_ok": san_ok,
        "sanitizer_records": san_records,
        "sanitizer_bit_equal": san_bit_equal,
        "cfl_base": cfl_base,
        "spike_factor": spike_factor,
        "governed_retries": g_summary["retries"],
        "governed_dt_final": g_summary["dt"],
        "governed_wall_s": round(governed_s, 2),
        "rollbacks_avoided": health["rollbacks_avoided"],
        "pre_divergence_catches": health["pre_divergence_catches"],
        "dt_trajectory": health["dt_trajectory"],
        "dt_adjusts": health["dt_adjusts"],
        "cfl_max_seen": health["cfl_max"],
        "ungoverned_outcome": ungoverned_outcome,
        "ungoverned_retries": ungoverned_retries,
        "nu": g_summary["nu"],
        "steps": spike_steps,
        "finite": bool(
            recovered
            and ungoverned_suffered
            and overhead_ok
            and tel_ok
            and tel_bit_equal
            and san_ok
            and san_bit_equal
        ),
    }


def bench_stats(nx, ny, ra, dt, steps):
    """In-scan physics-stats config (models/stats.py, ISSUE 14): stats-on
    vs stats-off through the GOVERNED runner advance path (the production
    shape: sentinels + stats share one scanned chunk), matched windows
    interleaved rep by rep, min-of-reps — the same protocol as the PR-8
    telemetry gate.

    Gates (all fold into ``finite``):

    * ``stats_bit_equal`` — both runners stepped the identical IC the
      identical number of steps; the accumulators only READ the state, so
      the committed trajectory must be EXACTLY equal (float equality),
    * ``stats_overhead_ok`` — wall overhead ≤5% at the default stride
      (the sample cost amortizes as ~1/stride),
    * ``budget_ok`` — the engine's budget-closure readout is finite and
      below threshold at 129².  The TIGHT gate is the kinetic-energy
      residual (production − dissipation − dKE/dt): an instantaneous-rate
      balance, so it must close even over this short spin-up window.  The
      Nu-consistency residual (plate-flux vs the exact-relation flux
      estimator) only converges in statistical stationarity — far beyond
      a bench budget — so it gets a finite + transient-sanity bound; the
      long-horizon campaigns the f64 ladder gates on are where it
      tightens."""
    import shutil
    import tempfile

    import jax as _jax
    import numpy as np

    from rustpde_mpi_tpu import Navier2D, ResilientRunner, config
    from rustpde_mpi_tpu.config import StabilityConfig, StatsConfig

    config.enable_compilation_cache()
    ke_budget_gate = 0.05
    nu_budget_gate = 3.0  # transient sanity bound (see docstring)

    def build(stats=False):
        model = Navier2D(nx, ny, ra, 1.0, dt, 1.0, "rbc", periodic=False)
        model.set_velocity(0.1, 2.0, 2.0)
        model.set_temperature(0.1, 2.0, 2.0)
        model.write_intervall = 1e9
        model.set_stability(StabilityConfig())
        if stats:
            model.set_stats(StatsConfig())
        return model

    L = max(16, int(steps))
    window = 8 * L  # 8 sub-chunks per timed window (boundary cadence real)
    reps = 7  # min-of-reps over interleaved windows: shared-box noise
    dirs = [tempfile.mkdtemp(prefix="bench_stats_") for _ in range(2)]
    try:
        runners = {}
        for key, d in (("on", dirs[0]), ("off", dirs[1])):
            runners[key] = ResilientRunner(
                build(stats=key == "on"),
                max_time=float("inf"),
                run_dir=d,
                checkpoint_every_s=None,
                max_chunk_steps=L,
            )
        walls = {"on": [], "off": []}
        for key, r in runners.items():  # compile + warm the chunk shapes
            r.advance(window)
            _jax.block_until_ready(r.pde.state)
        # the averaging window covers the TIMED windows only (the warmup
        # chunk holds the wildest piece of the spin-up transient)
        runners["on"].pde.reset_stats()
        for _ in range(reps):
            for key, r in runners.items():
                t0 = time.perf_counter()
                r.advance(window)
                _jax.block_until_ready(r.pde.state)
                walls[key].append(time.perf_counter() - t0)
        overhead = min(walls["on"]) / min(walls["off"]) - 1.0
        # exact float equality on the committed trajectory — the hard
        # contract: stats-on stepping is bit-identical to stats-off
        bit_equal = all(
            bool(
                np.array_equal(
                    np.asarray(getattr(runners["on"].pde.state, name)),
                    np.asarray(getattr(runners["off"].pde.state, name)),
                )
            )
            for name in runners["off"].pde.state._fields
        )
        health = runners["on"].pde.stats_summary()
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)

    overhead_ok = bool(overhead <= 0.05)
    budget_ok = bool(
        np.isfinite(health["nu_residual"])
        and np.isfinite(health["ke_residual"])
        and health["ke_residual"] < ke_budget_gate
        and health["nu_residual"] < nu_budget_gate
        and health["samples"] >= 2
    )
    steps_total = reps * window  # the timed reps only (warmup is untimed)
    return {
        "steps_per_sec": steps_total / sum(walls["on"]) if walls["on"] else 0.0,
        "plain_steps_per_sec": (
            steps_total / sum(walls["off"]) if walls["off"] else 0.0
        ),
        "stats_overhead_x": 1.0 + overhead,
        "stats_overhead_ok": overhead_ok,
        "stats_bit_equal": bit_equal,
        "stats_stride": int(runners["on"].pde.stats_engine.stride),
        "stats_samples": health["samples"],
        "nu_plate_avg": health["nu_plate_avg"],
        "nu_flux_avg": health["nu_flux_avg"],
        "nu_residual": health["nu_residual"],
        "ke_residual": health["ke_residual"],
        "ke_budget_gate": ke_budget_gate,
        "nu_budget_gate": nu_budget_gate,
        "tail_max": max(
            health[k]
            for k in (
                "tail_t_x", "tail_t_y", "tail_ux_x",
                "tail_ux_y", "tail_uy_x", "tail_uy_y",
            )
        ),
        "bl_thermal_pts": health["bl_thermal_pts"],
        "bl_visc_pts": health["bl_visc_pts"],
        "budget_ok": budget_ok,
        "steps": window,
        "finite": bool(bit_equal and overhead_ok and budget_ok),
    }


def bench_integrity(nx, ny, ra, dt, steps):
    """SDC-defense config (integrity/, ISSUE 20): digests-on vs digests-off
    through the governed runner advance path, matched windows interleaved
    rep by rep, min-of-reps — the stats129 protocol.  The overhead legs run
    at a huge audit cadence so they price the DIGEST STREAMING alone (the
    always-on cost: one bitcast-XOR/add tree reduction fused per chunk,
    result streamed with the observables future); the shadow re-execution
    audit re-steps a chunk on the side at its sampled cadence, so its cost
    is the chunk work divided by the cadence — a policy knob, not a tax,
    and it is gated by the detection leg instead.

    Gates (all fold into ``finite``):

    * ``integrity_bit_equal`` — digests only READ the state: the committed
      trajectory with auditing armed is EXACTLY equal (float equality) to
      the unaudited run,
    * ``integrity_overhead_ok`` — digest-streaming wall overhead ≤2%,
    * ``sdc_caught`` — an injected single-bit mantissa flip mid-run is
      detected by the shadow audit (``integrity_mismatch`` journaled),
      rolled back (``integrity_rollback``), and the completed run's final
      state is BIT-EQUAL to an uninjected run's — corruption fully erased,
      not merely noticed."""
    import shutil
    import tempfile

    import jax as _jax
    import numpy as np

    from rustpde_mpi_tpu import Navier2D, ResilientRunner, config
    from rustpde_mpi_tpu.config import IntegrityConfig, IOConfig
    from rustpde_mpi_tpu.utils.journal import read_journal

    config.enable_compilation_cache()

    def build(integrity=False, cadence=None):
        model = Navier2D(nx, ny, ra, 1.0, dt, 1.0, "rbc", periodic=False)
        model.set_velocity(0.1, 2.0, 2.0)
        model.set_temperature(0.1, 2.0, 2.0)
        model.write_intervall = 1e9
        if integrity:
            model.set_integrity(IntegrityConfig(cadence=cadence))
        return model

    L = max(16, int(steps))
    window = 8 * L  # 8 chunk boundaries per timed window (digest cadence real)
    reps = 7
    dirs = [tempfile.mkdtemp(prefix="bench_integrity_") for _ in range(5)]
    try:
        runners = {}
        for key, d in (("on", dirs[0]), ("off", dirs[1])):
            # cadence 10**9: chain digests stream at every boundary, the
            # shadow audit never fires — the always-on cost in isolation
            runners[key] = ResilientRunner(
                build(integrity=key == "on", cadence=10**9),
                max_time=float("inf"),
                run_dir=d,
                checkpoint_every_s=None,
                max_chunk_steps=L,
            )
        walls = {"on": [], "off": []}
        for key, r in runners.items():  # compile + warm the chunk shapes
            r.advance(window)
            _jax.block_until_ready(r.pde.state)
        for _ in range(reps):
            for key, r in runners.items():
                t0 = time.perf_counter()
                r.advance(window)
                _jax.block_until_ready(r.pde.state)
                walls[key].append(time.perf_counter() - t0)
        overhead = min(walls["on"]) / min(walls["off"]) - 1.0
        bit_equal = all(
            bool(
                np.array_equal(
                    np.asarray(getattr(runners["on"].pde.state, name)),
                    np.asarray(getattr(runners["off"].pde.state, name)),
                )
            )
            for name in runners["off"].pde.state._fields
        )

        # detection leg: clean vs injected, both fully audited (cadence 1),
        # short fixed horizon — the flip lands mid-run, the shadow audit
        # catches it at the chunk commit, rollback replays from the last
        # verified state, and the answers must agree to the BIT
        horizon, chunk = 40 * dt, 8
        det = {}
        for key, d, fault in (
            ("clean", dirs[2], None),
            ("hit", dirs[3], f"bitflip@{2 * chunk}"),
        ):
            r = ResilientRunner(
                build(integrity=True, cadence=1),
                max_time=horizon,
                run_dir=d,
                checkpoint_every_s=None,
                max_chunk_steps=chunk,
                fault=fault,
                io=IOConfig(async_checkpoints=False, overlap_dispatch=False),
            )
            r.run()
            det[key] = r.pde
        hit_events = [
            e.get("event")
            for e in read_journal(
                os.path.join(dirs[3], "journal.jsonl"), on_error="skip"
            )
        ]
        sdc_bit_equal = all(
            bool(
                np.array_equal(
                    np.asarray(getattr(det["clean"].state, name)),
                    np.asarray(getattr(det["hit"].state, name)),
                )
            )
            for name in det["clean"].state._fields
        )
        sdc_caught = bool(
            "bitflip_injected" in hit_events
            and "integrity_mismatch" in hit_events
            and "integrity_rollback" in hit_events
            and sdc_bit_equal
        )
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)

    overhead_ok = bool(overhead <= 0.02)
    steps_total = reps * window
    return {
        "steps_per_sec": steps_total / sum(walls["on"]) if walls["on"] else 0.0,
        "plain_steps_per_sec": (
            steps_total / sum(walls["off"]) if walls["off"] else 0.0
        ),
        "integrity_overhead_x": 1.0 + overhead,
        "integrity_overhead_ok": overhead_ok,
        "integrity_bit_equal": bit_equal,
        "sdc_caught": sdc_caught,
        "sdc_bit_equal": sdc_bit_equal,
        "steps": window,
        "finite": bool(bit_equal and overhead_ok and sdc_caught),
    }


def bench_pipeline(nx, ny, ra, dt, steps):
    """Overlapped-I/O config (utils/io_pipeline.py): the same horizon with a
    checkpoint at EVERY save boundary, run twice — once with fully blocking
    IO (``IOConfig.blocking()``: synchronous writes, fenced dispatches) and
    once with the overlapped pipeline (async cadence checkpoints, observable
    futures, dispatch double-buffering).

    The red/green gate is **equivalence under reordering**: the pipelined
    run must finish with the identical final state (bit-equal Nu and a final
    checkpoint whose content digest matches the blocking run's byte for
    byte), every submitted write must land digest-valid, and the journal
    must record async cadence checkpoints with zero failures.
    ``overlap_speedup_x`` is informational — on this 2-core CPU container
    the "background" worker competes with the stepping threads for the same
    cores, so the speedup only becomes real on a chip where compute and
    host IO are different hardware (the checkpoint-write seconds moved off
    the critical path are reported as ``io.write_s``)."""
    import json as _json
    import shutil
    import tempfile

    import numpy as np

    from rustpde_mpi_tpu import Navier2D, ResilientRunner, config
    from rustpde_mpi_tpu.config import IOConfig
    from rustpde_mpi_tpu.utils import checkpoint as cp

    config.enable_compilation_cache()

    def build():
        model = Navier2D(nx, ny, ra, 1.0, dt, 1.0, "rbc", periodic=False)
        model.set_velocity(0.1, 2.0, 2.0)
        model.set_temperature(0.1, 2.0, 2.0)
        model.write_intervall = 1e9  # checkpoints are the IO under test
        return model

    boundaries = 8
    save = (steps // boundaries) * dt
    max_time = steps * dt

    def run(io):
        run_dir = tempfile.mkdtemp(prefix="bench_pipeline_")
        try:
            runner = ResilientRunner(
                build(),
                max_time,
                save,
                run_dir=run_dir,
                checkpoint_every_s=None,
                checkpoint_every_t=save,
                io=io,
            )
            t0 = time.perf_counter()
            summary = runner.run()
            wall = time.perf_counter() - t0
            digest = cp.verify_snapshot(summary["checkpoint"])["digest"]
            with open(runner.journal_path, encoding="utf-8") as fh:
                events = [_json.loads(line) for line in fh]
            return summary, wall, digest, events
        finally:
            shutil.rmtree(run_dir, ignore_errors=True)

    # overlapped leg FIRST: both legs step the identical physics, so any
    # trace/compile warmup a cold cache leaves inside the first timed window
    # lands on the overlapped side — overlap_speedup_x can only be
    # UNDERstated by ordering, never inflated by compile time
    s_piped, wall_piped, digest_piped, ev_piped = run(IOConfig())
    s_block, wall_block, digest_block, _ = run(IOConfig.blocking())

    async_ckpts = sum(
        1 for e in ev_piped if e["event"] == "checkpoint" and e.get("async")
    )
    failures = sum(1 for e in ev_piped if e["event"] == "checkpoint_failed")
    equal = bool(
        s_piped["outcome"] == s_block["outcome"] == "done"
        and s_piped["nu"] == s_block["nu"]
        and digest_piped == digest_block
    )
    ok = bool(
        equal
        and async_ckpts >= 1
        and failures == 0
        and s_piped["nu"] is not None
        and np.isfinite(s_piped["nu"])
    )
    return {
        "steps_per_sec": steps / wall_piped,
        "blocking_steps_per_sec": steps / wall_block,
        "overlap_speedup_x": wall_block / wall_piped,
        "checkpoints": boundaries,
        "async_checkpoints": async_ckpts,
        "write_failures": failures,
        "io": s_piped["io"],
        "final_state_equal": equal,
        "nu": s_piped["nu"],
        "steps": steps,
        "finite": ok,
    }


def bench_sharded_io(reps=3):
    """Sharded-vs-gathered checkpoint IO on the 2-process CPU harness
    (tests/mp_worker.py ``bench_sharded`` mode): a real 2-controller
    ``jax.distributed`` cluster writes the same state both ways —

    * **sharded**: the distributed two-phase writer (per-host shard files +
      digest allgather + root manifest commit, utils/checkpoint),
    * **gathered**: the pre-sharded multihost shape — allgather every state
      leaf to every host, root serializes the full state.

    Reported: min wall seconds per write for both legs, bytes/host vs total
    bytes, and the commit barrier wait.  The red/green gate is durability,
    not speed (on one box both legs share the same disk): the final
    manifest must verify END-TO-END (manifest digest + every shard digest)
    and a cross-topology restore — the 2-process 4-device checkpoint read
    back into a SERIAL model — must be bit-equal to the workers' dumped
    global state.  Runs on CPU subprocesses regardless of the bench
    platform (the harness exists to prove the protocol, not the chip)."""
    import shutil
    import subprocess
    import tempfile

    sys.path.insert(0, os.path.join(_REPO, "tests"))
    from mp_harness import spawn_cluster  # ONE spawn recipe, shared with CI

    out_dir = tempfile.mkdtemp(prefix="bench_shardedio_")
    try:
        outs = spawn_cluster(
            out_dir, mode="bench_sharded", timeout=900, check=False
        )
        if outs is None:
            raise RuntimeError("bench_sharded cluster spawn timed out")
        for rc, out, err in outs:
            if rc != 0:
                raise RuntimeError(f"bench_sharded worker failed:\n{err[-2000:]}")
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            RUSTPDE_X64="1",
        )
        with open(os.path.join(out_dir, "result.json")) as f:
            r = json.load(f)

        # durability + cross-topology restore gate, in a clean CPU process
        verifier = r"""
import json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D
from rustpde_mpi_tpu.utils import checkpoint as cp

manifest, npz, nx = sys.argv[1], sys.argv[2], int(sys.argv[3])
attrs = cp.verify_snapshot(manifest)          # manifest + all shard digests
model = Navier2D(nx, nx, 1e4, 1.0, 2e-3, 1.0, "rbc", periodic=False)
model.read(manifest)                          # elastic: 2-proc mesh -> serial
dumped = np.load(npz)
equal = all(
    np.array_equal(np.asarray(getattr(model.state, name)), dumped[name])
    for name in model.state._fields
)
print(json.dumps({"verify_ok": True, "restore_equal": bool(equal),
                  "sharded": int(attrs["sharded"])}))
"""
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                verifier,
                r["manifest"],
                os.path.join(out_dir, "final_state.npz"),
                str(r["grid"][0]),
            ],
            capture_output=True,
            text=True,
            env=env,
            timeout=600,
            cwd=_REPO,
        )
        if out.returncode != 0:
            raise RuntimeError(f"sharded verify/restore failed:\n{out.stderr[-2000:]}")
        gate = json.loads(out.stdout.strip().splitlines()[-1])
        ok = bool(gate["verify_ok"] and gate["restore_equal"])
        return {
            # headline rate: sharded checkpoint commits per second
            "steps_per_sec": 1.0 / max(r["sharded_write_s"], 1e-9),
            "unit_note": "steps_per_sec = sharded two-phase commits/s (2-proc CPU)",
            "sharded_write_s": r["sharded_write_s"],
            "gathered_write_s": r["gathered_write_s"],
            "sharded_vs_gathered_x": r["gathered_write_s"] / r["sharded_write_s"],
            "bytes_host": r["bytes_host"],
            "bytes_total": r["bytes_total"],
            "shards": r["shards"],
            "barrier_s": r["barrier_s"],
            "grid": r["grid"],
            "nproc": r["nproc"],
            "manifest_verify_ok": gate["verify_ok"],
            "cross_topology_restore_equal": gate["restore_equal"],
            "finite": ok,
        }
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)


def _serve_fleet_leg(run_dir, timeout_s=900):
    """The serve129 fleet leg (ISSUE 15): 1 stateless proxy + 2 leased
    replicas on CPU over ONE shared durable queue, mixed-priority traffic
    submitted through the proxy, one replica SIGKILLed mid-campaign while
    it holds leases + durable parked continuations.

    Runs on the small 17^2 tier shape on purpose: the leg measures FLEET
    mechanics (lease break -> reclaim latency, per-class admission-to-
    first-observable percentiles, zero-lost / resumed-with-state), not
    step throughput — the single-process soak above already owns that.

    Returns the fleet payload; raises on a broken fleet (the caller
    records the error and degrades the gates to None, like the mp leg)."""
    import signal as _signal
    import subprocess
    import urllib.request

    import numpy as np

    from rustpde_mpi_tpu.serve import DurableQueue
    from rustpde_mpi_tpu.utils.journal import read_journal

    n_req = int(os.environ.get("RUSTPDE_FLEET_BENCH_REQUESTS", "10"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("RUSTPDE_FAULT", None)
    driver = os.path.join(_REPO, "examples", "navier_rbc_fleet.py")
    procs, logs = {}, {}

    def spawn(name, args):
        logs[name] = open(os.path.join(run_dir, f"{name}.log"), "w")
        procs[name] = subprocess.Popen(
            [sys.executable, driver, "--run-dir", run_dir, *args],
            stdout=logs[name], stderr=subprocess.STDOUT, text=True,
            env=env, cwd=_REPO,
        )
        return procs[name]

    def replica_events(rid):
        return read_journal(
            os.path.join(run_dir, "replicas", rid, "journal.jsonl"),
            on_error="skip",
        )

    t_start = time.perf_counter()
    try:
        spawn("proxy", ["--proxy", "--lease-ttl-s", "3"])
        addr, deadline = None, time.time() + 120
        while time.time() < deadline and addr is None:
            time.sleep(0.2)
            try:
                with open(os.path.join(run_dir, "proxy.log")) as fh:
                    for line in fh:
                        if line.startswith("{"):
                            addr = json.loads(line)["address"]
                            break
            except OSError:
                pass
        if not addr:
            raise RuntimeError("fleet proxy never bound")
        base = f"http://{addr[0]}:{addr[1]}"
        common = [
            "--replica", "--daemon", "--lease-ttl-s", "3",
            "--heartbeat-s", "0.2", "--slots", "2", "--chunk-steps", "8",
            "--ckpt-every-s", "1000",
        ]
        spawn("rA", [*common, "--replica-id", "rA"])
        spawn("rB", [*common, "--replica-id", "rB"])

        def post(payload):
            req = urllib.request.Request(
                base + "/requests", data=json.dumps(payload).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())

        classes = ["batch", "best-effort", "interactive"]
        for seed in range(n_req):
            pri = classes[seed % 3]
            body = dict(
                ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01,
                horizon=1.6 + 0.08 * seed, seed=seed, priority=pri,
                tenant=f"t{seed % 2}",
            )
            if pri == "interactive":
                body["deadline_s"] = 120.0
            code, _ = post(body)
            if code != 202:
                raise RuntimeError(f"fleet submit rejected: {code}")

        # SIGKILL whichever replica persisted a mid-flight continuation
        victim, deadline = None, time.time() + timeout_s
        while time.time() < deadline and victim is None:
            time.sleep(0.2)
            for rid in ("rA", "rB"):
                if any(
                    e.get("event") == "continuation_persisted"
                    and e.get("steps", 0) > 0
                    for e in replica_events(rid)
                ):
                    victim = rid
                    break
        if victim is None:
            raise RuntimeError("no mid-flight continuation ever persisted")
        procs[victim].send_signal(_signal.SIGKILL)
        survivor = "rB" if victim == "rA" else "rA"

        queue = DurableQueue(os.path.join(run_dir, "queue"), max_queue=512)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            counts = queue.counts()
            if (
                counts["done"] == n_req
                and counts["queued"] == 0
                and counts["running"] == 0
            ):
                break
            time.sleep(0.5)
        procs[survivor].send_signal(_signal.SIGTERM)
        procs[survivor].wait(timeout=300)
        procs["proxy"].send_signal(_signal.SIGTERM)
        procs["proxy"].wait(timeout=60)

        # per-class admission-to-first-observable percentiles from the
        # done records (each carries priority + the HA gate clock)
        per_class: dict = {}
        done_dir = os.path.join(run_dir, "queue", "done")
        for name in sorted(os.listdir(done_dir)):
            with open(os.path.join(done_dir, name)) as fh:
                res = json.load(fh)["result"]
            per_class.setdefault(res.get("priority", "batch"), []).append(
                res["admission_to_first_observable_s"]
            )
        pct = lambda vals, p: float(
            np.sort(np.asarray(vals))[
                min(len(vals) - 1, int(p / 100 * len(vals)))
            ]
        )
        class_latency = {
            cls: {"count": len(vals), "p50_s": pct(vals, 50), "p99_s": pct(vals, 99)}
            for cls, vals in sorted(per_class.items())
        }

        events = replica_events(survivor)
        breaks = [e for e in events if e.get("event") == "lease_broken"]
        reclaims = [
            e
            for e in events
            if e.get("event") == "lease_claimed"
            and breaks
            and e.get("t", 0) > breaks[0]["t"]
        ]
        resumed = [
            e
            for e in events
            if e.get("event") == "continuation_resumed"
            and e.get("steps", 0) > 0
        ]
        all_events = events + replica_events(victim)
        return {
            "requests": n_req,
            "replicas": 2,
            "proxies": 1,
            "victim": victim,
            "counts": counts,
            "leases_broken": len(breaks),
            "preemptions": sum(
                1 for e in all_events if e.get("event") == "request_preempted"
            ),
            "continuations_persisted": sum(
                1
                for e in all_events
                if e.get("event") == "continuation_persisted"
            ),
            "resumed_mid_flight": len(resumed),
            "lease_break_to_reclaim_s": (
                round(reclaims[0]["t"] - breaks[0]["t"], 3)
                if breaks and reclaims
                else None
            ),
            "class_latency": class_latency,
            "wall_s": round(time.perf_counter() - t_start, 1),
            "zero_lost": counts
            == {"queued": 0, "running": 0, "done": n_req, "failed": 0},
            "reclaimed_with_state": bool(breaks) and bool(resumed),
        }
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for log in logs.values():
            log.close()


def bench_autoscale(timeout_s=1200):
    """autoscale129: the autoscaling-fleet chaos leg (ISSUE 17).

    One standalone controller process (examples/navier_rbc_autoscale.py)
    scales a LocalProcessLauncher replica fleet for a seeded backlog on
    the small 17^2 tier shape while a Poisson schedule preempts its own
    replicas — a notice-SIGTERM + hard-SIGKILL mix, each arrival held
    until its victim provably holds mid-flight parked state so every
    preemption exercises the reclaim-WITH-state path.  Like the serve129
    fleet leg this measures fleet mechanics, not step throughput.

    Gates: zero_lost (every request done, zero failed, nothing stranded
    queued/running), reclaimed_with_state (some replica journaled
    continuation_resumed with steps > 0), preempted (the chaos actually
    fired), and slo_ok (p99 admission-to-first-observable under a CPU-
    tier bound that absorbs replica cold starts: each spawn pays a full
    interpreter + JAX import + first compile before its first chunk).
    Decision/spawn/retire counts come from the controller journal."""
    import shutil
    import subprocess
    import tempfile

    import numpy as np

    from rustpde_mpi_tpu.serve import DurableQueue
    from rustpde_mpi_tpu.utils.journal import read_journal

    n_req = int(os.environ.get("RUSTPDE_AUTOSCALE_BENCH_REQUESTS", "6"))
    run_dir = tempfile.mkdtemp(prefix="bench_autoscale_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("RUSTPDE_FAULT", None)
    t_start = time.perf_counter()
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(_REPO, "examples", "navier_rbc_autoscale.py"),
                "--run-dir", run_dir, "--requests", str(n_req),
                "--seed", "7", "--horizon", "1.5",
                "--min-replicas", "1", "--max-replicas", "2",
                "--queue-high", "1", "--sustain-s", "1",
                "--cooldown-s", "2", "--decide-s", "0.5",
                "--notice-s", "8", "--lease-ttl-s", "3",
                "--heartbeat-s", "0.2", "--chunk-steps", "8",
                "--chaos-preempts", "2", "--chaos-kill-frac", "0.5",
                "--chaos-mean-gap-s", "1",
            ],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=_REPO,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"autoscale driver rc={proc.returncode}: "
                f"{proc.stderr[-1500:]}"
            )
        final = [
            json.loads(line)
            for line in proc.stdout.splitlines()
            if line.startswith("{")
        ][-1]
        wall = time.perf_counter() - t_start

        counts = DurableQueue(
            os.path.join(run_dir, "queue"), max_queue=4 * n_req
        ).counts()
        latencies, completed_steps = [], 0
        done_dir = os.path.join(run_dir, "queue", "done")
        for name in sorted(os.listdir(done_dir)):
            with open(os.path.join(done_dir, name)) as fh:
                res = json.load(fh)["result"]
            latencies.append(res["admission_to_first_observable_s"])
            completed_steps += res["steps"]
        pct = lambda vals, p: float(
            np.sort(np.asarray(vals))[
                min(len(vals) - 1, int(p / 100 * len(vals)))
            ]
        ) if vals else None

        # journals: autoscale_* rows from the controller dir, lifecycle
        # evidence (notice drains, resumed continuations) from every
        # replica dir — autoscaled replica ids are not known a priori
        tallies = {
            "autoscale_decision": 0, "replica_spawned": 0,
            "replica_retired": 0, "preempt_notice": 0,
            "continuation_persisted": 0, "lease_broken": 0,
        }
        resumed = 0
        rroot = os.path.join(run_dir, "replicas")
        for name in sorted(os.listdir(rroot)):
            jpath = os.path.join(rroot, name, "journal.jsonl")
            if not os.path.isfile(jpath):
                continue
            for e in read_journal(jpath, on_error="skip"):
                ev = e.get("event")
                if ev in tallies:
                    tallies[ev] += 1
                if ev == "continuation_resumed" and e.get("steps", 0) > 0:
                    resumed += 1

        # CPU-tier SLO bound: cold replica start (interpreter + JAX import
        # + first compile) dominates; the gate catches requests STARVED by
        # a broken control loop, not steady-state latency
        slo_bound_s = 600.0
        p99 = pct(latencies, 99)
        preempts = final.get("notice", 0) + final.get("kill", 0)
        zero_lost = counts == {
            "queued": 0, "running": 0, "done": n_req, "failed": 0
        }
        return {
            # headline rate: fleet-mechanics leg — completed member-steps
            # over the whole scaled-and-preempted soak wall
            "steps_per_sec": completed_steps / max(wall, 1e-9),
            "unit_note": (
                "steps_per_sec = member-steps/s across the autoscaled "
                "chaos soak (17^2 CPU fleet; mechanics, not throughput)"
            ),
            "requests": n_req,
            "counts": counts,
            "decisions": final.get("decisions", 0),
            "spawned": final.get("spawned", 0),
            "retired": final.get("retired", 0),
            "preempts_notice": final.get("notice", 0),
            "preempts_kill": final.get("kill", 0),
            "preempts_dropped": final.get("dropped", 0),
            "journal": tallies,
            "resumed_mid_flight": resumed,
            "admission_p50_s": pct(latencies, 50),
            "admission_p99_s": p99,
            "slo_bound_s": slo_bound_s,
            "wall_s": round(wall, 1),
            "zero_lost": zero_lost,
            "reclaimed_with_state": resumed > 0,
            "preempted": preempts >= 1,
            "slo_ok": p99 is not None and p99 < slo_bound_s,
            "finite": bool(
                zero_lost and resumed > 0 and preempts >= 1
                and p99 is not None and p99 < slo_bound_s
            ),
        }
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)


def bench_serve_submesh(timeout_s=900):
    """serve_submesh129: the two-level gang-scheduled serving leg (PR 18).

    Mixed traffic on the 2-process CPU harness (tests/mp_worker's
    ``gang_serve`` mode): the fleet's 4 devices are carved into a
    2-device cross-process gang slice serving 34^2 SHARDED requests and
    a 2-device default remainder serving 18^2 vmapped requests, plus an
    in-worker probe that an unservable 259^2 request is a typed
    ``no_submesh`` rejection at the door.  Two runs: a clean BASELINE,
    then a CHAOS pair — one gang member SIGKILLed mid-sharded-chunk
    (``kill@10:gang0member1``: past the second chunk boundary, where the
    two-phase writer has COMMITTED the step-4 cadence checkpoint — a
    kill inside the first deferred-commit window leaves nothing
    restorable and the finisher would replay from scratch), then a clean
    finisher incarnation that re-forms the gang and restores the broken
    gang's surviving trajectory mid-flight from that checkpoint.

    Gates (folded into ``finite``): zero_lost on both runs,
    gang_killed (the fault fired and BOTH ranks exited nonzero —
    fate-sharing, no wedge), gang_reclaimed (typed ``gang_member_lost``
    containment + trajectories restored mid-flight), solo_ok (EVERY
    chaos done record matches an f64 solo serial rerun to rtol 1e-9 —
    both grid classes, including the reclaimed gang trajectories), and
    coresident_ok (no vmapped request swept into the gang containment
    requeue, and the 18^2 bucket's latency p99 within a loose CPU-tier
    factor of baseline: gang death must not stall co-resident
    buckets)."""
    import shutil
    import subprocess
    import tempfile

    import numpy as np

    from rustpde_mpi_tpu.utils.journal import read_journal

    n_gang = int(os.environ.get("RUSTPDE_GANG_BENCH_REQUESTS", "2"))
    n_vmap = max(2, n_gang)
    base_env = {
        "RUSTPDE_MP_GANG_REQUESTS": str(n_gang),
        "RUSTPDE_MP_VMAP_REQUESTS": str(n_vmap),
        "RUSTPDE_MP_SERVE_SLOTS": "2",
        "RUSTPDE_SYNC_TIMEOUT_S": "60",
        "RUSTPDE_DISPATCH_TIMEOUT_S": "60",
        "RUSTPDE_GANG_SYNC_TIMEOUT_S": "30",
        "RUSTPDE_SANITIZE": "1",
    }
    sys.path.insert(0, os.path.join(_REPO, "tests"))
    from mp_harness import spawn_cluster

    n_all = n_gang + n_vmap

    def records_of(out_dir):
        done_dir = os.path.join(out_dir, "serve", "queue", "done")
        recs = []
        for name in sorted(os.listdir(done_dir)):
            with open(os.path.join(done_dir, name)) as fh:
                recs.append(json.load(fh))
        return recs

    def result_of(out_dir):
        with open(os.path.join(out_dir, "result.json")) as fh:
            return json.load(fh)

    def zero_lost(r):
        return r["queue"] == {
            "queued": 0, "running": 0, "done": n_all, "failed": 0
        }

    def vmap_p99(recs):
        lat = sorted(
            r["result"]["latency_s"]
            for r in recs
            if int(r["request"]["nx"]) == 18
        )
        return float(lat[min(len(lat) - 1, int(0.99 * len(lat)))]) if lat else None

    base_dir = tempfile.mkdtemp(prefix="bench_submesh_base_")
    chaos_dir = tempfile.mkdtemp(prefix="bench_submesh_chaos_")
    try:
        # baseline: clean mixed traffic end to end
        t0 = time.perf_counter()
        outs = spawn_cluster(
            base_dir, mode="gang_serve", timeout=timeout_s, check=True,
            env_extra=base_env,
        )
        if outs is None:
            raise RuntimeError("submesh baseline spawn timed out")
        base_wall = time.perf_counter() - t0
        base_r = result_of(base_dir)
        base_recs = records_of(base_dir)
        base_p99 = vmap_p99(base_recs)

        # chaos: gang member 1 SIGKILLed mid-gang-campaign (fate-sharing:
        # both ranks must exit nonzero), then a clean finisher reclaims
        t1 = time.perf_counter()
        outs = spawn_cluster(
            chaos_dir, mode="gang_serve", timeout=timeout_s, check=False,
            env_extra={**base_env, "RUSTPDE_FAULT": "kill@10:gang0member1"},
        )
        if outs is None:
            raise RuntimeError("submesh chaos spawn timed out")
        gang_killed = all(o[0] != 0 for o in outs)
        outs = spawn_cluster(
            chaos_dir, mode="gang_serve", timeout=timeout_s, check=True,
            env_extra=base_env,
        )
        if outs is None:
            raise RuntimeError("submesh finisher spawn timed out")
        chaos_wall = time.perf_counter() - t1
        chaos_r = result_of(chaos_dir)
        chaos_recs = records_of(chaos_dir)
        chaos_p99 = vmap_p99(chaos_recs)

        # solo equivalence (rtol 1e-9) over EVERY chaos done record: f64
        # serial rerun per record in a subprocess (the harness pins
        # RUSTPDE_X64=1, so the solo shadow must match that precision)
        env = dict(os.environ, JAX_PLATFORMS="cpu", RUSTPDE_X64="1")
        env.pop("RUSTPDE_FAULT", None)
        iso_diffs = []
        for rec in chaos_recs:
            req, res = rec["request"], rec["result"]
            code = (
                "from rustpde_mpi_tpu import Navier2D; "
                f"m = Navier2D({req['nx']},{req['ny']},{req['ra']},"
                f"{req['pr']},{res['dt']},1.0,'{req.get('bc') or 'rbc'}',"
                "periodic=False); "
                f"m.init_random({res['amp'] or 0.1}, seed={res['seed']}); "
                f"m.update_n({res['steps']}); print(float(m.eval_nu()))"
            )
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=900, env=env, cwd=_REPO,
            )
            solo = float(out.stdout.strip().splitlines()[-1])
            iso_diffs.append(abs(res["nu"] - solo) / max(abs(solo), 1e-30))

        # containment scope: the gang's requeue rows must reference ONLY
        # the gang bucket — a vmapped id in a gang-tagged requeue means
        # the failure domain leaked into a co-resident bucket
        vmap_ids = {
            r["request"]["id"]
            for r in chaos_recs
            if int(r["request"]["nx"]) == 18
        }
        gang_requeues = [
            e
            for e in read_journal(
                os.path.join(chaos_dir, "serve", "journal.jsonl"),
                on_error="skip",
            )
            if e.get("event") == "request_requeued"
            and e.get("gang") is not None
        ]
        coresident_isolated = not any(
            e.get("id") in vmap_ids for e in gang_requeues
        )
        # loose CPU-tier bound: the chaos pair includes a full restart
        # (interpreter + compile), so the gate catches STALLED co-resident
        # buckets, not steady-state latency drift
        p99_factor = (
            chaos_p99 / base_p99
            if base_p99 and chaos_p99 is not None
            else None
        )
        coresident_ok = bool(
            coresident_isolated
            and p99_factor is not None
            and p99_factor <= 10.0
        )

        completed_steps = sum(r["result"]["steps"] for r in chaos_recs)
        iso_max = max(iso_diffs) if iso_diffs else None
        solo_ok = iso_max is not None and iso_max <= 1e-9
        gang_reclaimed = bool(
            chaos_r["gang_member_lost"] >= 1 and chaos_r["restored_sched"] >= 1
        )
        lost_ok = zero_lost(base_r) and zero_lost(chaos_r)
        return {
            # headline rate: fleet-mechanics leg — completed member-steps
            # over the chaos pair's wall (kill + reclaim + finish)
            "steps_per_sec": completed_steps / max(chaos_wall, 1e-9),
            "unit_note": (
                "steps_per_sec = member-steps/s across the gang-kill "
                "chaos pair (2-proc CPU sub-mesh harness; mechanics, "
                "not throughput)"
            ),
            "requests_gang": n_gang,
            "requests_vmapped": n_vmap,
            "baseline": {
                "wall_s": round(base_wall, 1),
                "gang_formed": base_r["gang_formed"],
                "submesh_rejected": base_r["submesh_rejected"],
                "vmapped_p99_s": base_p99,
            },
            "chaos": {
                "wall_s": round(chaos_wall, 1),
                "gang_formed": chaos_r["gang_formed"],
                "gang_member_lost": chaos_r["gang_member_lost"],
                "requeued": chaos_r["requeued"],
                "restored_mid_trajectory": chaos_r["restored_sched"],
                "vmapped_p99_s": chaos_p99,
            },
            "coresident_p99_factor": p99_factor,
            "solo_rel_err_max": iso_max,
            "zero_lost": lost_ok,
            "gang_killed": gang_killed,
            "gang_reclaimed": gang_reclaimed,
            "solo_ok": solo_ok,
            "coresident_ok": coresident_ok,
            "finite": bool(
                lost_ok
                and gang_killed
                and gang_reclaimed
                and solo_ok
                and coresident_ok
            ),
        }
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)
        shutil.rmtree(chaos_dir, ignore_errors=True)


def bench_coldstart(timeout_s=900):
    """coldstart129: the cold-start elimination leg (PR 19).

    Five subprocess server incarnations on the 17^2 tier shape measure
    the three layers of README "Cold starts" end to end:

    * **cold/cacheless** — RUSTPDE_COMPILE_CACHE=0, a never-seen key:
      the baseline TTFC (campaign open -> first committed chunk) and
      restart-to-first-result every layer is gated against,
    * **prime** — same key with the persistent cache armed (populates
      the shared cache dir),
    * **warm** — a restart against the populated cache PLUS a warm
      profile PLUS canonicalization: the off-rung request snaps into the
      prebuilt bucket and admission -> first chunk crosses ZERO
      compile_build rows (journal-asserted),
    * **drain -> restart -> elastic re-plan** — one run_dir drained
      mid-flight then resumed with a different slot count: the
      recompile counter must stay flat across the whole cycle.

    Gates: zero_jit_warm, ttfc_improved + restart_improved (warm vs
    cold/cacheless), recompile_flat (zero recompile=true rows across
    every leg), parity_ok (canonicalized-vs-direct Nu within the
    documented CanonicalConfig.rtol).  Fleet mechanics, not step
    throughput — the headline rate is member-steps over the whole
    multi-incarnation wall."""
    import shutil
    import subprocess
    import tempfile

    from rustpde_mpi_tpu.config import CanonicalConfig
    from rustpde_mpi_tpu.utils.governor import DtLadder
    from rustpde_mpi_tpu.utils.journal import read_journal

    base = tempfile.mkdtemp(prefix="bench_coldstart_")
    cache = os.path.join(base, "jax_cache")
    profile_path = os.path.join(base, "profile.json")
    # the quick 17^2 compat key AFTER canonicalization: the profile dt
    # must be the LADDER's float for the 9e-3 submit, computed from the
    # same CanonicalConfig defaults the example's --canonicalize arms
    canon = CanonicalConfig()
    ladder = DtLadder(canon.dt_anchor, ratio=canon.ladder_ratio,
                      dt_min=canon.dt_min, dt_max=canon.dt_max)
    dt_canon = float(ladder.dt(ladder.rung_for(9e-3)))
    with open(profile_path, "w") as fh:
        json.dump(
            [{"key": ["dns", 17, 17, 1e4, 1.0, dt_canon, 1.0, "rbc",
                      False, []],
              "k": 2}],
            fh,
        )

    def run(name, *, cache_on, warm=False, canonicalize=False,
            requests=1, slots=2, horizon="0.08", drain_after=None,
            run_dir=None):
        rd = run_dir or os.path.join(base, name)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("RUSTPDE_FAULT", None)
        env["RUSTPDE_COMPILE_CACHE"] = "1" if cache_on else "0"
        env["RUSTPDE_COMPILE_CACHE_DIR"] = cache
        argv = [
            sys.executable,
            os.path.join(_REPO, "examples", "navier_rbc_serve.py"),
            "--quick", "--requests", str(requests), "--slots", str(slots),
            "--dt", "9e-3", "--horizon", horizon, "--run-dir", rd,
        ]
        if warm:
            argv += ["--warm-profile", profile_path]
        if canonicalize:
            argv += ["--canonicalize"]
        if drain_after is not None:
            argv += ["--drain-after-s", str(drain_after)]
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=_REPO,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"coldstart leg {name} rc={proc.returncode}: "
                f"{proc.stderr[-1500:]}"
            )
        return read_journal(os.path.join(rd, "journal.jsonl"),
                            on_error="skip"), rd

    def stamp(rows, event):
        for r in rows:
            if r.get("event") == event:
                return r["t"]
        return None

    def ttfc(rows):
        a, b = stamp(rows, "campaign_start"), stamp(rows, "first_chunk")
        return (b - a) if a is not None and b is not None else None

    def first_result(rows):
        a, b = stamp(rows, "server_start"), stamp(rows, "request_done")
        return (b - a) if a is not None and b is not None else None

    def first_nu(rd):
        done = os.path.join(rd, "queue", "done")
        for name in sorted(os.listdir(done)):
            with open(os.path.join(done, name)) as fh:
                return json.load(fh)["result"]["nu"]
        return None

    t_start = time.perf_counter()
    try:
        cold_rows, cold_dir = run("cold_cacheless", cache_on=False)
        prime_rows, _ = run("prime", cache_on=True, canonicalize=True)
        warm_rows, warm_dir = run(
            "warm", cache_on=True, warm=True, canonicalize=True
        )
        # drain -> restart with a different slot count, one shared run_dir
        cycle_dir = os.path.join(base, "cycle")
        cyc1_rows, _ = run(
            "cycle_drain", cache_on=True, canonicalize=True, requests=2,
            slots=2, horizon="0.6", drain_after=4.0, run_dir=cycle_dir,
        )
        cyc2_rows, _ = run(
            "cycle_replan", cache_on=True, canonicalize=True, requests=0,
            slots=1, run_dir=cycle_dir,
        )
        wall = time.perf_counter() - t_start

        legs = {
            "cold": cold_rows, "prime": prime_rows, "warm": warm_rows,
            "cycle_drain": cyc1_rows, "cycle_replan": cyc2_rows,
        }
        recompiles = sum(
            1
            for rows in legs.values()
            for r in rows
            if r.get("event") == "compile_build" and r.get("recompile")
        )
        warm_builds = [
            r for r in warm_rows if r.get("event") == "compile_build"
        ]
        warm_hits = sum(
            1 for r in warm_rows if r.get("event") == "warm_pool_hit"
        )
        member_steps = sum(
            int(r.get("steps", 0))
            for rows in legs.values()
            for r in rows
            if r.get("event") == "request_done"
        )
        ttfc_cold, ttfc_warm = ttfc(cold_rows), ttfc(warm_rows)
        restart_cold = first_result(cold_rows)
        restart_prime = first_result(prime_rows)
        restart_warm = first_result(warm_rows)
        nu_direct, nu_canon = first_nu(cold_dir), first_nu(warm_dir)
        rtol = CanonicalConfig().rtol
        parity = (
            abs(nu_canon - nu_direct) / max(abs(nu_direct), 1e-12)
            if nu_direct is not None and nu_canon is not None
            else None
        )

        zero_jit_warm = warm_hits >= 1 and not warm_builds
        ttfc_improved = (
            ttfc_cold is not None and ttfc_warm is not None
            and ttfc_warm < ttfc_cold
        )
        restart_improved = (
            restart_cold is not None and restart_warm is not None
            and restart_warm < restart_cold
        )
        recompile_flat = recompiles == 0
        parity_ok = parity is not None and parity <= rtol
        return {
            "steps_per_sec": member_steps / max(wall, 1e-9),
            "unit_note": (
                "steps_per_sec = member-steps/s across all five "
                "incarnations (17^2 CPU; mechanics, not throughput)"
            ),
            "ttfc_cold_s": round(ttfc_cold, 3) if ttfc_cold else None,
            "ttfc_warm_s": round(ttfc_warm, 3) if ttfc_warm else None,
            "restart_to_first_result_cold_s": (
                round(restart_cold, 3) if restart_cold else None
            ),
            "restart_to_first_result_prime_s": (
                round(restart_prime, 3) if restart_prime else None
            ),
            "restart_to_first_result_warm_s": (
                round(restart_warm, 3) if restart_warm else None
            ),
            "warm_pool_hits": warm_hits,
            "warm_leg_compile_builds": len(warm_builds),
            "recompiles": recompiles,
            "canonicalized_parity_rel": (
                round(parity, 6) if parity is not None else None
            ),
            "parity_rtol": rtol,
            "wall_s": round(wall, 1),
            "zero_jit_warm": zero_jit_warm,
            "ttfc_improved": ttfc_improved,
            "restart_improved": restart_improved,
            "recompile_flat": recompile_flat,
            "parity_ok": parity_ok,
            "finite": bool(
                zero_jit_warm and ttfc_improved and restart_improved
                and recompile_flat and parity_ok
            ),
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def bench_serve(nx=129, ny=129, ra=1e7, dt=2e-3, steps_per_req=8):
    """serve129: the simulation-service soak (rustpde_mpi_tpu/serve/).

    Drives RUSTPDE_SERVE_BENCH_REQUESTS (default 200) queued requests
    through 8 continuously-batched ensemble slots across TWO process
    incarnations of examples/navier_rbc_serve.py — phase 1 is
    SIGTERM-drained mid-soak by a ``kill@`` fault (graceful drain:
    sharded slot-table checkpoint + re-enqueue), phase 2 restarts,
    restores the drained slots mid-trajectory, injects a batch-wide NaN
    (``nan@``: every in-flight request retries at dt/2) and drains the
    queue.  The hard-SIGKILL leg lives in the slow-tier chaos soak test
    (tests/test_serve.py) — the bench keeps two phases so its wall stays
    inside the driver budget.

    Reported: aggregate member-steps/s (dispatched work over serve wall,
    retry detours included), completed member-steps, and per-request
    latency percentiles (p50/p90/p99 of submit->resolve).  The red/green
    gate is the robustness contract, not a threshold: every request
    terminally resolved with ZERO lost and ZERO failed, the drain +
    restore + retry events all present in the journal, and a sample of
    results matching SOLO single-model reruns (per-request isolation
    against ground truth)."""
    import shutil
    import subprocess
    import tempfile

    import numpy as np

    from rustpde_mpi_tpu import config
    from rustpde_mpi_tpu.serve import DurableQueue
    from rustpde_mpi_tpu.utils.journal import read_journal

    config.enable_compilation_cache()
    n_req = int(os.environ.get("RUSTPDE_SERVE_BENCH_REQUESTS", "200"))
    horizon = steps_per_req * dt
    run_dir = tempfile.mkdtemp(prefix="bench_serve_")
    env = dict(os.environ)
    env.pop("RUSTPDE_FAULT", None)

    def phase(extra, timeout=1500):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(_REPO, "examples", "navier_rbc_serve.py"),
                "--nx", str(nx), "--ny", str(ny), "--ra", str(ra),
                "--dt", str(dt), "--horizon", str(horizon),
                # staggered horizons (+0..5 steps by seed): completions stop
                # aligning on one boundary, so drains catch work in flight —
                # the continuous-batching shape real mixed traffic has
                "--horizon-jitter", "6",
                "--slots", "8", "--max-queue", str(2 * n_req),
                "--run-dir", run_dir, "--ckpt-every-s", "10",
                *extra,
            ],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=_REPO,
        )
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"serve phase {extra} rc={proc.returncode}: "
                f"{proc.stderr[-1500:]}"
            )
        # the summary shares stdout with checkpoint-restore prints and the
        # per-request result lines: take the json line
        summary = next(
            json.loads(line)
            for line in proc.stdout.splitlines()
            if line.startswith('{"outcome"')
        )
        return summary, wall

    try:
        # phase 1: enqueue all, serve until the kill@ SIGTERM drains — the
        # drain step scales with the workload so a reduced
        # RUSTPDE_SERVE_BENCH_REQUESTS run still drains MID-soak instead
        # of finishing before the fault step is ever reached
        drain_at = max(8, min(3 * steps_per_req, (n_req * steps_per_req) // 16))
        s1, wall1 = phase(
            ["--requests", str(n_req), "--fault", f"kill@{drain_at}"]
        )
        # phase 2: restore the drained slots, NaN the batch mid-soak, finish
        s2, wall2 = phase(["--fault", f"nan@{2 * drain_at}"], timeout=2400)

        q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=2 * n_req)
        counts = q.counts()
        done_dir = os.path.join(run_dir, "queue", "done")
        latencies, completed_steps, sampled = [], 0, []
        for name in sorted(os.listdir(done_dir)):
            with open(os.path.join(done_dir, name)) as fh:
                res = json.load(fh)["result"]
            latencies.append(res["latency_s"])
            completed_steps += res["steps"]
            sampled.append(res)
        events = [
            e.get("event")
            for e in read_journal(os.path.join(run_dir, "journal.jsonl"))
        ]

        # isolation spot-check vs solo ground truth (subprocess: inherits
        # this run's precision mode + compile cache)
        iso_diffs = []
        for res in sampled[:: max(1, len(sampled) // 3)][:3]:
            code = (
                "import os, jax; jax.config.update('jax_platforms', "
                "os.environ.get('JAX_PLATFORMS') or jax.default_backend()); "
                "from rustpde_mpi_tpu import Navier2D; "
                f"m = Navier2D({nx},{ny},{ra},1.0,{res['dt']},1.0,'rbc',periodic=False); "
                f"m.init_random({res['amp'] or 0.1}, seed={res['seed']}); "
                f"m.update_n({res['steps']}); print(float(m.eval_nu()))"
            )
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=900, env=env, cwd=_REPO,
            )
            solo = float(out.stdout.strip().splitlines()[-1])
            iso_diffs.append(abs(res["nu"] - solo) / max(abs(solo), 1e-30))
        iso_tol = 1e-8 if os.environ.get("RUSTPDE_X64") == "1" else 1e-3

        # 2-process CPU leg (reusing tests/mp_harness + mp_worker's
        # serve_campaign mode): a root-coordinated campaign drains under a
        # SIGTERM fault, restarts on a GROWN fleet and completes — the
        # multihost serve path gets a tracked trajectory of drain/replan
        # counters in the BENCH payload, like shardedio129 tracks the
        # two-phase writer.  Best-effort on spawn timeout (recorded null).
        sys.path.insert(0, os.path.join(_REPO, "tests"))
        from mp_harness import spawn_cluster

        mp = None
        mp_dir = tempfile.mkdtemp(prefix="bench_serve_mp_")
        try:
            mp_req = int(os.environ.get("RUSTPDE_SERVE_MP_REQUESTS", "4"))
            mp_base = {
                "RUSTPDE_MP_SERVE_REQUESTS": str(mp_req),
                "RUSTPDE_SYNC_TIMEOUT_S": "60",
                "RUSTPDE_DISPATCH_TIMEOUT_S": "60",
                # collective-sequence sanitizer armed through the whole mp
                # leg (drain + grown-fleet restart): the run only passes if
                # every host executed the identical collective sequence
                "RUSTPDE_SANITIZE": "1",
            }
            t0 = time.perf_counter()
            outs = spawn_cluster(
                mp_dir, mode="serve_campaign", timeout=900, check=True,
                env_extra={**mp_base, "RUSTPDE_MP_SERVE_SLOTS": "2",
                           "RUSTPDE_FAULT": "kill@6"},
            )
            if outs is None:
                raise RuntimeError("serve mp phase-1 spawn timed out")
            outs = spawn_cluster(
                mp_dir, mode="serve_campaign", timeout=900, check=True,
                env_extra={**mp_base, "RUSTPDE_MP_SERVE_SLOTS": "3",
                           "RUSTPDE_FAULT": ""},
            )
            if outs is None:
                raise RuntimeError("serve mp phase-2 spawn timed out")
            mp_wall = time.perf_counter() - t0
            with open(os.path.join(mp_dir, "result.json")) as fh:
                mp_r = json.load(fh)
            mp = {
                "nproc": mp_r["nproc"],
                "requests": mp_req,
                "completed": mp_r["completed"],
                "drains": mp_r["drains"],
                "requeued": mp_r["requeued"],
                "replans": mp_r["replanned"],
                "dt_adjusts": mp_r["dt_adjusts"],
                "restored_mid_trajectory": mp_r["restored_sched"],
                "sanitizer": mp_r.get("sanitizer"),
                "wall_s": round(mp_wall, 1),
                "zero_lost": mp_r["queue"]["queued"] == 0
                and mp_r["queue"]["running"] == 0
                and mp_r["queue"]["failed"] == 0
                and mp_r["queue"]["done"] == mp_req,
                "drained_then_replanned": mp_r["drains"] >= 1
                and mp_r["replanned"] >= 1,
                # armed AND recorded AND zero desync trips across the leg
                "sanitizer_clean": bool(
                    (mp_r.get("sanitizer") or {}).get("enabled")
                    and (mp_r.get("sanitizer") or {}).get("records", 0) > 0
                    and (mp_r.get("sanitizer") or {}).get("desyncs", 1) == 0
                ),
            }
        except Exception as exc:  # noqa: BLE001 — mp leg must not kill the soak
            mp = {"error": f"{type(exc).__name__}: {exc}"}
        finally:
            shutil.rmtree(mp_dir, ignore_errors=True)

        # fleet leg (serve/fleet/): proxy + 2 leased replicas, replica
        # SIGKILL mid-campaign — lease-break/reclaim + per-class latency
        # + zero-lost/resumed-with-state, recorded like the mp leg
        fleet_dir = tempfile.mkdtemp(prefix="bench_serve_fleet_")
        try:
            fleet = _serve_fleet_leg(fleet_dir)
        except Exception as exc:  # noqa: BLE001 — recorded, not fatal
            fleet = {"error": f"{type(exc).__name__}: {exc}"}
        finally:
            shutil.rmtree(fleet_dir, ignore_errors=True)

        # observability attribution (ISSUE 13): the service-root
        # metrics.jsonl (root's force-dump at server stop) carries the
        # admission-to-first-observable histogram and the per-bucket MFU /
        # time-to-first-chunk series of the LAST incarnation; the journal's
        # compile_build rows give cross-incarnation recompile counts
        from rustpde_mpi_tpu.telemetry import read_metrics_jsonl

        journal_rows = read_journal(os.path.join(run_dir, "journal.jsonl"))
        builds_by_key: dict = {}
        for row in journal_rows:
            if row.get("event") == "compile_build":
                tag = row.get("key_tag", "?")
                cur = builds_by_key.setdefault(
                    tag, {"builds": 0, "wall_s_sum": 0.0}
                )
                # phase-stamped rows: only the "build" phase counts a model
                # build (the entry_points remainder row would double-count);
                # walls sum across phases to the true cold cost
                if row.get("phase", "build") == "build":
                    cur["builds"] += 1
                cur["wall_s_sum"] = round(
                    cur["wall_s_sum"] + float(row.get("wall_s", 0.0)), 4
                )
        for cur in builds_by_key.values():
            cur["recompiles"] = cur["builds"] - 1
        obs: dict = {"compile": builds_by_key}
        tel_rows = read_metrics_jsonl(os.path.join(run_dir, "metrics.jsonl"))
        admission_p50 = admission_p99 = None
        if tel_rows:
            snap = tel_rows[-1].get("snapshot", {})

            def series(name):
                return snap.get(name, {}).get("series", [])

            hist = next(
                iter(series("serve_admission_to_first_observable_seconds")),
                None,
            )
            if hist:
                admission_p50 = hist.get("p50")
                admission_p99 = hist.get("p99")
            obs["time_to_first_chunk_s"] = {
                s.get("labels", {}).get("key", "?"): {
                    "count": s.get("count"),
                    "p50": s.get("p50"),
                    "max": s.get("max"),
                }
                for s in series("serve_time_to_first_chunk_seconds")
            }
            obs["bucket_mfu"] = {
                s.get("labels", {}).get("bucket", "?"): s.get("value")
                for s in series("serve_mfu")
            }
            obs["fleet_utilization_final"] = next(
                (s.get("value") for s in series("serve_fleet_utilization")),
                None,
            )
        obs["traces_assembled"] = sum(
            1 for row in journal_rows if row.get("event") == "campaign_trace"
        )

        lat = np.sort(np.asarray(latencies)) if latencies else np.zeros(1)
        pct = lambda p: float(lat[min(len(lat) - 1, int(p / 100 * len(lat)))])
        member_steps = s1.get("member_steps", 0) + s2.get("member_steps", 0)
        serve_wall = s1.get("wall_s", wall1) + s2.get("wall_s", wall2)
        gates = {
            "zero_lost": counts["queued"] == 0 and counts["running"] == 0,
            "all_completed": counts["done"] == n_req,
            "zero_failed": counts["failed"] == 0,
            "drained_mid_soak": s1.get("outcome") == "drained"
            and "request_requeued" in events,
            "restored_mid_trajectory": any(
                e == "request_scheduled" for e in events
            ),
            "nan_retries_fired": "request_retry" in events,
            "isolation_vs_solo": bool(iso_diffs)
            and max(iso_diffs) < iso_tol,
        }
        return {
            # aggregate throughput across the full chaos cycle (dispatched
            # member-steps over serve wall, retry detours + drain included)
            "member_steps_per_sec": member_steps / serve_wall,
            "steps_per_sec": member_steps / serve_wall / 8.0,
            "completed_member_steps": completed_steps,
            "dispatched_member_steps": member_steps,
            "requests": n_req,
            "slots": 8,
            "steps_per_request": steps_per_req,
            "retries": s1.get("retried", 0) + s2.get("retried", 0),
            "latency_p50_s": pct(50),
            "latency_p90_s": pct(90),
            "latency_p99_s": pct(99),
            "latency_mean_s": float(np.mean(lat)),
            # the HA front-door gate metric (log-bucket approximate):
            # durable-queue enqueue to first streamed observable
            "admission_to_first_observable_p50_s": admission_p50,
            "admission_to_first_observable_p99_s": admission_p99,
            # compile/device attribution (ISSUE 13): per-compat-key build
            # walls + cross-incarnation recompiles, time-to-first-chunk,
            # per-bucket MFU, assembled campaign trace files
            "observability": obs,
            "isolation_max_rel_diff": max(iso_diffs) if iso_diffs else None,
            "phase_wall_s": [round(wall1, 1), round(wall2, 1)],
            "multiprocess": mp,
            # the HA fleet payload (replicas spawned, leases broken,
            # preemptions, break->reclaim latency, per-class percentiles)
            "fleet": fleet,
            # mp gates are ENFORCED when the 2-proc leg actually ran; a
            # recorded spawn failure ("error" in mp — e.g. a timeout on a
            # loaded box) degrades to the single-process gates alone, with
            # the error string visible in the payload rather than a
            # silently-red or silently-ignored gate
            "gates": {
                **gates,
                # None = the leg never ran (spawn failure recorded in
                # multiprocess.error) — distinct from a red False, which
                # only a leg that RAN can produce (and which fails finite)
                "mp_zero_lost": (
                    None if "error" in mp else bool(mp.get("zero_lost"))
                ),
                "mp_drained_then_replanned": (
                    None
                    if "error" in mp
                    else bool(mp.get("drained_then_replanned"))
                ),
                "mp_sanitizer_clean": (
                    None if "error" in mp else bool(mp.get("sanitizer_clean"))
                ),
                # fleet gates: None when the leg never ran (error recorded
                # in the fleet payload), red False only from a leg that RAN
                "fleet_zero_lost": (
                    None if "error" in fleet else bool(fleet.get("zero_lost"))
                ),
                "fleet_reclaimed_with_state": (
                    None
                    if "error" in fleet
                    else bool(fleet.get("reclaimed_with_state"))
                ),
            },
            "finite": all(gates.values())
            and (
                "error" in mp
                or bool(
                    mp.get("zero_lost")
                    and mp.get("drained_then_replanned")
                    and mp.get("sanitizer_clean")
                )
            )
            and (
                "error" in fleet
                or bool(
                    fleet.get("zero_lost") and fleet.get("reclaimed_with_state")
                )
            ),
        }
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)


def bench_pallasconv(steps=8):
    """Fused Pallas convection chain vs the unfused dense chain
    (RUSTPDE_CONV_KERNEL knob, ops/pallas_conv.py): ms/step, MFU and
    bit-tolerance deltas per grid.  The ``stepkernel`` leg runs the same
    A/B for the implicit half (RUSTPDE_STEP_KERNEL, ops/pallas_step.py:
    fused Helmholtz/Poisson solves + projection) and records the analytic
    HBM-bytes-per-step estimate both ways.

    Off-TPU the kernel runs in interpreter mode, so the ms/step numbers
    measure plumbing, not the chip — the honest speed A/B lands when a TPU
    is attached (the flagship rows 1025^2/2049^2/periodic1024 auto-enable
    there).  The gates that hold everywhere: parity within the documented
    tolerance (f64 1e-10, f32 1e-3 relative after 8 steps) and
    ``recompile_count`` FLAT across kernel-knob flips on live models (the
    knob binds at model build, never mid-run)."""
    import jax
    import numpy as np

    from rustpde_mpi_tpu import Navier2D, config
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps, mfu_estimate

    config.enable_compilation_cache()
    on_chip = jax.devices()[0].platform in ("tpu", "axon")
    cases = [("rbc129", dict(nx=129, ny=129, ra=1e7, dt=2e-3, periodic=False))]
    if on_chip:
        cases += [
            ("rbc1025", dict(nx=1025, ny=1025, ra=1e9, dt=1e-4, periodic=False)),
            ("rbc2049", dict(nx=2049, ny=2049, ra=1e9, dt=5e-5, periodic=False)),
            ("periodic1024", dict(nx=1024, ny=1025, ra=1e9, dt=1e-4, periodic=True)),
        ]
    parity_tol = 1e-10 if config.X64 else 1e-3
    prev_knob = os.environ.get("RUSTPDE_CONV_KERNEL")
    prev_step = os.environ.get("RUSTPDE_STEP_KERNEL")
    res = {"configs": {}, "interpret_mode": not on_chip, "parity_tol": parity_tol}
    ok = True
    try:
        for name, c in cases:
            ctor = Navier2D.new_periodic if c["periodic"] else Navier2D.new_confined

            def build(kernel, c=c, ctor=ctor):
                os.environ["RUSTPDE_CONV_KERNEL"] = kernel
                m = ctor(c["nx"], c["ny"], c["ra"], 1.0, c["dt"], 1.0, "rbc")
                m.set_velocity(0.1, 2.0, 2.0)
                m.set_temperature(0.1, 2.0, 2.0)
                return m

            row = {}
            for kernel in ("dense", "pallas"):
                m = build(kernel)
                if kernel == "pallas" and m._conv_impl is None:
                    raise RuntimeError("pallas conv kernels were not selected")
                r = benchmark_steps(m, steps)
                row[kernel] = {
                    "ms_per_step": r["ms_per_step"],
                    "steps_per_sec": r["steps_per_sec"],
                    "mfu": mfu_estimate(m, r["steps_per_sec"])["mfu"],
                }
                if kernel == "pallas":
                    live_pallas = m
            row["speedup_x"] = (
                row["dense"]["ms_per_step"] / row["pallas"]["ms_per_step"]
            )
            # bit-tolerance leg: fresh models, identical IC, 8 steps.  Each
            # leaf's deviation is normalized by the larger of its own scale
            # and the physical-field scale: the pseudo-pressure is ~zero at
            # near-incompressibility, so its own max is roundoff noise, not
            # a meaningful denominator
            d2, p2 = build("dense"), build("pallas")
            d2.update_n(8)
            p2.update_n(8)
            field_scale = max(
                float(np.abs(np.asarray(b)).max())
                for b in (d2.state.temp, d2.state.velx, d2.state.vely)
            )
            rel = 0.0
            for a, b in zip(p2.state, d2.state):
                a, b = np.asarray(a), np.asarray(b)
                scale = max(float(np.abs(b).max()), field_scale, 1e-30)
                rel = max(rel, float(np.abs(a - b).max() / scale))
            row["parity_max_rel"] = rel
            nu_d, nu_p = d2.eval_nu(), p2.eval_nu()
            row["nu_rel"] = abs(nu_p - nu_d) / max(1e-12, abs(nu_d))
            row["parity_ok"] = bool(
                rel < parity_tol and row["nu_rel"] < parity_tol
            )
            # knob flips must not leak recompiles into live models
            os.environ["RUSTPDE_CONV_KERNEL"] = "dense"
            before = (live_pallas.recompile_count, d2.recompile_count)
            live_pallas.update_n(4)
            os.environ["RUSTPDE_CONV_KERNEL"] = "pallas"
            d2.update_n(4)
            row["recompile_flat"] = bool(
                (live_pallas.recompile_count, d2.recompile_count) == before
            )
            ok = ok and row["parity_ok"] and row["recompile_flat"]
            res["configs"][name] = row

        # -- stepkernel leg: fused Helmholtz/Poisson solves + projection
        # (RUSTPDE_STEP_KERNEL, ops/pallas_step.py) vs the dense solver
        # chain — the implicit half of the step joining the fused path.
        # Same gates as the conv leg (parity floored by the physical field
        # scale, recompile_count flat across live-model knob flips), plus
        # the analytic HBM-bytes-per-step estimate both ways (the quantity
        # the megakernel exists to shrink; BASELINE.md traffic table).
        from rustpde_mpi_tpu.ops.pallas_step import step_traffic_estimate

        os.environ["RUSTPDE_CONV_KERNEL"] = "dense"  # isolate the step knob
        res["stepkernel"] = {}
        for name, c in cases:
            ctor = Navier2D.new_periodic if c["periodic"] else Navier2D.new_confined

            def build(kernel, c=c, ctor=ctor):
                os.environ["RUSTPDE_STEP_KERNEL"] = kernel
                m = ctor(c["nx"], c["ny"], c["ra"], 1.0, c["dt"], 1.0, "rbc")
                m.set_velocity(0.1, 2.0, 2.0)
                m.set_temperature(0.1, 2.0, 2.0)
                return m

            row = {}
            for kernel in ("dense", "pallas"):
                m = build(kernel)
                if kernel == "pallas":
                    if m._step_impl is None:
                        raise RuntimeError("pallas step kernels were not selected")
                    live_pallas = m
                    row["hbm_traffic"] = step_traffic_estimate(m)
                r = benchmark_steps(m, steps)
                row[kernel] = {
                    "ms_per_step": r["ms_per_step"],
                    "steps_per_sec": r["steps_per_sec"],
                    "mfu": mfu_estimate(m, r["steps_per_sec"])["mfu"],
                }
            row["speedup_x"] = (
                row["dense"]["ms_per_step"] / row["pallas"]["ms_per_step"]
            )
            d2, p2 = build("dense"), build("pallas")
            d2.update_n(8)
            p2.update_n(8)
            field_scale = max(
                float(np.abs(np.asarray(b)).max())
                for b in (d2.state.temp, d2.state.velx, d2.state.vely)
            )
            rel = 0.0
            for a, b in zip(p2.state, d2.state):
                a, b = np.asarray(a), np.asarray(b)
                scale = max(float(np.abs(b).max()), field_scale, 1e-30)
                rel = max(rel, float(np.abs(a - b).max() / scale))
            row["parity_max_rel"] = rel
            nu_d, nu_p = d2.eval_nu(), p2.eval_nu()
            row["nu_rel"] = abs(nu_p - nu_d) / max(1e-12, abs(nu_d))
            row["parity_ok"] = bool(
                rel < parity_tol and row["nu_rel"] < parity_tol
            )
            os.environ["RUSTPDE_STEP_KERNEL"] = "dense"
            before = (live_pallas.recompile_count, d2.recompile_count)
            live_pallas.update_n(4)
            os.environ["RUSTPDE_STEP_KERNEL"] = "pallas"
            d2.update_n(4)
            row["recompile_flat"] = bool(
                (live_pallas.recompile_count, d2.recompile_count) == before
            )
            ok = ok and row["parity_ok"] and row["recompile_flat"]
            res["stepkernel"][name] = row
    finally:
        for knob, prev in (
            ("RUSTPDE_CONV_KERNEL", prev_knob),
            ("RUSTPDE_STEP_KERNEL", prev_step),
        ):
            if prev is None:
                os.environ.pop(knob, None)
            else:
                os.environ[knob] = prev
    head = res["configs"]["rbc129"]
    res["steps_per_sec"] = head["pallas"]["steps_per_sec"]
    res["ms_per_step"] = head["pallas"]["ms_per_step"]
    res["mfu"] = {"mfu": head["pallas"]["mfu"]}
    res["speedup_x"] = head["speedup_x"]
    res["parity_max_rel"] = max(
        r["parity_max_rel"] for r in res["configs"].values()
    )
    sk = res["stepkernel"]["rbc129"]
    res["stepkernel_speedup_x"] = sk["speedup_x"]
    res["stepkernel_parity_max_rel"] = max(
        r["parity_max_rel"] for r in res["stepkernel"].values()
    )
    res["hbm_traffic_ratio"] = sk["hbm_traffic"]["traffic_ratio"]
    res["finite"] = bool(ok)
    return res


def bench_bandedsolve(repeats=None):
    """Banded-substitution micro-bench (ops/pallas_banded.bench_banded_paths,
    referenced by the module docstring and solver.py but previously not in
    the driver): sec/solve for the lane-parallel Pallas recurrence vs the
    dense-inverse GEMM vs the lax.scan substitution at the ADI solver's
    flagship shape (1023 rows x 1025 lanes).  Off-TPU the Pallas path runs
    in interpreter mode, so the recorded row keeps BASELINE.md's
    dense-inverse-vs-recurrence crossover claim reproducible per PR; the
    chip-honest crossover lands with the on-chip capture."""
    import jax

    from rustpde_mpi_tpu.ops.pallas_banded import bench_banded_paths

    on_chip = jax.devices()[0].platform in ("tpu", "axon")
    if repeats is None:
        repeats = 50 if on_chip else 5
    r = bench_banded_paths(repeats=repeats)
    return {
        "sec_per_solve": r,
        "solves_per_sec": 1.0 / r["dense_gemm"],
        "pallas_vs_dense_x": r["dense_gemm"] / r["pallas"],
        "scan_vs_dense_x": r["dense_gemm"] / r["banded_scan"],
        "interpret_mode": not on_chip,
        "repeats": repeats,
        "finite": all(v > 0.0 and v == v for v in r.values()),
    }


def bench_resilience(nx, ny, ra, dt, steps):
    """Recovery-overhead config (utils/resilience.py): the same horizon run
    twice — once clean (plain ``integrate``), once under a
    ``ResilientRunner`` with a NaN fault injected at the midpoint, which
    forces anchor-checkpoint rollback + dt-backoff (solver rebuild +
    re-jit) + a full retry at dt/2.  ``recovery_overhead_x`` is the honest
    price of surviving a divergence (~2.5x stepping work + checkpoint IO +
    the dt/2 recompile); the red/green gate is recovery integrity: the
    faulted run must reach max_time with exactly one retry, a journaled
    rollback, and finite Nu."""
    import json as _json
    import shutil
    import tempfile

    import numpy as np

    from rustpde_mpi_tpu import Navier2D, ResilientRunner, config, integrate

    config.enable_compilation_cache()

    def build(dt_):
        model = Navier2D(nx, ny, ra, 1.0, dt_, 1.0, "rbc", periodic=False)
        model.set_velocity(0.1, 2.0, 2.0)
        model.set_temperature(0.1, 2.0, 2.0)
        model.write_intervall = 1e9  # no flow-snapshot churn inside the bench
        return model

    max_time = steps * dt
    model = build(dt)
    t0 = time.perf_counter()
    integrate(model, max_time, None)
    clean_s = time.perf_counter() - t0

    run_dir = tempfile.mkdtemp(prefix="bench_resilience_")
    try:
        runner = ResilientRunner(
            build(dt),
            max_time,
            None,
            run_dir=run_dir,
            checkpoint_every_s=None,
            max_retries=1,
            dt_backoff=0.5,
            fault=f"nan@{steps // 2}",
        )
        t0 = time.perf_counter()
        summary = runner.run()
        faulted_s = time.perf_counter() - t0
        with open(runner.journal_path, encoding="utf-8") as fh:
            events = [_json.loads(line)["event"] for line in fh]
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)

    nu = summary["nu"]
    recovered = bool(
        summary["outcome"] == "done"
        and summary["retries"] == 1
        and "retry" in events
        and nu is not None
        and np.isfinite(nu)
    )
    return {
        # effective forward progress including the recovery detour
        "steps_per_sec": steps / faulted_s,
        "clean_steps_per_sec": steps / clean_s,
        "recovery_overhead_x": faulted_s / clean_s,
        "retries": summary["retries"],
        "final_dt": summary["dt"],
        "nu": nu,
        "steps": steps,
        "finite": recovered,
    }


def bench_workloads(nx=129, ny=129, ra=1e7, dt=2e-3, steps=16, k=4):
    """workloads129: the multi-model campaign subsystem
    (rustpde_mpi_tpu/workloads/ + models/campaign.py).

    Per registered model kind (dns / lnse / adjoint) a K-member vmapped
    ensemble is slope-timed at 129^2 — ``member_steps_per_sec`` per kind is
    the serving-capacity number for mixed-model campaigns.  Red/green
    gates: (1) per-kind solo-vs-ensemble parity at the 17^2 probe shape
    below 1e-9 relative (the PARITY.json drift probe), (2) the lnse
    eigenmode machinery puts the growth-rate SIGN on the right side of
    onset (decay at Ra=800, growth at Ra=4000 — the full Ra_c=1707.76 gate
    lives in the slow test tier)."""
    import numpy as np

    from rustpde_mpi_tpu import config
    from rustpde_mpi_tpu.models.ensemble import NavierEnsemble
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps
    from rustpde_mpi_tpu.workloads import (
        build_model,
        eigenmode_sweep,
        model_kinds,
        solo_ensemble_parity,
    )

    config.enable_compilation_cache()
    rates = {}
    for kind in model_kinds():
        kdt = 5e-3 if kind == "adjoint" else dt
        model = build_model(kind, nx, ny, ra, 1.0, kdt, 1.0, "rbc", False)
        members = []
        for seed in range(k):
            if kind == "adjoint":
                model.set_temperature(0.3 + 0.05 * seed, 1.0, 1.0)
                model.set_velocity(0.3 + 0.05 * seed, 1.0, 1.0)
            else:
                model.init_random(1e-2 if kind == "dns" else 1e-4, seed=seed)
            members.append(model.state)
        ens = NavierEnsemble(model, members)
        res = benchmark_steps(ens, steps=steps, warmup=4)
        rates[kind] = {
            "member_steps_per_sec": res["member_steps_per_sec"],
            "ms_per_member_step": res["ms_per_member_step"],
            "k": k,
        }

    parity = solo_ensemble_parity(steps=6)
    parity_ok = all(row["max_rel_diff"] < 1e-9 for row in parity.values())

    sweep = eigenmode_sweep(
        [800.0, 4000.0], nx=8, ny=17, dt=0.05, horizon=12.0, samples=6,
        run_dir=None, checkpoint_every_s=None,
    )
    sigma_lo, sigma_hi = sweep[0]["sigma_max"], sweep[1]["sigma_max"]
    onset_ok = bool(
        np.isfinite([sigma_lo, sigma_hi]).all() and sigma_lo < 0.0 < sigma_hi
    )

    return {
        # headline rate: the DNS kind (comparable to ensemble129)
        "steps_per_sec": rates["dns"]["member_steps_per_sec"] / k,
        "member_steps_per_sec": rates["dns"]["member_steps_per_sec"],
        "kinds": rates,
        "parity": parity,
        "parity_ok": parity_ok,
        "onset_sigma": {"ra800": sigma_lo, "ra4000": sigma_hi},
        "onset_sign_ok": onset_ok,
        "steps": steps,
        "finite": bool(parity_ok and onset_ok),
    }


def _read_prev():
    """(platform, results) from BENCH_FULL.json, (None, {}) if absent/corrupt
    — the single reader shared by the degraded emitter, the cpu-fallback
    guard, and main()'s merge logic."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            prev = json.load(f)
        results = prev.get("results")
        if isinstance(results, dict):
            return prev.get("platform"), results
    except (OSError, ValueError):
        pass
    return None, {}


def _emit_degraded(reason: str, detail: str = "") -> int:
    """Emit the final JSON line from the last recorded matrix when the TPU
    backend is unavailable (VERDICT r4 weak #2: an outage must degrade the
    record, not blank it).  Every config is marked stale; the payload carries
    an explicit ``tpu_unavailable`` flag so the driver's record stays
    parseable and honest."""
    platform, prev_results = _read_prev()
    primary = prev_results.get(PRIMARY, {})
    value = primary.get("steps_per_sec", 0.0) or 0.0
    payload = {
        "metric": _metric_string(
            PRIMARY,
            "steps/s",
            False,
            platform or "unknown",
            "; STALE — TPU backend unavailable",
        ),
        "value": round(float(value), 3),
        "unit": "steps/s",
        "vs_baseline": round(float(value) / CPU_BASELINE_STEPS_PER_SEC, 2),
        "tpu_unavailable": True,
        "degraded_reason": reason,
        "degraded_detail": detail[-400:],
        "shadow_drift_f32_vs_f64": {"evaluated": False, "reason": reason},
        "configs": {
            k: dict(v, stale=True)
            for k, v in prev_results.items()
            if isinstance(v, dict)
        },
    }
    print(json.dumps(payload))
    return 0


# connection-shaped failure signatures ONLY: a crash whose traceback merely
# *mentions* the backend (device OOM, a shape bug raised through the plugin)
# must stay red — these markers are the strings a dead/unreachable relay
# produces, not strings any on-device failure would
_OUTAGE_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "failed to connect",
    "Connection refused",
    "Connection reset",
    "Unable to initialize backend",
    "not in the list of known backends",
)


def _find_payload_line(text: str) -> str | None:
    """Last line of ``text`` that parses as a payload dict (has "metric")."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return line
    return None


def _payload_gates_ok(payload: dict) -> bool:
    """Re-derive main()'s ok flag from an emitted payload line.

    Used when the child printed its final line but then hung in teardown
    (TPU-client shutdown through a dead relay): the child's exit code is
    lost, so a green exit must be re-earned from the recorded gate fields —
    a failed-then-hung run must not read green (ADVICE r5)."""
    shadow = payload.get("shadow_drift_f32_vs_f64") or {}
    if shadow.get("evaluated") and not shadow.get("passed"):
        return False
    for name, row in (payload.get("configs") or {}).items():
        if not isinstance(row, dict) or row.get("stale"):
            continue  # stale rows were gated by the run that produced them
        if "error" in row or row.get("finite") is False:
            return False
        # denan() stores NaN max_error as None — treat missing/None as failed
        max_error = row.get("max_error", 1.0)
        if max_error is None:
            max_error = 1.0
        if name == "poisson1025" and not max_error < 1e-2:
            return False
        if name == "poisson1025_f64" and not max_error < 1e-6:
            return False
    return (payload.get("value") or 0) > 0


def _supervise() -> int:
    """Run the bench matrix in a child process behind a backend probe and a
    wall timeout, so a relay outage — whether the backend init *raises* (the
    r4 bench failure) or *hangs* (the r4 dryrun failure) — still yields one
    parseable JSON line with rc=0 instead of a traceback or a driver
    timeout."""
    import subprocess

    probe_timeout = float(os.environ.get("RUSTPDE_BENCH_PROBE_TIMEOUT_S", "150"))
    try:
        # honor an explicit JAX_PLATFORMS=cpu (sitecustomize force-registers
        # the axon platform programmatically, so the env var alone is not
        # enough — same dance as tests/conftest.py)
        probe = subprocess.run(
            [
                sys.executable,
                "-c",
                "import os, jax; "
                "os.environ.get('JAX_PLATFORMS') == 'cpu' and "
                "jax.config.update('jax_platforms', 'cpu'); "
                "print('PLATFORM:' + jax.devices()[0].platform)",
            ],
            capture_output=True,
            text=True,
            timeout=probe_timeout,
            cwd=_REPO,
        )
    except subprocess.TimeoutExpired:
        return _emit_degraded(
            "tpu_backend_probe_hang",
            f"jax.devices() did not return within {probe_timeout:.0f}s "
            "(axon relay outage: backend init hangs instead of raising)",
        )
    if probe.returncode != 0 or "PLATFORM:" not in probe.stdout:
        return _emit_degraded(
            "tpu_backend_init_failed", (probe.stderr or probe.stdout).strip()
        )
    platform = probe.stdout.strip().splitlines()[-1].split("PLATFORM:")[-1]
    # guard against a *silent* CPU fallback (TPU plugin init failing
    # non-fatally): a cpu-platform run must never clobber a recorded
    # TPU matrix — main() keys prev_results on the platform, so letting it
    # proceed would erase the record _emit_degraded depends on
    if platform == "cpu" and os.environ.get("RUSTPDE_BENCH_ALLOW_CPU") != "1":
        prev_platform, _ = _read_prev()
        if prev_platform not in (None, "cpu"):
            return _emit_degraded(
                "tpu_backend_fell_back_to_cpu",
                f"probe reports platform=cpu but the recorded matrix is "
                f"{prev_platform}; set RUSTPDE_BENCH_ALLOW_CPU=1 to bench "
                "on CPU anyway",
            )

    budget = float(os.environ.get("RUSTPDE_BENCH_BUDGET_S", "560"))
    slack = float(os.environ.get("RUSTPDE_BENCH_SLACK_S", "420"))
    env = dict(os.environ, RUSTPDE_BENCH_CHILD="1")
    try:
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            timeout=budget + slack,
            env=env,
            cwd=_REPO,
        )
        child_out, child_err, child_rc = child.stdout, child.stderr, child.returncode
    except subprocess.TimeoutExpired as exc:
        out, err = exc.stdout, exc.stderr
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        sys.stderr.write(err or "")
        # a fresh payload the child printed before hanging (e.g. in TPU-client
        # teardown through a dead relay) beats a stale degraded line — but the
        # hang ate the child's exit code, so the gates are re-derived from the
        # payload itself and the hang is tagged: a failed-then-hung run must
        # not read green
        line = _find_payload_line(out)
        if line is not None:
            payload = json.loads(line)
            # tag the hang without erasing a degradation the child already
            # recorded (e.g. its own backend-init fallback): the original
            # failure cause must survive into the driver's record
            if "degraded_reason" in payload:
                payload["teardown_hang"] = True
            else:
                payload["degraded_reason"] = "teardown_hang"
            print(json.dumps(payload))
            return 0 if _payload_gates_ok(payload) else 1
        return _emit_degraded(
            "bench_timeout",
            f"matrix run exceeded budget+slack ({budget + slack:.0f}s); "
            "mid-run relay hang suspected",
        )
    sys.stderr.write(child_err or "")
    # pass a valid payload line through verbatim, preserving the child's rc
    # (a genuine gate failure must stay red)
    line = _find_payload_line(child_out)
    if line is not None:
        print(line)
        return child_rc
    # child died without emitting the line: outage-shaped tracebacks (which
    # land on stderr) degrade to rc=0, anything else stays red (but parseable)
    detail = ((child_out or "") + "\n" + (child_err or "")).strip()
    outage = any(m in detail for m in _OUTAGE_MARKERS)
    rc = _emit_degraded(
        "bench_crashed_outage" if outage else "bench_crashed", detail
    )
    return rc if outage else (child_rc or 1)


def main() -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    sel = os.environ.get("RUSTPDE_BENCH_CONFIGS", "all")
    names = DEFAULT_CONFIGS if sel == "all" else [s.strip() for s in sel.split(",")]
    steps = int(os.environ.get("RUSTPDE_BENCH_STEPS", "64"))

    # wall budget: stop starting new configs once exceeded so the JSON line
    # is always emitted even under an external timeout; completed configs
    # merge into BENCH_FULL.json.  To keep the whole matrix fresh across
    # budgeted runs, the non-primary configs run least-recently-measured
    # first (per-entry 'seq' counters persisted in BENCH_FULL.json) — each
    # run picks up where the previous one was cut off.
    # default sized so the primary + its f64 drift anchor (the pinned-first
    # pair, ~430 s measured together) both fit in one run; later configs
    # start only if their last recorded wall time also fits
    budget = float(os.environ.get("RUSTPDE_BENCH_BUDGET_S", "560"))
    bench_start = time.perf_counter()

    prev_platform, prev_results = _read_prev()
    if prev_platform != platform:
        prev_results = {}
    seq = 1 + max(
        (v.get("seq", 0) for v in prev_results.values() if isinstance(v, dict)),
        default=0,
    )
    if sel == "all":
        pinned = [n for n in PINNED if n in names]
        tail = sorted(
            (n for n in names if n not in pinned),
            key=lambda n: prev_results.get(n, {}).get("seq", 0),
        )
        names = pinned + tail

    results: dict[str, dict] = {}
    skipped_for_budget: list[str] = []
    # starvation guard (ISSUE 4 satellite): the seq rotation keeps skips
    # fair, but a config whose last recorded wall no longer fits the budget
    # would be skipped forever in silence.  Count CONSECUTIVE budget skips
    # per config (persisted in BENCH_FULL.json, reset by any fresh
    # measurement) and fail the run once one crosses the limit.
    starve_limit = int(os.environ.get("RUSTPDE_BENCH_STARVE_LIMIT", "3"))
    starved_configs: dict[str, int] = {}
    ok = True
    for name in names:
        # gate on the *estimated completion* (elapsed + this config's last
        # recorded wall, default 120 s) so a run never starts a config that
        # would overshoot the budget — an external driver timeout near the
        # budget must still see the final JSON line
        est = prev_results.get(name, {}).get("bench_wall_s", 120.0) or 120.0
        if results and time.perf_counter() - bench_start + est > budget:
            print(
                f"# budget {budget:.0f}s would be exceeded (~{est:.0f}s for "
                f"{name}); skipping",
                file=sys.stderr,
            )
            skipped_for_budget.append(name)
            prev_entry = prev_results.get(name, {})
            starved_configs[name] = (
                int(prev_entry.get("starved_runs", 0)) + 1
                if isinstance(prev_entry, dict)
                else 1
            )
            continue
        t0 = time.perf_counter()
        try:
            if name == "rbc129":
                # small configs need a longer timed window: 64 steps is an
                # ~100 ms measurement through the relay, dominated by noise
                r = bench_navier(129, 129, 1e7, 2e-3, max(steps, 256))
            elif name == "ensemble129":
                # short window: at K=32 each timed step is 32 member-steps,
                # and the slope timing cancels the dispatch overhead anyway
                r = bench_ensemble(129, 129, 1e7, 2e-3, max(8, steps // 4))
            elif name == "resilience129":
                # the faulted leg re-runs the horizon at dt/2 (~2.5x the
                # stepping work) plus a recompile, so the window is capped
                # regardless of RUSTPDE_BENCH_STEPS
                r = bench_resilience(129, 129, 1e7, 2e-3, max(32, min(steps, 128)))
            elif name == "pipeline129":
                # two full horizons with a checkpoint every boundary; capped
                # like resilience129 so the doubled run fits the budget
                r = bench_pipeline(129, 129, 1e7, 2e-3, max(32, min(steps, 128)))
            elif name == "shardedio129":
                # 2-process CPU cluster (durability harness, chip-independent)
                r = bench_sharded_io()
            elif name == "serve129":
                # simulation-service soak: 200 requests through 8 slots in
                # subprocess incarnations (drain + NaN chaos cycle)
                r = bench_serve()
            elif name == "autoscale129":
                # autoscaled fleet under Poisson preemptions (ISSUE 17):
                # controller + launcher chaos leg, fleet mechanics gates
                r = bench_autoscale()
            elif name == "serve_submesh129":
                # gang-scheduled sub-mesh serving (PR 18): mixed sharded +
                # vmapped traffic, gang-kill chaos pair vs clean baseline
                r = bench_serve_submesh()
            elif name == "coldstart129":
                # cold-start elimination (PR 19): cache/warm-pool/
                # canonicalization legs, zero-jit warm admission gate
                r = bench_coldstart()
            elif name == "workloads129":
                # multi-model campaign rates (dns/lnse/adjoint) + the
                # parity and onset-sign gates
                r = bench_workloads(steps=max(8, min(steps, 32)))
            elif name == "pallasconv":
                # fused-vs-dense convection A/B: parity + recompile gates
                # everywhere, speed/MFU deltas honest only on-chip
                r = bench_pallasconv(steps=max(8, min(steps, 16)))
            elif name == "bandedsolve":
                # banded-path micro-bench: sec/solve per path at the ADI
                # solver's flagship shape (crossover claim, BASELINE.md)
                r = bench_bandedsolve()
            elif name == "stats129":
                # matched governed windows, stats-on vs stats-off; the
                # window is capped so the doubled run fits the budget
                r = bench_stats(129, 129, 1e7, 2e-3, max(32, min(steps, 64)))
            elif name == "integrity129":
                # digests-on vs off matched windows + the injected-bitflip
                # detection pair; capped like stats129 (four runs total)
                r = bench_integrity(129, 129, 1e7, 2e-3, max(32, min(steps, 64)))
            elif name == "governor129":
                # overhead leg slope-times two chains; the spike legs rerun
                # a capped horizon (governed: at the descended-ladder dt)
                r = bench_governor(129, 129, 1e7, 2e-3, max(32, min(steps, 64)))
            elif name in ("rbc129_f64", "rbc1025_f64", "rbc2049_f64", "poisson1025_f64"):
                env = dict(os.environ, RUSTPDE_X64="1")
                import subprocess

                if name == "rbc129_f64":
                    call = f"bench.bench_navier(129,129,1e7,2e-3,{max(steps, 256)})"
                elif name == "rbc2049_f64":
                    # f64 record at the flagship size; minimal window (L=4 /
                    # 4L=16: ~84 steps x 250 ms ≈ 21 s of stepping) — at 4
                    # steps/s the old L=8 window made this config eat the
                    # whole driver budget (523 s, VERDICT r4 next #4); the
                    # slope timing keeps the short window honest
                    call = "bench.bench_navier(2049,2049,1e9,5e-5,4)"
                elif name == "poisson1025_f64":
                    # BASELINE config #3's accuracy number (8.1e-8 expected):
                    # the f64 error belongs in the driver-visible matrix, not
                    # a BASELINE.md footnote (VERDICT r3 weak #7)
                    call = "bench.bench_poisson(1025, solves=8)"
                else:
                    # same ctor/seed as rbc1025; writes the f64 shadow state
                    # for the short-horizon gate.  Windows are short (f64 runs
                    # ~10x slower) — the slope timing makes them comparable.
                    call = (
                        "bench.bench_navier(1025,1025,1e9,1e-4,16,"
                        f"shadow_path={_shadow_path('f64')!r})"
                    )
                code = f"import bench, json; print(json.dumps({call}))"
                out = subprocess.run(
                    [sys.executable, "-c", code],
                    capture_output=True, text=True, env=env, timeout=1800,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                r = json.loads(out.stdout.strip().splitlines()[-1])
            elif name == "periodic":
                r = bench_navier(128, 65, 1e6, 1e-2, max(steps, 256), periodic=True)
            elif name == "periodic1024":
                # at-scale periodic (VERDICT r4 next #2): the reference's
                # production MPI shape (/root/reference/src/main.rs:17, 1024 x
                # 1025 periodic) at the flagship Ra — first performance
                # evidence for the split Re/Im Fourier x Chebyshev layout at
                # production size
                r = bench_navier(
                    1024, 1025, 1e9, 1e-4, max(16, steps // 4), periodic=True
                )
            elif name == "poisson1025":
                r = bench_poisson(1025)
            elif name == "rbc1025":
                r = bench_navier(
                    1025, 1025, 1e9, 1e-4, steps, shadow_path=_shadow_path("f32")
                )
            elif name == "rbc2049":
                r = bench_navier(2049, 2049, 1e9, 5e-5, max(16, steps // 4))
            elif name == "sh2048":
                r = bench_sh(2048)
            else:
                print(f"unknown config {name}", file=sys.stderr)
                continue
            r["bench_wall_s"] = round(time.perf_counter() - t0, 1)
            r["seq"] = seq
            results[name] = r
            ok = ok and r.get("finite", True)
            # accuracy gates for the Poisson configs (BASELINE #3): the MMS
            # error is deterministic, so a hard threshold is sound here
            if name == "poisson1025":
                ok = ok and r.get("max_error", 1.0) < 1e-2
            elif name == "poisson1025_f64":
                ok = ok and r.get("max_error", 1.0) < 1e-6
        except Exception as exc:  # record the failure, keep benching
            results[name] = {"error": f"{type(exc).__name__}: {exc}"}
            ok = False
        print(f"# {name}: {results[name]}", file=sys.stderr)

    # primary metric: rbc1025 when selected, else the first config that
    # reports a rate (a subset run must not report failure just because the
    # primary config was excluded)
    unit = "steps/s"
    primary_name = "rbc1025" if "rbc1025" in results else next(
        (k for k, v in results.items() if "steps_per_sec" in v), None
    )
    if primary_name is None:
        primary_name = next(
            (k for k, v in results.items() if "solves_per_sec" in v), None
        )
        unit = "solves/s"
    primary = results.get(primary_name, {})
    value = primary.get("steps_per_sec", primary.get("solves_per_sec", 0.0))
    # the CPU stand-in baseline is measured at the 1025^2 config only
    vs = (
        value / CPU_BASELINE_STEPS_PER_SEC if primary_name == "rbc1025" else 0.0
    )
    mfu = primary.get("mfu", {}).get("mfu")

    # precision tag of the run the metric actually reports (the f64 config
    # runs in its own X64=1 subprocess regardless of this process's env)
    x64 = os.environ.get("RUSTPDE_X64") == "1" or (
        primary_name or ""
    ).endswith("_f64")

    def denan(v):
        """Recursive NaN/inf -> None (bare NaN literals are not strict JSON)."""
        if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
            return None
        if isinstance(v, dict):
            return {k: denan(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [denan(x) for x in v]
        return v

    # every selected config appears in the headline JSON: fresh numbers from
    # this run, otherwise the last recorded number explicitly marked stale —
    # no silent budget holes (VERDICT r2 weak #1 / next #4)
    def sigfig(v, n=6):
        """Round floats to n significant digits (NOT fixed decimals: 4-dp
        rounding flattened small magnitudes like pattern_energy to 0.0,
        VERDICT r3 weak #6)."""
        if isinstance(v, float) and v == v and abs(v) not in (float("inf"),):
            return float(f"{v:.{n}g}")
        return v

    config_rows = {}
    for k in names:
        if k in results:
            config_rows[k] = {
                kk: sigfig(vv) for kk, vv in results[k].items() if kk != "mfu"
            }
        elif k in prev_results and isinstance(prev_results[k], dict):
            config_rows[k] = dict(prev_results[k], stale=True)

    # Accuracy gate at scale: SHORT-HORIZON SHADOWING (replaces the round-3
    # pointwise Nu-drift gate, which measured chaotic trajectory divergence
    # after 256 steps at Ra=1e9 — a statistic with no a-priori bound, so the
    # gate flapped; VERDICT r3 weak #1).  Here both precisions advance only
    # _SHADOW_STEPS steps from the identical deterministic IC: over 8 steps
    # (8e-4 time units, Lyapunov amplification e^(lambda*t) ~ 1) the f32 field
    # must track the f64 field at accumulated-roundoff level.  This measures
    # the NUMERICS, not the chaos: a broken f32 path shows order-1 drift after
    # even one step, while the correct path stays ~1e-5.  The gate is always
    # reported with an explicit "evaluated" flag so a budget-skipped anchor is
    # distinguishable from a pass (ADVICE r3 #3).
    shadow = {"evaluated": False, "reason": "f32+f64 shadow runs not both fresh"}
    s32 = results.get("rbc1025", {}).get("shadow")
    s64 = results.get("rbc1025_f64", {}).get("shadow")
    if s32 and s64:
        import numpy as np

        a = np.load(s32["path"])
        b = np.load(s64["path"])
        field_rel = float(np.linalg.norm(a - b) / np.linalg.norm(b))
        nu_rel = abs(s32["nu"] - s64["nu"]) / abs(s64["nu"])
        shadow = {
            "evaluated": True,
            "steps": _SHADOW_STEPS,
            "field_rel_l2": sigfig(field_rel),
            "nu_rel": sigfig(nu_rel),
            "gate_field_rel_l2": 1e-2,
            "passed": bool(field_rel < 1e-2),
        }
        ok = ok and shadow["passed"]

    payload = {
        "metric": _metric_string(primary_name, unit, x64, platform),
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": round(vs, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "shadow_drift_f32_vs_f64": shadow,
        "skipped_for_budget": skipped_for_budget,
        "starved_configs": starved_configs,
        "configs": denan(config_rows),
    }
    if any(c >= starve_limit for c in starved_configs.values()):
        worst = {k: c for k, c in starved_configs.items() if c >= starve_limit}
        print(
            f"# STARVED: {worst} skipped {starve_limit}+ consecutive recorded "
            "runs — raise RUSTPDE_BENCH_BUDGET_S or trim the config's window",
            file=sys.stderr,
        )
        ok = False
    sanitized = denan(results)
    # merge into the existing record so a subset/budgeted run updates its
    # configs without deleting the rest of the matrix — but never mix
    # platforms (a CPU run must not get attributed TPU numbers or vice
    # versa); per-entry 'seq' marks how fresh each number is
    record: dict = {"platform": platform, "results": dict(prev_results)}
    record["results"].update(sanitized)
    # persist consecutive-starvation counters (fresh results overwrote their
    # entry above, which resets a measured config's counter to absent/0)
    for name_, count in starved_configs.items():
        entry = record["results"].setdefault(name_, {})
        if isinstance(entry, dict):
            entry["starved_runs"] = count
    with open(os.path.join(_REPO, "BENCH_FULL.json"), "w") as f:
        json.dump(record, f, indent=1, default=str)
    print(json.dumps(payload))
    return 0 if ok and value > 0 else 1


if __name__ == "__main__":
    # the supervisor probes the backend and guards the matrix run with a
    # timeout; the child (RUSTPDE_BENCH_CHILD=1) does the actual benching
    if os.environ.get("RUSTPDE_BENCH_CHILD") == "1":
        sys.exit(main())
    sys.exit(_supervise())
