"""Benchmark harness: the 5 BASELINE.json configs + MFU estimate.

Prints ONE JSON line whose required fields are
``{"metric", "value", "unit", "vs_baseline"}`` (primary metric: timesteps/sec
of the confined 2-D RBC DNS at 1025^2, BASELINE config #4); the same object
carries the full config matrix under ``"configs"`` and an ``"mfu"`` estimate,
and the matrix is also written to BENCH_FULL.json.

Environment knobs:

    RUSTPDE_BENCH_CONFIGS  comma list / "all" (default) /
                           names: rbc129, periodic, poisson1025, rbc1025,
                                  rbc1025_f64, sh2048, rbc2049, rbc129_f64
    RUSTPDE_BENCH_STEPS    timed steps for the primary config (default 64)
    RUSTPDE_X64            1 for f64 parity mode (default 0 here)

``vs_baseline``: the reference publishes no numbers and cannot be built in
this container (no Rust toolchain), so the denominator is this framework's
own CPU path (f64, banded solvers — algorithmically the reference's serial
configuration) measured on this host at the same 1025^2 config; see
BASELINE.md "Measured stand-in baseline".
"""

import json
import os
import sys
import time

os.environ.setdefault("RUSTPDE_X64", "0")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# CPU f64 banded-path steps/s at 1025^2 Ra=1e9 measured on this container's
# host CPU, 2026-07-29 (BASELINE.md "Measured stand-in baseline").
CPU_BASELINE_STEPS_PER_SEC = 0.188

# primary config first: with a driver-side timeout or the RUSTPDE_BENCH_BUDGET_S
# cutoff, whatever completes still yields the primary metric line
DEFAULT_CONFIGS = [
    "rbc1025",
    "rbc1025_f64",
    "sh2048",
    "rbc129",
    "periodic",
    "poisson1025",
    "rbc129_f64",
    "rbc2049",
]


def bench_navier(nx, ny, ra, dt, steps, periodic=False, x64=None):
    from rustpde_mpi_tpu import Navier2D
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps, mfu_estimate

    ctor = Navier2D.new_periodic if periodic else Navier2D.new_confined
    model = ctor(nx, ny, ra, 1.0, dt, 1.0, "rbc")
    res = benchmark_steps(model, steps)
    nu, _, _, div = model.get_observables()
    res["nu"] = nu
    res["finite"] = bool(nu == nu and div == div)
    res["mfu"] = mfu_estimate(model, res["steps_per_sec"])
    return res


def bench_poisson(n, solves=32):
    """Standalone Poisson solve rate + MMS max error (BASELINE config #3,
    /root/reference/examples/poisson_mpi.rs analog)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rustpde_mpi_tpu import Space2, cheb_neumann
    from rustpde_mpi_tpu.solver import Poisson

    space = Space2(cheb_neumann(n), cheb_neumann(n))
    solver = Poisson(space, (1.0, 1.0))
    xs, ys = (b.points for b in space.bases)
    # Neumann-compatible zero-mean MMS mode (tests/test_solver.py convention)
    u = np.cos(np.pi * xs)[:, None] * np.cos(np.pi * ys)[None, :]
    f = -2.0 * np.pi**2 * u
    fhat_ortho = space.to_ortho(space.forward(jnp.asarray(f)))

    solve = jax.jit(solver.solve)
    out = solve(fhat_ortho)
    got = np.array(space.backward(out))
    got -= got.mean() - u.mean()  # defined up to a constant
    err = float(np.abs(got - u).max())
    np.asarray(out[:1, :1])
    t0 = time.perf_counter()
    for _ in range(solves):
        out = solve(fhat_ortho)
    np.asarray(out[:1, :1])
    elapsed = time.perf_counter() - t0
    return {"solves_per_sec": solves / elapsed, "max_error": err, "n": n}


def bench_sh(nx, steps=128):
    from rustpde_mpi_tpu import SwiftHohenberg2D
    from rustpde_mpi_tpu.utils.profiling import benchmark_steps

    model = SwiftHohenberg2D(nx, nx, r=0.35, dt=0.02, length=20.0)
    res = benchmark_steps(model, steps)
    res["pattern_energy"] = model.pattern_energy()
    res["finite"] = not model.exit()
    return res


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    sel = os.environ.get("RUSTPDE_BENCH_CONFIGS", "all")
    names = DEFAULT_CONFIGS if sel == "all" else [s.strip() for s in sel.split(",")]
    steps = int(os.environ.get("RUSTPDE_BENCH_STEPS", "64"))

    # wall budget: stop starting new configs once exceeded so the JSON line
    # is always emitted even under an external timeout; completed configs
    # merge into BENCH_FULL.json.  To keep the whole matrix fresh across
    # budgeted runs, the non-primary configs run least-recently-measured
    # first (per-entry 'seq' counters persisted in BENCH_FULL.json) — each
    # run picks up where the previous one was cut off.
    # default sized so the primary + its f64 drift anchor (the pinned-first
    # pair, ~430 s measured together) both fit in one run; later configs
    # start only if their last recorded wall time also fits
    budget = float(os.environ.get("RUSTPDE_BENCH_BUDGET_S", "560"))
    bench_start = time.perf_counter()

    prev_results: dict = {}
    try:
        with open("BENCH_FULL.json") as f:
            prev = json.load(f)
        if prev.get("platform") == platform and isinstance(prev.get("results"), dict):
            prev_results = prev["results"]
    except (OSError, ValueError):
        pass
    seq = 1 + max(
        (v.get("seq", 0) for v in prev_results.values() if isinstance(v, dict)),
        default=0,
    )
    if sel == "all":
        # primary first; its f64 drift anchor second (the accuracy gate needs
        # both from the same commit); the rest least-recently-measured first
        pinned = [n for n in ("rbc1025", "rbc1025_f64") if n in names]
        tail = sorted(
            (n for n in names if n not in pinned),
            key=lambda n: prev_results.get(n, {}).get("seq", 0),
        )
        names = pinned + tail

    results: dict[str, dict] = {}
    skipped_for_budget: list[str] = []
    ok = True
    for name in names:
        # gate on the *estimated completion* (elapsed + this config's last
        # recorded wall, default 120 s) so a run never starts a config that
        # would overshoot the budget — an external driver timeout near the
        # budget must still see the final JSON line
        est = prev_results.get(name, {}).get("bench_wall_s", 120.0) or 120.0
        if results and time.perf_counter() - bench_start + est > budget:
            print(
                f"# budget {budget:.0f}s would be exceeded (~{est:.0f}s for "
                f"{name}); skipping",
                file=sys.stderr,
            )
            skipped_for_budget.append(name)
            continue
        t0 = time.perf_counter()
        try:
            if name == "rbc129":
                # small configs need a longer timed window: 64 steps is an
                # ~100 ms measurement through the relay, dominated by noise
                r = bench_navier(129, 129, 1e7, 2e-3, max(steps, 256))
            elif name in ("rbc129_f64", "rbc1025_f64"):
                env = dict(os.environ, RUSTPDE_X64="1")
                import subprocess

                if name == "rbc129_f64":
                    call = f"bench.bench_navier(129,129,1e7,2e-3,{max(steps, 256)})"
                else:
                    # same ctor/seed/step-count as rbc1025 so the Nu values
                    # are directly comparable (the f32-vs-f64 drift gate)
                    call = f"bench.bench_navier(1025,1025,1e9,1e-4,{steps})"
                code = f"import bench, json; print(json.dumps({call}))"
                out = subprocess.run(
                    [sys.executable, "-c", code],
                    capture_output=True, text=True, env=env, timeout=1800,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                r = json.loads(out.stdout.strip().splitlines()[-1])
            elif name == "periodic":
                r = bench_navier(128, 65, 1e6, 1e-2, max(steps, 256), periodic=True)
            elif name == "poisson1025":
                r = bench_poisson(1025)
            elif name == "rbc1025":
                r = bench_navier(1025, 1025, 1e9, 1e-4, steps)
            elif name == "rbc2049":
                r = bench_navier(2049, 2049, 1e9, 5e-5, max(16, steps // 4))
            elif name == "sh2048":
                r = bench_sh(2048)
            else:
                print(f"unknown config {name}", file=sys.stderr)
                continue
            r["bench_wall_s"] = round(time.perf_counter() - t0, 1)
            r["seq"] = seq
            results[name] = r
            ok = ok and r.get("finite", True)
        except Exception as exc:  # record the failure, keep benching
            results[name] = {"error": f"{type(exc).__name__}: {exc}"}
            ok = False
        print(f"# {name}: {results[name]}", file=sys.stderr)

    # primary metric: rbc1025 when selected, else the first config that
    # reports a rate (a subset run must not report failure just because the
    # primary config was excluded)
    unit = "steps/s"
    primary_name = "rbc1025" if "rbc1025" in results else next(
        (k for k, v in results.items() if "steps_per_sec" in v), None
    )
    if primary_name is None:
        primary_name = next(
            (k for k, v in results.items() if "solves_per_sec" in v), None
        )
        unit = "solves/s"
    primary = results.get(primary_name, {})
    value = primary.get("steps_per_sec", primary.get("solves_per_sec", 0.0))
    # the CPU stand-in baseline is measured at the 1025^2 config only
    vs = (
        value / CPU_BASELINE_STEPS_PER_SEC if primary_name == "rbc1025" else 0.0
    )
    mfu = primary.get("mfu", {}).get("mfu")

    metric_names = {
        "rbc1025": "2D RBC confined 1025x1025 Ra=1e9",
        "rbc1025_f64": "2D RBC confined 1025x1025 Ra=1e9",
        "rbc2049": "2D RBC confined 2049x2049 Ra=1e9",
        "rbc129": "2D RBC confined 129x129 Ra=1e7",
        "rbc129_f64": "2D RBC confined 129x129 Ra=1e7",
        "periodic": "2D RBC periodic 128x65 Ra=1e6",
        "poisson1025": "Poisson standalone 1025x1025",
        "sh2048": "Swift-Hohenberg 2048x2048",
    }
    # precision tag of the run the metric actually reports (the f64 config
    # runs in its own X64=1 subprocess regardless of this process's env)
    x64 = os.environ.get("RUSTPDE_X64") == "1" or (
        primary_name or ""
    ).endswith("_f64")

    def denan(v):
        """Recursive NaN/inf -> None (bare NaN literals are not strict JSON)."""
        if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
            return None
        if isinstance(v, dict):
            return {k: denan(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [denan(x) for x in v]
        return v

    # every selected config appears in the headline JSON: fresh numbers from
    # this run, otherwise the last recorded number explicitly marked stale —
    # no silent budget holes (VERDICT r2 weak #1 / next #4)
    config_rows = {}
    for k in names:
        if k in results:
            config_rows[k] = {
                kk: (round(vv, 4) if isinstance(vv, float) else vv)
                for kk, vv in results[k].items()
                if kk != "mfu"
            }
        elif k in prev_results and isinstance(prev_results[k], dict):
            config_rows[k] = dict(prev_results[k], stale=True)

    # accuracy gate at scale: relative Nu drift of the f32 flagship window
    # against the f64 anchor run from the identical IC and step count
    # (replaces the finite-only check; BASELINE.md "f64 throughout").
    # Gate width: at Ra=1e9 the flow is chaotic, so reassociation-level f32
    # noise amplifies to percent-level Nu differences over the benchmark's
    # 2*steps executed steps (warmup + timed window) — measured 1.5e-2 and
    # 5.3e-2 across code revisions with correct numerics.  0.15 still fails hard on a genuinely broken f32 path
    # (precision regressions give order-1 drift or NaN).
    nu_drift = None
    r32, r64 = config_rows.get("rbc1025"), config_rows.get("rbc1025_f64")
    if (
        r32 and r64
        and "stale" not in r32 and "stale" not in r64  # same-commit runs only
        and r32.get("nu") and r64.get("nu")
        and r32.get("steps") == r64.get("steps")
    ):
        nu_drift = abs(r32["nu"] - r64["nu"]) / abs(r64["nu"])
        ok = ok and nu_drift < 0.15

    payload = {
        "metric": (
            f"{'timesteps' if unit == 'steps/s' else 'solves'}/sec, "
            f"{metric_names.get(primary_name, primary_name)} "
            f"({'f64' if x64 else 'f32'}, {platform})"
        ),
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": round(vs, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "nu_drift_f32_vs_f64": round(nu_drift, 6) if nu_drift is not None else None,
        "skipped_for_budget": skipped_for_budget,
        "configs": denan(config_rows),
    }
    sanitized = denan(results)
    # merge into the existing record so a subset/budgeted run updates its
    # configs without deleting the rest of the matrix — but never mix
    # platforms (a CPU run must not get attributed TPU numbers or vice
    # versa); per-entry 'seq' marks how fresh each number is
    record: dict = {"platform": platform, "results": dict(prev_results)}
    record["results"].update(sanitized)
    with open("BENCH_FULL.json", "w") as f:
        json.dump(record, f, indent=1, default=str)
    print(json.dumps(payload))
    return 0 if ok and value > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
