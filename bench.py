"""Benchmark: timesteps/sec of the confined 2-D RBC DNS at 1025^2.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config follows BASELINE.json #4 (1025^2, Ra=1e9).  Runs f32 on the TPU by
default (RUSTPDE_X64=0); override via env:

    RUSTPDE_BENCH_NX     grid size              (default 1025)
    RUSTPDE_BENCH_STEPS  timed steps            (default 64)
    RUSTPDE_X64          1 for f64 parity mode  (default 0 here)

``vs_baseline``: the reference publishes no numbers and cannot be built in
this container (no Rust toolchain), so the recorded baseline is this
framework's own CPU path (f64, banded solvers — algorithmically the
reference's serial configuration) measured on this host at the same config;
see BASELINE.md "Measured stand-in baseline".
"""

import json
import os
import sys
import time

os.environ.setdefault("RUSTPDE_X64", "0")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# CPU f64 banded-path steps/s at 1025^2 Ra=1e9 measured on this container's
# host CPU, 2026-07-29 (see BASELINE.md "Measured stand-in baseline"); the
# denominator for vs_baseline.
CPU_BASELINE_STEPS_PER_SEC = 0.188


def main() -> int:
    import jax

    from rustpde_mpi_tpu import Navier2D

    nx = int(os.environ.get("RUSTPDE_BENCH_NX", "1025"))
    steps = int(os.environ.get("RUSTPDE_BENCH_STEPS", "64"))

    import numpy as np

    def sync(m):
        # a data readback, not just block_until_ready: the axon TPU relay's
        # dispatch is async past block_until_ready, so only materializing
        # bytes on the host guarantees the computation finished
        return np.asarray(m.state.temp[:1, :1])

    model = Navier2D.new_confined(nx, nx, 1e9, 1.0, 1e-4, 1.0, "rbc")
    model.update_n(steps)  # compile the exact bucket sequence + warm up
    sync(model)

    t0 = time.perf_counter()
    model.update_n(steps)
    sync(model)
    elapsed = time.perf_counter() - t0

    value = steps / elapsed
    nu, _, _, div = model.get_observables()
    ok = all(map(lambda v: v == v, (nu, div)))  # NaN guard

    vs = value / CPU_BASELINE_STEPS_PER_SEC if CPU_BASELINE_STEPS_PER_SEC else 0.0
    print(
        json.dumps(
            {
                "metric": f"timesteps/sec, 2D RBC confined {nx}x{nx} Ra=1e9 "
                f"({'f64' if os.environ.get('RUSTPDE_X64') == '1' else 'f32'}, "
                f"{jax.devices()[0].platform})",
                "value": round(value, 3),
                "unit": "steps/s",
                "vs_baseline": round(vs, 2),
            }
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
