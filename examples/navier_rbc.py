"""Rayleigh–Bénard convection in a confined cell.

TPU rebuild of the reference's headline example
(/root/reference/examples/navier_rbc.rs: 129x129, Ra=1e7, Pr=1, dt=2e-3,
integrate to t=10 saving every 1.0).  `--quick` runs a small fast config for
end-to-end verification; `--periodic` switches to the Fourier x Chebyshev
configuration (/root/reference/examples/navier_rbc_periodic.rs).
"""

import argparse
import sys
import time

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import Navier2D, integrate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--periodic", action="store_true")
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="pencil-shard over all visible devices (jax.sharding Mesh)",
    )
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--ra", type=float, default=None)
    ap.add_argument("--dt", type=float, default=None)
    ap.add_argument("--max-time", type=float, default=None)
    args = ap.parse_args()

    if args.quick:
        nx, ny, ra, dt, max_time, save = 33, 33, 1e5, 0.01, 1.0, 0.25
    else:
        nx, ny, ra, dt, max_time, save = 129, 129, 1e7, 2e-3, 10.0, 1.0
    nx = args.nx or nx
    ny = args.ny or ny
    ra = args.ra or ra
    dt = args.dt or dt
    max_time = args.max_time or max_time

    mesh = None
    if args.mesh:
        from rustpde_mpi_tpu.parallel import make_mesh

        mesh = make_mesh()
        print(f"pencil mesh over {mesh.size} devices")
    ctor = Navier2D.new_periodic if args.periodic else Navier2D.new_confined
    navier = ctor(nx, ny, ra, 1.0, dt, 1.0, "rbc", mesh=mesh)

    t0 = time.perf_counter()
    navier.callback()
    integrate(navier, max_time, save)
    wall = time.perf_counter() - t0
    steps = round(navier.get_time() / dt)
    print(f"{steps} steps in {wall:.2f} s -> {steps / wall:.2f} steps/s")

    ok = not navier.exit() and navier.eval_nu() > 0.0
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
