"""Rayleigh–Bénard convection over sinusoidal roughness elements.

Working port of /root/reference/examples/navier_rbc_roughness.rs (a stub
printing "Currently unimplemented..." in the reference) — this framework
actually applies the volume-penalization term the reference only stores
(models/solid_masks.py, SURVEY.md S7.8): tanh-smoothed sinusoidal roughness
on both plates, held at the plate temperatures (+0.5 / -0.5).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import Navier2D, integrate
from rustpde_mpi_tpu.models.solid_masks import solid_roughness_sinusoid


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ra", type=float, default=1e5)
    ap.add_argument("--dt", type=float, default=0.01)
    ap.add_argument("--height", type=float, default=0.1)
    ap.add_argument("--wavenumber", type=float, default=10.0)
    ap.add_argument("--max-time", type=float, default=None)
    args = ap.parse_args()

    if args.quick:
        nx, max_time, save = 33, 1.0, 0.25
    else:
        nx, max_time, save = 129, 10.0, 1.0
    if args.nx is not None:
        nx = args.nx
    if args.max_time is not None:
        max_time = args.max_time

    navier = Navier2D.new_confined(nx, nx, args.ra, 1.0, args.dt, 1.0, "rbc")
    x, y = navier.x
    mask, value = solid_roughness_sinusoid(x, y, args.height, args.wavenumber)
    navier.set_solid(mask, value)
    navier.set_velocity(0.2, 1.0, 1.0)
    navier.set_temperature(0.2, 1.0, 1.0)

    print(f"RBC with roughness: {nx}x{nx}, Ra={args.ra:g}, height={args.height}")
    t0 = time.perf_counter()
    integrate(navier, max_time, save)
    wall = time.perf_counter() - t0
    steps = round(navier.get_time() / navier.get_dt())
    nu, nuv, re, div = navier.get_observables()
    print(
        f"done: {steps} steps in {wall:.1f}s ({steps / wall:.1f} steps/s), "
        f"Nu={nu:.4f} Re={re:.3f} |div|={div:.2e}"
    )
    # solid check: velocity magnitude deep inside the roughness elements
    import numpy as np

    ux = navier.get_field("velx")
    uy = navier.get_field("vely")
    speed = np.sqrt(ux**2 + uy**2)
    deep = mask > 0.99
    print(
        f"max |u| inside solid: {speed[deep].max():.2e}   "
        f"in fluid: {speed[~deep].max():.2e}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
