"""Fleet serving: stateless proxies + replica SimServers over one queue.

One shared ``--run-dir`` holds the durable queue, the bucket leases, the
parked continuations and the per-replica journals; any number of proxy
and replica processes attach to it.  Kill any one of them — proxies are
stateless, replicas are leased — and the fleet keeps serving.

Start a proxy (prints its bound address as a JSON line)::

    python examples/navier_rbc_fleet.py --proxy --http-port 0 --run-dir data/fleet

Start two replicas (each is one SimServer in fleet mode)::

    python examples/navier_rbc_fleet.py --replica --replica-id rA --run-dir data/fleet
    python examples/navier_rbc_fleet.py --replica --replica-id rB --run-dir data/fleet

Submit mixed-priority traffic through the proxy::

    curl -X POST localhost:<port>/requests -d '{"ra":1e4,"nx":17,"ny":17,
      "dt":0.01,"horizon":0.2,"priority":"interactive","deadline_s":30}'
    curl localhost:<port>/stats      # queue + leases + replica heartbeats

SIGTERM drains a replica gracefully; SIGKILL exercises the lease-break
path (survivors re-claim the dead replica's requests and resume them
mid-flight from the durable parked state).
"""

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu.config import FleetConfig, ServeConfig  # noqa: E402


def run_proxy(args) -> int:
    from rustpde_mpi_tpu.serve.fleet.proxy import FleetProxy

    fleet = FleetConfig(
        lease_ttl_s=args.lease_ttl_s, default_quota=args.quota
    )
    proxy = FleetProxy(
        args.run_dir,
        port=args.http_port or 0,
        max_queue=args.max_queue,
        fleet=fleet,
    )
    proxy.start()
    # the bench driver parses this line for the ephemeral port
    print(json.dumps({"proxy": proxy.proxy_id, "address": list(proxy.address)}),
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    proxy.stop()
    print(json.dumps({"outcome": "stopped", **proxy.stats()}), flush=True)
    return 0


def run_replica(args) -> int:
    from rustpde_mpi_tpu.serve import SimServer

    fleet = FleetConfig(
        replica_id=args.replica_id,
        lease_ttl_s=args.lease_ttl_s,
        heartbeat_s=args.heartbeat_s,
        default_quota=args.quota,
        preempt_slack_s=args.preempt_slack_s,
    )
    cfg = ServeConfig(
        run_dir=args.run_dir,
        slots=args.slots,
        max_queue=args.max_queue,
        chunk_steps=args.chunk_steps,
        checkpoint_every_s=args.ckpt_every_s,
        idle_exit=not args.daemon,
        poll_s=0.1,
        http_port=None,  # the proxy tier is the front door
        fleet=fleet,
    )
    server = SimServer(cfg, fault=args.fault)
    summary = server.serve()
    print(json.dumps(summary), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--proxy", action="store_true")
    mode.add_argument("--replica", action="store_true")
    ap.add_argument("--run-dir", default="data/fleet")
    ap.add_argument("--replica-id", default="")
    ap.add_argument("--http-port", type=int, default=0)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--chunk-steps", type=int, default=8)
    ap.add_argument("--max-queue", type=int, default=512)
    ap.add_argument("--ckpt-every-s", type=float, default=30.0)
    ap.add_argument("--lease-ttl-s", type=float, default=None)
    ap.add_argument("--heartbeat-s", type=float, default=None)
    ap.add_argument("--quota", type=int, default=None)
    ap.add_argument("--preempt-slack-s", type=float, default=30.0)
    ap.add_argument("--daemon", action="store_true",
                    help="keep serving after the queue drains (replicas)")
    ap.add_argument("--fault", default=None,
                    help="nan@<step> | spike@<step> | kill@<step> | slow@<step>")
    args = ap.parse_args()
    return run_proxy(args) if args.proxy else run_replica(args)


if __name__ == "__main__":
    sys.exit(main())
