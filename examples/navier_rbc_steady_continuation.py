"""Steady-state continuation in Rayleigh number.

Working version of /root/reference/examples/navier_rbc_steady_continuation.rs
(a commented-out stub in the reference): walk a log-spaced Ra list, solving
for the steady state at each Ra with the adjoint descent solver
(Navier2DAdjoint), warm-starting every solve from the previous Ra's converged
field, and record the Nu(Ra) continuation curve.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import Navier2DAdjoint, integrate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nx", type=int, default=33)
    ap.add_argument("--ny", type=int, default=33)
    ap.add_argument("--ra-start", type=float, default=1e4)
    ap.add_argument("--ra-stop", type=float, default=10 ** 4.2)
    ap.add_argument("--num", type=int, default=3)
    # the reference's commented continuation stub uses dt=0.5 on a 128x65
    # periodic grid; the confined 33^2 descent here needs the steady
    # example's small pseudo-step (examples/navier_rbc_steady.py, dt=5e-3)
    ap.add_argument("--dt", type=float, default=5e-3)
    ap.add_argument("--max-time", type=float, default=100.0)
    ap.add_argument("--out", default="data/continuation.txt")
    args = ap.parse_args()

    ra_list = np.logspace(np.log10(args.ra_start), np.log10(args.ra_stop), args.num)
    os.makedirs("data", exist_ok=True)
    restart = None
    rows = []
    for ra in ra_list:
        print(f"\n=== Ra = {ra:.3e} ===")
        navier = Navier2DAdjoint.new_confined(
            args.nx, args.ny, float(ra), 1.0, args.dt, 1.0, "rbc"
        )
        if restart is not None:
            navier.read(restart)
            navier.reset_time()
        else:
            navier.set_temperature(0.2, 1.0, 1.0)
            navier.set_velocity(0.2, 1.0, 1.0)
        integrate(navier, args.max_time, args.max_time / 4.0)
        fname = f"data/steady_ra{ra:4.2e}.h5"
        navier.write(fname)
        restart = fname
        nu, nuvol, re, _div = navier.get_observables()
        res = navier.residual()
        rows.append((ra, nu, nuvol, re, res))
        print(f"Ra={ra:.3e}: Nu={nu:.6f} Nuvol={nuvol:.6f} Re={re:.4f} res={res:.2e}")

    with open(args.out, "w") as f:
        for row in rows:
            f.write("  ".join(f"{v:8.6e}" for v in row) + "\n")
    print(f"\n ==> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
