"""Linear-stability workload: lnse eigenmode sweep for the critical
Rayleigh number.

Runs the workloads/eigenmodes.py campaign — per Rayleigh number, a vmapped
ensemble of linearized perturbations seeded on different horizontal modes,
governed and checkpointed under ResilientRunner — fits the leading growth
rates from the streamed energy trajectory, and interpolates the growth-rate
sign change.  For the rigid-rigid layer (periodic-x at the critical
wavelength) the analytic answer is Ra_c = 1707.76 (Chandrasekhar).

Usage:  python examples/navier_lnse_eigenmodes.py [--quick] [--run-dir DIR]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu.workloads import (  # noqa: E402
    RAC_RIGID,
    critical_rayleigh,
    eigenmode_sweep,
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="tiny smoke sweep")
    ap.add_argument("--run-dir", default="data/eigenmodes")
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--horizon", type=float, default=None)
    args = ap.parse_args()

    if args.quick:
        ras = [1500.0, 1950.0]
        ny = args.ny or 17
        horizon = args.horizon or 12.0
        samples = 6
    else:
        ras = [1500.0, 1600.0, 1700.0, 1800.0, 1900.0]
        ny = args.ny or 33
        horizon = args.horizon or 60.0
        samples = 24

    results = eigenmode_sweep(
        ras, nx=8, ny=ny, dt=0.05, horizon=horizon, samples=samples,
        run_dir=args.run_dir,
    )
    for r in results:
        print(
            f"Ra = {r['ra']:8.1f}   sigma_max = {r['sigma_max']:+.5f}   "
            f"(modes {r['modes']}, {r['steps']} steps"
            f"{', resumed' if r['resumed'] else ''})"
        )
    rac = critical_rayleigh(results)
    err = abs(rac - RAC_RIGID) / RAC_RIGID
    print(f"Ra_c = {rac:.1f}   (analytic {RAC_RIGID}, rel err {err:.2%})")
    ok = err < 0.05
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
