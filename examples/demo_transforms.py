"""Demo: spectral transforms + differentiation through the public API.

Counterpart of the reference's basis doc-tests (/root/reference/src/field.rs:47-57)
as a runnable example.  Works on CPU (f64) and TPU (f32, set RUSTPDE_X64=0).

    RUSTPDE_X64=0 python examples/demo_transforms.py      # TPU
    JAX_PLATFORMS=cpu python examples/demo_transforms.py  # CPU f64
"""

import sys

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

import rustpde_mpi_tpu as rp


def main():
    print("devices:", jax.devices())

    # Confined: Chebyshev x Chebyshev with Dirichlet BCs
    nx, ny = 65, 65
    space = rp.Space2(rp.cheb_dirichlet(nx), rp.cheb_dirichlet(ny))
    field = rp.Field2(space)
    x, y = field.x
    X, Y = np.meshgrid(x, y, indexing="ij")
    u = np.sin(np.pi * X) * np.sin(np.pi * Y)

    field.v = u  # forward transform
    err_rt = float(abs(np.asarray(field.v) - u).max())
    dudx = space.backward_ortho(space.gradient(field.vhat, [1, 0]))
    err_dx = float(abs(np.asarray(dudx) - np.pi * np.cos(np.pi * X) * np.sin(np.pi * Y)).max())
    print(f"confined  round-trip max err: {err_rt:.3e}   d/dx max err: {err_dx:.3e}")

    # Periodic: Fourier x Chebyshev.  On backends without complex dtypes
    # (the TPU chip) fourier_r2c transparently selects the split Re/Im
    # representation, so the same code runs everywhere.
    space_p = rp.Space2(rp.fourier_r2c(64), rp.cheb_dirichlet(65))
    fp = rp.Field2(space_p)
    xp, yp = fp.x
    XP, YP = np.meshgrid(xp, yp, indexing="ij")
    up = np.cos(2 * XP) * np.sin(np.pi * YP)
    fp.v = up
    err_rt_p = float(abs(np.asarray(fp.v) - up).max())
    lap = space_p.backward_ortho(
        space_p.gradient(fp.vhat, [2, 0]) + space_p.gradient(fp.vhat, [0, 2])
    )
    expect = -(4 + np.pi**2) * up
    err_lap = float(abs(np.asarray(lap) - expect).max())
    print(f"periodic  round-trip max err: {err_rt_p:.3e}   laplacian max err: {err_lap:.3e}")

    tol = 1e-8 if rp.config.X64 else 1e-2
    ok = max(err_rt, err_dx, err_rt_p) < tol and err_lap < tol * 100
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
