"""2-D Swift–Hohenberg pattern formation: du/dt = [r - (lap+1)^2] u - u^3.

TPU rebuild of the reference's user-level "bring your own PDE" demo
(/root/reference/examples/swift_hohenberg_2d.rs: 512^2, length=20, r=0.35,
dt=0.02, integrate to t=1000 saving every 10).  BASELINE.json config #5 runs
this at 2048^2 (use --nx 2048).  The IMEX step is diagonal in Fourier space;
on the TPU chip the transforms run as real MXU matmuls over the split Re/Im
representation.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import SwiftHohenberg2D, integrate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--r", type=float, default=0.35)
    ap.add_argument("--dt", type=float, default=0.02)
    ap.add_argument("--length", type=float, default=20.0)
    ap.add_argument("--max-time", type=float, default=None)
    ap.add_argument("--save", type=float, default=None)
    args = ap.parse_args()

    if args.quick:
        nx, max_time, save = 64, 20.0, 10.0
    else:
        nx, max_time, save = 512, 1000.0, 10.0
    if args.nx is not None:
        nx = args.nx
    if args.max_time is not None:
        max_time = args.max_time
    if args.save is not None:
        save = args.save

    pde = SwiftHohenberg2D(nx, nx, args.r, args.dt, args.length)
    print(f"SwiftHohenberg2D {nx}x{nx}, r={args.r}, dt={args.dt}, length={args.length}")
    pde.callback()
    t0 = time.perf_counter()
    integrate(pde, max_time, save)
    wall = time.perf_counter() - t0
    steps = round(pde.get_time() / pde.get_dt())
    print(
        f"done: t={pde.get_time():.2f} ({steps} steps) in {wall:.1f}s "
        f"({steps / wall:.1f} steps/s), pattern energy={pde.pattern_energy():.4e}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
