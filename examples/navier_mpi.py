"""Mesh-sharded Navier–Stokes runs — the MPI examples' counterpart.

One script covers /root/reference/examples/{navier_mpi, navier_periodic_mpi,
navier_periodic_hc_mpi}.rs: the same ``Navier2D`` model pencil-sharded over a
``jax.sharding.Mesh`` of all visible devices (physical y-pencils / spectral
x-pencils with XLA all-to-all pencil flips — the GSPMD form of the
reference's Decomp2d transposes).  On one real chip this degenerates to a
1-device mesh; run under a virtual CPU mesh to exercise the collectives:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/navier_mpi.py --quick
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the container's sitecustomize force-sets jax_platforms programmatically,
    # overriding the env var; honor it again (same dance as tests/conftest.py)
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--periodic", action="store_true")
    ap.add_argument("--bc", default="rbc", choices=["rbc", "hc"])
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--ra", type=float, default=1e5)
    ap.add_argument("--dt", type=float, default=0.01)
    ap.add_argument("--max-time", type=float, default=None)
    args = ap.parse_args()

    import jax
    from jax.sharding import Mesh

    from rustpde_mpi_tpu import Navier2D, integrate
    from rustpde_mpi_tpu.parallel.mesh import AXIS

    devices = jax.devices()
    mesh = Mesh(np.array(devices), (AXIS,))
    print(f"pencil mesh over {len(devices)} {devices[0].platform} device(s)")

    if args.quick:
        nx, ny, max_time, save = 33, 33, 1.0, 0.5
    else:
        nx, ny, max_time, save = 128, 129, 10.0, 5.0
    nx = args.nx or nx
    ny = args.ny or ny
    max_time = args.max_time or max_time

    ctor = Navier2D.new_periodic if args.periodic else Navier2D.new_confined
    navier = ctor(nx, ny, args.ra, 1.0, args.dt, 1.0, args.bc, mesh=mesh)
    navier.set_velocity(0.2, 1.0, 1.0)
    navier.set_temperature(0.2, 1.0, 1.0)
    t0 = time.perf_counter()
    integrate(navier, max_time, save)
    wall = time.perf_counter() - t0
    steps = round(navier.get_time() / navier.get_dt())
    nu, nuv, re, div = navier.get_observables()
    ok = nu == nu and div == div
    print(
        f"done: {steps} steps in {wall:.1f}s ({steps / wall:.1f} steps/s), "
        f"Nu={nu:.4f} Re={re:.3f} |div|={div:.2e}  {'OK' if ok else 'FAILED'}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
