"""Ra-sweep ensemble driver: batched RBC statistics via NavierEnsemble.

For each Rayleigh number in the sweep, K seed-decorrelated members advance as
ONE vmapped device dispatch per interval (models/ensemble.py) — the batched
analogue of launching K independent runs per Ra.  Members must share the
implicit operators (they bake ``dt*nu`` into the solver factorizations), so
the sweep maps to one ensemble per Ra with the batching *inside* each Ra; a
diverging member freezes and is reported per member instead of killing its
batch (the graceful-degradation column in the summary table).

Usage:
    python examples/navier_rbc_ensemble.py                 # 3-decade sweep
    python examples/navier_rbc_ensemble.py --ras 1e7,1e8 --members 16
    python examples/navier_rbc_ensemble.py --quick          # CI smoke case
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import Navier2D, NavierEnsemble, integrate


def run_sweep(ras, members, nx, ny, max_time, save_intervall, amp=0.1):
    rows = []
    for ra in ras:
        # explicit-convection stability: dt shrinks with the free-fall
        # velocity ~ sqrt(Ra); anchored at the 129^2 Ra=1e7 bench config
        dt = min(2e-3, 2e-3 * np.sqrt(1e7 / ra))
        model = Navier2D.new_confined(nx, ny, ra, 1.0, dt, 1.0, "rbc")
        ens = NavierEnsemble.from_seeds(model, seeds=range(members), amp=amp)
        integrate(ens, max_time, save_intervall)
        nu = ens.eval_nu()
        alive = ens.alive()
        live = nu[alive]
        rows.append(
            {
                "ra": ra,
                "dt": dt,
                "alive": int(alive.sum()),
                "members": members,
                "nu_mean": float(live.mean()) if alive.any() else float("nan"),
                "nu_std": float(live.std()) if alive.any() else float("nan"),
                "steps_done": np.asarray(ens.steps_done).tolist(),
            }
        )
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ras", default="1e5,1e6,1e7", help="comma list of Ra values")
    ap.add_argument("--members", type=int, default=8, help="ensemble size K per Ra")
    ap.add_argument("--nx", type=int, default=57)
    ap.add_argument("--ny", type=int, default=57)
    ap.add_argument("--max-time", type=float, default=1.0)
    ap.add_argument("--save-intervall", type=float, default=0.5)
    ap.add_argument(
        "--quick", action="store_true", help="tiny smoke configuration (CI)"
    )
    args = ap.parse_args(argv)

    if args.quick:
        ras, members, nx, ny = [1e4, 1e5], 2, 17, 17
        max_time, save_intervall = 0.05, 0.05
    else:
        ras = [float(s) for s in args.ras.split(",")]
        members, nx, ny = args.members, args.nx, args.ny
        max_time, save_intervall = args.max_time, args.save_intervall

    rows = run_sweep(ras, members, nx, ny, max_time, save_intervall)

    print(f"\n{'Ra':>10}  {'alive':>7}  {'Nu mean':>9}  {'Nu std':>9}")
    for row in rows:
        print(
            f"{row['ra']:10.2e}  {row['alive']:>3}/{row['members']:<3}  "
            f"{row['nu_mean']:9.4f}  {row['nu_std']:9.4f}"
        )
    # a sweep where every member of every Ra diverged is a failed run
    return 0 if any(row["alive"] for row in rows) else 1


if __name__ == "__main__":
    raise SystemExit(main())
