"""Scenario-axis demo: config-carried step modifiers + the vmapped
solid-mask geometry sweep.

Three workloads/modifiers.py scenarios on one small RBC cell:

1. **passive scalar** — released equal to the temperature at matched
   diffusivity, it must STAY equal (exact analytic validation of the new
   transport term);
2. **rotating frame** — f-plane Coriolis: in incompressible 2-D flow the
   force is irrotational and absorbed by the pressure, so velocity and
   temperature track the non-rotating run while the pressure shifts;
3. **geometry sweep** — K solid-cylinder geometries advanced as ONE
   vmapped donated scan, each bit-matching a solo set_solid run.

Usage:  python examples/navier_rbc_scenarios.py [--quick]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rustpde_mpi_tpu import Navier2D  # noqa: E402
from rustpde_mpi_tpu.models.solid_masks import solid_cylinder_inner  # noqa: E402
from rustpde_mpi_tpu.workloads import ScenarioConfig, geometry_sweep  # noqa: E402


def build(nx, ny, scenario=None):
    model = Navier2D(nx, ny, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False,
                     scenario=scenario)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.write_intervall = 1e9
    return model


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    nx = ny = 17 if args.quick else 65
    steps = 30 if args.quick else 200
    ok = True

    # 1. passive scalar mirrors temperature (exact)
    m = build(nx, ny, ScenarioConfig(passive_scalar=True))
    m.set_field("scal", m.get_field("temp"))
    m.update_n(steps)
    drift = np.abs(m.get_field("scal") - m.get_field("temp")).max()
    print(f"passive scalar: |c - T|_max = {drift:.3e} after {steps} steps "
          f"(exact mirror at matched diffusivity)")
    ok &= drift < 1e-10

    # 2. rotating frame: pressure absorbs the Coriolis force
    base = build(nx, ny)
    rot = build(nx, ny, ScenarioConfig(coriolis=2.0))
    base.update_n(steps)
    rot.update_n(steps)

    def rel(name):
        a, b = base.get_field(name), rot.get_field(name)
        return np.abs(a - b).max() / max(np.abs(a).max(), 1e-300)

    print(f"rotating frame f=2: vel drift {max(rel('velx'), rel('vely')):.2e}, "
          f"temp drift {rel('temp'):.2e}, PRESSURE drift {rel('pres'):.2e} "
          f"(irrotational force -> absorbed by pressure)")
    ok &= max(rel("velx"), rel("vely"), rel("temp")) < 1e-2 < rel("pres")

    # 3. vmapped geometry sweep vs solo penalized runs
    template = build(nx, ny)
    xs, ys = (b.points for b in template.field_space.bases)
    geoms = [
        solid_cylinder_inner(xs, ys, 0.0, 0.0, 0.3),
        solid_cylinder_inner(xs, ys, 0.4, -0.2, 0.2),
        solid_cylinder_inner(xs, ys, -0.4, 0.3, 0.25),
    ]
    final, obs = geometry_sweep(template, geoms, min(steps, 10))
    solo = build(nx, ny)
    solo.set_solid(*geoms[0])
    solo.update_n(min(steps, 10))
    worst = max(
        float(np.abs(np.asarray(getattr(final, n)[0])
                     - np.asarray(getattr(solo.state, n))).max())
        for n in ("temp", "velx", "vely")
    )
    print(f"geometry sweep: K={len(geoms)} obstacles in one vmapped scan, "
          f"Nu per geometry = {[f'{v:.4f}' for v in obs[0]]}, "
          f"member-0 vs solo set_solid max diff = {worst:.3e}")
    ok &= worst < 1e-10

    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
