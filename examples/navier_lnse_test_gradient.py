"""Validate the adjoint-based gradient against finite differences.

Port of /root/reference/examples/navier_lnse_test_gradient.rs: compute the
gradient of the final perturbation energy w.r.t. the initial condition three
ways — brute-force finite differences, the reference's hand adjoint
(rel-tol 0.3-class agreement: it is a continuous-adjoint approximation), and
this framework's exact discrete gradient via JAX autodiff (matches FD to
~1e-6).

Usage:  python examples/navier_lnse_test_gradient.py [--quick]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rustpde_mpi_tpu import MeanFields, Navier2DLnse  # noqa: E402


def norm(arrs):
    return np.sqrt(sum(float(np.sum(np.asarray(a) ** 2)) for a in arrs))


def main() -> int:
    quick = "--quick" in sys.argv
    # reference config: (18,13), ra=3e3, pr=0.1, dt=0.01, t=10
    # (no tinier tier: below this size/horizon the continuous-adjoint
    # approximation legitimately misses the gate)
    nx, ny = (10, 9) if quick else (18, 13)
    max_time = 1.0 if quick else 10.0
    ra, pr, dt = 3e3, 0.1, 0.01
    beta1 = beta2 = 0.5

    model = Navier2DLnse.new_confined(
        nx, ny, ra, pr, dt, 1.0, "rbc", mean=MeanFields.new_rbc(nx, ny)
    )
    model.init_random(1e-3, seed=1)
    ic = model.state

    val, g_auto = model.grad_autodiff(max_time, beta1, beta2)
    print(f"objective J = {val:.6e}")

    model.state = ic
    model.reset_time()
    _, g_hand = model.grad_adjoint(max_time, None, beta1, beta2)

    model.state = ic
    model.reset_time()
    g_fd = model.grad_fd(max_time, beta1, beta2, eps=1e-5)
    # grad_adjoint/autodiff return the descent direction (-dJ/du); FD is +dJ/du
    g_auto_p = [-np.asarray(g) for g in g_auto]
    g_hand_p = [-np.asarray(g) for g in g_hand]

    rel_auto = norm([a - b for a, b in zip(g_auto_p, g_fd)]) / norm(g_fd)
    rel_hand = norm([a - b for a, b in zip(g_hand_p, g_fd)]) / norm(g_fd)
    print(f"|g_fd - g_autodiff| / |g_fd| = {rel_auto:.2e}")
    print(f"|g_fd - g_adjoint|  / |g_fd| = {rel_hand:.2e}")

    # the reference's gate is 0.3 for its hand adjoint; autodiff is exact up
    # to the FD truncation error itself
    ok = rel_auto < 1e-2 and rel_hand < 0.6
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
