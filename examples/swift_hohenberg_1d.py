"""1-D Swift–Hohenberg equation: du/dt = [r - (lap+1)^2] u - u^3.

TPU rebuild of /root/reference/examples/swift_hohenberg_1d.rs (128 points,
length=10, r=0.2, dt=0.01, integrate to t=100 saving every 5).  Exercises the
1-D space/field layer (Space1/Field1) end to end.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import SwiftHohenberg1D, integrate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nx", type=int, default=128)
    ap.add_argument("--r", type=float, default=0.2)
    ap.add_argument("--dt", type=float, default=0.01)
    ap.add_argument("--length", type=float, default=10.0)
    ap.add_argument("--max-time", type=float, default=100.0)
    ap.add_argument("--save", type=float, default=5.0)
    args = ap.parse_args()

    pde = SwiftHohenberg1D(args.nx, args.r, args.dt, args.length)
    print(f"SwiftHohenberg1D nx={args.nx}, r={args.r}, dt={args.dt}, length={args.length}")
    t0 = time.perf_counter()
    integrate(pde, args.max_time, args.save)
    wall = time.perf_counter() - t0
    steps = round(pde.get_time() / pde.get_dt())
    print(
        f"done: t={pde.get_time():.2f} ({steps} steps) in {wall:.1f}s "
        f"({steps / wall:.1f} steps/s), |F|={pde.norm():.4e}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
