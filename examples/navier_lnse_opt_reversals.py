"""Optimal-perturbation campaign: gradient-based search for disturbances
that trigger flow reversals.

Port of /root/reference/examples/navier_lnse_opt_reversals.rs:24-80: find a
large-scale-circulation base state with the DNS, build its mirrored state as
the optimization target, then iterate energy-constrained steepest descent on
the initial perturbation using the adjoint gradient of the final-time
distance to the target.

Usage:  python examples/navier_lnse_opt_reversals.py [--quick]
  --quick shrinks the grid/horizons so the whole campaign runs in ~a minute.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rustpde_mpi_tpu import (  # noqa: E402
    MeanFields,
    Navier2D,
    Navier2DNonLin,
    integrate,
    steepest_descent_energy_constrained,
)
from rustpde_mpi_tpu.models.lnse import l2_norm  # noqa: E402


def mirror_field(velx, vely, temp):
    """x-mirrored LSC state (the reversed circulation)
    (navier_lnse_opt_reversals.rs:7-13)."""
    return -velx[::-1, :], -vely.copy(), temp[::-1, :]


def find_base_field(nx, ny, dt, ra, pr, aspect, max_time):
    model = Navier2D.new_confined(nx, ny, ra, pr, dt, aspect, "rbc")
    model.init_random(1e-3)
    model.write_intervall = max_time * 10
    integrate(model, max_time, save_intervall=max_time)
    return model


def main() -> int:
    quick = "--quick" in sys.argv
    tiny = "--tiny" in sys.argv  # CI smoke tier
    nx, ny = (12, 11) if tiny else (24, 21) if quick else (128, 57)
    ra, pr, aspect = 1e5, 1.0, 1.0
    dt = 0.02
    base_time = 4.0 if tiny else 20.0 if quick else 300.0
    max_iter = 1 if tiny else 3 if quick else 30
    horizons = [2.0] if tiny else [5.0] if quick else np.linspace(5.0, 50.0, 5)
    energies = [1e-4] if (tiny or quick) else np.logspace(10.0, 0.0, 7) / 1e10
    alpha_0 = 1.0
    beta1 = beta2 = 0.5

    base = find_base_field(nx, ny, dt, ra, pr, aspect, base_time)
    base.write("data/mean.h5")
    mean = MeanFields.read_from(nx, ny, "data/mean.h5", bc="rbc")

    # target: mirrored base state, expressed as a perturbation about the mean
    mu, mv, mt = mean.physical()
    tu, tv, tt = mirror_field(mu, mv, mt)
    target = MeanFields(mean.space)
    target.velx = mean.space.forward(np.asarray(tu - mu))
    target.vely = mean.space.forward(np.asarray(tv - mv))
    target.temp = mean.space.forward(np.asarray(tt - mt))

    for max_time in horizons:
        for e_constraint in energies:
            print(f"MAX TIME {max_time}  ENERGY {e_constraint:.2e}")
            model = Navier2DNonLin.new_confined(
                nx, ny, ra, pr, dt, aspect, "rbc", mean=mean
            )
            model.init_random(1e-3)
            # scale IC to the energy constraint
            u, v, t = (np.asarray(a) for a in model._phys(model.state))
            e0 = float(l2_norm(u, u, v, v, t, t, beta1, beta2)) / u.size
            fac = np.sqrt(e_constraint / e0)
            model.set_field("velx", u * fac)
            model.set_field("vely", v * fac)
            model.set_field("temp", t * fac)

            best = np.inf
            alpha = alpha_0
            j_old = 0.0
            for it in range(max_iter):
                # fresh pressure every iteration
                # (navier_lnse_opt_reversals.rs:127-131)
                import jax.numpy as jnp

                model.state = model.state._replace(
                    pres=jnp.zeros_like(model.state.pres),
                    pseu=jnp.zeros_like(model.state.pseu),
                )
                model.reset_time()
                u0, v0, t0 = (np.asarray(a) for a in model._phys(model.state))
                fun_val, grads = model.grad_adjoint(
                    max_time, None, beta1, beta2, target=target
                )
                # backtracking step control (navier_lnse_opt_reversals.rs:143-152)
                if it > 0 and fun_val > j_old:
                    alpha /= 2.0
                    print(f"  set alpha: {alpha:4.2e}")
                    if alpha < 1e-3:
                        print("  alpha too small. Reset")
                        alpha = alpha_0
                j_old = fun_val
                print(f"  iter {it}: J = {fun_val:.6e}  alpha = {alpha:.3f}")
                best = min(best, fun_val)
                gu, gv, gt = (np.asarray(g) for g in grads)
                un, vn, tn = steepest_descent_energy_constrained(
                    u0, v0, t0, gu, gv, gt, beta1, beta2, alpha
                )
                model.reset_time()
                model.set_field("velx", un)
                model.set_field("vely", vn)
                model.set_field("temp", tn)
            print(f"  best J = {best:.6e}")
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
