"""The simulation service: continuously-batched ensemble serving.

Run a service that accepts Rayleigh–Bénard simulation requests through a
durable on-disk queue (and optionally a thin HTTP front), batches
compatible requests into ensemble slots LLM-style, and streams per-request
results back as each resolves — surviving NaN members, SIGTERM drains and
hard kills along the way (rerun the same command to recover).

Batch mode — enqueue a sweep and drain the queue::

    python examples/navier_rbc_serve.py --quick --requests 24

Chaos: inject a NaN into the running batch (per-request retry at dt/2),
or SIGTERM/SIGKILL the process mid-flight and rerun to resume::

    python examples/navier_rbc_serve.py --quick --requests 24 --fault nan@40

Daemon mode with the HTTP front (Ctrl-C drains gracefully)::

    python examples/navier_rbc_serve.py --daemon --http-port 8808
    curl -X POST localhost:8808/requests -d '{"ra":1e4,"nx":17,"ny":17,"dt":0.01,"horizon":0.2}'
    curl localhost:8808/stats
    curl localhost:8808/metrics    # live Prometheus exposition (telemetry/)
    curl localhost:8808/healthz    # liveness + queue depth + slot utilization
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import RequestFailed  # noqa: E402
from rustpde_mpi_tpu.config import CanonicalConfig, ServeConfig  # noqa: E402
from rustpde_mpi_tpu.serve import AdmissionError, SimServer  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--requests", type=int, default=0,
                    help="enqueue this many requests before serving")
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--ra", type=float, default=None)
    ap.add_argument("--dt", type=float, default=None)
    ap.add_argument("--horizon", type=float, default=None)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--run-dir", default="data/serve")
    ap.add_argument("--ckpt-every-s", type=float, default=60.0)
    ap.add_argument("--daemon", action="store_true",
                    help="keep serving after the queue drains")
    ap.add_argument("--http-port", type=int, default=None,
                    help="enable the HTTP front on this port (0 = ephemeral)")
    ap.add_argument("--fault", default=None,
                    help="nan@<step> | spike@<step> | kill@<step> | slow@<step>")
    ap.add_argument("--warm-profile", default=None,
                    help="warm campaign pool traffic profile: a JSON path, "
                    "or 'journal' to learn it from this run_dir's history "
                    "(see README 'Cold starts')")
    ap.add_argument("--canonicalize", action="store_true",
                    help="snap request dt onto the service ladder at "
                    "admission (CanonicalConfig defaults)")
    ap.add_argument("--drain-after-s", type=float, default=None,
                    help="request a graceful drain this many seconds in "
                    "(the soak harness's deterministic SIGTERM stand-in)")
    ap.add_argument("--horizon-jitter", type=int, default=0,
                    help="stagger request horizons by (seed %% N) extra "
                    "steps: slot completions stop aligning on one boundary, "
                    "which is what makes continuous batching (and drains "
                    "that catch work in flight) realistic")
    args = ap.parse_args()

    if args.quick:
        nx, ny, ra, dt, horizon = 17, 17, 1e4, 1e-2, 0.2
    else:
        nx, ny, ra, dt, horizon = 65, 65, 1e6, 2e-3, 1.0
    nx, ny = args.nx or nx, args.ny or ny
    ra, dt = args.ra or ra, args.dt or dt
    horizon = args.horizon or horizon

    cfg = ServeConfig(
        run_dir=args.run_dir,
        slots=args.slots,
        max_queue=args.max_queue,
        checkpoint_every_s=args.ckpt_every_s,
        idle_exit=not args.daemon,
        http_port=args.http_port,
        warm_profile=args.warm_profile,
        canonicalize=CanonicalConfig() if args.canonicalize else None,
    )
    server = SimServer(cfg, fault=args.fault)

    ids = []
    for seed in range(args.requests):
        h = horizon
        if args.horizon_jitter:
            h += (seed % args.horizon_jitter) * dt
        try:
            req = server.submit(
                {"ra": ra, "pr": 1.0, "nx": nx, "ny": ny, "dt": dt,
                 "horizon": h, "seed": seed}
            )
        except AdmissionError as exc:
            print(f"request {seed} rejected: {exc}", file=sys.stderr)
            continue
        ids.append(req.id)

    if args.drain_after_s is not None:
        import threading

        threading.Timer(args.drain_after_s, server.request_drain).start()
    summary = server.serve()
    print(json.dumps(summary))

    failed = 0
    for rid in ids:
        try:
            result = server.result(rid)
        except RequestFailed as exc:
            print(f"  {rid}: FAILED — {exc}", file=sys.stderr)
            failed += 1
            continue
        if result is not None:
            print(f"  {rid}: nu={result['nu']:.6g} steps={result['steps']} "
                  f"latency={result['latency_s']:.2f}s")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
