"""Rayleigh–Bénard convection with the overlapped I/O pipeline.

The resilient runner's checkpoint/diagnostics IO moved off the device's
critical path (utils/io_pipeline.py): cadence checkpoints are fetched to
host at the boundary and serialized + digest-stamped + fsynced on a
background worker, the printed Nu line / info.txt rows ride observable
futures one boundary behind the device, and the chunked driver's break
checks are double-buffered so the dispatch queue is never fenced.

Run the same campaign both ways and compare the summary's ``io`` block:

    python examples/navier_rbc_pipelined.py --quick
    python examples/navier_rbc_pipelined.py --quick --blocking

``write_s`` is worker time that the blocking mode would have spent holding
the device idle; ``queue_wait_s`` is back-pressure (the disk falling behind
the cadence).  Stepping results are bit-identical either way — the pipeline
reorders IO, never physics.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import DispatchHang, DivergenceError, Navier2D, ResilientRunner
from rustpde_mpi_tpu.config import IOConfig


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--ra", type=float, default=None)
    ap.add_argument("--dt", type=float, default=None)
    ap.add_argument("--max-time", type=float, default=None)
    ap.add_argument("--run-dir", default="data/pipelined")
    ap.add_argument(
        "--ckpt-every-t", type=float, default=None,
        help="sim-time checkpoint cadence (default: every save interval)",
    )
    ap.add_argument(
        "--blocking", action="store_true",
        help="disable the pipeline (synchronous IO) for an A/B comparison",
    )
    ap.add_argument(
        "--queue-depth", type=int, default=1,
        help="in-flight background writes before submission blocks",
    )
    ap.add_argument(
        "--fault", default=None,
        help="deterministic fault injection, e.g. nan@<step> (RUSTPDE_FAULT works too)",
    )
    ap.add_argument("--fresh", action="store_true", help="no auto-resume")
    args = ap.parse_args()

    if args.quick:
        nx, ny, ra, dt, max_time, save = 33, 33, 1e5, 0.01, 1.0, 0.25
    else:
        nx, ny, ra, dt, max_time, save = 129, 129, 1e7, 2e-3, 10.0, 1.0
    nx = args.nx or nx
    ny = args.ny or ny
    ra = args.ra or ra
    dt = args.dt or dt
    max_time = args.max_time or max_time

    io = (
        IOConfig.blocking()
        if args.blocking
        else IOConfig(queue_depth=args.queue_depth)
    )
    model = Navier2D.new_confined(nx, ny, ra, 1.0, dt, 1.0, "rbc")
    runner = ResilientRunner(
        model,
        max_time=max_time,
        save_intervall=save,
        run_dir=args.run_dir,
        checkpoint_every_s=None,
        checkpoint_every_t=args.ckpt_every_t or save,
        fault=args.fault,
        resume=not args.fresh,
        io=io,
    )
    try:
        summary = runner.run()
    except DivergenceError as exc:
        print(f"unrecoverable divergence: {exc}")
        return 2
    except DispatchHang as exc:
        print(f"dispatch hang: {exc}")
        return 3
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
