"""Rayleigh–Bénard convection under the resilient run harness.

The long-run driver for real campaigns (utils/resilience.py): atomic rolling
checkpoints on a wall-clock/sim-time cadence, auto-resume from the newest
valid checkpoint, SIGTERM/SIGINT checkpoint-then-exit (safe under preemption
— just rerun the same command to continue), divergence retry with dt
backoff, and a JSONL journal of everything that happened.

Kill it mid-flight and rerun; it picks up where the last checkpoint left
off.  Inject failures deterministically to watch recovery work:

    python examples/navier_rbc_resilient.py --quick --fault nan@40
    RUSTPDE_FAULT=kill@60 python examples/navier_rbc_resilient.py --quick
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import DispatchHang, DivergenceError, Navier2D, ResilientRunner


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--ra", type=float, default=None)
    ap.add_argument("--dt", type=float, default=None)
    ap.add_argument("--max-time", type=float, default=None)
    ap.add_argument("--run-dir", default="data/resilient")
    ap.add_argument(
        "--ckpt-every-s", type=float, default=300.0,
        help="wall-clock checkpoint cadence (seconds)",
    )
    ap.add_argument(
        "--ckpt-every-t", type=float, default=None,
        help="sim-time checkpoint cadence",
    )
    ap.add_argument("--keep", type=int, default=3, help="retention window")
    ap.add_argument("--retries", type=int, default=3, help="divergence retries")
    ap.add_argument(
        "--dt-backoff", type=float, default=0.5,
        help="dt shrink factor per divergence retry",
    )
    ap.add_argument(
        "--dispatch-timeout-s", type=float, default=None,
        help="hang watchdog deadline per device dispatch (default off)",
    )
    ap.add_argument(
        "--fault", default=None,
        help="inject a deterministic fault: nan@<step> | spike@<step> | "
        "kill@<step> | slow@<step> (also via RUSTPDE_FAULT; spike is the "
        "finite incipient blow-up the governed driver "
        "examples/navier_rbc_governed.py catches pre-NaN)",
    )
    ap.add_argument(
        "--fresh", action="store_true",
        help="start a new campaign (no auto-resume); refuses to run if "
        "--run-dir still holds a previous campaign's checkpoints",
    )
    ap.add_argument("--mesh", action="store_true", help="pencil-shard over all devices")
    ap.add_argument(
        "--sharded", action="store_true",
        help="force the distributed two-phase checkpoint format (per-host "
        "shard files + manifest commit marker); auto-selected on "
        "multi-process runtimes either way",
    )
    args = ap.parse_args()

    if args.quick:
        nx, ny, ra, dt, max_time, save = 33, 33, 1e5, 0.01, 1.0, 0.25
    else:
        nx, ny, ra, dt, max_time, save = 129, 129, 1e7, 2e-3, 10.0, 1.0
    nx = args.nx or nx
    ny = args.ny or ny
    ra = args.ra or ra
    dt = args.dt or dt
    max_time = args.max_time or max_time

    mesh = None
    if args.mesh:
        from rustpde_mpi_tpu.parallel import make_mesh

        mesh = make_mesh()

    io = None
    if args.sharded:
        from rustpde_mpi_tpu.config import IOConfig

        io = IOConfig(sharded_checkpoints=True)

    model = Navier2D.new_confined(nx, ny, ra, 1.0, dt, 1.0, "rbc", mesh=mesh)
    runner = ResilientRunner(
        model,
        max_time=max_time,
        save_intervall=save,
        run_dir=args.run_dir,
        checkpoint_every_s=args.ckpt_every_s,
        checkpoint_every_t=args.ckpt_every_t,
        keep=args.keep,
        max_retries=args.retries,
        dt_backoff=args.dt_backoff,
        dispatch_timeout_s=args.dispatch_timeout_s,
        fault=args.fault,
        resume=not args.fresh,
        io=io,
    )
    try:
        summary = runner.run()
    except DivergenceError as exc:
        print(f"unrecoverable divergence: {exc}")
        return 2
    except DispatchHang as exc:
        print(f"dispatch hang: {exc}")
        return 3
    print(json.dumps(summary))
    # "preempted" is a clean exit: the checkpoint is on disk, rerunning the
    # same command resumes the campaign
    return 0


if __name__ == "__main__":
    sys.exit(main())
