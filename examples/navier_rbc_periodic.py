"""Horizontally-periodic Rayleigh–Bénard convection (Fourier x Chebyshev).

Port of /root/reference/examples/navier_rbc_periodic.rs (128x129, Ra=1e5,
Pr=1, dt=0.01, aspect=1 -> lateral length 2*pi, integrate to t=10 saving
every 5).  On the TPU chip the Fourier axis runs in the split Re/Im
representation (no complex dtypes there); --bc hc selects the horizontally-
periodic convection cell with heated-bottom cosine profile.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import Navier2D, integrate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nx", type=int, default=128)
    ap.add_argument("--ny", type=int, default=129)
    ap.add_argument("--ra", type=float, default=1e5)
    ap.add_argument("--dt", type=float, default=0.01)
    ap.add_argument("--bc", default="rbc", choices=["rbc", "hc"])
    ap.add_argument("--max-time", type=float, default=10.0)
    ap.add_argument("--save", type=float, default=5.0)
    args = ap.parse_args()

    navier = Navier2D.new_periodic(args.nx, args.ny, args.ra, 1.0, args.dt, 1.0, args.bc)
    print(f"periodic RBC {args.nx}x{args.ny}, Ra={args.ra:g}, bc={args.bc}")
    t0 = time.perf_counter()
    integrate(navier, args.max_time, args.save)
    wall = time.perf_counter() - t0
    steps = round(navier.get_time() / navier.get_dt())
    nu, nuv, re, div = navier.get_observables()
    ok = div == div and nu == nu
    print(
        f"done: {steps} steps in {wall:.1f}s ({steps / wall:.1f} steps/s), "
        f"Nu={nu:.4f} Re={re:.3f} |div|={div:.2e}  {'OK' if ok else 'FAILED'}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
