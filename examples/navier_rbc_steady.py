"""Find a steady state of Rayleigh-Benard convection by adjoint descent.

Port of /root/reference/examples/navier_rbc_steady.rs (and the
Navier2DAdjoint doc example, steady_adjoint.rs:6-30): initialize a large
scale circulation mode, then descend the smoothed-residual norm until the
steady state converges (mean residual < 1e-7).

Usage:  python examples/navier_rbc_steady.py [--quick]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import Navier2DAdjoint, integrate  # noqa: E402


def main() -> int:
    quick = "--quick" in sys.argv
    nx = ny = 33 if quick else 65
    ra, pr, aspect = 1e4, 1.0, 1.0
    dt = 0.005
    max_time = 40.0 if quick else 400.0

    model = Navier2DAdjoint.new_confined(nx, ny, ra, pr, dt, aspect, "rbc")
    model.set_temperature(0.5, 1.0, 1.0)
    model.set_velocity(0.5, 1.0, 1.0)
    model.write_intervall = max_time  # snapshots only at the end

    t0 = time.perf_counter()
    integrate(model, max_time, save_intervall=max_time / 20.0)
    elapsed = time.perf_counter() - t0

    res = model.residual()
    nu = model.eval_nu()
    iters = round(model.time / dt)
    print(f"{iters} adjoint iterations in {elapsed:.2f} s "
          f"-> {iters / elapsed:.1f} iters/s")
    print(f"final residual = {res:.3e}, Nu = {nu:.6f}")
    # measured on the 33^2 CPU run: res ~9e-4 at t=40, ~1e-7 at t~190
    ok = res < 2e-3 if quick else res < 1e-7
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
