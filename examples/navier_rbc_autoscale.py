"""Autoscaling fleet on preemptible capacity: controller + chaos driver.

One process runs the :class:`Autoscaler` control loop over a shared
``--run-dir``: it watches the durable queue (depth, deadline slack) and
the replica heartbeats, and drives a :class:`LocalProcessLauncher` that
spawns/retires ``SimServer`` replicas as subprocesses.  Optionally it
plays the preemptible-capacity adversary against its own fleet — a
Poisson arrival process of preemptions, each either a notice-SIGTERM
(the replica parks its running slots durably inside the
``RUSTPDE_PREEMPT_NOTICE_S`` window and releases its leases) or a hard
SIGKILL (survivors break the dead replica's leases and resume from the
parked continuations).  Loss-free either way.

Seed some work and let the controller scale for it::

    python examples/navier_rbc_autoscale.py --run-dir data/autoscale \
        --requests 6 --max-replicas 3 --notice-s 5

Chaos soak — preempt twice, half of them hard kills::

    python examples/navier_rbc_autoscale.py --run-dir data/autoscale \
        --requests 6 --chaos-preempts 2 --chaos-kill-frac 0.5 --seed 7

``--steps N`` bounds the controller to N decide ticks (0 = run until the
queue drains and the fleet is idle); the exit line is a JSON summary of
decisions/spawns/retirements/preemptions for drivers to parse.
"""

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu.config import AutoscaleConfig  # noqa: E402


def persisted_mid_flight(run_dir: str, rid: str) -> bool:
    """Has this replica durably parked a mid-flight continuation yet?
    The chaos schedule only preempts victims that will resume WITH state
    — an idle or still-importing replica proves nothing."""
    path = os.path.join(run_dir, "replicas", rid, "journal.jsonl")
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                if (event.get("event") == "continuation_persisted"
                        and event.get("steps", 0) > 0):
                    return True
    except OSError:
        pass
    return False


def submit_requests(run_dir: str, n: int, seed: int,
                    horizon: float) -> list[str]:
    """Durably enqueue n small RBC requests (the controller scales FOR
    work, so the demo seeds some) — same fsynced handoff a proxy makes."""
    from rustpde_mpi_tpu.serve.queue import DurableQueue
    from rustpde_mpi_tpu.serve.request import SimRequest

    rng = random.Random(seed)
    queue = DurableQueue(os.path.join(run_dir, "queue"), max_queue=1 << 20)
    ids = []
    for i in range(n):
        req = SimRequest.from_dict(
            {
                "ra": rng.choice([1e4, 2e4]),
                "nx": 17,
                "ny": 17,
                "dt": 0.01,
                "horizon": horizon,
                "tenant": f"t{i % 2}",
            }
        )
        req.validate()
        queue.submit(req)
        ids.append(req.id)
    return ids


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-dir", default="data/autoscale")
    ap.add_argument("--requests", type=int, default=0,
                    help="seed this many small RBC requests before scaling")
    ap.add_argument("--horizon", type=float, default=0.5,
                    help="sim horizon of each seeded request")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--queue-high", type=int, default=4)
    ap.add_argument("--sustain-s", type=float, default=2.0)
    ap.add_argument("--idle-sustain-s", type=float, default=6.0)
    ap.add_argument("--slack-low-s", type=float, default=30.0)
    ap.add_argument("--cooldown-s", type=float, default=10.0)
    ap.add_argument("--decide-s", type=float, default=1.0)
    ap.add_argument("--notice-s", type=float, default=None,
                    help="arm RUSTPDE_PREEMPT_NOTICE_S in spawned replicas")
    ap.add_argument("--steps", type=int, default=0,
                    help="controller decide ticks (0 = until drained + idle)")
    ap.add_argument("--chaos-preempts", type=int, default=0,
                    help="total Poisson-arrival preemptions to inject")
    ap.add_argument("--chaos-kill-frac", type=float, default=0.5,
                    help="fraction of preemptions that SIGKILL (vs notice)")
    ap.add_argument("--chaos-mean-gap-s", type=float, default=4.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--chunk-steps", type=int, default=4)
    ap.add_argument("--lease-ttl-s", type=float, default=None)
    ap.add_argument("--heartbeat-s", type=float, default=None)
    args = ap.parse_args()

    import time

    from rustpde_mpi_tpu import config as _config
    from rustpde_mpi_tpu.serve.fleet import Autoscaler, LocalProcessLauncher

    # arm the persistent compile cache BEFORE constructing the launcher:
    # every replica this controller spawns inherits the cache dir through
    # the launcher env and boots warm against serialized executables
    _config.ensure_compile_cache()
    os.makedirs(args.run_dir, exist_ok=True)
    if args.requests:
        ids = submit_requests(args.run_dir, args.requests, args.seed,
                              args.horizon)
        print(json.dumps({"submitted": ids}), flush=True)

    serve_args = ["--slots", str(args.slots),
                  "--chunk-steps", str(args.chunk_steps)]
    if args.lease_ttl_s is not None:
        serve_args += ["--lease-ttl-s", str(args.lease_ttl_s)]
    if args.heartbeat_s is not None:
        serve_args += ["--heartbeat-s", str(args.heartbeat_s)]
    launcher = LocalProcessLauncher(
        args.run_dir, serve_args=serve_args, notice_s=args.notice_s,
        log_dir=os.path.join(args.run_dir, "launcher-logs"),
    )
    asc = Autoscaler(
        args.run_dir,
        launcher,
        AutoscaleConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            queue_high=args.queue_high,
            sustain_s=args.sustain_s,
            idle_sustain_s=args.idle_sustain_s,
            slack_low_s=args.slack_low_s,
            cooldown_s=args.cooldown_s,
            decide_s=args.decide_s,
            notice_s=args.notice_s,
        ),
    )

    # Poisson-arrival preemption schedule: exponential gaps, deterministic
    # under --seed, each one SIGKILL with prob --chaos-kill-frac else a
    # notice-SIGTERM retire through the launcher
    rng = random.Random(args.seed + 1)
    chaos_at = []
    t = time.monotonic()
    for _ in range(args.chaos_preempts):
        t += rng.expovariate(1.0 / max(0.1, args.chaos_mean_gap_s))
        chaos_at.append(t)
    preempted = {"notice": 0, "kill": 0, "dropped": 0}

    tick = 0
    try:
        while True:
            decision = asc.step()
            tick += 1
            now = time.monotonic()
            while chaos_at and chaos_at[0] <= now:
                victims = [h for h in launcher.handles()
                           if launcher.alive(h) and not h.retired
                           and persisted_mid_flight(args.run_dir,
                                                    h.replica_id)]
                if not victims:
                    # a due arrival is HELD until some replica is provably
                    # mid-flight (has parked state to resume from) — but
                    # a drained queue will never produce one: drop then
                    if decision["queued"] == 0 and decision["running"] == 0:
                        preempted["dropped"] += len(chaos_at)
                        chaos_at.clear()
                    break
                chaos_at.pop(0)
                victim = rng.choice(victims)
                if rng.random() < args.chaos_kill_frac:
                    launcher.kill(victim)
                    preempted["kill"] += 1
                else:
                    launcher.retire(victim)  # SIGTERM -> notice drain
                    preempted["notice"] += 1
                print(json.dumps({"chaos_preempt": victim.replica_id,
                                  **preempted}), flush=True)
            if args.steps and tick >= args.steps:
                break
            if not args.steps and not chaos_at:
                # every submitted request reached a terminal state: done
                # (the finally clause retires whatever fleet remains)
                if decision["queued"] == 0 and decision["running"] == 0:
                    break
            time.sleep(args.decide_s)
    finally:
        asc.stop(retire_fleet=True)
    print(json.dumps({"outcome": "done", **asc.stats(), **preempted}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
