"""Standalone Poisson / Helmholtz solves with analytic verification.

Analog of the reference's solver check examples
(/root/reference/examples/poisson_mpi.rs:30-49, hholtz_mpi.rs): solve with a
manufactured solution on the device and assert the max error.
"""

import sys

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu.solver import Hholtz, Poisson


def check(name: str, err: float, tol: float) -> bool:
    ok = err < tol
    print(f"{name:<40s} max|err| = {err:8.2e}  {'OK' if ok else 'FAILED'}")
    return ok


def main() -> int:
    nx, ny = 65, 65
    ok = True

    # Poisson, cheb_dirichlet^2 (examples/poisson_mpi.rs analytic check)
    space = rp.Space2(rp.cheb_dirichlet(nx), rp.cheb_dirichlet(ny))
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    expected = np.cos(n * X) * np.cos(n * Y)
    f = -2.0 * n * n * expected
    sol = Poisson(space, (1.0, 1.0)).solve(space.to_ortho(space.forward(f)))
    err = float(np.abs(np.asarray(space.backward(sol)) - expected).max())
    ok &= check("poisson cheb_dirichlet^2", err, 1e-6)

    # Helmholtz (I - c D2) u = f, cheb_dirichlet^2
    c = 0.1
    f = expected * (1.0 + c * 2.0 * n * n)
    sol = Hholtz(space, (c, c)).solve(space.to_ortho(space.forward(f)))
    err = float(np.abs(np.asarray(space.backward(sol)) - expected).max())
    ok &= check("hholtz cheb_dirichlet^2", err, 1e-6)

    # Poisson, fourier x chebyshev (periodic variant); complex-dtype path,
    # skipped on backends without complex support (TPU uses SplitSpace2)
    try:
        space = rp.Space2(rp.fourier_r2c(16), rp.cheb_dirichlet(ny))
        x, y = space.base_x.points, space.base_y.points
        X, Y = np.meshgrid(x, y, indexing="ij")
        expected = np.cos(2 * X) * np.cos(n * Y)
        f = -(4.0 + n * n) * expected
        sol = Poisson(space, (1.0, 1.0)).solve(space.to_ortho(space.forward(f)))
        err = float(np.abs(np.asarray(space.backward(sol)) - expected).max())
        ok &= check("poisson fourier_r2c x cheb_dirichlet", err, 1e-6)
    except NotImplementedError as exc:
        print(f"poisson fourier x cheb: skipped ({exc})")

    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
