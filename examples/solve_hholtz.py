"""Standalone Helmholtz solves with analytic verification (assert-style).

Port of the reference's MPI solver-check examples
(/root/reference/examples/hholtz_mpi.rs: 257^2 cheb_dirichlet^2, alpha=1e-5,
f = cos(pi/2 x) cos(pi/2 y) -> u = f / (1 + 2 alpha (pi/2)^2);
hholtz_periodic_mpi.rs: the Fourier x Chebyshev variant).  ``--mesh`` runs
the same solves GSPMD-sharded over all visible devices — the reference runs
these under ``cargo mpirun -np 2`` and panics on mismatch; here a failed
allclose exits nonzero.
"""

import argparse
import contextlib
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the container's sitecustomize force-sets jax_platforms programmatically,
    # overriding the env var; honor it again (same dance as tests/conftest.py)
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from rustpde_mpi_tpu import Space2, cheb_dirichlet, fourier_r2c
from rustpde_mpi_tpu.solver import HholtzAdi

ALPHA = 1e-5


def check(space, note: str, f, lam: float, mesh=None, tol: float = 1e-6) -> None:
    """Solve (I - ALPHA*lap) u = f where lap f = -lam * f, so u = f/(1+ALPHA*lam)."""
    import jax.numpy as jnp

    from rustpde_mpi_tpu.parallel.mesh import use_mesh

    scope = use_mesh(mesh) if mesh is not None else contextlib.nullcontext()
    with scope:
        solver = HholtzAdi(space, (ALPHA, ALPHA))
        expected = f / (1.0 + ALPHA * lam)
        rhs = space.to_ortho(space.forward(jnp.asarray(f)))
        out = np.asarray(space.backward(solver.solve(rhs)))
    err = float(np.abs(out - expected).max())
    status = "OK" if err < tol else "FAILED"
    print(f"  {note}: max |err| = {err:.3e}  {status}")
    if err >= tol:
        raise SystemExit(1)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=257)
    ap.add_argument("--mesh", action="store_true", help="shard over all devices")
    args = ap.parse_args()

    mesh = None
    if args.mesh:
        import jax
        from jax.sharding import Mesh

        from rustpde_mpi_tpu.parallel.mesh import AXIS

        mesh = Mesh(np.array(jax.devices()), (AXIS,))
        print(f"pencil mesh over {len(jax.devices())} devices")

    n = args.n
    hn = np.pi / 2.0
    print(f"Helmholtz ADI checks at {n}x{n} (alpha={ALPHA:g}):")

    # confined: f = cos(pi/2 x) cos(pi/2 y), lap f = -2 (pi/2)^2 f
    sp = Space2(cheb_dirichlet(n), cheb_dirichlet(n))
    xs, ys = (b.points for b in sp.bases)
    f = np.cos(hn * xs)[:, None] * np.cos(hn * ys)[None, :]
    check(sp, "cheb x cheb   ", f, 2.0 * hn * hn, mesh)

    # periodic x: f = cos(2x) cos(pi/2 y), lap f = -(4 + (pi/2)^2) f
    sp = Space2(fourier_r2c(n - 1), cheb_dirichlet(n))
    xs, ys = (b.points for b in sp.bases)
    f = np.cos(2.0 * xs)[:, None] * np.cos(hn * ys)[None, :]
    check(sp, "fourier x cheb", f, 4.0 + hn * hn, mesh)
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
