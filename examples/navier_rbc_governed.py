"""Rayleigh–Bénard convection under the proactive stability governor.

The governed long-run driver (utils/governor.py + utils/resilience.py): the
scanned step chunks carry on-device sentinels — per-step CFL number,
volume-averaged kinetic energy and the pre-projection |div| residual — and a
host-side governor drives dt toward a target Courant number on a geometric,
rung-cached dt ladder.  An incipient blow-up trips the hard CFL ceiling
*before* NaNs appear: the chunk is rolled back in memory (no checkpoint IO)
and dt descends the ladder; after a healthy stretch the governor climbs back
up.  The reactive checkpoint-rollback machinery of
examples/navier_rbc_resilient.py stays underneath as the last resort.

Watch the whole loop on a deterministic incipient blow-up (a finite
velocity spike, caught pre-NaN):

    python examples/navier_rbc_governed.py --quick --fault spike@40
    RUSTPDE_FAULT=spike@60 python examples/navier_rbc_governed.py --quick

The run prints the journal's cfl/dt_adjust trail and ends with the RunHealth
summary (dt trajectory, sentinel extrema, checkpoint rollbacks avoided).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu import (
    DispatchHang,
    DivergenceError,
    Navier2D,
    ResilientRunner,
)
from rustpde_mpi_tpu.config import StabilityConfig


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small fast config")
    ap.add_argument("--nx", type=int, default=None)
    ap.add_argument("--ny", type=int, default=None)
    ap.add_argument("--ra", type=float, default=None)
    ap.add_argument("--dt", type=float, default=None)
    ap.add_argument("--max-time", type=float, default=None)
    ap.add_argument("--run-dir", default="data/governed")
    ap.add_argument(
        "--target-cfl", type=float, default=0.5,
        help="Courant number the dt controller drives toward",
    )
    ap.add_argument(
        "--max-cfl", type=float, default=1.0,
        help="hard on-device ceiling: chunks early-exit (pre-divergence) above it",
    )
    ap.add_argument(
        "--ladder-ratio", type=float, default=2.0,
        help="geometric dt-ladder spacing (solver factorizations cached per rung)",
    )
    ap.add_argument(
        "--grow-after", type=int, default=4,
        help="healthy chunks at a rung before climbing back up the ladder",
    )
    ap.add_argument(
        "--dt-max", type=float, default=None,
        help="ladder ceiling (default: the starting dt)",
    )
    ap.add_argument("--dt-min", type=float, default=None, help="ladder floor")
    ap.add_argument(
        "--ckpt-every-s", type=float, default=300.0,
        help="wall-clock checkpoint cadence (the reactive safety net below)",
    )
    ap.add_argument("--retries", type=int, default=3, help="reactive divergence retries")
    ap.add_argument(
        "--fault", default=None,
        help="inject a deterministic fault: spike@<step> (pre-divergence "
        "catch) | nan@<step> | kill@<step> | slow@<step> (also via "
        "RUSTPDE_FAULT)",
    )
    ap.add_argument(
        "--spike-factor", type=float, default=None,
        help="velocity scale of the spike fault (default 50, or "
        "RUSTPDE_SPIKE_FACTOR)",
    )
    args = ap.parse_args()

    if args.quick:
        nx, ny, ra, dt, max_time, save = 33, 33, 1e5, 0.01, 1.0, 0.25
    else:
        nx, ny, ra, dt, max_time, save = 129, 129, 1e7, 2e-3, 10.0, 1.0
    nx = args.nx or nx
    ny = args.ny or ny
    ra = args.ra or ra
    dt = args.dt or dt
    max_time = args.max_time or max_time

    model = Navier2D.new_confined(nx, ny, ra, 1.0, dt, 1.0, "rbc")
    runner = ResilientRunner(
        model,
        max_time=max_time,
        save_intervall=save,
        run_dir=args.run_dir,
        checkpoint_every_s=args.ckpt_every_s,
        max_retries=args.retries,
        fault=args.fault,
        spike_factor=args.spike_factor,
        stability=StabilityConfig(
            target_cfl=args.target_cfl,
            max_cfl=args.max_cfl,
            ladder_ratio=args.ladder_ratio,
            grow_after=args.grow_after,
            dt_max=args.dt_max,
            dt_min=args.dt_min,
        ),
    )
    try:
        summary = runner.run()
    except DivergenceError as exc:
        print(f"unrecoverable divergence: {exc}")
        return 2
    except DispatchHang as exc:
        print(f"dispatch hang: {exc}")
        return 3

    # replay the governor's trail from the journal
    with open(runner.journal_path, encoding="utf-8") as fh:
        for line in fh:
            event = json.loads(line)
            if event["event"] in ("pre_divergence", "dt_adjust", "run_health"):
                print(json.dumps(event))
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
