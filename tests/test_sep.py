"""Parity tests for the sep-layout kernels at sizes where every production
impl actually engages (the small-n suite never reaches _StripTrapezoid's
192-row minimum or the fused conv paths — round-4 review finding)."""

import numpy as np
import pytest

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu.ops import chebyshev as chb
from rustpde_mpi_tpu.ops import transforms as tr
from rustpde_mpi_tpu.ops.folded import FoldedMatrix, parity_perm, parity_perm_inv

import jax.numpy as jnp

_dev = lambda m: jnp.asarray(m)  # noqa: E731


@pytest.mark.parametrize("n", [513, 512])
def test_trapezoid_strips_engage_and_match(n):
    S = chb.stencil_dirichlet(n)
    for order in (1, 2):
        G = chb.diff_matrix(n, order) @ S
        fm = FoldedMatrix(G, _dev, sep_in=True, sep_out=True)
        assert "trapezoid" in fm.kind, fm.kind  # the production impl runs
        assert fm.flops_factor < 0.45
        rng = np.random.default_rng(order)
        x = rng.standard_normal((G.shape[1], 3))
        got = np.asarray(fm.apply(jnp.asarray(x[parity_perm(G.shape[1])]), 0))
        want = (G @ x)[parity_perm(G.shape[0])]
        np.testing.assert_allclose(got, want, atol=1e-11 * np.abs(want).max())


@pytest.mark.parametrize("n", [17, 16, 33])
def test_fwd_cut_matches_masked_forward(n):
    """forward_dealiased (dead GEMM rows dropped) == forward * 2/3-mask."""
    sep = rp.Space2(rp.cheb_dirichlet(n), rp.cheb_neumann(n + 1), sep=True, method="matmul")
    assert all(sep.sep)
    rng = np.random.default_rng(0)
    v = rng.standard_normal(sep.shape_physical)
    got = np.asarray(sep.forward_dealiased(v))
    want = np.asarray(sep.forward(v)) * sep.dealias_mask()
    np.testing.assert_allclose(got, want, atol=1e-13)


@pytest.mark.parametrize("deriv", [(1, 0), (0, 1), (2, 0), (1, 1)])
def test_backward_gradient_fusion_matches(deriv):
    """Syn @ D @ S fusion (incl. the sign=-1 odd-order synthesis symmetry)
    == backward_ortho(gradient(.))."""
    sep = rp.Space2(rp.cheb_dirichlet(33), rp.cheb_neumann(32), sep=True, method="matmul")
    assert all(sep.sep)
    rng = np.random.default_rng(1)
    vhat = sep.forward(rng.standard_normal(sep.shape_physical))
    got = np.asarray(sep.backward_gradient(vhat, deriv, (1.0, 2.0)))
    want = np.asarray(sep.backward_ortho(sep.gradient(vhat, deriv, (1.0, 2.0))))
    np.testing.assert_allclose(got, want, atol=1e-10)


@pytest.mark.parametrize("n,order", [(33, 1), (32, 2), (17, 3)])
def test_cheb_derivative_sep_matches(n, order):
    rng = np.random.default_rng(2)
    c = rng.standard_normal((n, 4))
    want = np.asarray(tr.cheb_derivative(jnp.asarray(c), order, 0))
    got = np.asarray(
        tr.cheb_derivative_sep(jnp.asarray(c[parity_perm(n)]), order, 0)
    )[parity_perm_inv(n)]
    np.testing.assert_allclose(got, want, atol=1e-11 * max(1.0, np.abs(want).max()))


def test_sep_layout_roundtrip_io_boundary():
    """spectral_to_natural/from_natural invert each other and match the
    natural-space coefficients."""
    nat = rp.Space2(rp.cheb_dirichlet(19), rp.cheb_dirichlet(18), sep=False, method="matmul")
    sep = rp.Space2(rp.cheb_dirichlet(19), rp.cheb_dirichlet(18), sep=True, method="matmul")
    rng = np.random.default_rng(3)
    v = rng.standard_normal(nat.shape_physical)
    a = np.asarray(nat.forward(v))
    b = sep.forward(v)
    np.testing.assert_allclose(sep.spectral_to_natural(b), a, atol=1e-13)
    np.testing.assert_allclose(
        np.asarray(sep.spectral_from_natural(sep.spectral_to_natural(b))),
        np.asarray(b),
        atol=0,
    )


@pytest.mark.parametrize("deriv", [(0, 0), (1, 0), (0, 1)])
def test_backward_gradient_fast_matches(deriv, monkeypatch):
    """The fast-key plumbing (('bwd','fast') / ('bwd_grad',o,'fast')): under
    X64 (the CI default) no downgrade happens, so fast == exact bitwise; the
    key construction and base_key slicing are exercised either way."""
    sep = rp.Space2(rp.cheb_dirichlet(33), rp.cheb_neumann(32), sep=True, method="matmul")
    assert all(sep.sep)
    rng = np.random.default_rng(7)
    vhat = sep.forward(rng.standard_normal(sep.shape_physical))
    fast = np.asarray(sep.backward_gradient(vhat, deriv, (1.0, 2.0), fast=True))
    exact = np.asarray(sep.backward_gradient(vhat, deriv, (1.0, 2.0), fast=False))
    np.testing.assert_array_equal(fast, exact)
    # the alias path: fast keys must map to the SAME cached FoldedMatrix
    base = sep.bases[0]
    key = ("bwd_grad", 1) if deriv[0] else "bwd"
    fkey = key + ("fast",) if isinstance(key, tuple) else (key, "fast")
    assert base._sep_dev(fkey) is base._sep_dev(key)


def test_backward_fast_matches_backward():
    sep = rp.Space2(rp.cheb_dirichlet(17), rp.cheb_dirichlet(16), sep=True, method="matmul")
    rng = np.random.default_rng(8)
    vhat = sep.forward(rng.standard_normal(sep.shape_physical))
    np.testing.assert_array_equal(
        np.asarray(sep.backward_fast(vhat)), np.asarray(sep.backward(vhat))
    )


def test_fused_projection_gradient_helper():
    """bases.fused_projection_gradient: matmul-only gating, periodic -> None,
    value-keyed dedup (square grids share operators), and numerical equality
    with the unfused from_ortho(gradient(.)) chain."""
    from rustpde_mpi_tpu.bases import fused_projection_gradient

    q = rp.Space2(rp.cheb_neumann(33), rp.cheb_neumann(33), method="matmul")
    u = rp.Space2(rp.cheb_dirichlet(33), rp.cheb_dirichlet(33), method="matmul")
    gx = fused_projection_gradient(u, q, (1, 0))
    gy = fused_projection_gradient(u, q, (0, 1))
    assert gx and gy
    # square grid: the order-0 cast of gy and gx share one cached operator
    assert gx[1] is gy[0]
    rng = np.random.default_rng(11)
    vhat = q.forward(rng.standard_normal(q.shape_physical))
    ax = vhat.ndim - 2
    got = np.asarray(gx[1].apply(gx[0].apply(vhat, ax), ax + 1))
    want = np.asarray(u.from_ortho(q.gradient(vhat, (1, 0), None)))
    np.testing.assert_allclose(got, want, atol=1e-11)
    # fft-method spaces (the recurrence path) are not fused
    q_fft = rp.Space2(rp.cheb_neumann(17), rp.cheb_neumann(17), method="fft")
    u_fft = rp.Space2(rp.cheb_dirichlet(17), rp.cheb_dirichlet(17), method="fft")
    assert fused_projection_gradient(u_fft, q_fft, (1, 0)) is None
    # periodic axes (diagonal Fourier gradient) are not fused either
    q_per = rp.Space2(rp.fourier_r2c(16), rp.cheb_neumann(17))
    u_per = rp.Space2(rp.fourier_r2c(16), rp.cheb_dirichlet(17))
    assert fused_projection_gradient(u_per, q_per, (1, 0)) is None


def test_fwd_cut_fast_key_plumbing(monkeypatch):
    """("fwd_cut","fast"): aliases the exact entry when RUSTPDE_FWD_PRECISION
    is unset/highest (default OFF until measured on-chip), and builds a
    distinct impl carrying the precision override when set to high."""
    from rustpde_mpi_tpu import config as cfg

    sep = rp.Space2(rp.cheb_dirichlet(33), rp.cheb_neumann(33), sep=True, method="matmul")
    b = sep.bases[0]
    monkeypatch.delenv("RUSTPDE_FWD_PRECISION", raising=False)
    assert b._sep_dev(("fwd_cut", "fast")) is b._sep_dev("fwd_cut")
    if cfg.X64:
        return  # f64 never downgrades; alias behavior above is the contract
    b2 = rp.cheb_dirichlet(35)
    monkeypatch.setenv("RUSTPDE_FWD_PRECISION", "high")
    fast = b2._sep_dev(("fwd_cut", "fast"))
    assert fast is not b2._sep_dev("fwd_cut")
    assert fast._impl.precision == "high"
    # fast forward == exact forward on CPU (precision hint is a no-op there)
    rng = np.random.default_rng(1)
    v = rng.standard_normal(sep.shape_physical)
    got = np.asarray(sep.forward_dealiased(v, fast=False))
    want = np.asarray(sep.forward(v)) * sep.dealias_mask()
    np.testing.assert_allclose(got, want, atol=1e-13)


def test_mixed_sep_periodic_space(monkeypatch):
    """Periodic (split-Fourier x, Chebyshev y) space with the Chebyshev axis
    sep: the per-axis fused paths — forward_dealiased with a vector cut on
    the Fourier axis, backward_gradient with the fused chain on the sep axis
    only — match the unfused forms exactly."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    sp = rp.Space2(rp.fourier_r2c(16), rp.cheb_dirichlet(17), method="matmul", sep=True)
    assert sp.sep == (False, True)
    rng = np.random.default_rng(3)
    v = rng.standard_normal(sp.shape_physical)
    got = np.asarray(sp.forward_dealiased(v))
    want = np.asarray(sp.forward(v)) * sp.dealias_mask()
    np.testing.assert_allclose(got, want, atol=1e-12)
    vhat = sp.forward(jnp.asarray(v))
    for deriv in [(0, 0), (1, 0), (0, 1), (1, 1), (0, 2)]:
        got = np.asarray(sp.backward_gradient(vhat, deriv, None))
        want = np.asarray(sp.backward_ortho(sp.gradient(vhat, deriv, None)))
        np.testing.assert_allclose(
            got, want, atol=1e-10 * max(1.0, np.abs(want).max()), err_msg=str(deriv)
        )


@pytest.mark.slow
def test_periodic_model_forced_sep_matches_default():
    """A periodic Navier model with the Chebyshev axis forced sep
    (RUSTPDE_SEP=1) reproduces the default-layout trajectory to roundoff —
    the at-scale periodic layout candidate (VERDICT r4 next #2)."""
    import json
    import os
    import subprocess
    import sys

    code = (
        "import jax, json\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from rustpde_mpi_tpu import Navier2D\n"
        "m = Navier2D.new_periodic(16, 17, 1e4, 1.0, 1e-2, 1.0, 'rbc')\n"
        "import sys; print('sep', m.temp_space.sep, file=sys.stderr)\n"
        "m.set_velocity(0.1, 2.0, 2.0); m.set_temperature(0.1, 2.0, 2.0)\n"
        "m.update_n(60)\n"
        "print(json.dumps(list(m.get_observables())))\n"
    )
    obs = {}
    for sep in ("0", "1"):
        env = dict(
            os.environ,
            RUSTPDE_FORCE_TPU_PATH="1",
            RUSTPDE_SEP=sep,
            JAX_PLATFORMS="cpu",
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=600,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        obs[sep] = json.loads(out.stdout.strip().splitlines()[-1])
    for a, b in zip(obs["0"], obs["1"]):
        assert abs(a - b) <= 1e-9 * max(1.0, abs(a)), (obs["0"], obs["1"])
