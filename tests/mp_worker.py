"""Worker program for tests/test_multiprocess.py (not a pytest module).

One process of an N-process ``jax.distributed`` run on CPU devices.  Modes
(argv[5], default ``basic``):

* ``basic`` — builds the global pencil mesh, advances a sharded Navier2D,
  exercises the multihost.py host-local/global conversions + barrier,
  gathers the state and (on rank 0 only) writes a snapshot + JSON result
  for the parent to compare against a single-process run.
* ``sharded_run`` — drives a ResilientRunner over the 2-process mesh with
  SHARDED two-phase checkpoints (utils/checkpoint.write_sharded_snapshot
  via the runner).  Fault injection comes from the environment
  (``RUSTPDE_FAULT`` host-scoped specs, ``RUSTPDE_SHARD_CRASH`` two-phase
  window kills, ``RUSTPDE_SYNC_TIMEOUT_S`` barrier watchdog), so the
  parent test can kill one host between shard fsync and manifest commit
  and prove recovery.  Rank 0 dumps the final global state (allgathered)
  so the parent can assert elastic restore is bit-equal.
* ``bench_sharded`` — times sharded-vs-gathered checkpoint writes for
  ``bench.py shardedio129`` (repetitions, bytes/host, and the final-state
  dump for the parent's cross-topology restore gate).
* ``serve_campaign`` — runs a :class:`~rustpde_mpi_tpu.serve.SimServer`
  across the 2-process mesh (root-coordinated scheduling: root owns the
  queue/journal, every slot decision is broadcast).  Root enqueues
  ``RUSTPDE_MP_SERVE_REQUESTS`` requests on the FIRST incarnation (the
  queue directory is the idempotence guard); faults come from
  ``RUSTPDE_FAULT`` (SIGTERM drain, host-scoped SIGKILL, batch NaN) and
  the slot count from ``RUSTPDE_MP_SERVE_SLOTS`` so restarts can resize
  the fleet (elastic re-plan).  Root dumps summary + journal counters.
* ``gang_serve`` — TWO-LEVEL serving over the same 2-process mesh:
  ``ServeConfig.submesh`` carves the 4 CPU devices into one 2-device
  gang sub-mesh plus a 2-device default remainder, and root enqueues
  MIXED traffic — ``RUSTPDE_MP_GANG_REQUESTS`` pencil-sharded 34^2
  flagship requests (stamped ``submesh=2`` at admission) interleaved
  with ``RUSTPDE_MP_VMAP_REQUESTS`` vmapped 18^2 requests riding the
  remainder.  Gang-scoped faults (``RUSTPDE_FAULT=kill@<n>:gang0member1``)
  SIGKILL one gang member mid-campaign; the gang barrier watchdog
  (``RUSTPDE_GANG_SYNC_TIMEOUT_S``) must convert the wedge into a typed
  ``GangMemberLost`` and containment must requeue-with-state.  Root also
  proves door-time admission: an unshardable grid comes back as a typed
  ``reason="no_submesh"`` rejection, never a durable queue row.  Root
  dumps summary + the gang journal counters.

* ``integrity_serve`` — the SDC soak: a serve campaign with on-device
  digests + shadow audits armed (cadence 1, single-strike quarantine)
  under ``RUSTPDE_FAULT=bitflip@<n>:host1`` — the audit must catch the
  flip, the quarantine must trip, containment must requeue, and zero
  requests may be lost.

argv: coordinator_port process_id num_processes out_dir [mode]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize forces axon otherwise


def _build_model(mesh, nx=34, ny=34, dt=0.01):
    from rustpde_mpi_tpu import Navier2D

    # 34^2: spectral dims (32, 32) divide the 4-device mesh -- the
    # multi-process host-local/global conversions require divisible
    # pencil dims (JAX rejects uneven global shardings outside jit)
    model = Navier2D(nx, ny, 1e4, 1.0, dt, 1.0, "rbc", periodic=False, mesh=mesh)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.write_intervall = 1e9  # runner checkpoints are the IO under test
    return model


def _dump_state(model, path):
    """Rank-0 dump of the full global state (allgather) — the parent's
    bit-equality reference for elastic restore."""
    import numpy as np
    from jax.experimental import multihost_utils

    from rustpde_mpi_tpu.parallel import multihost

    leaves = {
        name: np.asarray(
            multihost_utils.process_allgather(getattr(model.state, name), tiled=True)
        )
        for name in model.state._fields
    }
    multihost.sync_hosts("pre-dump")
    if multihost.is_root():
        np.savez(path, time=model.time, **leaves)
    multihost.sync_hosts("post-dump")


def mode_basic(out_dir):
    import numpy as np

    from rustpde_mpi_tpu.parallel import multihost

    mesh = multihost.global_pencil_mesh()
    assert mesh.devices.size == jax.process_count() * len(jax.local_devices())

    model = _build_model(mesh)
    model.update_n(10)
    nu, nuvol, re, div = model.get_observables()

    # multihost conversions round-trip: global -> host-local slab -> global
    temp = model.state.temp
    local = multihost.host_local_array(temp)
    assert local.shape[0] == temp.shape[0]  # pencil split is along axis 1
    rebuilt = multihost.global_array(local, temp.sharding)
    diff = float(jax.jit(lambda a, b: jax.numpy.abs(a - b).max())(rebuilt, temp))
    assert diff == 0.0, diff

    # gather-to-every-host (the root-IO pattern) + rank-0 snapshot write
    from jax.experimental import multihost_utils

    full = np.asarray(multihost_utils.process_allgather(temp, tiled=True))
    checksum = float(np.abs(full).sum())
    multihost.sync_hosts("pre-write")
    if multihost.is_root():
        import h5py

        with h5py.File(os.path.join(out_dir, "snapshot_mp.h5"), "w") as f:
            f["temp"] = full
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "nu": nu,
                    "nuvol": nuvol,
                    "re": re,
                    "div": div,
                    "checksum": checksum,
                    "ndev_global": int(mesh.devices.size),
                    "nproc": jax.process_count(),
                },
                f,
            )
    multihost.sync_hosts("post-write")


def mode_sharded_run(out_dir):
    from rustpde_mpi_tpu import ResilientRunner
    from rustpde_mpi_tpu.config import IOConfig
    from rustpde_mpi_tpu.parallel import multihost

    mesh = multihost.global_pencil_mesh()
    model = _build_model(mesh)
    # RUSTPDE_MP_BLOCKING_IO=1 pins synchronous shard writes so a
    # SHARD_CRASH kill lands deterministically inside the two-phase window
    # (async submits would race the surviving host's next dispatch)
    io = (
        IOConfig(async_checkpoints=False, overlap_dispatch=False, diag_lag=0)
        if os.environ.get("RUSTPDE_MP_BLOCKING_IO") == "1"
        else None
    )
    runner = ResilientRunner(
        model,
        max_time=0.2,
        save_intervall=0.05,
        run_dir=os.path.join(out_dir, "run"),
        checkpoint_every_s=None,
        checkpoint_every_t=0.05,
        keep=3,
        io=io,
    )
    summary = runner.run()  # a SHARD_CRASH/FAULT env kills us mid-protocol
    _dump_state(model, os.path.join(out_dir, "final_state.npz"))
    if multihost.is_root():
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "outcome": summary["outcome"],
                    "step": summary["step"],
                    "time": summary["time"],
                    "checkpoint": summary["checkpoint"],
                    "sharded": True,
                    "nproc": jax.process_count(),
                },
                f,
            )


def mode_bench_sharded(out_dir, reps=3):
    import numpy as np
    from jax.experimental import multihost_utils

    from rustpde_mpi_tpu.parallel import multihost
    from rustpde_mpi_tpu.utils import checkpoint as cp

    mesh = multihost.global_pencil_mesh()
    nx = int(os.environ.get("RUSTPDE_BENCH_SHARDED_N", "130"))
    model = _build_model(mesh, nx=nx, ny=nx, dt=2e-3)
    model.update_n(4)

    # sharded leg: the collective two-phase writer, timed end to end
    sharded_s = []
    stats = None
    for rep in range(reps):
        path = cp.checkpoint_path(os.path.join(out_dir, "sharded"), rep)
        multihost.sync_hosts("bench-sharded-start")
        t0 = time.perf_counter()
        stats = cp.write_sharded_snapshot(model, path, step=rep)
        sharded_s.append(time.perf_counter() - t0)
    manifest = cp.checkpoint_path(os.path.join(out_dir, "sharded"), reps - 1)

    # gathered leg: what multihost checkpointing had to do before the
    # sharded path existed — allgather every leaf to every host, root
    # serializes the full state
    gathered_s = []
    for rep in range(reps):
        multihost.sync_hosts("bench-gathered-start")
        t0 = time.perf_counter()
        leaves = [
            np.asarray(
                multihost_utils.process_allgather(
                    getattr(model.state, name), tiled=True
                )
            )
            for name in model.state._fields
        ]
        if multihost.is_root():
            items = []
            for name, arr in zip(model.state._fields, leaves):
                if np.iscomplexobj(arr):
                    items.append((f"state/{name}_re", np.ascontiguousarray(arr.real), "raw"))
                    items.append((f"state/{name}_im", np.ascontiguousarray(arr.imag), "raw"))
                else:
                    items.append((f"state/{name}", arr, "raw"))
            snap = cp.HostSnapshot(datasets=items, step=rep, time=model.time)
            cp.write_host_snapshot(
                snap, os.path.join(out_dir, f"gathered_{rep}.h5")
            )
        multihost.sync_hosts("bench-gathered-end")
        gathered_s.append(time.perf_counter() - t0)

    _dump_state(model, os.path.join(out_dir, "final_state.npz"))
    if multihost.is_root():
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "sharded_write_s": min(sharded_s),
                    "gathered_write_s": min(gathered_s),
                    "bytes_host": stats["bytes_host"],
                    "bytes_total": stats["bytes_total"],
                    "shards": stats["shards"],
                    "barrier_s": stats["barrier_s"],
                    "manifest": manifest,
                    "grid": [nx, nx],
                    "nproc": jax.process_count(),
                },
                f,
            )


def mode_serve_campaign(out_dir):
    from rustpde_mpi_tpu.config import ServeConfig
    from rustpde_mpi_tpu.parallel import multihost
    from rustpde_mpi_tpu.serve import AdmissionError, SimServer
    from rustpde_mpi_tpu.utils.journal import read_journal

    n_req = int(os.environ.get("RUSTPDE_MP_SERVE_REQUESTS", "5"))
    slots = int(os.environ.get("RUSTPDE_MP_SERVE_SLOTS", "2"))
    run_dir = os.path.join(out_dir, "serve")
    cfg = ServeConfig(
        run_dir=run_dir,
        slots=slots,
        max_queue=4 * n_req,
        chunk_steps=4,
        checkpoint_every_s=2.0,  # tight cadence: a SIGKILL must leave a
        # recent slot-table checkpoint to restore mid-trajectory from
        http_port=None,
    )
    srv = SimServer(cfg)  # fault rides RUSTPDE_FAULT (host-scoped specs ok)
    if multihost.is_root():
        counts = srv.queue.counts()
        if sum(counts.values()) == 0:  # first incarnation only
            for seed in range(n_req):
                # 34^2 grid: spectral dims divide the 4-device mesh; the
                # jittered horizon staggers completions off one boundary
                try:
                    srv.submit(
                        {
                            "ra": 1e4,
                            "pr": 1.0,
                            "nx": 34,
                            "ny": 34,
                            "dt": 0.01,
                            "horizon": 0.08 + (seed % 3) * 0.02,
                            "seed": seed,
                        }
                    )
                except AdmissionError:
                    pass
    summary = srv.serve()
    from rustpde_mpi_tpu.parallel import sanitizer

    # MetricsDumper multihost-collision regression (ISSUE 13 satellite):
    # every rank constructs a dumper over the SAME logical path in the
    # shared out_dir — non-root ranks must land on a .p<rank>-suffixed
    # file instead of interleaving torn lines into root's
    from rustpde_mpi_tpu.telemetry.exporters import MetricsDumper

    shared = os.path.join(out_dir, "mp_metrics.jsonl")
    dumper = MetricsDumper(shared)
    dumper.dump(step=0)
    if multihost.is_root():
        expected = shared
    else:
        expected = os.path.join(
            out_dir, f"mp_metrics.p{jax.process_index()}.jsonl"
        )
    assert dumper.path == expected, (dumper.path, expected)
    assert os.path.exists(expected), expected
    multihost.sync_hosts("metrics-suffix-dumped")

    # root-side trace assembly (ISSUE 13 tentpole): when any chunk ran,
    # the campaign-close gather must have written Perfetto trace files on
    # root with events from EVERY host
    import glob as _glob

    trace_files = sorted(
        _glob.glob(os.path.join(run_dir, "campaigns", "*", "trace_*.json"))
    )
    trace_hosts = 0
    for tf in trace_files:
        with open(tf) as fh:
            payload = json.load(fh)
        pids = {e.get("pid") for e in payload.get("traceEvents", [])}
        trace_hosts = max(trace_hosts, len(pids))
    if multihost.is_root() and summary["member_steps"] > 0:
        assert trace_files, "no campaign trace assembled on root"
        assert trace_hosts == jax.process_count(), (
            trace_hosts,
            jax.process_count(),
        )
    if multihost.is_root():
        events = [
            e.get("event")
            for e in read_journal(
                os.path.join(run_dir, "journal.jsonl"), on_error="skip"
            )
        ]
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "outcome": summary["outcome"],
                    "completed": summary["completed"],
                    "failed": summary["failed"],
                    "retried": summary["retried"],
                    "replans": summary["replans"],
                    # collective-sequence sanitizer counters (armed via
                    # RUSTPDE_SANITIZE in the chaos soak / bench mp leg)
                    "sanitizer": sanitizer.stats(),
                    "trace_files": len(trace_files),
                    "trace_hosts": trace_hosts,
                    "queue": srv.queue.counts(),
                    "slots": slots,
                    "nproc": jax.process_count(),
                    "drains": events.count("drain"),
                    "requeued": events.count("request_requeued"),
                    "replanned": events.count("campaign_replanned"),
                    "dt_adjusts": events.count("bucket_dt_adjust"),
                    "retries": events.count("request_retry"),
                    "restored_sched": sum(
                        1
                        for e in read_journal(
                            os.path.join(run_dir, "journal.jsonl"),
                            on_error="skip",
                        )
                        if e.get("event") == "request_scheduled"
                        and e.get("restored")
                        and e.get("steps_done", 0) > 0
                    ),
                },
                f,
            )


def mode_gang_serve(out_dir):
    from rustpde_mpi_tpu.config import ServeConfig, SubmeshConfig
    from rustpde_mpi_tpu.parallel import multihost
    from rustpde_mpi_tpu.serve import AdmissionError, SimServer
    from rustpde_mpi_tpu.serve.request import RequestError
    from rustpde_mpi_tpu.utils.journal import read_journal

    n_gang = int(os.environ.get("RUSTPDE_MP_GANG_REQUESTS", "2"))
    n_vmap = int(os.environ.get("RUSTPDE_MP_VMAP_REQUESTS", "3"))
    slots = int(os.environ.get("RUSTPDE_MP_SERVE_SLOTS", "2"))
    run_dir = os.path.join(out_dir, "serve")
    cfg = ServeConfig(
        run_dir=run_dir,
        slots=slots,
        max_queue=4 * (n_gang + n_vmap) + 8,
        chunk_steps=4,
        checkpoint_every_s=2.0,  # tight cadence: the gang SIGKILL must
        # leave a recent sharded slot-table checkpoint to restore from
        http_port=None,
        # 4 CPU devices, 2 processes: one 2-device gang slice (one device
        # from each process) + a 2-device default remainder.  34^2 is the
        # smallest grid whose spectral extent (32) divides the slice, so
        # shard_min_nx=34 makes it the flagship gang traffic.
        submesh=SubmeshConfig(shapes=(2,), shard_min_nx=34),
    )
    srv = SimServer(cfg)  # fault rides RUSTPDE_FAULT (gang scopes ok)
    if multihost.is_root():
        counts = srv.queue.counts()
        if sum(counts.values()) == 0:  # first incarnation only
            for seed in range(n_gang):
                # flagship sharded traffic: stamped submesh=2 at the door
                try:
                    srv.submit(
                        {
                            "ra": 1e4,
                            "pr": 1.0,
                            "nx": 34,
                            "ny": 34,
                            "dt": 0.01,
                            "horizon": 0.08 + (seed % 2) * 0.04,
                            "seed": 100 + seed,
                        }
                    )
                except AdmissionError:
                    pass
            for seed in range(n_vmap):
                # co-resident vmapped traffic on the default remainder
                try:
                    srv.submit(
                        {
                            "ra": 1e4,
                            "pr": 1.0,
                            "nx": 18,
                            "ny": 18,
                            "dt": 0.01,
                            "horizon": 0.08 + (seed % 3) * 0.02,
                            "seed": seed,
                        }
                    )
                except AdmissionError:
                    pass
            # admission containment (PR-18 satellite): a grid that must
            # shard but fits no configured shape is a typed door-time
            # rejection, never a durable poison pill in the queue
            reason = None
            try:
                srv.submit(
                    {
                        "ra": 1e4,
                        "pr": 1.0,
                        "nx": 259,
                        "ny": 259,
                        "dt": 0.01,
                        "horizon": 0.02,
                        "seed": 999,
                    }
                )
            except (RequestError, ValueError) as exc:
                reason = getattr(exc, "reason", None)
            assert reason == "no_submesh", reason
    summary = srv.serve()
    if multihost.is_root():
        events = [
            e.get("event")
            for e in read_journal(
                os.path.join(run_dir, "journal.jsonl"), on_error="skip"
            )
        ]
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "outcome": summary["outcome"],
                    "completed": summary["completed"],
                    "failed": summary["failed"],
                    "retried": summary["retried"],
                    "replans": summary["replans"],
                    "queue": srv.queue.counts(),
                    "slots": slots,
                    "nproc": jax.process_count(),
                    "gang_formed": events.count("gang_formed"),
                    "gang_member_lost": events.count("gang_member_lost"),
                    "gang_parked": events.count("gang_parked"),
                    "gang_replanned": events.count("gang_replanned"),
                    "gang_form_failed": events.count("gang_form_failed"),
                    "submesh_rejected": events.count("submesh_rejected"),
                    "drains": events.count("drain"),
                    "requeued": events.count("request_requeued"),
                    "replanned": events.count("campaign_replanned"),
                    "retries": events.count("request_retry"),
                    "restored_sched": sum(
                        1
                        for e in read_journal(
                            os.path.join(run_dir, "journal.jsonl"),
                            on_error="skip",
                        )
                        if e.get("event") == "request_scheduled"
                        and e.get("restored")
                        and e.get("steps_done", 0) > 0
                    ),
                },
                f,
            )


def mode_integrity_serve(out_dir):
    """SDC soak over the 2-process mesh (integrity tentpole): the serve
    campaign runs with digests + shadow audits armed at cadence 1 and a
    single-strike quarantine ledger, while ``RUSTPDE_FAULT=bitflip@<n>:host1``
    silently flips one mantissa bit of a host-1-owned spectral column
    mid-campaign.  The audit must catch it, the strike must cross the
    quarantine threshold (typed IntegrityError), the scheduler must
    contain WITHOUT killing the replica (requeue-with-progress, unhealthy
    heartbeat), and every request must still complete — zero lost.  Root
    dumps summary + journal/ledger evidence for the parent."""
    from rustpde_mpi_tpu.config import IntegrityConfig, ServeConfig
    from rustpde_mpi_tpu.integrity import QuarantineLedger
    from rustpde_mpi_tpu.parallel import multihost
    from rustpde_mpi_tpu.serve import AdmissionError, SimServer
    from rustpde_mpi_tpu.utils.journal import read_journal

    n_req = int(os.environ.get("RUSTPDE_MP_SERVE_REQUESTS", "3"))
    run_dir = os.path.join(out_dir, "serve")
    cfg = ServeConfig(
        run_dir=run_dir,
        slots=2,
        max_queue=4 * n_req,
        chunk_steps=4,
        checkpoint_every_s=2.0,
        http_port=None,
        # cadence 1: every committed chunk is shadow-audited, so the one
        # injected flip cannot slip past; one strike quarantines, so the
        # containment path (IntegrityError -> requeue -> re-carve) fires
        # on the FIRST mismatch
        integrity=IntegrityConfig(cadence=1, strikes=1),
    )
    srv = SimServer(cfg)  # fault rides RUSTPDE_FAULT=bitflip@<n>:host1
    if multihost.is_root():
        counts = srv.queue.counts()
        if sum(counts.values()) == 0:  # first incarnation only
            for seed in range(n_req):
                try:
                    srv.submit(
                        {
                            "ra": 1e4,
                            "pr": 1.0,
                            "nx": 34,
                            "ny": 34,
                            "dt": 0.01,
                            "horizon": 0.08 + (seed % 2) * 0.04,
                            "seed": seed,
                        }
                    )
                except AdmissionError:
                    pass
    summary = srv.serve()
    if multihost.is_root():
        events = [
            e.get("event")
            for e in read_journal(
                os.path.join(run_dir, "journal.jsonl"), on_error="skip"
            )
        ]
        ledger = QuarantineLedger(run_dir, strikes=1)
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "outcome": summary["outcome"],
                    "completed": summary["completed"],
                    "failed": summary["failed"],
                    "queue": srv.queue.counts(),
                    "nproc": jax.process_count(),
                    "bitflip_injected": events.count("bitflip_injected"),
                    "integrity_mismatch": events.count("integrity_mismatch"),
                    "integrity_rollback": events.count("integrity_rollback"),
                    "integrity_contained": events.count("integrity_contained"),
                    "device_quarantined": events.count("device_quarantined"),
                    "requeued": events.count("request_requeued"),
                    "quarantined": list(ledger.quarantined()),
                },
                f,
            )


def mode_sanitize_desync(out_dir):
    """Collective-sequence sanitizer exercise (tests/test_sanitizer.py).

    Drives a pure root_decides loop (one fixed-shape scalar broadcast per
    call, so a skipped call leaves the transport pairable) with the
    sanitizer armed from the environment.  With
    ``RUSTPDE_SANITIZE_INJECT=skip_broadcast@<n>:host1`` armed, host 1
    silently skips its <n>-th broadcast — the PR-10 drain-check bug shape —
    and BOTH ranks must raise a typed CollectiveDesyncError naming the
    divergent call site within one verification cadence, instead of
    wedging silently.  Each rank writes its own result file."""
    from rustpde_mpi_tpu.parallel import multihost, sanitizer
    from rustpde_mpi_tpu.parallel.sanitizer import CollectiveDesyncError

    sanitizer.reset()  # pick up the spawn env on a clean ring
    result = {"raised": None, "site": None, "seq": None, "message": None}
    try:
        for i in range(40):
            multihost.root_decides(i % 3 == 0)
        multihost.sync_hosts("sanitize-clean-done")
    except CollectiveDesyncError as exc:
        result["raised"] = "CollectiveDesyncError"
        result["site"] = exc.site
        result["seq"] = exc.seq
        result["message"] = str(exc)
    result["stats"] = sanitizer.stats()
    with open(
        os.path.join(out_dir, f"sanitize_rank{jax.process_index()}.json"), "w"
    ) as f:
        json.dump(result, f)


def main():
    port, pid, nproc, out_dir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    mode = sys.argv[5] if len(sys.argv) > 5 else "basic"

    from rustpde_mpi_tpu.parallel import multihost

    started = multihost.initialize_distributed(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert started and jax.process_count() == nproc

    modes = {
        "basic": mode_basic,
        "sharded_run": mode_sharded_run,
        "bench_sharded": mode_bench_sharded,
        "serve_campaign": mode_serve_campaign,
        "gang_serve": mode_gang_serve,
        "integrity_serve": mode_integrity_serve,
        "sanitize_desync": mode_sanitize_desync,
    }
    if mode not in modes:
        raise SystemExit(f"unknown mode {mode!r}")
    try:
        modes[mode](out_dir)
    except BaseException:
        # durable per-rank traceback: a peer's abort can kill this process
        # mid-stderr-print, so the parent test would otherwise never see
        # WHICH exception started the cascade
        import traceback

        with open(os.path.join(out_dir, f"rank{pid}.err"), "w") as f:
            traceback.print_exc(file=f)
        raise
    print(f"RANK{pid} OK", flush=True)


if __name__ == "__main__":
    main()
