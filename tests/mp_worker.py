"""Worker program for tests/test_multiprocess.py (not a pytest module).

One process of an N-process ``jax.distributed`` run on CPU devices: builds
the global pencil mesh, advances a sharded Navier2D, exercises the
multihost.py host-local/global conversions + barrier, gathers the state and
(on rank 0 only) writes a snapshot + JSON result for the parent to compare
against a single-process run.

argv: coordinator_port process_id num_processes out_dir
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize forces axon otherwise


def main():
    port, pid, nproc, out_dir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    import numpy as np

    from rustpde_mpi_tpu import Navier2D
    from rustpde_mpi_tpu.parallel import multihost

    started = multihost.initialize_distributed(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert started and jax.process_count() == nproc

    mesh = multihost.global_pencil_mesh()
    assert mesh.devices.size == nproc * len(jax.local_devices())

    # 34^2: spectral dims (32, 32) divide the 4-device mesh -- the
    # multi-process host-local/global conversions require divisible
    # pencil dims (JAX rejects uneven global shardings outside jit)
    model = Navier2D(34, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False, mesh=mesh)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(10)
    nu, nuvol, re, div = model.get_observables()

    # multihost conversions round-trip: global -> host-local slab -> global
    temp = model.state.temp
    local = multihost.host_local_array(temp)
    assert local.shape[0] == temp.shape[0]  # pencil split is along axis 1
    rebuilt = multihost.global_array(local, temp.sharding)
    diff = float(jax.jit(lambda a, b: jax.numpy.abs(a - b).max())(rebuilt, temp))
    assert diff == 0.0, diff

    # gather-to-every-host (the root-IO pattern) + rank-0 snapshot write
    from jax.experimental import multihost_utils

    full = np.asarray(multihost_utils.process_allgather(temp, tiled=True))
    checksum = float(np.abs(full).sum())
    multihost.sync_hosts("pre-write")
    if multihost.is_root():
        import h5py

        with h5py.File(os.path.join(out_dir, "snapshot_mp.h5"), "w") as f:
            f["temp"] = full
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "nu": nu,
                    "nuvol": nuvol,
                    "re": re,
                    "div": div,
                    "checksum": checksum,
                    "ndev_global": int(mesh.devices.size),
                    "nproc": jax.process_count(),
                },
                f,
            )
    multihost.sync_hosts("post-write")
    print(f"RANK{pid} OK", flush=True)


if __name__ == "__main__":
    main()
