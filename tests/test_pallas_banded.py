"""Pallas banded-substitution kernel: exactness vs the scan path.

Runs in Pallas interpreter mode on the CPU CI mesh; on a real TPU the same
kernel compiles natively (verified on-chip: max diff 0.0 vs the scan path,
and the microbenchmark recorded in BASELINE.md)."""

import numpy as np
import pytest

import jax.numpy as jnp

from rustpde_mpi_tpu.ops.banded import BandedSolver, banded_lu_factor
from rustpde_mpi_tpu.ops.pallas_banded import (
    PallasBandedSolver,
    banded_solve_pallas,
)


def _system(n, p=2, q=4, seed=0):
    rng = np.random.default_rng(seed)
    dense = np.eye(n) * 4.0
    for d in range(1, p + 1):
        dense += np.diag(rng.uniform(0.2, 0.6, n - d), k=-d)
    for d in range(1, q + 1):
        dense += np.diag(rng.uniform(0.2, 0.6, n - d), k=d)
    return dense


@pytest.mark.parametrize("n,lanes", [(16, 8), (33, 130), (64, 128)])
def test_pallas_matches_scan(n, lanes):
    p, q = 2, 4
    dense = _system(n, p, q)
    rng = np.random.default_rng(1)
    b = jnp.asarray(rng.standard_normal((n, lanes)))
    ref = BandedSolver(dense, p, q).solve(b, 0)
    out = PallasBandedSolver(dense, p, q, interpret=True).solve(b, 0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-10)


def test_pallas_reconstructs_solution():
    """A x = b round-trip (the reference's kernel test pattern,
    /root/reference/src/solver/fdma.rs:277-337)."""
    n, p, q = 24, 2, 4
    dense = _system(n, p, q, seed=3)
    rng = np.random.default_rng(4)
    b = rng.standard_normal((n, 4))
    lower, upper = banded_lu_factor(dense, p, q)
    x = banded_solve_pallas(
        jnp.asarray(lower), jnp.asarray(upper), jnp.asarray(b), p, q,
        interpret=True,
    )
    np.testing.assert_allclose(dense @ np.asarray(x), b, atol=1e-9)


def test_pallas_solver_axis1_and_batch():
    """solve() moves an arbitrary axis into the lane position."""
    n, p, q = 16, 2, 4
    dense = _system(n, p, q, seed=5)
    rng = np.random.default_rng(6)
    b = jnp.asarray(rng.standard_normal((7, n)))
    ref = BandedSolver(dense, p, q).solve(b, 1)
    out = PallasBandedSolver(dense, p, q, interpret=True).solve(b, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-10)


def test_pallas_via_axis_solver_dispatch():
    """method="pallas" is selectable through the solver layer."""
    from rustpde_mpi_tpu import Space2, cheb_dirichlet
    from rustpde_mpi_tpu.solver import HholtzAdi

    space = Space2(cheb_dirichlet(24), cheb_dirichlet(24))
    # interpret-mode pallas on CPU: patch the auto-detection via solver attr
    adi_pallas = HholtzAdi(space, (1e-3, 1e-3), method="pallas")
    for ax in adi_pallas.solvers:
        if hasattr(ax.solver, "interpret"):
            ax.solver.interpret = True
    adi_ref = HholtzAdi(space, (1e-3, 1e-3), method="banded")
    rng = np.random.default_rng(7)
    f = jnp.asarray(rng.standard_normal((24, 24)))
    rhs = space.to_ortho(space.forward(f))
    np.testing.assert_allclose(
        np.asarray(adi_pallas.solve(rhs)), np.asarray(adi_ref.solve(rhs)), atol=1e-9
    )
