"""Config dataclass, diagnostics map, profiling API, BC helper coverage."""

import numpy as np

from rustpde_mpi_tpu import Navier2D
from rustpde_mpi_tpu.config import NavierConfig
from rustpde_mpi_tpu.models.boundary_conditions import (
    bc_zero_values,
    transfer_function,
)
from rustpde_mpi_tpu.utils.profiling import (
    StepTimer,
    benchmark_steps,
    mfu_estimate,
    step_flops,
)


def _tiny_model():
    return Navier2D.from_config(NavierConfig(nx=17, ny=17, ra=1e4, dt=0.01))


def test_from_config_matches_ctor():
    cfg = NavierConfig(nx=17, ny=17, ra=1e4, dt=0.01, write_intervall=2.0)
    m = Navier2D.from_config(cfg)
    assert (m.nx, m.ny) == (17, 17)
    assert m.params["ra"] == 1e4
    assert m.write_intervall == 2.0
    m.update()
    assert np.isfinite(m.get_observables()[0])


def test_diagnostics_map_filled_by_callback(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    m = _tiny_model()
    m.update_n(5)
    m.callback()
    m.update_n(5)
    m.callback()
    assert len(m.diagnostics["time"]) == 2
    assert len(m.diagnostics["nu"]) == 2
    assert m.diagnostics["time"][1] > m.diagnostics["time"][0]


def test_benchmark_steps_and_mfu():
    m = _tiny_model()
    res = benchmark_steps(m, steps=4, warmup=2)
    assert res["steps_per_sec"] > 0
    assert res["ms_per_step"] > 0
    flops = step_flops(m)
    assert flops and flops > 1e5
    mfu = mfu_estimate(m, res["steps_per_sec"])
    assert 0 < mfu["mfu"] < 1.5  # sane fraction of assumed peak


def test_step_timer():
    t = StepTimer()
    t.tick(10)
    t.tick(10)
    s = t.summary()
    assert s["chunks"] == 2 and s["steps"] == 20
    assert s["steps_per_sec_min"] <= s["steps_per_sec_max"]


def test_workload_api_exports():
    """The workloads satellite: the multi-model campaign surface must be
    importable from the package root (API pin — mirrors the robustness pin
    in test_serve.py)."""
    import rustpde_mpi_tpu as rp

    for name in (
        "CampaignModelBase",
        "ScenarioConfig",
        "build_model",
        "model_kinds",
        "register_model_kind",
        "validate_campaign_model",
        "eigenmode_sweep",
        "critical_rayleigh",
        "steady_state_find",
        "geometry_sweep",
        "Navier2DLnse",
        "Navier2DAdjoint",
    ):
        assert hasattr(rp, name), name
    assert set(rp.model_kinds()) >= {"dns", "lnse", "adjoint"}
    # the models package exports the campaign contract + both ported models
    from rustpde_mpi_tpu import models as mdl

    for name in ("CampaignModelBase", "CAMPAIGN_MODEL_ATTRS",
                 "Navier2DLnse", "Navier2DAdjoint", "AdjointState",
                 "NavierScalarState", "scenario_signature"):
        assert hasattr(mdl, name), name


def test_transfer_function_limits():
    """Smooth three-level transfer (boundary_conditions.rs:262-274): hits
    v_l at the left edge, v_m in the middle, v_r at the right edge."""
    x = np.linspace(-1, 1, 201)
    v = transfer_function(x, 0.5, 0.0, -0.5, k=50.0)
    assert abs(v[0] - 0.5) < 1e-6
    assert abs(v[100]) < 1e-6
    assert abs(v[-1] + 0.5) < 1e-6
    mask = bc_zero_values(x, x, k=50.0)
    assert mask.shape == (201, 201)
    assert abs(mask[0, 0] - 0.5) < 1e-6  # bottom plate value


def test_telemetry_api_exports():
    """The telemetry subsystem's public surface (API pin): the package
    root carries the module + the two classes other layers hand around,
    and the telemetry package itself exports the full documented set."""
    import rustpde_mpi_tpu as rp

    for name in ("telemetry", "MetricsRegistry", "ThroughputMonitor"):
        assert hasattr(rp, name), name
    for name in (
        "REGISTRY",
        "RECORDER",
        "counter",
        "gauge",
        "histogram",
        "snapshot",
        "span",
        "instant",
        "prometheus_text",
        "PROMETHEUS_CONTENT_TYPE",
        "MetricsDumper",
        "read_metrics_jsonl",
        "FlightRecorder",
        "dump_flight_record",
        "arm_exit_dump",
        "gather_global_snapshot",
        "merge_snapshots",
        "set_enabled",
        "enabled",
    ):
        assert hasattr(rp.telemetry, name), name
    # the default registry is ONE process-wide object shared by every layer
    assert rp.telemetry.default_registry() is rp.telemetry.REGISTRY
