"""LNSE / NonLin / adjoint-gradient stack tests (SURVEY.md S2 rows
`Navier2DLnse`, `lnse_adj_grad`, `lnse_fd_grad`, `Navier2DNonLin`,
`meanfield`, `opt_routines`)."""

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    MeanFields,
    Navier2D,
    Navier2DLnse,
    Navier2DNonLin,
    steepest_descent_energy_constrained,
)
from rustpde_mpi_tpu.models.lnse import l2_norm


def _norm(arrs):
    return np.sqrt(sum(float(np.sum(np.asarray(a) ** 2)) for a in arrs))


def _lnse(nx=14, ny=11, ra=3e3, pr=0.1, dt=0.01, cls=Navier2DLnse, seed=1):
    model = cls.new_confined(nx, ny, ra, pr, dt, 1.0, "rbc", mean=MeanFields.new_rbc(nx, ny))
    model.init_random(1e-3, seed=seed)
    return model


# -- linear stability physics -------------------------------------------------


@pytest.mark.slow
def test_lnse_subcritical_perturbations_decay():
    """About the conduction state below Ra_c ~ 1708 every perturbation decays."""
    model = _lnse(ra=1000.0)
    e0 = model.energy(0.5, 0.5)
    model.update_n(400)
    assert model.energy(0.5, 0.5) < 0.5 * e0


def test_lnse_supercritical_perturbations_grow():
    """Above onset the linearized operator has an unstable mode: after the
    random-noise transient decays (t < ~6), the leading eigenmode grows
    exponentially (measured ~x2 per 2 time units at Ra=1e4)."""
    model = _lnse(nx=17, ny=17, ra=1e4, pr=1.0)
    model.update_n(800)  # past the transient
    e_mid = model.energy(0.5, 0.5)
    model.update_n(400)
    assert model.energy(0.5, 0.5) > 2.0 * e_mid


# -- NonLin equivalence -------------------------------------------------------


def test_nonlin_with_conduction_mean_equals_navier2d():
    """The perturbation form about the conduction profile must reproduce the
    full DNS exactly (mean convection/diffusion terms == the bc lift terms)."""
    nx = ny = 17
    nav = Navier2D(nx, ny, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    nav.set_velocity(0.1, 1.0, 1.0)
    nav.set_temperature(0.1, 1.0, 1.0)
    nl = Navier2DNonLin.new_confined(
        nx, ny, 1e4, 1.0, 0.01, 1.0, "rbc", mean=MeanFields.new_rbc(nx, ny)
    )
    for name in ("velx", "vely", "temp"):
        nl.set_field(name, nav.get_field(name))
    nav.update_n(50)
    nl.update_n(50)
    for name in ("temp", "velx", "vely"):
        np.testing.assert_allclose(
            np.asarray(getattr(nl.state, name)),
            np.asarray(getattr(nav.state, name)),
            atol=1e-13,
        )


# -- gradients ---------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("cls", [Navier2DLnse, Navier2DNonLin])
def test_autodiff_gradient_matches_directional_fd(cls):
    """jax.grad through the scanned forward loop is the exact gradient of the
    discrete objective: central-difference directional derivative matches to
    ~1e-6 (vs the reference's 30% hand-adjoint tolerance,
    examples/navier_lnse_test_gradient.rs:33-50)."""
    model = _lnse(cls=cls)
    _, grads = model.grad_autodiff(0.5, 0.5, 0.5)
    u0, v0, t0 = (np.asarray(a) for a in model._phys(model.state))
    objective = model._objective_fn(50, 0.5, 0.5, None)
    rng = np.random.default_rng(0)
    dirs = [rng.standard_normal(a.shape) for a in (u0, v0, t0)]
    eps = 1e-6
    jp = float(objective(*[a + eps * d for a, d in zip((u0, v0, t0), dirs)]))
    jm = float(objective(*[a - eps * d for a, d in zip((u0, v0, t0), dirs)]))
    fd = (jp - jm) / (2 * eps)
    # grads are the descent direction -dJ/du (MAXIMIZE=False)
    ad = -sum(float(np.sum(g * d)) for g, d in zip(grads, dirs))
    assert ad == pytest.approx(fd, rel=1e-5)


@pytest.mark.slow
def test_fd_gradient_matches_autodiff_pointwise():
    """The ported brute-force FD gradient (vmapped) agrees with autodiff."""
    model = _lnse(nx=10, ny=9)
    ic = model.state
    _, g_auto = model.grad_autodiff(0.2, 0.5, 0.5)
    model.state = ic
    model.reset_time()
    g_fd = model.grad_fd(0.2, 0.5, 0.5, eps=1e-5)
    # forward differences at eps=1e-5 on a ~1e-9 objective: modest tolerance
    for ga, gf in zip(g_auto, g_fd):
        num = np.sqrt(np.sum((np.asarray(gf) - (-np.asarray(ga))) ** 2))
        den = max(np.sqrt(np.sum(np.asarray(gf) ** 2)), 1e-300)
        assert num / den < 1e-2


@pytest.mark.slow
@pytest.mark.parametrize("cls", [Navier2DLnse, Navier2DNonLin])
def test_hand_adjoint_gradient_agreement(cls):
    """Port of the reference's adjoint-vs-FD validation
    (examples/navier_lnse_test_gradient.rs, rel-tol 0.3): the hand adjoint is
    a continuous-adjoint approximation; against the *exact* discrete gradient
    its error is config/seed dependent (measured 0.35-0.50 here, flat in dt),
    so the gate is 0.6 with the direction check as the real assertion.  On
    the reference's own matched config and tolerance the hand adjoint passes
    0.3 — see test_reference_gradient_protocol_rel03."""
    model = _lnse(cls=cls)
    ic = model.state
    val_a, g_auto = model.grad_autodiff(1.0, 0.5, 0.5)
    model.state = ic
    model.reset_time()
    val_h, g_hand = model.grad_adjoint(1.0, None, 0.5, 0.5)
    # identical forward loops -> identical objective values
    assert val_h == pytest.approx(val_a, rel=1e-10)
    rel = _norm([a - b for a, b in zip(g_auto, g_hand)]) / _norm(g_auto)
    assert rel < 0.6
    # the approximate gradient must still be a descent direction
    cos = sum(float(np.sum(a * b)) for a, b in zip(g_auto, g_hand))
    cos /= _norm(g_auto) * _norm(g_hand)
    assert cos > 0.7


# -- optimization routine -----------------------------------------------------


def test_steepest_descent_preserves_energy():
    rng = np.random.default_rng(5)
    shape = (12, 12)
    u, v, t = (rng.standard_normal(shape) for _ in range(3))
    gu, gv, gt = (rng.standard_normal(shape) for _ in range(3))
    un, vn, tn = steepest_descent_energy_constrained(
        u, v, t, gu, gv, gt, 0.5, 0.5, alpha=0.7
    )
    e0 = float(l2_norm(u, u, v, v, t, t, 0.5, 0.5))
    e1 = float(l2_norm(un, un, vn, vn, tn, tn, 0.5, 0.5))
    assert e1 == pytest.approx(e0, rel=1e-10)
    with pytest.raises(ValueError):
        steepest_descent_energy_constrained(u, v, t, gu, gv, gt, 0.5, 0.5, 7.0)


# -- mean fields --------------------------------------------------------------


def test_meanfields_profiles_and_roundtrip(tmp_path):
    mean = MeanFields.new_rbc(14, 11)
    _, _, t = mean.physical()
    # linear profile from +0.5 (bottom) to -0.5 (top)
    np.testing.assert_allclose(t[:, 0], 0.5, atol=1e-12)
    np.testing.assert_allclose(t[:, -1], -0.5, atol=1e-12)

    fname = str(tmp_path / "mean.h5")
    mean.write(fname)
    other = MeanFields.read_from(14, 11, fname)
    np.testing.assert_allclose(
        np.asarray(other.temp), np.asarray(mean.temp), atol=1e-12
    )
    # missing file falls back to the analytic profile
    fallback = MeanFields.read_from(14, 11, str(tmp_path / "nope.h5"), bc="rbc")
    np.testing.assert_allclose(
        np.asarray(fallback.temp), np.asarray(mean.temp), atol=1e-12
    )


def test_meanfields_read_from_dns_snapshot(tmp_path):
    """Reading a composite-space DNS snapshot reconstructs the physical
    fields exactly (the reference's coefficient zero-pad would not)."""
    nav = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    nav.set_velocity(0.3, 1.0, 1.0)
    nav.update_n(5)
    fname = str(tmp_path / "mean.h5")
    nav.write(fname)
    mean = MeanFields.read_from(16, 17, fname)
    u, v, t = mean.physical()
    np.testing.assert_allclose(u, nav.get_field("velx"), atol=1e-12)
    np.testing.assert_allclose(v, nav.get_field("vely"), atol=1e-12)
    np.testing.assert_allclose(t, nav.get_field("temp"), atol=1e-12)


@pytest.mark.slow
def test_reference_gradient_protocol_rel03():
    """The reference's exact validation protocol
    (examples/navier_lnse_test_gradient.rs): periodic 18x13, Ra=3e3, Pr=0.1,
    dt=0.01, init_random(1e-3), horizon 10.0, beta=(0.5,0.5), hand adjoint
    vs FD of the same forward loop, rel tol 0.3.  Measured 0.169 here —
    resolves the round-2 question about the looser 0.6 gate in
    test_hand_adjoint_gradient_agreement: that gate compares a *different*
    config against the exact discrete gradient; on the reference's own
    protocol the hand adjoint meets the reference's own tolerance."""
    model = Navier2DLnse.new_periodic(18, 13, 3e3, 0.1, 0.01, 1.0, "rbc")
    model.init_random(1e-3)
    ic = model.state
    _, g_adj = model.grad_adjoint(10.0, 10.0, 0.5, 0.5)
    model.state = ic
    model.reset_time()
    g_fd = model.grad_fd(10.0, 0.5, 0.5)
    # grad_adjoint returns the descent direction (-dJ/du); FD measures +dJ/du
    ga = [-np.asarray(g) for g in g_adj]
    num = _norm([a - b for a, b in zip(ga, g_fd)])
    rel = num / _norm(ga)
    assert rel < 0.3, rel
