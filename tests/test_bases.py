"""Basis-layer tests: transform round-trips, differentiation of known
functions, Galerkin stencil identities, quasi-inverse identities.

Models the reference's inline solver tests + doc-tests (SURVEY.md S4), plus
the boundary conditions each composite base must satisfy by construction.
"""

import numpy as np
import pytest

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu.ops import chebyshev as chb


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [8, 9, 33])
@pytest.mark.parametrize("method", ["fft", "matmul"])
def test_chebyshev_roundtrip(n, method):
    base = rp.chebyshev(n)
    rng = np.random.default_rng(0)
    u = rng.standard_normal(n)
    uh = base.forward(u, 0, method)
    back = base.backward(uh, 0, method)
    np.testing.assert_allclose(np.asarray(back), u, atol=1e-12)


def test_chebyshev_fft_matches_matmul():
    n = 17
    base = rp.chebyshev(n)
    rng = np.random.default_rng(1)
    u = rng.standard_normal((n, 5))
    a = np.asarray(base.forward(u, 0, "fft"))
    b = np.asarray(base.forward(u, 0, "matmul"))
    np.testing.assert_allclose(a, b, atol=1e-12)


def test_chebyshev_coefficients_of_polynomial():
    # u(x) = T_0 + 2 T_1 + 3 T_3  ->  exact coefficient recovery
    n = 9
    base = rp.chebyshev(n)
    x = base.points
    u = 1.0 + 2.0 * x + 3.0 * (4 * x**3 - 3 * x)
    uh = np.asarray(base.forward(u, 0, "fft"))
    expect = np.zeros(n)
    expect[0], expect[1], expect[3] = 1.0, 2.0, 3.0
    np.testing.assert_allclose(uh, expect, atol=1e-12)


@pytest.mark.parametrize("n", [8, 16])
def test_fourier_r2c_roundtrip(n):
    base = rp.fourier_r2c(n)
    rng = np.random.default_rng(2)
    u = rng.standard_normal(n)
    uh = base.forward(u, 0)
    back = np.asarray(base.backward(uh, 0))
    np.testing.assert_allclose(back, u, atol=1e-12)


def test_fourier_c2c_roundtrip():
    n = 12
    base = rp.fourier_c2c(n)
    rng = np.random.default_rng(3)
    u = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    uh = base.forward(u, 0)
    back = np.asarray(base.backward(uh, 0))
    np.testing.assert_allclose(back, u, atol=1e-12)


# ---------------------------------------------------------------------------
# differentiation
# ---------------------------------------------------------------------------


def test_chebyshev_derivative_of_sin():
    n = 32
    base = rp.chebyshev(n)
    x = base.points
    u = np.sin(np.pi * x)
    uh = base.forward(u, 0, "fft")
    du = np.asarray(base.backward(base.gradient(uh, 1, 0), 0, "fft"))
    np.testing.assert_allclose(du, np.pi * np.cos(np.pi * x), atol=1e-8)
    d2u = np.asarray(base.backward(base.gradient(uh, 2, 0), 0, "fft"))
    np.testing.assert_allclose(d2u, -np.pi**2 * np.sin(np.pi * x), atol=1e-6)


def test_fourier_derivative_of_wave():
    n = 32
    base = rp.fourier_r2c(n)
    x = base.points
    u = np.cos(3 * x)
    uh = base.forward(u, 0)
    du = np.asarray(base.backward(base.gradient(uh, 1, 0), 0))
    np.testing.assert_allclose(du, -3 * np.sin(3 * x), atol=1e-10)


def test_space2_mixed_gradient_with_scale():
    nx, ny = 32, 33
    space = rp.Space2(rp.fourier_r2c(nx), rp.chebyshev(ny))
    scale = [2.0, 1.0]
    x = space.base_x.points * scale[0]
    y = space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    u = np.cos(2 * X / scale[0]) * np.sin(np.pi * Y)
    vhat = space.forward(u)
    dudx = np.asarray(space.backward(space.gradient(vhat, [1, 0], scale)))
    expect = -(2 / scale[0]) * np.sin(2 * X / scale[0]) * np.sin(np.pi * Y)
    np.testing.assert_allclose(dudx, expect, atol=1e-8)


# ---------------------------------------------------------------------------
# composite bases: boundary conditions + ortho casts
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "factory", [rp.cheb_dirichlet, rp.cheb_neumann, rp.cheb_dirichlet_neumann]
)
def test_composite_roundtrip_via_ortho(factory):
    n = 16
    base = factory(n)
    rng = np.random.default_rng(4)
    comp = rng.standard_normal(base.m)
    ortho = base.to_ortho(comp, 0)
    back = np.asarray(base.from_ortho(ortho, 0))
    np.testing.assert_allclose(back, comp, atol=1e-10)


def test_dirichlet_basis_satisfies_bc():
    n = 12
    S = rp.cheb_dirichlet(n).stencil
    Tm1 = np.array([(-1.0) ** k for k in range(n)])  # T_k(-1)
    Tp1 = np.ones(n)  # T_k(1)
    np.testing.assert_allclose(Tm1 @ S, 0.0, atol=1e-12)
    np.testing.assert_allclose(Tp1 @ S, 0.0, atol=1e-12)


def test_neumann_basis_satisfies_bc():
    n = 12
    S = rp.cheb_neumann(n).stencil
    dTm1 = np.array([(-1.0) ** (k + 1) * k**2 for k in range(n)])  # T_k'(-1)
    dTp1 = np.array([float(k**2) for k in range(n)])  # T_k'(1)
    np.testing.assert_allclose(dTm1 @ S, 0.0, atol=1e-12)
    np.testing.assert_allclose(dTp1 @ S, 0.0, atol=1e-12)


def test_dirichlet_neumann_basis_satisfies_bc():
    n = 12
    S = rp.cheb_dirichlet_neumann(n).stencil
    Tm1 = np.array([(-1.0) ** k for k in range(n)])
    dTp1 = np.array([float(k**2) for k in range(n)])
    np.testing.assert_allclose(Tm1 @ S, 0.0, atol=1e-12)
    np.testing.assert_allclose(dTp1 @ S, 0.0, atol=1e-12)


def test_composite_forward_reproduces_bc_function():
    # a function that already satisfies dirichlet BCs is reproduced exactly
    n = 24
    base = rp.cheb_dirichlet(n)
    x = base.points
    u = np.sin(np.pi * x)
    uh = base.forward(u, 0, "fft")
    back = np.asarray(base.backward(uh, 0, "fft"))
    np.testing.assert_allclose(back, u, atol=1e-10)


# ---------------------------------------------------------------------------
# quasi-inverse identities (the contract the solver layer builds on)
# ---------------------------------------------------------------------------


def test_b2_is_quasi_inverse_of_d2():
    n = 16
    D2 = chb.diff_matrix(n, 2)
    B2 = chb.quasi_inverse_b2(n)
    prod = B2 @ D2
    np.testing.assert_allclose(prod[2:, :], np.eye(n)[2:, :], atol=1e-10)
    np.testing.assert_allclose(prod[:2, :], 0.0, atol=1e-12)


def test_helmholtz_operator_is_banded():
    # pinv @ S must be 4-banded with offsets (-2, 0, 2, 4) — the structure the
    # reference's Fdma kernel exploits (/root/reference/src/solver/fdma.rs).
    n = 16
    base = rp.cheb_dirichlet(n)
    S = base.mass()
    pinv = base.laplace_inv_eye() @ base.laplace_inv()
    A = pinv @ S
    m = A.shape[0]
    for i in range(m):
        for j in range(m):
            if j - i not in (-2, 0, 2, 4):
                assert abs(A[i, j]) < 1e-12, (i, j, A[i, j])


def test_dirichlet_neumann_operator_is_seven_banded():
    n = 16
    base = rp.cheb_dirichlet_neumann(n)
    S = base.mass()
    pinv = base.laplace_inv_eye() @ base.laplace_inv()
    A = pinv @ S
    m = A.shape[0]
    for i in range(m):
        for j in range(m):
            if j - i not in (-2, -1, 0, 1, 2, 3, 4):
                assert abs(A[i, j]) < 1e-12, (i, j, A[i, j])


@pytest.mark.slow
def test_space2_leading_batch_dims():
    """Space transforms/gradients/solvers are polymorphic over extra leading
    batch dims (stacked same-space fields) and match per-field application."""
    import jax.numpy as jnp

    from rustpde_mpi_tpu.solver import HholtzAdi, Poisson

    space = rp.Space2(rp.cheb_dirichlet(17), rp.cheb_dirichlet(16))
    rng = np.random.default_rng(7)
    a, b = rng.standard_normal((2, 17, 16))
    stacked_phys = jnp.stack([jnp.asarray(a), jnp.asarray(b)])
    fw = space.forward(stacked_phys)
    np.testing.assert_allclose(np.asarray(fw[0]), np.asarray(space.forward(a)), atol=1e-13)
    np.testing.assert_allclose(np.asarray(fw[1]), np.asarray(space.forward(b)), atol=1e-13)
    bw = space.backward(fw)
    np.testing.assert_allclose(np.asarray(bw[0]), np.asarray(space.backward(space.forward(a))), atol=1e-13)
    g = space.gradient(fw, (1, 1), (1.0, 1.0))
    np.testing.assert_allclose(
        np.asarray(g[1]), np.asarray(space.gradient(space.forward(b), (1, 1), (1.0, 1.0))), atol=1e-12
    )
    # identical-operator implicit solves, batched
    adi = HholtzAdi(space, (0.1, 0.1))
    rhs = jnp.stack([space.to_ortho(space.forward(a)), space.to_ortho(space.forward(b))])
    out = adi.solve(rhs)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(adi.solve(rhs[0])), atol=1e-12)
    np.testing.assert_allclose(np.asarray(out[1]), np.asarray(adi.solve(rhs[1])), atol=1e-12)
    poi_space = rp.Space2(rp.cheb_neumann(17), rp.cheb_neumann(16))
    poi = Poisson(poi_space, (1.0, 1.0))
    rhs_n = jnp.stack([jnp.asarray(rng.standard_normal((17, 16))) for _ in range(2)])
    out = poi.solve(rhs_n)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(poi.solve(rhs_n[0])), atol=1e-11)
