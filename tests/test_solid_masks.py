"""Solid-mask builders + applied volume penalization.

The reference stores masks but never applies them (navier.rs:86); the
penalization wiring is this framework's extension (SURVEY.md S7.8), so these
tests check the physics directly: u -> 0 and temp -> enforced value inside
the solid."""

import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D
from rustpde_mpi_tpu.models.solid_masks import (
    solid_cylinder_inner,
    solid_porosity,
    solid_porosity_interpolate,
    solid_rectangle,
    solid_roughness_sinusoid,
)


def _cheb_grid(n):
    return -np.cos(np.pi * np.arange(n) / (n - 1))


def test_cylinder_mask_geometry():
    x = y = np.linspace(-1, 1, 101)
    mask, value = solid_cylinder_inner(x, y, 0.2, 0.0, 0.3)
    r = np.sqrt((0.2 - x[:, None]) ** 2 + (0.0 - y[None, :]) ** 2)
    assert np.all(mask[r < 0.3 - 0.03 - 1e-12] == 1.0)
    assert np.all(mask[r > 0.3 + 0.03 + 1e-12] == 0.0)
    layer = (np.abs(r - 0.3) < 0.03) & (mask > 0) & (mask < 1)
    assert layer.any()  # smooth tanh transition exists
    assert np.all(value == 0.0)


def test_rectangle_mask_geometry():
    x = y = np.linspace(-1, 1, 64)
    mask, _ = solid_rectangle(x, y, 0.0, 0.5, 0.2, 0.1)
    inside = (np.abs(x[:, None]) < 0.2) & (np.abs(y[None, :] - 0.5) < 0.1)
    np.testing.assert_array_equal(mask, inside.astype(float))


def test_roughness_mask_values():
    x = _cheb_grid(65)
    y = _cheb_grid(65)
    mask, value = solid_roughness_sinusoid(x, y, 0.1, 10.0)
    # where the sinusoid is above the plate the wall row is solid at the
    # plate temperature (y_rough < 0 where sin(kx) < -0.5 leaves gaps —
    # the reference's formula behaves identically, solid_masks.rs:96-99)
    rough = np.sin(10.0 * x) + 0.5 > 0.0
    assert np.all(mask[rough, 0] == 1.0)
    assert np.all(value[rough, 0] == 0.5)
    assert np.all(mask[rough, -1] == 1.0)
    assert np.all(value[rough, -1] == -0.5)
    # interior is fluid
    assert np.all(mask[:, 25:40] == 0.0)
    assert mask.min() >= 0.0 and mask.max() <= 1.0


def test_porosity_masks():
    x = y = _cheb_grid(129)
    mask, _ = solid_porosity(x, y, 0.4, 0.8)
    frac = mask.mean()
    assert 0.02 < frac < 0.5  # some circles materialized
    m2, v2 = solid_porosity_interpolate(65, 65, 0.4, 0.8)
    assert m2.shape == (65, 65)
    # spectral interpolation of an indicator overshoots a little but stays
    # near [0, 1]
    assert -0.3 < m2.min() and m2.max() < 1.3


def test_penalization_forces_zero_velocity():
    """Cylinder obstacle in a driven RBC cell: after integration the flow
    inside the solid is orders of magnitude weaker than the fluid flow."""
    model = Navier2D.new_confined(33, 33, 1e5, 1.0, 0.01, 1.0, "rbc")
    x, y = model.x
    mask, value = solid_cylinder_inner(x, y, 0.0, 0.0, 0.3)
    model.set_solid(mask, value)
    model.set_velocity(0.2, 1.0, 1.0)
    model.set_temperature(0.2, 1.0, 1.0)
    model.update_n(100)
    assert not model.exit()
    ux, uy = model.get_field("velx"), model.get_field("vely")
    speed = np.sqrt(ux**2 + uy**2)
    deep = mask > 0.99
    assert speed[deep].max() < 2e-3
    assert speed[~deep].max() > 50 * speed[deep].max()


def test_penalization_enforces_temperature():
    model = Navier2D.new_confined(33, 33, 1e4, 1.0, 0.01, 1.0, "rbc")
    x, y = model.x
    mask, _ = solid_cylinder_inner(x, y, 0.0, 0.0, 0.25)
    value = np.full_like(mask, 0.3)  # heated obstacle
    model.set_solid(mask, value)
    model.update_n(200)
    temp = model.get_field("temp")
    # total physical temperature = temp + tempbc lift
    from rustpde_mpi_tpu.models.boundary_conditions import bc_rbc_values

    xs, ys = (b.points for b in model.field_space.bases)
    total = temp + bc_rbc_values(xs, ys)
    deep = mask > 0.99
    np.testing.assert_allclose(total[deep], 0.3, atol=5e-3)


def test_set_solid_none_restores_plain_step():
    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    ref = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    x, y = model.x
    mask, value = solid_cylinder_inner(x, y, 0.0, 0.0, 0.3)
    model.set_solid(mask, value)
    model.set_solid(None)
    assert model.solid is None
    # identical ICs -> identical trajectories once the mask is removed
    for name in ("temp", "velx", "vely"):
        model.set_field(name, ref.get_field(name))
    model.update_n(5)
    ref.update_n(5)
    np.testing.assert_allclose(
        model.get_field("temp"), ref.get_field("temp"), atol=1e-12
    )


@pytest.mark.slow
def test_penalized_sharded_matches_serial():
    """The penalization is elementwise in physical space — it must shard
    transparently under the pencil mesh."""
    import jax
    from jax.sharding import Mesh

    from rustpde_mpi_tpu.parallel.mesh import AXIS

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs >=4 virtual devices")
    mesh = Mesh(np.array(devices[:4]), (AXIS,))
    serial = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    sharded = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mesh=mesh)
    x, y = serial.x
    mask, value = solid_cylinder_inner(x, y, 0.0, 0.0, 0.3)
    serial.set_solid(mask, value)
    sharded.set_solid(mask, value)
    for name in ("temp", "velx", "vely"):
        sharded.set_field(name, serial.get_field(name))
    serial.update_n(5)
    sharded.update_n(5)
    np.testing.assert_allclose(
        sharded.get_field("temp"), serial.get_field("temp"), atol=1e-11
    )
    np.testing.assert_allclose(
        sharded.get_field("velx"), serial.get_field("velx"), atol=1e-11
    )
