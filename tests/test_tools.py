"""Tooling tests: particle tracer (C++ core vs numpy fallback), XDMF
generator, plotting scripts — all over real snapshot files."""

import os
import subprocess
import sys

import numpy as np
import pytest

from rustpde_mpi_tpu.tools import ParticleSwarm, create_xmf, native_available

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _circular_field(n=65):
    x = np.linspace(-1, 1, n)
    y = np.linspace(-1, 1, n)
    ux = np.broadcast_to(-y[None, :], (n, n)).copy()
    uy = np.broadcast_to(x[:, None], (n, n)).copy()
    return x, y, ux, uy


def test_tracer_circular_orbit_numpy():
    """In u=(-y, x) a particle orbits at constant radius; RK4 at dt=1e-3
    conserves it to ~1e-9 over a quarter turn."""
    x, y, ux, uy = _circular_field()
    swarm = ParticleSwarm([(0.5, 0.0)], x, y, 0.001, backend="numpy")
    n = round(np.pi / 2 / 0.001)
    frozen = swarm.update(ux, uy, n)
    assert frozen == 0
    r = np.hypot(swarm.px[0], swarm.py[0])
    assert abs(r - 0.5) < 1e-6
    # quarter turn: (0.5, 0) -> (0, 0.5)
    assert abs(swarm.px[0]) < 1e-2 and abs(swarm.py[0] - 0.5) < 1e-2


@pytest.mark.skipif(not native_available(), reason="g++ build unavailable")
def test_tracer_native_matches_numpy():
    x, y, ux, uy = _circular_field()
    rng = np.random.default_rng(4)
    pos = rng.uniform(-0.6, 0.6, size=(50, 2))
    s_np = ParticleSwarm(pos, x, y, 0.01, backend="numpy")
    s_cc = ParticleSwarm(pos, x, y, 0.01, backend="native")
    f1 = s_np.update(ux, uy, 100)
    f2 = s_cc.update(ux, uy, 100)
    assert f1 == f2
    np.testing.assert_allclose(s_cc.px, s_np.px, atol=1e-12)
    np.testing.assert_allclose(s_cc.py, s_np.py, atol=1e-12)
    # velocity sampling agrees too
    u1, v1 = s_np.sample(ux, uy)
    u2, v2 = s_cc.sample(ux, uy)
    np.testing.assert_allclose(u2, u1, atol=1e-12)
    np.testing.assert_allclose(v2, v1, atol=1e-12)


def test_tracer_out_of_bounds_freezes():
    """A particle advected toward the boundary freezes instead of escaping
    (the reference ignores the per-step error, lib.rs ParticleSwarm::update)."""
    n = 33
    x = y = np.linspace(-1, 1, n)
    ux = np.ones((n, n))
    uy = np.zeros((n, n))
    for backend in ["numpy"] + (["native"] if native_available() else []):
        swarm = ParticleSwarm([(0.9, 0.0), (-0.5, 0.0)], x, y, 0.01, backend=backend)
        frozen = swarm.update(ux, uy, 50)
        assert frozen == 1, backend
        assert swarm.px[0] <= 1.0 + 1e-12
        assert swarm.px[1] > 0.0 - 1e-12  # still moving


def test_tracer_nonuniform_grid_interpolation():
    """Bilinear sampling of a bilinear function is exact, Chebyshev grid."""
    n = 33
    x = y = -np.cos(np.pi * np.arange(n) / (n - 1))
    f = 2.0 + 0.5 * x[:, None] + 0.25 * y[None, :] + 0.1 * x[:, None] * y[None, :]
    g = np.zeros_like(f)
    for backend in ["numpy"] + (["native"] if native_available() else []):
        swarm = ParticleSwarm([(0.3, -0.4), (0.111, 0.77)], x, y, 0.01, backend=backend)
        u, _ = swarm.sample(f, g)
        expect = 2.0 + 0.5 * swarm.px + 0.25 * swarm.py + 0.1 * swarm.px * swarm.py
        np.testing.assert_allclose(u, expect, atol=1e-12, err_msg=backend)


@pytest.fixture(scope="module")
def snapshot_dir(tmp_path_factory):
    """Two real snapshots from a tiny RBC run."""
    from rustpde_mpi_tpu import Navier2D

    d = tmp_path_factory.mktemp("run") / "data"
    d.mkdir()
    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    model.update_n(5)
    model.write(str(d / "flow0.05.h5"))
    model.update_n(5)
    model.write(str(d / "flow0.10.h5"))
    return d


def test_create_xmf(snapshot_dir):
    import xml.etree.ElementTree as ET

    written = create_xmf(str(snapshot_dir))
    assert len(written) == 2
    assert os.path.exists(snapshot_dir / "cartesian.nc")
    tree = ET.parse(written[0])
    root = tree.getroot()
    assert root.tag == "Xdmf"
    grid = root.find("Domain/Grid")
    attrs = grid.findall("Attribute")
    assert [a.get("Name") for a in attrs] == ["temp", "ux", "uy", "pres"]
    item = attrs[0].find("DataItem")
    assert item.text.endswith(":/temp/v")
    # cartesian meshgrid round-trips the snapshot coords
    import h5py

    with h5py.File(snapshot_dir / "cartesian.nc") as f:
        xx = np.asarray(f["x"])
    with h5py.File(written[0].replace("xmf000000.xmf", "flow0.05.h5")) as f:
        pass
    assert xx.shape == (17, 17)
    # time ordering: first xmf corresponds to t=0.05
    t0 = float(grid.find("Time").get("Value"))
    assert abs(t0 - 0.05) < 1e-9


def test_trace_files_over_snapshots(snapshot_dir):
    import h5py

    files = sorted(str(p) for p in snapshot_dir.glob("flow*.h5"))
    with h5py.File(files[0]) as f:
        x = np.asarray(f["ux/x"])
        y = np.asarray(f["ux/y"])
    swarm = ParticleSwarm.from_rectangle(0.0, 0.0, 0.2, 20, x, y, 0.005)
    swarm.trace_files(files, snapshot_dt=0.05)
    assert len(swarm.history) == 3
    assert swarm.time == pytest.approx(0.1)
    swarm.write_history(str(snapshot_dir / "traj.txt"))
    rows = np.loadtxt(snapshot_dir / "traj.txt")
    assert rows.shape == (60, 3)


def test_plot2d_script(snapshot_dir):
    out = snapshot_dir / "fig.png"
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "plot", "plot2d.py"),
            "--file",
            str(snapshot_dir / "flow0.10.h5"),
            "--out",
            str(out),
        ],
        capture_output=True,
        text=True,
        cwd=str(snapshot_dir),
        timeout=180,
    )
    assert res.returncode == 0, res.stderr
    assert out.exists() and out.stat().st_size > 10_000


def test_plot_statistics_script(tmp_path):
    """statistics.h5 written by the Statistics subsystem renders."""
    from rustpde_mpi_tpu import Navier2D, Statistics

    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    stats = Statistics(model, save_stat=0.05, write_stat=0.1)
    model.update_n(5)
    stats.update(model)
    fname = tmp_path / "statistics.h5"
    stats.write(str(fname))
    out = tmp_path / "stat.png"
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "plot", "plot_statistics.py"),
            "--file",
            str(fname),
            "--out",
            str(out),
        ],
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert res.returncode == 0, res.stderr
    assert out.exists()
