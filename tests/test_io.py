"""Checkpoint/restart tests: reference HDF5 layout, round-trip, and
resolution-change restart via spectral interpolation (SURVEY.md S3.5)."""

import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D

h5py = pytest.importorskip("h5py")


def _run_model(nx=17, ny=17, periodic=False):
    model = Navier2D(nx, ny, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=periodic)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(10)
    return model


def test_snapshot_layout(tmp_path):
    model = _run_model()
    fname = str(tmp_path / "flow.h5")
    model.write(fname)
    with h5py.File(fname, "r") as h5:
        for var in ("ux", "uy", "temp", "pres", "tempbc"):
            for ds in ("x", "dx", "y", "dy", "v", "vhat"):
                assert f"{var}/{ds}" in h5, f"missing {var}/{ds}"
        assert float(np.asarray(h5["time"])) == pytest.approx(0.1)
        for key in ("ra", "pr", "nu", "ka"):
            assert key in h5


def test_roundtrip_restores_state(tmp_path):
    model = _run_model()
    fname = str(tmp_path / "flow.h5")
    model.write(fname)

    other = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    other.read(fname)
    assert other.time == pytest.approx(model.time)
    for attr in ("temp", "velx", "vely", "pres"):
        np.testing.assert_allclose(
            np.asarray(getattr(other.state, attr)),
            np.asarray(getattr(model.state, attr)),
            atol=1e-14,
        )
    # restart continues identically
    model.update_n(5)
    other.update_n(5)
    np.testing.assert_allclose(
        np.asarray(other.state.temp), np.asarray(model.state.temp), atol=1e-13
    )


def test_restart_with_resolution_change(tmp_path):
    model = _run_model(nx=17, ny=17)
    fname = str(tmp_path / "flow.h5")
    model.write(fname)

    finer = Navier2D(25, 25, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    finer.read(fname)
    # zero-padded spectral restart: coefficient prefix is exact, tail is zero
    old = np.asarray(model.state.temp)
    new = np.asarray(finer.state.temp)
    np.testing.assert_allclose(new[: old.shape[0], : old.shape[1]], old, atol=1e-14)
    assert np.abs(new[old.shape[0] :, :]).max() == 0.0
    # Nu agrees up to the quadrature difference between the two grids
    assert finer.eval_nu() == pytest.approx(model.eval_nu(), rel=1e-2)
    finer.update_n(5)
    assert np.all(np.isfinite(np.asarray(finer.state.temp)))


@pytest.mark.slow
def test_periodic_restart_with_resolution_change(tmp_path):
    """Periodic x-axis resolution change: the physical field must be
    preserved, not just coefficient prefixes.  This repo's r2c forward is
    amplitude-normalized, so a plain spectral zero-pad is exact — the
    reference's (new-1)/(old-1) renormalization (needed for its unnormalized
    rustfft convention) would scale the field by O(1)."""
    model = _run_model(nx=16, ny=17, periodic=True)
    fname = str(tmp_path / "flow.h5")
    model.write(fname)

    finer = Navier2D(32, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    finer.read(fname)
    # physical values at the coarse grid's points: the 32-point uniform grid
    # contains every 16-point grid point at even indices
    coarse_v = model.get_field("temp")
    fine_v = finer.get_field("temp")
    np.testing.assert_allclose(fine_v[::2, :], coarse_v, atol=1e-13)
    # observables agree (y-grid unchanged, x interpolation exact)
    assert finer.eval_nu() == pytest.approx(model.eval_nu(), rel=1e-8)
    finer.update_n(5)
    assert np.all(np.isfinite(np.asarray(np.abs(finer.state.temp))))


def test_periodic_restart_parity_flip(tmp_path):
    """nx 16 -> 17 keeps the r2c spectral shape (m=9) but re-types the
    Nyquist row as a regular +k mode, which must be halved."""
    model = _run_model(nx=16, ny=17, periodic=True)
    fname = str(tmp_path / "flow.h5")
    model.write(fname)

    odd = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    odd.read(fname)
    old = np.asarray(model.state.temp)
    new = np.asarray(odd.state.temp)
    np.testing.assert_allclose(new[:-1, :], old[:-1, :], atol=1e-14)
    np.testing.assert_allclose(new[-1, :], 0.5 * old[-1, :], atol=1e-14)
    # plate Nu depends only on the k=0 column -> unchanged
    assert odd.eval_nu() == pytest.approx(model.eval_nu(), rel=1e-8)


def test_periodic_roundtrip(tmp_path):
    model = _run_model(nx=16, ny=17, periodic=True)
    fname = str(tmp_path / "flow.h5")
    model.write(fname)
    with h5py.File(fname, "r") as h5:
        assert "temp/vhat_re" in h5 and "temp/vhat_im" in h5

    other = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    other.read(fname)
    np.testing.assert_allclose(
        np.asarray(other.state.temp), np.asarray(model.state.temp), atol=1e-14
    )


def test_field2_readwrite_trait(tmp_path):
    """Per-field IO API (the reference's ReadWrite trait on Field2)."""
    import jax.numpy as jnp

    from rustpde_mpi_tpu import Field2, Space2, cheb_dirichlet, fourier_r2c

    fname = str(tmp_path / "field.h5")
    space = Space2(fourier_r2c(16), cheb_dirichlet(17))
    f = Field2(space)
    rng = np.random.default_rng(8)
    f.vhat = space.forward(jnp.asarray(rng.standard_normal((16, 17))))
    f.write(fname, "temp")
    g = Field2(space)
    g.read(fname, "temp")
    np.testing.assert_allclose(np.asarray(g.v), np.asarray(f.v), atol=1e-12)
    # resolution-change restart through the same trait
    space2 = Space2(fourier_r2c(32), cheb_dirichlet(17))
    h = Field2(space2)
    h.read(fname, "temp")
    # the coarse field evaluated on the fine grid: compare at shared points
    np.testing.assert_allclose(
        np.asarray(h.v)[::2, :], np.asarray(f.v), atol=1e-10
    )
