"""Explicit pencil decomposition + collectives (parallel/decomp.py).

The models run on GSPMD constraints; this explicit shard_map/all_to_all
surface is the MPI-parity API and is validated the idiomatic-JAX way: on the
virtual 8-device mesh against the unsharded ground truth (SURVEY.md S4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rustpde_mpi_tpu.parallel.decomp import (
    Decomp2d,
    all_gather_sum,
    broadcast_scalar,
    gather_root,
    scatter_root,
)
from rustpde_mpi_tpu.parallel.mesh import AXIS, make_mesh


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return make_mesh()


def test_pencil_bookkeeping(mesh):
    d = Decomp2d((20, 17), mesh)
    # y-pencil: axis 0 split 20 over 8 -> sizes 3,3,3,3,2,2,2,2
    sizes = [d.y_pencil(r).sz[0] for r in range(8)]
    assert sizes == [3, 3, 3, 3, 2, 2, 2, 2]
    assert sum(sizes) == 20
    # contiguous coverage
    assert d.y_pencil(0).st == (0, 0)
    for r in range(1, 8):
        assert d.y_pencil(r).st[0] == d.y_pencil(r - 1).en[0] + 1
    assert d.y_pencil(7).en == (19, 16)
    # x-pencil distributes axis 1; axis_contig flags the undivided axis
    assert d.x_pencil(3).sz[0] == 20
    assert d.y_pencil(0).axis_contig == 1
    assert d.x_pencil(0).axis_contig == 0


def _spec_tuple(spec, ndim: int) -> tuple:
    """PartitionSpec padded to ``ndim`` with None: newer JAX normalizes away
    trailing Nones, so specs must be compared in padded form."""
    t = tuple(spec)
    return t + (None,) * (ndim - len(t))


def test_transpose_round_trip(mesh):
    d = Decomp2d((16, 24), mesh)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((16, 24))
    x_pen = d.place_x_pencil(a)
    y_pen = d.transpose_x_to_y(x_pen)
    # repartition preserves the global view
    np.testing.assert_array_equal(gather_root(y_pen), a)
    # layout actually changed: axis 0 now sharded
    assert _spec_tuple(y_pen.sharding.spec, 2) == (AXIS, None)
    back = d.transpose_y_to_x(y_pen)
    np.testing.assert_array_equal(gather_root(back), a)
    assert _spec_tuple(back.sharding.spec, 2) == (None, AXIS)


def test_transpose_inside_jit(mesh):
    d = Decomp2d((16, 16), mesh)
    a = jnp.arange(256.0).reshape(16, 16)

    @jax.jit
    def f(x):
        y = d.transpose_x_to_y(x)
        return d.transpose_y_to_x(y * 2.0)

    out = f(d.place_x_pencil(a))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(a) * 2.0)


@pytest.mark.parametrize("shape", [(17, 16), (129, 65), (257, 129), (1025, 33)])
def test_transpose_uneven_extents(mesh, shape):
    """The explicit all-to-all surface handles the production (odd) grids —
    129/1025-class extents not divisible by the 8-rank mesh (VERDICT r2 weak
    #5; funspace's transpose_x_to_y takes any extent)."""
    d = Decomp2d(shape, mesh)
    rng = np.random.default_rng(3)
    a = rng.standard_normal(shape)
    y_pen = d.transpose_x_to_y(jnp.asarray(a))
    np.testing.assert_array_equal(gather_root(y_pen), a)
    back = d.transpose_y_to_x(y_pen)
    np.testing.assert_array_equal(gather_root(back), a)

    @jax.jit
    def f(x):
        return d.transpose_y_to_x(d.transpose_x_to_y(x) * 2.0)

    np.testing.assert_array_equal(np.asarray(f(jnp.asarray(a))), a * 2.0)


def test_all_gather_sum(mesh):
    d = Decomp2d((16, 8), mesh)
    rng = np.random.default_rng(1)
    a = rng.standard_normal((16, 8))
    total = all_gather_sum(d.place_y_pencil(a), mesh)
    np.testing.assert_allclose(float(total), a.sum(), rtol=1e-12)


def test_broadcast_scalar(mesh):
    assert float(broadcast_scalar(3.25, mesh)) == 3.25


def test_scatter_gather_root(mesh):
    d = Decomp2d((16, 16), mesh)
    a = np.arange(256.0).reshape(16, 16)
    sharded = scatter_root(a, d, pencil="x")
    assert _spec_tuple(sharded.sharding.spec, 2) == (None, AXIS)
    np.testing.assert_array_equal(gather_root(sharded), a)


def test_slice_io_roundtrip(tmp_path, mesh):
    from rustpde_mpi_tpu.utils.slice_io import (
        read_pencil,
        read_slice,
        write_pencils,
        write_slice,
    )

    fname = str(tmp_path / "slices.h5")
    rng = np.random.default_rng(2)
    a = rng.standard_normal((16, 24))
    # pencil-streamed write reproduces the global array
    d = Decomp2d((16, 24), mesh)
    write_pencils(fname, "v", d.place_y_pencil(a), d, pencil="y")
    np.testing.assert_array_equal(read_slice(fname, "v", (0, 0), (16, 24)), a)
    # one rank's slab
    p = d.y_pencil(3)
    block = read_pencil(fname, "v", d, 3, pencil="y")
    np.testing.assert_array_equal(
        block, a[p.st[0] : p.st[0] + p.sz[0], :]
    )
    # complex slab IO via re/im pairs
    c = rng.standard_normal((8, 8)) + 1j * rng.standard_normal((8, 8))
    write_slice(fname, "w", c, (4, 4), (16, 16))
    got = read_slice(fname, "w", (4, 4), (8, 8), is_complex=True)
    np.testing.assert_array_equal(got, c)
    # shape-mismatch guard
    with pytest.raises(ValueError, match="exists with shape"):
        write_slice(fname, "v", a, (0, 0), (8, 24))


def test_multihost_single_process_degenerate(mesh):
    """The multi-host glue degenerates to identity single-process, so the
    same program text runs on one chip, the virtual mesh, and a pod."""
    from jax.sharding import NamedSharding, PartitionSpec

    from rustpde_mpi_tpu.parallel import multihost as mh

    assert mh.initialize_distributed() is False  # no coordinator configured
    assert mh.process_index() == 0 and mh.is_root()
    m = mh.global_pencil_mesh()
    assert m.shape[AXIS] == len(jax.devices())
    a = np.arange(64.0).reshape(8, 8)
    sharded = mh.global_array(a, NamedSharding(m, PartitionSpec(AXIS, None)))
    np.testing.assert_array_equal(mh.host_local_array(sharded), a)
    mh.sync_hosts()  # no-op


def test_concurrent_pencil_writer_matches_sequential(tmp_path, mesh):
    """write_pencils_concurrent (per-rank shard files in parallel + an HDF5
    virtual dataset) exposes the same global dataset the rank-sequential
    writer produces -- the TPU-native analog of the reference's disabled
    MPIO path (/root/reference/src/field_mpi/io_mpi.rs:14-108)."""
    from rustpde_mpi_tpu.utils.slice_io import (
        read_pencil,
        read_slice,
        write_pencils_concurrent,
    )

    fname = str(tmp_path / "conc.h5")
    rng = np.random.default_rng(5)
    a = rng.standard_normal((16, 24))
    d = Decomp2d((16, 24), mesh)
    write_pencils_concurrent(fname, "v", d.place_y_pencil(a), d, pencil="y")
    np.testing.assert_array_equal(read_slice(fname, "v", (0, 0), (16, 24)), a)
    p = d.y_pencil(5)
    np.testing.assert_array_equal(
        read_pencil(fname, "v", d, 5, pencil="y"),
        a[p.st[0] : p.st[0] + p.sz[0], :],
    )
    # complex arrays split into _re/_im virtual datasets like write_slice
    c = rng.standard_normal((16, 24)) + 1j * rng.standard_normal((16, 24))
    write_pencils_concurrent(fname, "w", c, d, pencil="y")
    got = read_slice(fname, "w", (0, 0), (16, 24), is_complex=True)
    np.testing.assert_array_equal(got, c)
    # overwrite works (virtual dataset replaced, shards rewritten)
    write_pencils_concurrent(fname, "v", d.place_y_pencil(2 * a), d, pencil="y")
    np.testing.assert_array_equal(read_slice(fname, "v", (0, 0), (16, 24)), 2 * a)
