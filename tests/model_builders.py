"""Shared tiny-model builders for the robustness-layer test files.

test_resilience, test_io_pipeline, test_sharded_ckpt and test_serve all
exercise harness machinery (checkpoints, journals, pipelines, scheduling)
on top of the SAME small confined RBC configuration — the physics is
incidental, the jit shapes are not: one set of builders keeps every file
on identical shapes, so the whole tier compiles each entry point once per
pytest process (and hits the persistent XLA cache across runs), instead of
each module paying its own trace+compile for a cosmetically different
model.  The matching session-scoped stepped fixture lives in conftest.py
(``stepped_rbc17``).
"""

from rustpde_mpi_tpu import Navier2D


def build_rbc17(dt=0.01):
    """17^2 confined RBC at Ra=1e4 — the tier's canonical tiny model."""
    model = Navier2D(17, 17, 1e4, 1.0, dt, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    # keep the save-window callback from littering data/ with flow files;
    # harness checkpoints/journals are what these tests assert on
    model.write_intervall = 1e9
    return model


def build_rbc33(mesh=None, dt=0.01, nx=33, ny=32):
    """33x32 build (optionally mesh-sharded) — the sharded-checkpoint
    shape; nx/ny overridable for the odd-size edge cases."""
    model = Navier2D(nx, ny, 1e4, 1.0, dt, 1.0, "rbc", periodic=False, mesh=mesh)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.write_intervall = 1e9
    return model
