"""Nu-parity regression gate.

PARITY.json (written by scripts/record_parity.py) holds the f64 golden
Nusselt trajectory for the reference's flagship config
(/root/reference/src/main.rs:37-58: confined RBC 129^2, Ra=1e7, dt=2e-3) and
the recorded f32-vs-f64 drift.  This test re-runs the head of that trajectory
and asserts reproduction to the 1e-6 parity tolerance (BASELINE.md
north-star), making parity a number the suite enforces rather than an
aspiration.
"""

import json
import os

import pytest

from rustpde_mpi_tpu import Navier2D, config

PARITY = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "PARITY.json")


@pytest.mark.skipif(not os.path.exists(PARITY), reason="PARITY.json not recorded")
def test_f64_nu_trajectory_matches_recorded():
    if not config.X64:
        pytest.skip("parity gold is f64")
    with open(PARITY, encoding="utf-8") as fh:
        gold = json.load(fh)
    cfg = gold["config"]
    model = Navier2D(
        cfg["nx"], cfg["ny"], cfg["ra"], cfg["pr"], cfg["dt"], cfg["aspect"],
        cfg["bc"], periodic=False,
    )
    model.init_random(cfg["amp"], seed=0)
    n_check = 4  # first 200 steps keep CI fast; full trajectory via the script
    for row in gold["nu_f64"][:n_check]:
        model.update_n(cfg["sample_every"])
        nu, nuvol, re, div = model.get_observables()
        assert model.time == pytest.approx(row["time"], abs=1e-9)
        assert nu == pytest.approx(row["nu"], rel=1e-6)
        assert nuvol == pytest.approx(row["nuvol"], rel=1e-6)
        assert re == pytest.approx(row["re"], rel=1e-6)


def test_recorded_f32_drift_is_small():
    if not os.path.exists(PARITY):
        pytest.skip("PARITY.json not recorded")
    with open(PARITY, encoding="utf-8") as fh:
        gold = json.load(fh)
    # the f32 path must statistically track f64: drift well below 1% over
    # the recorded window (actual recorded value ~3e-5)
    assert gold["max_drift"] < 1e-2
