"""In-scan physics-stats engine tests (models/stats.py, ISSUE 14): the
bit-identity hard contract (stats-on stepping == stats-off stepping,
exact float equality), engine-vs-eager-legacy accumulator parity,
per-member ensemble windows + lane-refill resets, checkpoint durability
(gathered + sharded + a real SIGKILL/resume cycle bit-equal to an
uninterrupted run — the PR-2/PR-5 kill-window contract extended to the
stats leaves), the typed journal events replacing the legacy flow's
silent prints, the runner's health streaming, and both export layouts
(legacy statistics.h5 root + per-member engine groups) through the plot
reader."""

import os
import subprocess
import sys

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    Navier2D,
    NavierEnsemble,
    ResilientRunner,
    Statistics,
    export_stats,
)
from rustpde_mpi_tpu.config import StabilityConfig, StatsConfig
from rustpde_mpi_tpu.models.stats import HEALTH_NAMES, StatsEngine
from rustpde_mpi_tpu.telemetry import metrics as tm
from rustpde_mpi_tpu.utils import checkpoint as cp
from rustpde_mpi_tpu.utils.journal import JournalWriter, read_journal

h5py = pytest.importorskip("h5py")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the tier-canonical tiny shape (model_builders): every stats-armed test
# shares stride=2 on 17^2/dt=0.01 so the whole file compiles each stats
# entry point once per pytest process
from model_builders import build_rbc17 as _build

_STRIDE = 2


def _armed(stride=_STRIDE):
    m = _build()
    m.set_stats(StatsConfig(stride=stride))
    return m


def _assert_state_equal(a, b):
    for name in a._fields:
        assert np.array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        ), name


def _assert_stats_equal(pa, pb):
    for name in pa.stats_state._fields:
        assert np.array_equal(
            np.asarray(getattr(pa.stats_state, name)),
            np.asarray(getattr(pb.stats_state, name)),
        ), name
    assert np.array_equal(
        np.asarray(pa._stats_tick), np.asarray(pb._stats_tick)
    )


# -- the hard contract: stats-on stepping is bit-identical to stats-off -------


def test_stats_on_bit_identical_to_stats_off_and_matches_legacy():
    """The accumulators only READ the stepped state: the committed
    trajectory must be EXACTLY equal (float equality) with the engine
    armed, the sample counter follows the stride — and over that same
    trajectory the engine's running averages of the legacy-parity set
    (T/ux/uy spectral sums + the pointwise Nusselt field) match the eager
    models/statistics.py accumulator sampling the stats-off twin at the
    same cadence, to fp tolerance."""
    on, off = _armed(), _build()
    on.update_n(12)
    legacy = Statistics(off, _STRIDE * off.dt, 1.0)
    for _ in range(12 // _STRIDE):
        off.update_n(_STRIDE)
        legacy.update(off)
    _assert_state_equal(on.state, off.state)
    n = float(np.asarray(on.stats_state.samples)[0])
    assert n == 12 // _STRIDE == legacy.num_save
    assert int(np.asarray(on._stats_tick)[0]) == 12
    for e, l in (
        ("t_sum", "t_avg"),
        ("ux_sum", "ux_avg"),
        ("uy_sum", "uy_avg"),
        ("nusselt_sum", "nusselt"),
    ):
        a = np.asarray(getattr(on.stats_state, e)) / n
        b = np.asarray(getattr(legacy, l))
        assert np.abs(a - b).max() <= 1e-12 * max(np.abs(b).max(), 1.0), e


def test_stats_governed_bit_identical_and_survives_rollback_contract():
    """Sentinels + stats share one scanned chunk (the production shape):
    the governed trajectory stays bit-identical to a governed stats-off
    run, and the sums accumulate on the sentinel carry."""
    on, off = _armed(), _build()
    for m in (on, off):
        m.set_stability(StabilityConfig())
    on.update_n(8)
    off.update_n(8)
    _assert_state_equal(on.state, off.state)
    assert float(np.asarray(on.stats_state.samples)[0]) == 8 // _STRIDE


def test_stats_ensemble_bit_identical_per_member_windows_and_refill():
    """Vmapped engine: member trajectories bit-equal to a stats-off
    ensemble, per-member sample counters, and a ``set_member`` lane refill
    resets ONLY that member's averaging window."""
    on = NavierEnsemble(_armed(), [_build().state for _ in range(2)])
    off = NavierEnsemble(_build(), [_build().state for _ in range(2)])
    assert on.stats_armed and not off.stats_armed
    on.update_n(8)
    off.update_n(8)
    _assert_state_equal(on.state, off.state)
    samples = np.asarray(on.stats_state.samples).reshape(-1)
    assert samples.tolist() == [4.0, 4.0]
    keep = np.asarray(on.stats_state.t_sum)[0].copy()
    on.set_member(1, _build().state)
    samples = np.asarray(on.stats_state.samples).reshape(-1)
    assert samples.tolist() == [4.0, 0.0]
    assert np.array_equal(np.asarray(on.stats_state.t_sum)[0], keep)


# -- layout generality --------------------------------------------------------


def test_stats_spectra_natural_mode_order_on_split_layout(monkeypatch):
    """Review regression: split-Fourier storage is [Re | Im] half-blocks,
    so a naive 'top third of stored rows' tail reads Im parts of mid-range
    modes instead of high wavenumbers.  The engine folds per-mode energies
    into natural ascending order: the forced-split model's accumulated
    spectra (and the tail sentinels) match the complex default's to fp
    (the two trajectories are equal to ~1e-15, tests/test_split.py)."""

    def build():
        m = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
        m.set_velocity(0.1, 1.0, 1.0)
        m.set_temperature(0.1, 1.0, 1.0)
        m.set_stats(StatsConfig(stride=2))
        m.update_n(8)
        return m

    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    split = build()
    from rustpde_mpi_tpu.bases import BaseKind

    assert split.temp_space.base_kind(0) == BaseKind.FOURIER_R2C_SPLIT
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cplx = build()
    for leaf in ("spec_x", "spec_y"):
        a = np.asarray(getattr(split.stats_state, leaf))
        b = np.asarray(getattr(cplx.stats_state, leaf))
        assert a.shape == b.shape, leaf  # per-MODE rows, not storage rows
        assert np.abs(a - b).max() <= 1e-9 * np.abs(b).max(), leaf
    hs, hc = split.stats_summary(), cplx.stats_summary()
    for k in HEALTH_NAMES:
        if k.startswith("bl_"):
            continue  # discrete grid-point counts may flip on an fp tie
        assert hs[k] == pytest.approx(hc[k], rel=1e-6, abs=1e-12), k


def test_stats_engine_rejects_non_dns():
    class Fake:
        MODEL_KIND = "lnse"

    with pytest.raises(TypeError, match="not supported"):
        StatsEngine(Fake())


# -- checkpoint durability ----------------------------------------------------


def test_stats_gathered_checkpoint_roundtrip_bit_equal(tmp_path):
    """Gathered single-file snapshots carry the stats leaves exactly: a
    restore + continued stepping is bit-equal to the uninterrupted run."""
    a = _armed()
    a.update_n(6)
    path = str(tmp_path / "snap.h5")
    cp.write_snapshot(a, path)
    b = _armed()
    cp.read_snapshot(b, path)
    _assert_stats_equal(a, b)
    a.update_n(6)
    b.update_n(6)
    _assert_state_equal(a.state, b.state)
    _assert_stats_equal(a, b)


def test_stats_sharded_checkpoint_roundtrip_and_legacy_restart(tmp_path):
    """The sharded two-phase format carries the ``stats/`` datasets
    bit-exactly; a sharded checkpoint written BEFORE the engine was armed
    restores the state exactly and restarts the averaging window at zero
    instead of failing."""
    a = _armed()
    a.update_n(6)
    path = str(tmp_path / "ckpt_0000000006.h5")
    cp.write_sharded_snapshot(a, path, step=6)
    b = _armed()
    cp.read_sharded_snapshot(b, path)
    _assert_stats_equal(a, b)
    _assert_state_equal(a.state, b.state)
    # stats-off-written checkpoint into an armed model: window restarts
    off = _build()
    off.update_n(6)
    old = str(tmp_path / "ckpt_0000000007.h5")
    cp.write_sharded_snapshot(off, old, step=6)
    c = _armed()
    c.update_n(4)  # non-zero sums that must reset
    cp.read_sharded_snapshot(c, old)
    _assert_state_equal(off.state, c.state)
    assert float(np.asarray(c.stats_state.samples)[0]) == 0.0
    assert int(np.asarray(c._stats_tick)[0]) == 0


def test_stats_ensemble_checkpoint_roundtrip_bit_equal(tmp_path):
    """Per-member gathered snapshots carry the stacked stats leaves."""
    a = NavierEnsemble(_armed(), [_build().state for _ in range(2)])
    a.update_n(6)
    path = str(tmp_path / "ens.h5")
    cp.write_ensemble_snapshot(a, path)
    b = NavierEnsemble(_armed(), [_build().state for _ in range(2)])
    cp.read_ensemble_snapshot(b, path)
    _assert_stats_equal(a, b)
    a.update_n(6)
    b.update_n(6)
    _assert_state_equal(a.state, b.state)
    _assert_stats_equal(a, b)


_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["RUSTPDE_X64"] = "1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D, ResilientRunner, config
from rustpde_mpi_tpu.config import StatsConfig
config.enable_compilation_cache()

m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
m.set_velocity(0.1, 1.0, 1.0); m.set_temperature(0.1, 1.0, 1.0)
m.write_intervall = 1e9
m.set_stats(StatsConfig(stride=2))
# host-scoped kill = hard SIGKILL at global step 12 (utils/faults.py) —
# checkpoints exist at the 0.05 save cadence (steps 5 and 10) before it
ResilientRunner(
    m, max_time=0.3, save_intervall=0.05, run_dir=sys.argv[1],
    checkpoint_every_s=None, max_chunk_steps=4, fault="kill@12:host0",
).run()
os._exit(1)  # unreachable: the SIGKILL fired mid-run
"""


@pytest.mark.slow
def test_stats_sigkill_resume_bit_equal_to_uninterrupted(tmp_path):
    """The durability headliner (acceptance criterion): a child process is
    SIGKILLed mid-campaign — no drain, no final checkpoint — and the
    resumed run's final state AND running averages are bit-equal to an
    uninterrupted run of the same horizon.  This is the PR-2/PR-5
    kill-window contract extended to the stats leaves (slow tier, like
    those suites' own kill e2e legs; the fast tier pins the same
    mechanism via the gathered/sharded roundtrip bit-equality above)."""
    run_dir = str(tmp_path / "killed")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD.format(repo=_REPO), run_dir],
        capture_output=True,
        text=True,
        timeout=500,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == -9, (proc.returncode, proc.stderr[-2000:])
    assert cp.latest_checkpoint(run_dir) is not None
    resumed = _armed()
    r2 = ResilientRunner(
        resumed,
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        max_chunk_steps=4,
    )
    s2 = r2.run()
    assert s2["outcome"] == "done" and r2.resumed
    straight = _armed()
    s1 = ResilientRunner(
        straight,
        max_time=0.3,
        save_intervall=0.05,
        run_dir=str(tmp_path / "straight"),
        checkpoint_every_s=None,
        max_chunk_steps=4,
    ).run()
    assert s1["outcome"] == "done" and s1["step"] == s2["step"]
    _assert_state_equal(straight.state, resumed.state)
    _assert_stats_equal(straight, resumed)
    assert s1["stats"] == s2["stats"]  # the health readout agrees too


def test_stats_span_exact_across_dt_rung_moves():
    """Review regression: the dKE/dt window span is accumulated per sample
    at that sample's OWN stride*dt (the accumulator is rebuilt per rung),
    so a governor ladder move mid-window keeps the kinetic-energy budget
    exact — reconstructing the span from the current dt would mis-scale
    the old-rung samples by the rung ratio."""
    m = _armed()
    m.update_n(8)  # 4 samples at dt=0.01
    m.set_dt(0.005)
    m.update_n(8)  # 4 samples at dt=0.005
    span = float(np.asarray(m.stats_state.span_sum)[0])
    first = float(np.asarray(m.stats_state.span_first)[0])
    assert span == pytest.approx(4 * _STRIDE * 0.01 + 4 * _STRIDE * 0.005)
    assert first == pytest.approx(_STRIDE * 0.01)  # anchored at sample 1
    assert float(np.asarray(m.stats_state.samples)[0]) == 8


@pytest.mark.slow
def test_stats_resolution_elastic_restore_restarts_window(tmp_path, capsys):
    """Review regression: the gathered format restores elastically across
    resolutions (state leaves interpolate) — stale-shaped stats sums can't,
    so the averaging window restarts at zero instead of handing the stats
    chunk a shape mismatch."""
    small = _armed()
    small.update_n(4)
    path = str(tmp_path / "small.h5")
    cp.write_snapshot(small, path)
    big = Navier2D(33, 32, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    big.set_velocity(0.1, 1.0, 1.0)
    big.set_temperature(0.1, 1.0, 1.0)
    big.write_intervall = 1e9
    big.set_stats(StatsConfig(stride=_STRIDE))
    big.update_n(4)  # non-zero sums that must reset
    cp.read_snapshot(big, path)
    assert float(np.asarray(big.stats_state.samples)[0]) == 0.0
    assert "restart from zero" in capsys.readouterr().out
    big.update_n(4)  # the stats chunk still runs on the restored state
    assert float(np.asarray(big.stats_state.samples)[0]) == 4 // _STRIDE


# -- typed events replacing the legacy flow's silent prints -------------------


def test_legacy_stats_mismatch_is_typed_journal_event(tmp_path, capsys):
    """``Statistics.update`` rejecting a time-regressed sample journals a
    typed ``stats_mismatch`` + bumps the telemetry counter (the reference
    print is kept), so a run can't silently stop averaging."""
    model = _build()
    model.update_n(2)
    stats = Statistics(model, 0.01, 1.0)
    stats.tot_time = 1e9  # a mismatched restart: navier time < stat time
    writer = JournalWriter(str(tmp_path / "journal.jsonl"))
    model.journal_writer = writer
    before = tm.counter("stats_mismatch_total").value
    try:
        stats.update(model)
    finally:
        model.journal_writer = None
        writer.close()
    assert stats.num_save == 0  # averages NOT updated
    assert tm.counter("stats_mismatch_total").value == before + 1
    events = read_journal(str(tmp_path / "journal.jsonl"))
    assert events[-1]["event"] == "stats_mismatch"
    assert events[-1]["stat_time"] == 1e9
    assert "time mismatch" in capsys.readouterr().out


def test_legacy_stats_write_failure_is_typed_journal_event(
    tmp_path, monkeypatch, capsys
):
    """The IO callback's swallowed ``unable to write statistics`` print
    becomes a typed ``stats_write_failed`` + counter; the run survives
    (reference never-fatal semantics)."""
    from rustpde_mpi_tpu.utils import navier_io

    monkeypatch.chdir(tmp_path)
    model = _build()
    model.update_n(2)
    stats = Statistics(model, 0.01, 0.01)  # update+write at every boundary
    model.statistics = stats
    monkeypatch.setattr(
        Statistics, "write", lambda self, path: (_ for _ in ()).throw(
            OSError("disk full")
        )
    )
    writer = JournalWriter(str(tmp_path / "journal.jsonl"))
    model.journal_writer = writer
    before = tm.counter("stats_write_failed_total").value
    try:
        navier_io.callback(model, suppress_io=True)
    finally:
        model.journal_writer = None
        model.statistics = None
        writer.close()
    assert tm.counter("stats_write_failed_total").value == before + 1
    events = read_journal(str(tmp_path / "journal.jsonl"))
    row = next(e for e in events if e["event"] == "stats_write_failed")
    assert "disk full" in row["error"]
    assert "unable to write statistics" in capsys.readouterr().out


# -- runner health streaming --------------------------------------------------


def test_runner_streams_health_gauges_and_threshold_events(tmp_path):
    """A stats-armed runner resolves the lag=1 health future each chunk
    boundary (the save-intervall cadence — the same boundaries checkpoints
    ride): the summary carries the HEALTH_NAMES readout, the stats_*
    gauges are live, and absurdly low thresholds make the typed
    ``resolution_warning`` / ``budget_drift`` events fire exactly once per
    excursion (crossing latch)."""
    model = _build()
    model.set_stats(
        StatsConfig(stride=_STRIDE, tail_warn=1e-12, budget_warn=1e-12)
    )
    runner = ResilientRunner(
        model,
        max_time=0.16,
        save_intervall=0.04,
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
        max_chunk_steps=4,
    )
    summary = runner.run()
    st = summary["stats"]
    assert set(st) == set(HEALTH_NAMES)
    assert st["samples"] == 16 // _STRIDE
    assert np.isfinite(st["nu_residual"]) and np.isfinite(st["ke_residual"])
    snap = tm.REGISTRY.snapshot()
    assert "stats_samples" in snap and "stats_budget_residual" in snap
    events = read_journal(str(tmp_path / "run" / "journal.jsonl"))
    names = [e["event"] for e in events]
    assert names.count("resolution_warning") == 1  # latched, not per-boundary
    assert names.count("budget_drift") == 1
    warn = next(e for e in events if e["event"] == "resolution_warning")
    assert warn["field"] in ("temp", "ux", "uy") and warn["axis"] in ("x", "y")
    drift = next(e for e in events if e["event"] == "budget_drift")
    assert drift["threshold"] == 1e-12 and drift["samples"] >= 2


# -- serve: per-request stats summaries ---------------------------------------


def test_serve_done_records_carry_stats_summary(tmp_path):
    """``ServeConfig.stats`` arms the engine on every DNS campaign
    ensemble; each done record then carries the member's health vector at
    completion (captured before any lane is released or refilled)."""
    from rustpde_mpi_tpu.config import ServeConfig
    from rustpde_mpi_tpu.serve import SimServer

    srv = SimServer(
        ServeConfig(
            run_dir=str(tmp_path / "serve"),
            slots=2,
            chunk_steps=4,
            checkpoint_every_s=None,
            http_port=None,
            stats=StatsConfig(stride=_STRIDE),
        )
    )
    req = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1, bc="rbc")
    ids = [srv.submit(dict(req, seed=s)).id for s in range(3)]
    summary = srv.serve()
    assert summary["completed"] == 3 and summary["failed"] == 0
    for rid in ids:
        st = srv.result(rid)["stats"]
        assert set(st) == set(HEALTH_NAMES)
        assert st["samples"] >= 1
        assert np.isfinite(st["nu_plate_avg"]) and np.isfinite(st["nu_residual"])


# -- exports + plot reader ----------------------------------------------------


@pytest.mark.slow
def test_export_layouts_and_plot_reader(tmp_path):
    """``export_stats`` writes the legacy root layout for a single model
    and ``member{i}/`` groups for an ensemble; plot/plot_statistics.py
    renders legacy files, engine ensemble exports (``--member``) and the
    engine's ``--profiles`` extras."""
    single = _armed()
    single.update_n(4)
    solo_h5 = str(tmp_path / "solo.h5")
    export_stats(single, solo_h5)
    with h5py.File(solo_h5, "r") as f:
        assert "temp/v" in f and "nusselt/v" in f  # legacy reference layout
        assert "profiles/t_mean" in f and "spectra/x" in f
        assert int(f.attrs["stride"]) == _STRIDE
    ens = NavierEnsemble(_armed(), [_build().state for _ in range(2)])
    ens.update_n(4)
    ens_h5 = str(tmp_path / "ens.h5")
    export_stats(ens, ens_h5)
    with h5py.File(ens_h5, "r") as f:
        assert int(np.asarray(f["members"])) == 2
        assert "member0/temp/v" in f and "member1/profiles/t_mean" in f
        # the RUNNING ensemble's clock, not the frozen template model's
        assert float(np.asarray(f["member0/tot_time"])) == pytest.approx(
            ens.time
        )
        assert float(np.asarray(f["member0/avg_time"])) == pytest.approx(
            2 * _STRIDE * 0.01  # span accumulated per sample at its own dt
        )
    out = str(tmp_path / "plot.png")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "plot", "plot_statistics.py"),
            "--file", ens_h5, "--member", "1", "--profiles", "--out", out,
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    for suffix in ("", "_nusselt", "_profiles"):
        assert os.path.exists(str(tmp_path / f"plot{suffix}.png")), suffix
    # layout selection (in-process: matplotlib stays lazy): legacy root,
    # member groups, out-of-range member as a clean typed exit
    sys.path.insert(0, os.path.join(_REPO, "plot"))
    try:
        from plot_statistics import stats_root
    finally:
        sys.path.pop(0)
    with h5py.File(solo_h5, "r") as f:
        assert stats_root(f, 0) is f
    with h5py.File(ens_h5, "r") as f:
        assert stats_root(f, 1).name == "/member1"
        with pytest.raises(SystemExit, match="out of range"):
            stats_root(f, 7)


def test_export_requires_armed_engine():
    with pytest.raises(RuntimeError, match="armed stats engine"):
        export_stats(_build(), "/tmp/never_written.h5")


# -- API pin ------------------------------------------------------------------


def test_stats_api_exports():
    """The physics-observability surface is importable from the package
    root + the models package (API pin, mirrors the workloads pin)."""
    import rustpde_mpi_tpu as rp
    from rustpde_mpi_tpu import models as mdl

    for name in ("StatsEngine", "StatsState", "export_stats"):
        assert hasattr(rp, name), name
    for name in ("HEALTH_NAMES", "StatsEngine", "StatsState", "export_stats"):
        assert hasattr(mdl, name), name
    assert "nu_residual" in HEALTH_NAMES and "samples" in HEALTH_NAMES
    from rustpde_mpi_tpu import config as cfg

    knobs = set(cfg.env_knobs())
    assert {
        "RUSTPDE_STATS",
        "RUSTPDE_STATS_STRIDE",
        "RUSTPDE_STATS_TAIL_WARN",
        "RUSTPDE_STATS_BUDGET_WARN",
    } <= knobs
