"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the test strategy from SURVEY.md S4: kernel/MMS tests run on CPU in
f64; sharded paths are validated on a virtual multi-device CPU mesh and
compared bit-for-bit against the unsharded results.

Two-tier suite (VERDICT r3 #8): heavyweight end-to-end tests (multiprocess
spawns, example smoke runs, long convergence loops) are marked ``slow`` and
skipped by default so the default selection stays under ~8 min.  Run the
full suite with ``RUSTPDE_SLOW=1 python -m pytest tests/ -q`` (CI / driver)
or ``-m slow`` for only the slow tier.
"""

import os

import pytest

os.environ["JAX_PLATFORMS"] = "cpu"  # env presets the TPU platform; tests run on a virtual CPU mesh
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RUSTPDE_X64", "1")
# The container's sitecustomize registers the TPU plugin and forces
# jax_platforms="axon,cpu" programmatically (overriding the env var), so we
# must override it back after import.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache: repeated suite runs skip recompilation
from rustpde_mpi_tpu import config as _rp_config  # noqa: E402

_rp_config.enable_compilation_cache()

# Pre-kill stack dump: the tier-1 driver runs `timeout -k 10 870 pytest ...`,
# and a single silent in-test hang (PR 1's pencil-writer deadlock) turns the
# whole run into an unexplained rc=124.  Arm faulthandler to dump every
# thread's stack shortly BEFORE that kill fires so the log names the hang.
# RUSTPDE_TEST_TRACEBACK_S overrides the deadline; 0 disables.  The full
# tier (RUSTPDE_SLOW=1) legitimately runs past any tier-1 deadline, so the
# timer is only armed for the default selection unless explicitly requested.
import faulthandler  # noqa: E402

_DUMP_AFTER_S = float(
    os.environ.get("RUSTPDE_TEST_TRACEBACK_S")
    or ("0" if os.environ.get("RUSTPDE_SLOW") == "1" else "840")
)
if _DUMP_AFTER_S > 0:
    faulthandler.dump_traceback_later(_DUMP_AFTER_S, exit=False)


@pytest.fixture(scope="session")
def stepped_rbc17():
    """ONE stepped 17^2 model shared by the checkpoint/IO-layer tests
    across test_resilience / test_io_pipeline / test_serve: they only need
    *a* valid state to write/verify/restore, and every per-module build
    was ~1-2 s of duplicated tier-1 wall (plus duplicated trace time).
    The state is SCRATCH — tests may read snapshots into it or step it;
    nothing may assume a particular state on entry."""
    from model_builders import build_rbc17

    model = build_rbc17()
    model.update_n(4)
    return model


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight end-to-end test (skipped unless RUSTPDE_SLOW=1 or -m slow)"
    )


def pytest_sessionfinish(session, exitstatus):
    faulthandler.cancel_dump_traceback_later()


def pytest_collection_modifyitems(config, items):
    if os.environ.get("RUSTPDE_SLOW") == "1" or config.getoption("-m", default=""):
        return
    skip = pytest.mark.skip(reason="slow tier: set RUSTPDE_SLOW=1 (or -m slow) to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
