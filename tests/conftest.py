"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the test strategy from SURVEY.md S4: kernel/MMS tests run on CPU in
f64; sharded paths are validated on a virtual multi-device CPU mesh and
compared bit-for-bit against the unsharded results.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # env presets the TPU platform; tests run on a virtual CPU mesh
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RUSTPDE_X64", "1")

# The container's sitecustomize registers the TPU plugin and forces
# jax_platforms="axon,cpu" programmatically (overriding the env var), so we
# must override it back after import.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
