"""Space1/Field1 + Swift–Hohenberg tests.

Test model follows SURVEY.md S4: transform round-trips and derivative checks
for the 1-D spaces, linear-growth-rate validation of the SH IMEX scheme
against the exact modal update factor, and split-vs-complex equality of the
doubly-periodic space (the TPU representation checked against the CPU FFT
path on identical data)."""

import jax.numpy as jnp
import numpy as np
import pytest

from rustpde_mpi_tpu.bases import (
    BiPeriodicSpace2,
    Space1,
    cheb_dirichlet,
    chebyshev,
    fourier_r2c,
    fourier_r2c_split,
)
from rustpde_mpi_tpu.field import Field1
from rustpde_mpi_tpu.models.swift_hohenberg import (
    SwiftHohenberg1D,
    SwiftHohenberg2D,
)


# ---------------------------------------------------------------------------
# Space1 / Field1
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "base_fn", [chebyshev, cheb_dirichlet, fourier_r2c, fourier_r2c_split]
)
def test_space1_roundtrip(base_fn):
    n = 24
    space = Space1(base_fn(n))
    rng = np.random.default_rng(3)
    if space.base.kind.is_chebyshev and space.base.m < n:
        # composite base: start from spectral coefficients (not every physical
        # field satisfies the BCs)
        vhat = jnp.asarray(rng.standard_normal(space.base.m))
        v = space.backward(vhat)
        vhat2 = space.forward(v)
        np.testing.assert_allclose(np.asarray(vhat2), np.asarray(vhat), atol=1e-10)
    else:
        v = jnp.asarray(rng.standard_normal(n))
        v2 = space.backward(space.forward(v))
        np.testing.assert_allclose(np.asarray(v2), np.asarray(v), atol=1e-10)


def test_space1_gradient_fourier():
    n = 32
    space = Space1(fourier_r2c(n))
    x = space.base.points
    v = jnp.asarray(np.sin(3 * x))
    dv = space.backward_ortho(space.gradient(space.forward(v), 1))
    np.testing.assert_allclose(np.asarray(dv), 3 * np.cos(3 * x), atol=1e-10)
    # with a length scale: d/dx sin(3 x/L) = (3/L) cos(3 x/L)
    dv_s = space.backward_ortho(space.gradient(space.forward(v), 1, scale=[2.0]))
    np.testing.assert_allclose(np.asarray(dv_s), 1.5 * np.cos(3 * x), atol=1e-10)


def test_space1_gradient_chebyshev():
    n = 24
    space = Space1(chebyshev(n))
    x = space.base.points
    v = jnp.asarray(x**3)
    dv = space.backward_ortho(space.gradient(space.forward(v), 1))
    np.testing.assert_allclose(np.asarray(dv), 3 * x**2, atol=1e-8)


def test_space1_split_matches_complex():
    n = 20
    rng = np.random.default_rng(7)
    v = rng.standard_normal(n)
    sc = Space1(fourier_r2c(n), method="fft")
    ss = Space1(fourier_r2c_split(n))
    c = np.asarray(sc.forward(jnp.asarray(v)))
    s = np.asarray(ss.forward(jnp.asarray(v)))
    m = n // 2 + 1
    np.testing.assert_allclose(s[:m], c.real, atol=1e-12)
    np.testing.assert_allclose(s[m:], c.imag, atol=1e-12)
    # gradient equivalence through the physical representation
    g_c = np.asarray(sc.backward_ortho(sc.gradient(sc.forward(jnp.asarray(v)), 2)))
    g_s = np.asarray(ss.backward_ortho(ss.gradient(ss.forward(jnp.asarray(v)), 2)))
    np.testing.assert_allclose(g_s, g_c, atol=1e-10)


def test_field1_api():
    space = Space1(fourier_r2c(16))
    f = Field1(space)
    f.v = np.cos(space.base.points)
    f.scale([2.0])
    assert f.x[0][-1] > 6.0  # stretched
    np.testing.assert_allclose(float(f.average()), 0.0, atol=1e-12)


# ---------------------------------------------------------------------------
# BiPeriodicSpace2: split matmul path vs complex FFT path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(16, 16), (12, 17), (15, 14)])
def test_biperiodic_roundtrip(shape):
    nx, ny = shape
    rng = np.random.default_rng(5)
    v = rng.standard_normal((nx, ny))
    for method in ("fft", "matmul"):
        space = BiPeriodicSpace2(nx, ny, method=method)
        v2 = np.asarray(space.backward(space.forward(jnp.asarray(v))))
        np.testing.assert_allclose(v2, v, atol=1e-10, err_msg=method)


def test_biperiodic_split_matches_complex_fft():
    nx, ny = 16, 18
    rng = np.random.default_rng(11)
    v = jnp.asarray(rng.standard_normal((nx, ny)))
    s_fft = np.asarray(BiPeriodicSpace2(nx, ny, method="fft").forward(v))
    s_mm = np.asarray(BiPeriodicSpace2(nx, ny, method="matmul").forward(v))
    np.testing.assert_allclose(s_mm, s_fft, atol=1e-12)
    # against direct numpy reference
    c = np.fft.fft(np.fft.rfft(np.asarray(v), axis=1) / ny, axis=0) / nx
    np.testing.assert_allclose(s_fft[0], c.real, atol=1e-12)
    np.testing.assert_allclose(s_fft[1], c.imag, atol=1e-12)


def test_biperiodic_gradient():
    nx, ny = 24, 24
    space = BiPeriodicSpace2(nx, ny)
    x, y = space.coords()
    v = jnp.asarray(np.sin(2 * x)[:, None] * np.cos(3 * y)[None, :])
    # d2/dx2: -4 * v
    lap = space.backward(space.gradient(space.forward(v), (2, 0)))
    np.testing.assert_allclose(np.asarray(lap), -4 * np.asarray(v), atol=1e-9)
    # mixed: d/dx d/dy
    g = space.backward(space.gradient(space.forward(v), (1, 1)))
    expect = 2 * np.cos(2 * x)[:, None] * (-3 * np.sin(3 * y)[None, :])
    np.testing.assert_allclose(np.asarray(g), expect, atol=1e-9)


def test_biperiodic_hermitian_projection():
    nx, ny = 12, 12
    space = BiPeriodicSpace2(nx, ny)
    rng = np.random.default_rng(2)
    v = jnp.asarray(rng.standard_normal((nx, ny)))
    s = space.forward(v)
    # coefficients of a real field are already Hermitian -> projection is id
    s2 = space.enforce_hermitian_x(s)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s), atol=1e-12)
    # a perturbed column is symmetrized: c(-k,0) == conj(c(k,0))
    bad = s.at[0, 3, 0].add(0.5)
    fixed = np.asarray(space.enforce_hermitian_x(bad))
    np.testing.assert_allclose(fixed[0, 3, 0], fixed[0, nx - 3, 0], atol=1e-12)
    np.testing.assert_allclose(fixed[1, 3, 0], -fixed[1, nx - 3, 0], atol=1e-12)
    # the ky-Nyquist column (even ny) is self-conjugate too — anti-Hermitian
    # drift there must also be projected out
    nyq = space.my - 1
    bad2 = s.at[1, 2, nyq].add(0.5)
    fixed2 = np.asarray(space.enforce_hermitian_x(bad2))
    np.testing.assert_allclose(fixed2[1, 2, nyq], -fixed2[1, nx - 2, nyq], atol=1e-12)


def test_sh2d_nyquist_unstable_mode_stays_bounded():
    """ny/2 / length near k=1 makes the ky-Nyquist modes linearly unstable
    (matl < 1): without the Nyquist Hermitian projection, anti-Hermitian
    roundoff there grows ~(1/matl)^n and the run eventually NaNs."""
    model = SwiftHohenberg2D(16, 16, r=0.35, dt=0.05, length=8.0)
    k_nyq = (model.ny // 2) / model.scale[1]
    matl_nyq = 1.0 + model.dt * ((1.0 - k_nyq**2) ** 2 - model.r)
    assert matl_nyq < 1.0  # config genuinely exercises the unstable column
    model.update_n(4000)
    assert not model.exit()
    assert np.max(np.abs(model.theta_physical())) < 2.0


# ---------------------------------------------------------------------------
# Swift–Hohenberg physics
# ---------------------------------------------------------------------------


def test_sh1d_linear_growth_rate():
    """Tiny-amplitude single mode evolves by the exact IMEX modal factor
    1/(1 + dt*((1-k^2)^2 - r)) per step (cubic negligible at 1e-8)."""
    nx, length, r, dt = 64, 2.0, 0.3, 0.05
    model = SwiftHohenberg1D(nx, r, dt, length)
    x = model.x[0]
    mode = 2  # k = mode / length
    eps = 1e-8
    model.set_theta(eps * np.cos(mode * x / length))
    a0 = np.max(np.abs(model.theta_physical()))
    nsteps = 20
    model.update_n(nsteps)
    a1 = np.max(np.abs(model.theta_physical()))
    k = mode / length
    factor = (1.0 / (1.0 + dt * ((1.0 - k**2) ** 2 - r))) ** nsteps
    np.testing.assert_allclose(a1 / a0, factor, rtol=1e-6)


def test_sh1d_supercritical_saturates():
    """r > 0: the near-critical mode grows, then the cubic saturates it near
    amplitude ~ 2*sqrt(r/3) (the classic SH roll amplitude)."""
    nx, length, r, dt = 128, 10.0, 0.2, 0.05
    model = SwiftHohenberg1D(nx, r, dt, length)
    model.update_n(4000)
    amp = np.max(np.abs(model.theta_physical()))
    assert not model.exit()
    assert 0.1 < amp < 1.0  # grown from 1e-5, bounded by the cubic


def test_sh2d_linear_growth_rate():
    nx = ny = 32
    length, r, dt = 2.0, 0.25, 0.02
    model = SwiftHohenberg2D(nx, ny, r, dt, length)
    x, y = model.x
    eps = 1e-8
    mx, my_ = 2, 1
    v = eps * np.cos(mx * x[:, None] / length) * np.cos(my_ * y[None, :] / length)
    model.set_theta(v)
    a0 = np.max(np.abs(model.theta_physical()))
    nsteps = 10
    model.update_n(nsteps)
    a1 = np.max(np.abs(model.theta_physical()))
    k2 = (mx / length) ** 2 + (my_ / length) ** 2
    factor = (1.0 / (1.0 + dt * ((1.0 - k2) ** 2 - r))) ** nsteps
    np.testing.assert_allclose(a1 / a0, factor, rtol=1e-6)


def test_sh2d_pattern_forms_and_is_bounded():
    nx = ny = 48
    model = SwiftHohenberg2D(nx, ny, r=0.35, dt=0.02, length=8.0)
    e0 = model.pattern_energy()
    model.update_n(2500)
    e1 = model.pattern_energy()
    assert not model.exit()
    assert e1 > 50 * e0  # pattern grew out of the random IC
    assert np.max(np.abs(model.theta_physical())) < 2.0  # cubic bounded


def test_sh2d_write_read_roundtrip(tmp_path):
    model = SwiftHohenberg2D(16, 16, r=0.3, dt=0.02, length=5.0)
    model.update_n(5)
    fname = str(tmp_path / "sh.h5")
    model._write(fname)
    model2 = SwiftHohenberg2D(16, 16, r=0.3, dt=0.02, length=5.0)
    model2.read(fname)
    assert model2.time == pytest.approx(model.time)
    np.testing.assert_allclose(
        model2.theta_physical(), model.theta_physical(), atol=1e-12
    )


def test_sh1d_write_read_roundtrip(tmp_path):
    model = SwiftHohenberg1D(32, r=0.2, dt=0.01, length=10.0)
    model.update_n(3)
    fname = str(tmp_path / "sh1.h5")
    model._write(fname)
    model2 = SwiftHohenberg1D(32, r=0.2, dt=0.01, length=10.0)
    model2.read(fname)
    np.testing.assert_allclose(
        model2.theta_physical(), model.theta_physical(), atol=1e-12
    )
