"""Static-analysis layer (tools/lint) + the env-knob registry contract.

The fixture snippets reproduce the repo's own FIXED bugs — the PR-10
drain-check-outside-the-root-plan desync and the PR-5
np.asarray-on-a-sharded-array fetch — and assert each rule flags the buggy
shape while the shipped fix passes clean.  A repo-wide test keeps HEAD
lint-clean (zero unsuppressed findings, zero stale baseline entries), and
the knob test diffs ``config.env_knobs()`` against a grep of the source
tree AND the README knob table, so a new ``RUSTPDE_*`` knob cannot ship
unregistered or undocumented.
"""

import os
import re

from tools.lint import core, lint_source, run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return sorted(f.rule for f in findings)


# -- RPD001: collective under a host-local condition (the PR-10 bug) ----------

PR10_DRAIN_BUG = '''
def _fill_slots(self, slots, key):
    if self._drain:
        return
    plan = broadcast_obj(self._plan())
    self._apply(plan)
'''

PR10_DRAIN_FIXED = '''
def _fill_slots(self, slots, key):
    drain = root_decides(self._drain)
    if drain:
        return
    plan = broadcast_obj(self._plan())
    self._apply(plan)
'''


def test_rpd001_flags_drain_check_outside_root_plan():
    found = lint_source(PR10_DRAIN_BUG, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD001" in rules_of(found)
    (f,) = [f for f in found if f.rule == "RPD001"]
    assert "early-exit" in f.message


def test_rpd001_fixed_form_passes():
    found = lint_source(PR10_DRAIN_FIXED, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD001" not in rules_of(found)


def test_rpd001_collective_inside_host_local_branch():
    src = '''
def go(self):
    if is_root():
        sync_hosts("inside")
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD001" in rules_of(found)


def test_rpd001_out_of_scope_module_not_flagged():
    found = lint_source(PR10_DRAIN_BUG, "rustpde_mpi_tpu/models/navier.py")
    assert "RPD001" not in rules_of(found)


# -- RPD002: collective on an exception path ----------------------------------


def test_rpd002_sync_in_except_and_finally():
    src = '''
def teardown(self):
    try:
        self.close()
    except Exception:
        sync_hosts("bye")
    finally:
        broadcast(1)
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert rules_of([f for f in found if f.rule == "RPD002"]) == ["RPD002", "RPD002"]


# -- RPD003: use after donate -------------------------------------------------

DONATE_BUG = '''
import jax

step = jax.jit(_step, donate_argnums=(0,))

def advance(state):
    new = step(state)
    return state
'''

DONATE_FIXED = '''
import jax

step = jax.jit(_step, donate_argnums=(0,))

def advance(state):
    state = step(state)
    return state
'''


def test_rpd003_use_after_donate():
    found = lint_source(DONATE_BUG, "rustpde_mpi_tpu/models/fixture.py")
    assert "RPD003" in rules_of(found)
    assert "RPD003" not in rules_of(
        lint_source(DONATE_FIXED, "rustpde_mpi_tpu/models/fixture.py")
    )


# -- RPD004: os.replace without a parent-dir fsync ----------------------------


def test_rpd004_replace_without_dirsync():
    bug = '''
import os

def commit(tmp, dst):
    os.replace(tmp, dst)
'''
    fixed = '''
import os

def commit(tmp, dst):
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(dst))
'''
    assert "RPD004" in rules_of(lint_source(bug, "rustpde_mpi_tpu/serve/queue.py"))
    assert "RPD004" not in rules_of(lint_source(fixed, "rustpde_mpi_tpu/serve/queue.py"))
    # non-durability modules are out of scope (best-effort caches etc.)
    assert "RPD004" not in rules_of(lint_source(bug, "rustpde_mpi_tpu/tools/xdmf.py"))


# -- RPD005: asarray on a possibly-sharded array (the PR-5 bug) ---------------

PR5_ASARRAY_BUG = '''
import numpy as np

def poison_mask(model):
    leaf = model.state.temp
    return np.asarray(leaf)
'''

PR5_ASARRAY_FIXED = '''
import numpy as np

def poison_mask(model):
    leaf = model.state.temp
    return np.asarray(leaf.addressable_data(0))
'''


def test_rpd005_flags_asarray_on_sharded_leaf():
    found = lint_source(PR5_ASARRAY_BUG, "rustpde_mpi_tpu/utils/checkpoint.py")
    assert "RPD005" in rules_of(found)


def test_rpd005_addressable_fetch_passes():
    found = lint_source(PR5_ASARRAY_FIXED, "rustpde_mpi_tpu/utils/checkpoint.py")
    assert "RPD005" not in rules_of(found)


def test_rpd005_host_scalars_pass():
    src = '''
import numpy as np

def pack(h5, t):
    a = np.asarray(float(t))
    b = np.asarray(h5["time"])
    return a, b
'''
    assert "RPD005" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/utils/checkpoint.py")
    )


# -- RPD006: raw RUSTPDE_* env reads ------------------------------------------


def test_rpd006_raw_env_read_flagged_outside_config():
    src = '''
import os

def fault():
    return os.environ.get("RUSTPDE_FAULT")
'''
    assert "RPD006" in rules_of(
        lint_source(src, "rustpde_mpi_tpu/utils/resilience.py")
    )
    # the two allowed modules stay raw by design
    assert "RPD006" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/utils/faults.py")
    )
    assert "RPD006" not in rules_of(lint_source(src, "rustpde_mpi_tpu/config.py"))


def test_rpd006_module_level_subscript_read_flagged():
    src = 'import os\n_FLAG = os.environ["RUSTPDE_FAULT"]\n'
    assert "RPD006" in rules_of(
        lint_source(src, "rustpde_mpi_tpu/utils/resilience.py")
    )


def test_rpd006_env_get_passes():
    src = '''
from ..config import env_get

def fault():
    return env_get("RUSTPDE_FAULT")
'''
    assert "RPD006" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/utils/resilience.py")
    )


# -- RPD007: cross-module private reach ---------------------------------------


def test_rpd007_private_reach_on_constructed_import():
    src = '''
from ..utils.resilience import ResilientRunner

def drive(model):
    runner = ResilientRunner(model)
    runner._drain_io()
'''
    assert "RPD007" in rules_of(
        lint_source(src, "rustpde_mpi_tpu/workloads/fixture.py")
    )
    fixed = src.replace("runner._drain_io()", "runner.drain_io()")
    assert "RPD007" not in rules_of(
        lint_source(fixed, "rustpde_mpi_tpu/workloads/fixture.py")
    )


def test_rpd007_stdlib_and_namedtuple_idioms_pass():
    src = '''
import sys
import os

def f(state):
    frame = sys._getframe(1)
    os._exit(9)
    return state._fields
'''
    assert "RPD007" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/utils/fixture.py")
    )


# -- RPD008: span tags around collective dispatches ---------------------------


def test_rpd008_host_local_span_kwarg_flagged():
    src = '''
import time

def loop(self, runner, n):
    with span("serve_chunk", t=time.monotonic()):
        runner.update_n(n)
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD008" in rules_of(found)
    (f,) = [f for f in found if f.rule == "RPD008"]
    assert "host-local" in f.message


def test_rpd008_computed_span_name_flagged():
    src = '''
import os

def loop(self, runner, n):
    with span(f"chunk_{os.getpid()}"):
        runner.update_n(n)
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD008" in rules_of(found)
    assert any("LITERAL name" in f.message for f in found)


def test_rpd008_shipped_shape_passes():
    # the repo's own shape: literal name, args from a root-broadcast plan
    src = '''
def loop(self, runner, running):
    n = broadcast_obj(self._plan())
    with span("serve_chunk", steps=n, slots=len(running)):
        runner.update_n(n)
'''
    assert "RPD008" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd008_span_without_collective_body_not_flagged():
    src = '''
import time

def log_it(self):
    with span("host_only", t=time.monotonic()):
        self.counter += 1
'''
    assert "RPD008" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd008_out_of_scope_module_not_flagged():
    src = '''
import time

def loop(self, runner, n):
    with span("chunk", t=time.monotonic()):
        runner.update_n(n)
'''
    assert "RPD008" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/models/navier.py")
    )


# -- RPD009: dispatch after lease renewal without a fence consult -------------


def test_rpd009_dispatch_after_renew_without_fence_flagged():
    # the PR-18 review shape: a renew can raise LeaseLost and leave the
    # replica fenced; the next barrier races the reclaimer
    src = '''
def boundary(self, runner, n):
    self._lease.renew()
    sync_hosts("chunk-boundary")
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD009" in rules_of(found)
    (f,) = [f for f in found if f.rule == "RPD009"]
    assert "fencing check" in f.message


def test_rpd009_fence_check_between_passes():
    src = '''
def boundary(self, runner, ens, slots, key, n):
    self._fleet_heartbeat()
    if self._fence_check(ens, slots, key):
        return
    runner.update_n(n)
'''
    assert "RPD009" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd009_fenced_flag_read_counts_as_consult():
    src = '''
def boundary(self, runner, n):
    self._lease.renew()
    fenced = broadcast_obj(self._fenced)
    if fenced:
        return
    runner.update_n(n)
'''
    assert "RPD009" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd009_guard_counts_as_consult():
    src = '''
def requeue(self, lease, queue, req):
    lease.renew()
    lease.guard()
    sync_hosts("requeue")
'''
    assert "RPD009" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/fleet/gang.py")
    )


def test_rpd009_dispatch_before_renew_not_flagged():
    # the renew ends the region; dispatches before it are not in it
    src = '''
def boundary(self, runner, n):
    sync_hosts("chunk-boundary")
    self._lease.renew()
'''
    assert "RPD009" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd009_out_of_scope_module_not_flagged():
    src = '''
def boundary(self, runner, n):
    self._lease.renew()
    sync_hosts("chunk-boundary")
'''
    assert "RPD009" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/tools/fixture.py")
    )


# -- RPD010: compile construction on the per-boundary hot path ----------------


def test_rpd010_jit_in_boundary_method_flagged():
    # the cold-start regression shape PR 19 exists to kill: a trace at a
    # chunk boundary stalls a LIVE campaign for seconds
    src = '''
def _settle_boundary(self, runner, ens, slots, key):
    step = jax.jit(ens.step_fn, donate_argnums=(0,))
    runner.dispatch(step)
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD010" in rules_of(found)
    (f,) = [f for f in found if f.rule == "RPD010"]
    assert "_build_runner" in f.message


def test_rpd010_model_build_in_fill_slots_flagged():
    src = '''
def _fill_slots(self, runner, ens, slots, key):
    model = build_model_for_key(key, mesh=None)
    ens.set_member(0, model.state)
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD010" in rules_of(found)


def test_rpd010_aot_lower_in_campaign_loop_flagged():
    src = '''
def _campaign_loop(self, runner, ens, slots, key):
    exe = self._step_n_jit.lower(consts, state, n=8).compile()
    exe(consts, state)
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    assert "RPD010" in rules_of(found)


def test_rpd010_str_lower_passes_clean():
    # argument-less .lower() is str.lower, not an AOT lowering
    src = '''
def _flush_results(self, force=False):
    tag = self._state.name.lower()
    self._emit(tag)
'''
    assert "RPD010" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd010_build_runner_is_out_of_region():
    # campaign OPEN is where builds belong — the rule only polices the
    # per-boundary methods
    src = '''
def _build_runner(self, key, k=None):
    model = build_model_for_key(key, mesh=self._campaign_mesh(key))
    step = jax.jit(model.step, static_argnames=("n",))
    return model, step
'''
    assert "RPD010" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/scheduler.py")
    )


def test_rpd010_out_of_scope_module_not_flagged():
    src = '''
def _campaign_loop(self):
    fn = jax.jit(self.step)
    return fn
'''
    assert "RPD010" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/models/campaign.py")
    )


# -- generic layer ------------------------------------------------------------


def test_gen_unused_import_and_noqa():
    src = "import json\nimport os  # noqa: F401\nprint(1)\n"
    found = lint_source(src, "rustpde_mpi_tpu/serve/fixture.py")
    assert [f.rule for f in found] == ["GEN-F401"]
    assert "json" in found[0].message


def test_gen_unused_local():
    src = '''
def f():
    x = compute()
    _scratch = compute()
    return 1
'''
    found = [f for f in lint_source(src, "rustpde_mpi_tpu/serve/fixture.py")
             if f.rule == "GEN-F841"]
    assert len(found) == 1 and "'x'" in found[0].message


def test_gen_class_attribute_is_not_a_local():
    src = '''
def make():
    class Handler:
        timeout = 30.0
    return Handler
'''
    assert "GEN-F841" not in rules_of(
        lint_source(src, "rustpde_mpi_tpu/serve/fixture.py")
    )


def test_gen_mutable_default():
    src = "def f(a, b=[]):\n    return a\n"
    assert "GEN-B006" in rules_of(lint_source(src, "rustpde_mpi_tpu/fixture.py"))


def test_gen_fstring_without_placeholder_and_format_spec_regression():
    src = 'x = f"plain"\ny = f"{x:.3e} ok"\n'
    found = [f for f in lint_source(src, "rustpde_mpi_tpu/fixture.py")
             if f.rule == "GEN-F541"]
    # exactly ONE: the format-spec of y parses as a nested placeholder-less
    # JoinedStr and must NOT be flagged (the fixer once stripped real
    # f-strings because of this)
    assert len(found) == 1 and found[0].line == 1


# -- suppression + baseline mechanics -----------------------------------------


# the marker is assembled at runtime so the repo-wide lint pass does not
# read these fixture lines as suppressions of THIS file
_MARK = "lint-" + "ok"


def test_suppression_requires_reason():
    src = f'''
import os

def fault():
    return os.environ.get("RUSTPDE_FAULT")  # {_MARK}: RPD006
'''
    found = lint_source(src, "rustpde_mpi_tpu/utils/resilience.py")
    assert "RPD000" in rules_of(found)  # bare suppression is itself flagged
    assert "RPD006" in rules_of(found)  # and does not suppress


def test_suppression_with_reason_suppresses():
    src = f'''
import os

def fault():
    return os.environ.get("RUSTPDE_FAULT")  # {_MARK}: RPD006 fixture exercises the raw read
'''
    found = lint_source(src, "rustpde_mpi_tpu/utils/resilience.py")
    assert "RPD006" not in rules_of(found) and "RPD000" not in rules_of(found)


def test_suppression_multi_rule_lists():
    # space- AND comma-separated rule lists both suppress every listed rule
    src = f'''
import os

def probe():
    if is_root():
        sync_hosts(os.environ.get("RUSTPDE_FAULT"))  # {_MARK}: RPD001 RPD006 fixture covers both
'''
    found = lint_source(src, "rustpde_mpi_tpu/serve/fixture.py")
    assert "RPD001" not in rules_of(found) and "RPD006" not in rules_of(found)
    # a bare multi-rule marker (no reason after the rule tokens) is RPD000
    bare = src.replace("RPD001 RPD006 fixture covers both", "RPD001, RPD006")
    found = lint_source(bare, "rustpde_mpi_tpu/serve/fixture.py")
    assert "RPD000" in rules_of(found)
    assert "RPD001" in rules_of(found)  # and nothing was suppressed


# -- repo-wide contract -------------------------------------------------------


def test_repo_is_lint_clean():
    """HEAD carries zero unsuppressed findings and zero stale baseline
    entries — the acceptance contract of scripts/lint.py (exit 0)."""
    result = run_lint(root=REPO)
    msgs = "\n".join(str(f) for f in result.new[:20])
    assert not result.new, f"new lint findings:\n{msgs}"
    stale = "\n".join(str(e) for e in result.stale_baseline[:10])
    assert not result.stale_baseline, f"stale baseline entries:\n{stale}"
    # every baseline entry carries a real written reason
    for entry in core.load_baseline():
        assert entry.get("reason") and "TODO" not in entry["reason"], entry


# -- env-knob registry contract -----------------------------------------------

_KNOB_RE = re.compile(r"RUSTPDE_[A-Z0-9_]+")


def _grep_knob_names():
    names = set()
    files = core.collect_files(REPO) + ["__graft_entry__.py"]
    for rel in files:
        try:
            with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
                names.update(_KNOB_RE.findall(fh.read()))
        except OSError:
            pass
    return names


def _readme_knob_names():
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        text = fh.read()
    start = text.index("## Environment knobs")
    end = text.find("\n## ", start + 1)
    section = text[start : end if end != -1 else len(text)]
    return set(_KNOB_RE.findall(section))


def test_every_knob_in_source_is_registered():
    from rustpde_mpi_tpu import config

    registered = set(config.env_knobs())
    used = _grep_knob_names()
    missing = used - registered
    assert not missing, (
        f"RUSTPDE_* knobs read in source but not registered in "
        f"config.env_knobs(): {sorted(missing)}"
    )


def test_every_registered_knob_is_used_somewhere():
    from rustpde_mpi_tpu import config

    stale = set(config.env_knobs()) - _grep_knob_names()
    assert not stale, f"registered knobs no longer read anywhere: {sorted(stale)}"


def test_readme_knob_table_matches_registry():
    from rustpde_mpi_tpu import config

    registered = set(config.env_knobs())
    documented = _readme_knob_names()
    undocumented = registered - documented
    assert not undocumented, (
        f"knobs registered but missing from the README 'Environment knobs' "
        f"table: {sorted(undocumented)}"
    )
    phantom = documented - registered
    assert not phantom, (
        f"README knob table rows without a registry entry: {sorted(phantom)}"
    )


def test_env_get_refuses_unregistered_names():
    import pytest

    from rustpde_mpi_tpu import config

    # name built by concatenation so the registry-completeness grep above
    # does not pick this negative fixture up as a "used" knob
    with pytest.raises(config.UnregisteredKnobError):
        config.env_get("RUSTPDE_" + "NOT_A_KNOB")
    # non-RUSTPDE names pass through untouched (JAX_*, TPU_* stay raw)
    assert config.env_get("JAX_NOT_A_KNOB", "x") == "x"
