"""Parity-folded matrix application (ops/folded.py).

The folded path must be numerically interchangeable with the plain GEMM on
every matrix family the framework builds, on even and odd sizes, along both
axes — and the fold must actually engage (flops_factor 0.5) wherever the
parity structure exists."""

import numpy as np
import pytest

import jax.numpy as jnp

from rustpde_mpi_tpu.bases import (
    cheb_dirichlet,
    cheb_dirichlet_neumann,
    cheb_neumann,
    chebyshev,
)
from rustpde_mpi_tpu.ops import chebyshev as chb
from rustpde_mpi_tpu.ops.folded import FoldedMatrix


def _dev(m):
    return jnp.asarray(m)


def _check(mat, expect_kind=None, batch=5, atol=1e-12):
    fm = FoldedMatrix(mat, _dev)
    if expect_kind is not None:
        assert fm.kind == expect_kind, (fm.kind, expect_kind)
    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.standard_normal((mat.shape[1], batch)))
    ref0 = np.asarray(mat) @ np.asarray(x0)
    np.testing.assert_allclose(np.asarray(fm.apply(x0, 0)), ref0, atol=atol)
    x1 = jnp.asarray(rng.standard_normal((batch, mat.shape[1])))
    ref1 = np.asarray(x1) @ np.asarray(mat).T
    np.testing.assert_allclose(np.asarray(fm.apply(x1, 1)), ref1, atol=atol)
    return fm


@pytest.mark.parametrize("n", [16, 17])
@pytest.mark.parametrize("base_fn", [chebyshev, cheb_dirichlet, cheb_neumann])
def test_transform_matrices_fold(base_fn, n):
    """Both transform directions fold (for even n both reflection symmetries
    hold simultaneously and either fold type is valid)."""
    base = base_fn(n)
    fwd = base.projection @ chb.analysis_matrix(n)
    bwd = chb.synthesis_matrix(n) @ base.stencil
    for mat in (fwd, bwd, chb.synthesis_matrix(n)):
        fm = _check(mat)
        assert fm.kind in ("analysis", "synthesis"), fm.kind
        assert fm.flops_factor == 0.5


@pytest.mark.parametrize("n", [16, 17])
def test_spectral_operators_fold_checkerboard(n):
    base = cheb_dirichlet(n)
    # the stencil's two diagonals run as shifted adds; the dense projection
    # and gradient matrices fold checkerboard
    s = _check(base.stencil, "banded")
    assert s.flops_factor < 0.5
    _check(base.projection, "checker")
    _check(base.gradient_matrix(1), "checker")
    _check(base.gradient_matrix(2), "checker")
    # a parity-preserving implicit-solve inverse
    peye = base.laplace_inv_eye()
    pinv = peye @ base.laplace_inv()
    op = pinv @ base.stencil - 0.1 * (peye @ base.stencil)
    _check(np.linalg.inv(op), "checker", atol=1e-10)


def test_mixed_bc_base_falls_back_to_plain():
    base = cheb_dirichlet_neumann(17)
    fwd = base.projection @ chb.analysis_matrix(17)
    fm = _check(fwd, "plain")
    assert fm.flops_factor == 1.0


def test_unstructured_matrix_is_plain():
    rng = np.random.default_rng(1)
    _check(rng.standard_normal((12, 14)), "plain")


def test_folded_accepts_complex_input():
    fm = FoldedMatrix(chb.synthesis_matrix(16), _dev)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((16, 3)) + 1j * rng.standard_normal((16, 3)))
    ref = chb.synthesis_matrix(16) @ np.asarray(x)
    np.testing.assert_allclose(np.asarray(fm.apply(x, 0)), ref, atol=1e-12)


def test_disable_env(monkeypatch):
    monkeypatch.setenv("RUSTPDE_FOLDED", "0")
    fm = FoldedMatrix(chb.synthesis_matrix(16), _dev)
    assert fm.kind == "plain"


@pytest.mark.slow
def test_space_transform_equivalence_folded_vs_plain(monkeypatch):
    """End-to-end: Space2 matmul transforms with folding on vs off."""
    import subprocess
    import sys
    import os

    code = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
from rustpde_mpi_tpu import Space2, cheb_dirichlet, cheb_neumann
space = Space2(cheb_dirichlet(17), cheb_neumann(16), method="matmul")
rng = np.random.default_rng(5)
vhat = jnp.asarray(rng.standard_normal(space.shape_spectral))
v = space.backward(vhat)
out = {
    "v": np.asarray(v).tolist(),
    "rt": np.asarray(space.forward(v)).tolist(),
    "grad": np.asarray(space.gradient(vhat, (1, 1))).tolist(),
}
print("OUT:" + json.dumps(out))
"""
    import json

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = {}
    for flag in ("1", "0"):
        env = dict(os.environ, RUSTPDE_FOLDED=flag, RUSTPDE_X64="1")
        res = subprocess.run(
            [sys.executable, "-c", code % repo],
            capture_output=True, text=True, env=env, timeout=300,
        )
        line = [l for l in res.stdout.splitlines() if l.startswith("OUT:")]
        assert line, res.stderr[-500:]
        results[flag] = json.loads(line[0][4:])
    for key in ("v", "rt", "grad"):
        np.testing.assert_allclose(
            np.asarray(results["1"][key]), np.asarray(results["0"][key]),
            atol=1e-12, err_msg=key,
        )


def test_modal_maps_fold_with_parity_interleaved_eig():
    """The parity-interleaved eigen ordering makes the fast-diag modal maps
    checkerboard, so they fold; the singular mode still sits at index 0."""
    from rustpde_mpi_tpu import Space2, cheb_neumann
    from rustpde_mpi_tpu.solver import FastDiag, Poisson, _axis_modal_data

    space = Space2(cheb_neumann(16), cheb_neumann(17))
    lam, fwd, bwd = _axis_modal_data(space, 0, 1.0, 1.0)
    assert FoldedMatrix(fwd, _dev).kind == "checker"
    assert FoldedMatrix(bwd, _dev).kind == "checker"
    assert abs(lam[0]) < 1e-9  # pure-Neumann singular mode at index 0
    solver = Poisson(space, (1.0, 1.0))
    impl = solver._solver
    if isinstance(impl, FastDiag):
        assert impl.fwd[0].flops_factor == 0.5


def test_circular_folds_on_fourier_matrices(monkeypatch):
    """Split-Fourier and DFT cos/sin matrices fold under the circular
    reflection j -> (n-j) mod n, for even and odd n (gate lowered so the
    small unit sizes exercise the fold math)."""
    from rustpde_mpi_tpu.ops import folded, fourier as fou

    monkeypatch.setattr(folded, "_CIRC_MIN_DIM", 4)
    for n in (16, 17):
        fwd = _check(fou.split_forward_matrix(n), "circ_analysis")
        assert fwd.flops_factor == 0.5
        bwd = _check(fou.split_backward_matrix(n), "circ_synthesis")
        assert bwd.flops_factor == 0.5


def test_circ_both_quarter_fold_on_dft_matrices(monkeypatch):
    """DFT cos/sin matrices carry both circular symmetries with one output
    sign -> quarter-flops fold."""
    from rustpde_mpi_tpu.ops import folded

    from rustpde_mpi_tpu.ops import fourier as fou

    monkeypatch.setattr(folded, "_CIRC_MIN_DIM", 4)
    for n in (16, 17):
        cos = _check(fou.dft_cos_matrix(n), "circ_both")
        sin = _check(fou.dft_sin_matrix(n), "circ_both")
        assert cos.flops_factor == 0.25
        assert sin.flops_factor == 0.25


def test_circular_fold_size_gate():
    """Below the size gate the circular families stay plain (their gathers
    cost more than the saved flops on dispatch-bound small GEMMs); at
    transform scale they engage."""
    from rustpde_mpi_tpu.ops import folded, fourier as fou

    gate = folded._CIRC_MIN_DIM
    small = FoldedMatrix(fou.split_forward_matrix(gate // 2), _dev)
    assert small.kind == "plain"
    big = FoldedMatrix(fou.split_forward_matrix(2 * gate), _dev)
    assert big.kind == "circ_analysis"
    assert FoldedMatrix(fou.dft_cos_matrix(gate), _dev).kind == "circ_both"


def test_banded_apply_families():
    """Exactly-banded operators (stencils, B2 quasi-inverse, restricted eye)
    run as shifted adds, matching the dense product to machine epsilon."""
    for mat in (
        chb.stencil_dirichlet(33),
        chb.stencil_neumann(32),
        chb.stencil_dirichlet_neumann(33),
        chb.quasi_inverse_b2(32),
        chb.restricted_eye(33),
        chb.restricted_eye(32) @ chb.quasi_inverse_b2(32),
    ):
        fm = _check(mat, "banded", atol=1e-13)
        assert fm.flops_factor < 0.25


def test_hybrid_cast_rejects_complex_input():
    # the hybrid cast path (f64 state through f32 device transforms) is only
    # defined real->real: astype(float32) on a complex operand would silently
    # drop the imaginary part, so it must raise instead
    rng = np.random.default_rng(0)
    fm = FoldedMatrix(rng.standard_normal((8, 8)), _dev, cast=np.float32)
    ok = fm.apply(jnp.asarray(rng.standard_normal((8, 5))), 0)
    assert ok.dtype == jnp.float64  # output cast back to the input dtype
    bad = jnp.asarray(rng.standard_normal((8, 5)) + 1j)
    with pytest.raises(TypeError, match="imaginary"):
        fm.apply(bad, 0)
