"""Resilience-harness tests: atomic crash-safe checkpoints, corrupt-file
rejection, NaN-divergence rollback with dt backoff, SIGTERM
checkpoint-then-exit + resume, dispatch watchdogs, and ensemble member
respawn (utils/resilience.py + the durable layer in utils/checkpoint.py)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    DispatchHang,
    DivergenceError,
    Navier2D,
    NavierEnsemble,
    ResilientRunner,
    integrate,
)
from rustpde_mpi_tpu.utils import checkpoint as cp
from rustpde_mpi_tpu.utils.resilience import FaultPlan, call_with_watchdog

h5py = pytest.importorskip("h5py")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# shared tier-wide builder + session-scoped stepped model (conftest.py):
# test_io_pipeline/test_sharded_ckpt/test_serve reuse the same jit shapes
from model_builders import build_rbc17 as _build


def _events(run_dir):
    with open(os.path.join(run_dir, "journal.jsonl"), encoding="utf-8") as fh:
        return [json.loads(line) for line in fh]


# -- durable checkpoints ------------------------------------------------------


def test_atomic_write_crash_safety(tmp_path, stepped_rbc17):
    """Kill the writer mid-``write_snapshot``: the previous checkpoint must
    still read back digest-clean (atomicity), with at worst a ``.tmp``
    leftover that the checkpoint listing ignores."""
    path = str(tmp_path / "ckpt_0000000002.h5")
    child = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["RUSTPDE_X64"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D
from rustpde_mpi_tpu.utils import checkpoint as cp

m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
m.set_velocity(0.1, 1.0, 1.0); m.set_temperature(0.1, 1.0, 1.0)
m.update_n(2)
path = sys.argv[1]
cp.write_snapshot(m, path, step=2)          # the checkpoint that must survive
cp.verify_snapshot(path)
m.update_n(2)

calls = [0]
orig = cp._write_array
def bomb(group, name, data):
    calls[0] += 1
    if calls[0] > 7:
        os._exit(9)                          # simulated preemption mid-write
    orig(group, name, data)
cp._write_array = bomb
cp.write_snapshot(m, path, step=4)           # must die before os.replace
os._exit(1)                                  # unreachable if the kill fired
""".format(repo=_REPO)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", child, path],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 9, proc.stderr
    # the step-2 checkpoint is intact and digest-clean
    attrs = cp.verify_snapshot(path)
    assert int(attrs["step"]) == 2
    stepped_rbc17.read(path)
    assert stepped_rbc17.time == pytest.approx(0.02)
    # listing skips any .tmp corpse the kill left behind
    assert cp.checkpoint_files(str(tmp_path)) == [path]


def test_truncated_file_rejected_and_latest_skips(tmp_path, stepped_rbc17):
    model = stepped_rbc17
    good = cp.checkpoint_path(str(tmp_path), 2)
    cp.write_snapshot(model, good, step=2)
    model.update_n(2)
    newer = cp.checkpoint_path(str(tmp_path), 4)
    cp.write_snapshot(model, newer, step=4)
    with open(newer, "r+b") as fh:
        fh.truncate(os.path.getsize(newer) // 2)
    with pytest.raises(cp.CheckpointError, match="truncated"):
        cp.verify_snapshot(newer)
    with pytest.raises(cp.CheckpointError):
        model.read(newer)
    # latest falls back to the previous valid checkpoint
    assert cp.latest_checkpoint(str(tmp_path)) == good


def test_digest_mismatch_rejected(tmp_path, stepped_rbc17):
    model = stepped_rbc17
    path = cp.checkpoint_path(str(tmp_path), 0)
    cp.write_snapshot(model, path, step=0)
    with h5py.File(path, "r+") as h5:
        h5["temp/v"][0, 0] = 1e6  # bit rot: content changed, digest not
    with pytest.raises(cp.CheckpointError, match="digest mismatch"):
        cp.verify_snapshot(path)
    with pytest.raises(cp.CheckpointError, match="digest mismatch"):
        model.read(path)
    assert cp.latest_checkpoint(str(tmp_path)) is None


def test_checkpoint_errors_are_typed(tmp_path, stepped_rbc17):
    """Malformed files raise CheckpointError naming the file and the missing
    group/dataset — not bare KeyError / h5py OSError."""
    model = stepped_rbc17
    empty = str(tmp_path / "empty.h5")
    with h5py.File(empty, "w"):
        pass
    with pytest.raises(cp.CheckpointError, match="ux"):
        model.read(empty)
    # a group with no datasets: the missing dataset is named
    partial = str(tmp_path / "partial.h5")
    with h5py.File(partial, "w") as h5:
        h5.require_group("ux")
    with pytest.raises(cp.CheckpointError, match="vhat"):
        model.read(partial)
    # not an HDF5 file at all
    garbage = str(tmp_path / "garbage.h5")
    with open(garbage, "wb") as fh:
        fh.write(b"not hdf5 at all")
    with pytest.raises(cp.CheckpointError, match="truncated"):
        model.read(garbage)
    # ensemble reader gets the same treatment
    ens = NavierEnsemble.from_seeds(model, seeds=range(2))
    with pytest.raises(cp.CheckpointError, match="members"):
        ens.read(empty)
    # read_unwrap swallows it like the reference's unwrap-or-print
    model.read_unwrap(empty)


def test_rotation_keeps_window(tmp_path, stepped_rbc17):
    model = stepped_rbc17
    for step in range(5):
        cp.write_snapshot(model, cp.checkpoint_path(str(tmp_path), step), step=step)
        cp.rotate_checkpoints(str(tmp_path), keep=3)
    files = cp.checkpoint_files(str(tmp_path))
    assert [os.path.basename(f) for f in files] == [
        "ckpt_0000000002.h5",
        "ckpt_0000000003.h5",
        "ckpt_0000000004.h5",
    ]
    assert cp.latest_checkpoint(str(tmp_path)) == files[-1]


# -- watchdog / fault plumbing ------------------------------------------------


def test_call_with_watchdog():
    import time as _time

    assert call_with_watchdog(lambda: 42, None) == 42
    assert call_with_watchdog(lambda: 42, 5.0) == 42
    with pytest.raises(ValueError, match="boom"):
        call_with_watchdog(lambda: (_ for _ in ()).throw(ValueError("boom")), 5.0)
    with pytest.raises(DispatchHang, match="deadline-test"):
        call_with_watchdog(lambda: _time.sleep(5.0), 0.2, label="deadline-test")


def test_fault_spec_parsing():
    assert FaultPlan.from_spec(None) is None
    assert FaultPlan.from_spec("") is None
    plan = FaultPlan.from_spec("nan@12")
    assert (plan.kind, plan.step, plan.fired) == ("nan", 12, False)
    for bad in ("nan", "typo@3", "nan@x"):
        with pytest.raises(ValueError):
            FaultPlan.from_spec(bad)


# -- the runner ---------------------------------------------------------------


@pytest.mark.slow
def test_nan_rollback_dt_backoff_matches_clean_run(tmp_path):
    """The end-to-end recovery demo: a NaN injected mid-run rolls back to
    the anchor checkpoint, halves dt, and completes; the journal records the
    retry and the final state equals an unfaulted run at the reduced dt
    (rollback target is the step-0 anchor, so the recovered trajectory IS
    the clean reduced-dt trajectory)."""
    run_dir = str(tmp_path / "run")
    runner = ResilientRunner(
        _build(),
        max_time=0.2,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        max_retries=2,
        dt_backoff=0.5,
        fault="nan@6",
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert summary["retries"] == 1
    assert summary["dt"] == pytest.approx(0.005)
    assert summary["time"] == pytest.approx(0.2)
    assert np.isfinite(summary["nu"])

    events = [e["event"] for e in _events(run_dir)]
    assert events == [
        "start",
        "checkpoint",  # anchor
        "fault_injected",
        "divergence",
        "retry",
        "checkpoint",  # final
        "io_overlap",  # run-end pipeline summary (async IO is the default)
        "done",
    ]
    retry = next(e for e in _events(run_dir) if e["event"] == "retry")
    assert retry["dt"] == pytest.approx(0.005)
    assert retry["attempt"] == 1

    clean = _build(dt=0.005)
    integrate(clean, 0.2, None)
    assert summary["nu"] == pytest.approx(clean.eval_nu(), rel=1e-10)
    # final checkpoint reads back digest-clean
    assert cp.verify_snapshot(summary["checkpoint"])["digest"]


@pytest.mark.slow
def test_retries_exhausted_raises(tmp_path):
    """Faults every attempt (nan at a step the retry revisits) exhaust
    max_retries and surface as DivergenceError, journaled as giveup."""
    run_dir = str(tmp_path / "run")

    class AlwaysDiverges(ResilientRunner):
        def _rollback(self):
            super()._rollback()
            self.fault = FaultPlan.from_spec(f"nan@{self.step + 4}")

    runner = AlwaysDiverges(
        _build(),
        max_time=0.5,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        max_retries=1,
        fault="nan@4",
    )
    with pytest.raises(DivergenceError, match="exhausted"):
        runner.run()
    events = [e["event"] for e in _events(run_dir)]
    assert events.count("divergence") == 2
    # giveup is the terminal RUN event; the telemetry layer appends its
    # flight_record pointer behind it as the session unwinds (PR 8)
    assert [e for e in events if e != "flight_record"][-1] == "giveup"
    assert events[-1] == "flight_record"


def test_sigterm_checkpoints_then_resume_continues(tmp_path):
    """SIGTERM mid-flight (the kill fault signals this very process)
    checkpoints-then-exits cleanly; a fresh runner on the same run_dir
    resumes from that checkpoint and completes with a digest-valid final
    snapshot."""
    run_dir = str(tmp_path / "run")
    r1 = ResilientRunner(
        _build(),
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        fault="kill@12",
    )
    s1 = r1.run()
    assert s1["outcome"] == "preempted"
    ckpt = s1["checkpoint"]
    assert ckpt is not None
    step1 = int(cp.verify_snapshot(ckpt)["step"])
    assert step1 >= 12

    r2 = ResilientRunner(
        _build(),  # fresh model: resume must restore state AND step counter
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
    )
    s2 = r2.run()
    assert s2["outcome"] == "done"
    assert s2["time"] == pytest.approx(0.3)
    assert s2["step"] == 30
    events = [e["event"] for e in _events(run_dir)]
    assert "preempted" in events and "resumed" in events and events[-1] == "done"
    resumed = next(e for e in _events(run_dir) if e["event"] == "resumed")
    assert resumed["step"] == step1
    assert cp.verify_snapshot(s2["checkpoint"])["digest"]
    assert np.isfinite(s2["nu"])


@pytest.mark.slow
def test_preempt_without_save_intervall(tmp_path):
    """Even with no save boundaries (save_intervall=None would otherwise
    dispatch the whole horizon as ONE chunk), dispatches are capped at
    max_chunk_steps, so a SIGTERM is honored mid-horizon with a checkpoint
    at the break — not after max_time."""
    run_dir = str(tmp_path / "run")
    runner = ResilientRunner(
        _build(),
        max_time=0.3,
        save_intervall=None,
        run_dir=run_dir,
        checkpoint_every_s=None,
        fault="kill@7",
        max_chunk_steps=5,
    )
    summary = runner.run()
    assert summary["outcome"] == "preempted"
    assert summary["step"] < 30  # stopped mid-horizon
    assert int(cp.verify_snapshot(summary["checkpoint"])["step"]) == summary["step"]


def test_fresh_run_refuses_stale_run_dir(tmp_path, stepped_rbc17):
    """resume=False on a run_dir holding a previous campaign's checkpoints
    must refuse: a later rollback would silently splice the old campaign's
    trajectory into the new run."""
    run_dir = str(tmp_path / "run")
    cp.write_snapshot(stepped_rbc17, cp.checkpoint_path(run_dir, 7), step=7)
    runner = ResilientRunner(
        stepped_rbc17, max_time=0.1, run_dir=run_dir, resume=False
    )  # raises before touching the model
    with pytest.raises(ValueError, match="previous run"):
        runner.run()


@pytest.mark.slow
def test_resume_restores_backed_off_dt(tmp_path):
    """A checkpoint written after a dt backoff carries its dt as a root
    attr; resuming a fresh runner (constructed at the original dt) must
    restore the backed-off dt — otherwise every preemption cycle would
    re-diverge at the original step size and burn a fresh retry budget."""
    run_dir = str(tmp_path / "run")
    donor = _build(dt=0.005)  # stands in for a post-backoff run
    donor.update_n(4)
    cp.write_snapshot(donor, cp.checkpoint_path(run_dir, 4), step=4)
    runner = ResilientRunner(
        _build(dt=0.01),  # rerun of the original command: original dt
        max_time=0.1,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert summary["dt"] == pytest.approx(0.005)
    assert summary["time"] == pytest.approx(0.1)
    events = [e["event"] for e in _events(run_dir)]
    assert "dt_restored" in events


@pytest.mark.slow
def test_slow_fault_trips_dispatch_watchdog(tmp_path):
    """The slow fault stalls a dispatch past the watchdog deadline: thread
    stacks are dumped and a structured DispatchHang is raised (instead of a
    silent hang), with the hang journaled."""
    model = _build()
    # warm the jit caches (scan buckets 4/2/1 + observables) so compile time
    # cannot eat the watchdog deadline
    model.update_n(7)
    model.eval_nu()
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.reset_time()
    run_dir = str(tmp_path / "run")
    runner = ResilientRunner(
        model,
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        fault="slow@7",
        dispatch_timeout_s=3.0,
    )
    with pytest.raises(DispatchHang, match="update_n"):
        runner.run()
    events = [e["event"] for e in _events(run_dir)]
    # dispatch_hang is the terminal RUN event; the flight-record pointer
    # rides behind it as the session unwinds (PR 8)
    assert [e for e in events if e != "flight_record"][-1] == "dispatch_hang"
    assert events[-1] == "flight_record"
    assert "fault_injected" in events


@pytest.mark.slow
def test_checkpoint_cadence_sim_time(tmp_path):
    """checkpoint_every_t drops a rolling window of checkpoints at the
    sim-time cadence, pruned to ``keep``."""
    run_dir = str(tmp_path / "run")
    runner = ResilientRunner(
        _build(),
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        checkpoint_every_t=0.1,
        keep=2,
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    files = cp.checkpoint_files(run_dir)
    assert len(files) == 2  # retention window
    cadence = [e for e in _events(run_dir) if e.get("reason") == "cadence"]
    assert len(cadence) >= 2


# -- dt backoff + ensembles ---------------------------------------------------


@pytest.mark.slow
def test_set_dt_matches_fresh_model():
    """set_dt rebuilds the dt-baked solver pipeline exactly: a live model
    switched to dt/2 steps identically to a fresh dt/2 model handed the same
    state."""
    model = _build()
    model.update_n(5)
    fresh = Navier2D(17, 17, 1e4, 1.0, 0.005, 1.0, "rbc", periodic=False)
    fresh.state = model.state
    model.set_dt(0.005)
    model.update_n(4)
    fresh.update_n(4)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(model.state, attr)),
            np.asarray(getattr(fresh.state, attr)),
            atol=1e-13,
            err_msg=attr,
        )
    with pytest.raises(ValueError):
        model.set_dt(-1.0)


def test_ensemble_respawn_equivalence():
    """Respawning a dead member from a perturbed healthy donor revives it
    without touching any surviving member's state (bitwise)."""
    import jax

    model = _build()
    ens = NavierEnsemble.from_seeds(model, seeds=range(3))
    ens.update_n(4)
    dead = jax.tree.map(lambda x: x * float("nan"), ens.member_state(1))
    ens.set_member(1, dead)
    assert list(ens.alive()) == [True, False, True]
    before = {
        attr: np.asarray(getattr(ens.state, attr)).copy()
        for attr in ("temp", "velx", "vely", "pres", "pseu")
    }
    assert ens.respawn_dead(amp=1e-3, seed=0) == 1
    assert ens.alive().all()
    for attr, prev in before.items():
        arr = np.asarray(getattr(ens.state, attr))
        np.testing.assert_array_equal(arr[0], prev[0], err_msg=attr)
        np.testing.assert_array_equal(arr[2], prev[2], err_msg=attr)
        assert np.isfinite(arr[1]).all(), attr
    # respawned member steps fine at the ensemble's (possibly backed-off) dt
    ens.set_dt(0.005)
    ens.update_n(2)
    assert ens.alive().all()
    # no-ops: all alive / all dead
    assert ens.respawn_dead() == 0
    ens.set_member(0, jax.tree.map(lambda x: x * float("nan"), ens.member_state(0)))
    ens.set_member(1, jax.tree.map(lambda x: x * float("nan"), ens.member_state(1)))
    ens.set_member(2, jax.tree.map(lambda x: x * float("nan"), ens.member_state(2)))
    assert ens.respawn_dead() == 0


@pytest.mark.slow
def test_runner_drives_ensemble(tmp_path):
    """The runner wraps an ensemble unchanged: NaN-poisoning all members
    fires the all-dead break criterion, rolls back, backs off dt, and
    completes; the restored checkpoint carries the per-member layout."""
    model = _build()
    ens = NavierEnsemble.from_seeds(model, seeds=range(2))
    run_dir = str(tmp_path / "run")
    runner = ResilientRunner(
        ens,
        max_time=0.2,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        max_retries=1,
        fault="nan@6",
        respawn_members=True,
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert summary["retries"] == 1
    assert ens.alive().all()
    assert summary["dt"] == pytest.approx(0.005)
    assert np.isfinite(summary["nu"])
    with h5py.File(summary["checkpoint"], "r") as h5:
        assert "member0" in h5 and "member1" in h5


@pytest.mark.slow
def test_resilience_config_roundtrip(tmp_path):
    from rustpde_mpi_tpu.config import NavierConfig, ResilienceConfig

    rcfg = ResilienceConfig(
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
        checkpoint_every_t=0.1,
        keep=2,
        max_retries=1,
    )
    cfg = NavierConfig(nx=17, ny=17, ra=1e4, dt=0.01, resilience=rcfg)
    model = Navier2D.from_config(cfg)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    runner = ResilientRunner.from_config(
        model, cfg.resilience, max_time=0.1, save_intervall=0.05
    )
    assert runner.keep == 2 and runner.max_retries == 1
    assert runner.run()["outcome"] == "done"
